#!/usr/bin/env python
"""Training driver — CLI-compatible with the reference's Hydra entry point.

Usage (reference: train.py + sweeps/*.sh)::

    python train.py                               # defaults
    python train.py model=large loss=nll          # group overrides
    python train.py model.learning_rate=1e-3      # value overrides
    python train.py -m model.learning_rate=1e-3,1e-4 trainer.max_epochs=100,200

Capability parity with the reference driver (reference: train.py:70-220):
data bootstrap, datamodule + model construction from config, TensorBoard
logger with composed name/version, best/last checkpointing, LR monitoring,
fit + test, final hparams logging, and returning the best validation score.
The Lightning Trainer is replaced by the in-tree TPU trainer
(masters_thesis_tpu.train.Trainer); the joblib multirun launcher by a
process-per-job native launcher.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

from masters_thesis_tpu.config import (
    Config,
    compose,
    expand_multirun,
    register_resolver,
    to_flat_dict,
)

CONFIG_DIR = Path(__file__).resolve().parent / "configs"

def _register_resolvers() -> None:
    """Register the derived-config resolvers (reference: train.py:39-42).

    Called at import AND inside ``_run_job``: a multirun worker process that
    receives ``_run_job`` by value (cloudpickle) never executes this module's
    import side effects, so registration must be part of the job itself.
    """
    register_resolver(
        "input_size_from_interaction",
        lambda interaction: 3 if interaction else 5,
    )
    # K-factor generalization of the above: features are
    # [r_stock, f_1..f_K, r_stock*f_k...] (2K+1) with interaction_only,
    # plus the squared channels (3K+2) otherwise. At K=1 this reduces to
    # the scalar resolver's 3/5.
    register_resolver(
        "input_size_from_factors",
        lambda interaction, n_factors: (
            2 * int(n_factors) + 1 if interaction else 3 * int(n_factors) + 2
        ),
    )


_register_resolvers()


def bootstrap(cfg: Config) -> bool:
    """Materialize source arrays for the selected datamodule.

    (reference: train.py:15-36 — import-time side effects there; explicit
    and config-driven here.) Returns False if real CSVs are missing.
    """
    from masters_thesis_tpu.data.pipeline import bootstrap_real, bootstrap_synthetic

    dmcfg = cfg.datamodule
    # Synthetic-DGP datamodules (synthetic, universe) carry n_stocks; the
    # real datamodule carries raw_dir instead.
    if "n_stocks" in dmcfg:
        # The DGP seed is its own key (default 0), NOT cfg.seed: sweeping the
        # training seed must not invalidate (or conflict with) a shared
        # bootstrapped dataset.
        bootstrap_synthetic(
            Path(dmcfg.data_dir),
            n_stocks=dmcfg.n_stocks,
            n_samples=dmcfg.n_samples,
            seed=dmcfg.get("dgp_seed", 0),
            variant=dmcfg.get("dgp_variant", "no_outliers"),
            n_factors=dmcfg.get("n_factors", 1),
        )
        return True
    if not bootstrap_real(Path(dmcfg.raw_dir), Path(dmcfg.data_dir)):
        print(
            f"Real data CSVs not found under {dmcfg.raw_dir}; download the "
            "Fama-French daily factors + 25 portfolios files first.",
            file=sys.stderr,
        )
        return False
    return True


def build_datamodule(cfg: Config):
    from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule

    d = cfg.datamodule
    return FinancialWindowDataModule(
        Path(d.data_dir),
        lookback_window=d.lookback_window,
        target_window=d.target_window,
        stride=d.stride,
        prediction_task=d.prediction_task,
        interaction_only=d.interaction_only,
        batch_size=d.batch_size,
        engine=d.get("engine", "auto"),
        store_shards=d.get("store_shards", None),
    )


def build_spec(cfg: Config):
    """Model registry lookup + hparams (reference: train.py:45-67,121-136)."""
    from masters_thesis_tpu.models.objectives import get_model_spec

    hparams = dict(
        input_size=cfg.model.input_size,
        hidden_size=cfg.model.hidden_size,
        num_layers=cfg.model.num_layers,
        dropout=cfg.model.dropout,
        n_factors=cfg.model.get("n_factors", 1),
        learning_rate=cfg.model.learning_rate,
        weight_decay=cfg.model.weight_decay,
        remat=cfg.model.get("remat", False),
        kernel_impl=cfg.model.get("kernel_impl", "auto"),
    )
    if "mse_weight" in cfg.loss:
        hparams["mse_weight"] = cfg.loss.mse_weight
    return get_model_spec(cfg.loss.module_class, **hparams)


def run(cfg: Config) -> float:
    """One training run; returns the best validation loss (the sweep
    objective the reference returns at train.py:220)."""
    from masters_thesis_tpu.train import Trainer
    from masters_thesis_tpu.train.logging import TensorBoardLogger
    from masters_thesis_tpu.utils import enable_persistent_compilation_cache

    # Sweep jobs after the first skip the multi-second XLA compiles.
    enable_persistent_compilation_cache()

    # Multi-host single-job training: initialize the JAX distributed runtime
    # first so every host sees the global device mesh (replaces Lightning's
    # NCCL process-group bring-up; SURVEY.md §2.2).
    if cfg.trainer.get("distributed", False):
        from masters_thesis_tpu.parallel import distributed_initialize

        # required=True: the user asked for distributed — a misconfigured
        # coordinator must fail loudly, not degrade to single-host.
        distributed_initialize(required=True)

    if not bootstrap(cfg):
        return float("inf")
    dm = build_datamodule(cfg)
    spec = build_spec(cfg)

    logger = TensorBoardLogger(
        cfg.logger.save_dir, cfg.logger.name, cfg.logger.version
    )
    ckpt_dir = logger.log_dir / "checkpoints"

    t = cfg.trainer
    # trainer.telemetry: 'auto' puts the run's events.jsonl next to the TB
    # logs; an explicit path pins the run dir; null/false disables.
    tel_cfg = t.get("telemetry", None)
    telemetry = None
    if tel_cfg:
        from masters_thesis_tpu.telemetry import TelemetryRun

        telemetry = TelemetryRun(
            logger.log_dir / "telemetry"
            if tel_cfg == "auto"
            else Path(tel_cfg)
        )
    profile_steps = t.get("profile_steps", None)
    trainer = Trainer(
        max_epochs=t.max_epochs,
        gradient_clip_val=t.gradient_clip_val,
        precision=t.precision,
        check_val_every_n_epoch=t.get("check_val_every_n_epoch", 1),
        strategy=t.strategy,
        epoch_mode=t.epoch_mode,
        shard_axis=t.get("shard_axis", "window"),
        n_devices=t.get("n_devices", None),
        enable_progress_bar=t.enable_progress_bar,
        enable_model_summary=t.enable_model_summary,
        profile=t.get("profile", False),
        profile_steps=tuple(profile_steps) if profile_steps else None,
        logger=logger,
        ckpt_dir=ckpt_dir,
        seed=cfg.seed,
        name=t.name,
        resume=t.get("resume", False),
        preflight=t.get("preflight", False),
        telemetry=telemetry,
        cost_profile=t.get("cost_profile", None),
        hang_timeout_s=t.get("hang_timeout_s", None),
        checkpoint_every_n_epochs=cfg.get("resilience", {}).get(
            "checkpoint_every_n_epochs", None
        ),
    )

    init_state = None
    if cfg.checkpoint:
        from masters_thesis_tpu.train.checkpoint import restore_checkpoint

        mode = cfg.get("checkpoint_mode", "full")
        if mode not in ("full", "params"):
            raise ValueError(
                f"checkpoint_mode must be 'full' or 'params', got {mode!r}"
            )
        params, opt_state, ckpt_spec, _ = restore_checkpoint(
            Path(cfg.checkpoint)
        )
        if mode == "full":
            # Exact resume: the checkpoint's spec (objective, lr, ...) wins.
            spec = ckpt_spec
            init_state = (params, opt_state)
        else:
            # 'params' = warmup protocol: reuse weights, fresh optimizer —
            # and the CONFIG keeps deciding the objective/lr/dropout (the
            # thesis fine-tunes a combined-pretrained model under each of
            # the three losses; tex/diplomski_rad.tex:1134-1147,
            # sweeps/experiment_warmup.sh). Only the weight shapes must
            # match the checkpoint.
            if (ckpt_spec.hidden_size, ckpt_spec.num_layers) != (
                spec.hidden_size, spec.num_layers,
            ):
                raise ValueError(
                    "checkpoint_mode=params needs a matching architecture: "
                    f"checkpoint is hidden={ckpt_spec.hidden_size}/"
                    f"layers={ckpt_spec.num_layers}, config asks "
                    f"hidden={spec.hidden_size}/layers={spec.num_layers}"
                )
            init_state = (params, None)

    result = trainer.fit(spec, dm, init_state=init_state)
    test_metrics = trainer.test(spec, result.params, dm)

    # Final hparams + test metrics table (reference: train.py:204-211).
    logger.log_hparams(
        to_flat_dict(cfg),
        {
            "test/mae": test_metrics.get("mae", float("nan")),
            "test/nll": test_metrics.get("nll", float("nan")),
            "test/best_val_loss": result.best_val_loss,
        },
    )
    logger.close()
    if telemetry is not None:
        telemetry.close()
        print(
            "telemetry: python -m masters_thesis_tpu.telemetry summarize "
            f"{telemetry.run_dir}"
        )
    print(
        f"done: best_val={result.best_val_loss:.6g} "
        f"test_mae={test_metrics.get('mae', float('nan')):.6g} "
        f"test_nll={test_metrics.get('nll', float('nan')):.6g} "
        f"steps/sec={result.steps_per_sec:.1f}"
    )
    return result.best_val_loss


def _plain(obj):
    """Config -> plain dict/list tree (yaml.safe_dump rejects subclasses)."""
    if isinstance(obj, dict):
        return {k: _plain(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_plain(v) for v in obj]
    return obj


def _write_job_metadata(job_dir: Path, cfg: Config, overrides: list[str]):
    """Hydra-compatible per-job metadata: .hydra/config.yaml + overrides.yaml
    (what a Hydra user expects to find inside multirun/<date>/<time>/<n>/)."""
    import yaml

    meta_dir = job_dir / ".hydra"
    meta_dir.mkdir(parents=True, exist_ok=True)
    (meta_dir / "config.yaml").write_text(
        yaml.safe_dump(_plain(cfg), sort_keys=False)
    )
    (meta_dir / "overrides.yaml").write_text(yaml.safe_dump(list(overrides)))


def _run_job(
    config_dir: str,
    overrides: list[str],
    job_index: int | None = None,
    sweep_dir: str | None = None,
) -> float:
    """Top-level function so the process-pool launcher can pickle it."""
    _register_resolvers()
    cfg = compose(config_dir, overrides=overrides)
    if job_index is not None:
        if sweep_dir is not None and not Path(cfg.logger.save_dir).is_absolute():
            # Hydra multirun layout: each sweep point owns a numbered job
            # dir <sweep_dir>/<job_idx>/ holding its logs, checkpoints, and
            # .hydra metadata (the reference gets this from Hydra's
            # numbered per-job sweep dirs, configs/config.yaml:6,17-19).
            job_dir = Path(sweep_dir) / str(job_index)
            cfg.logger["save_dir"] = str(job_dir / cfg.logger.save_dir)
            _write_job_metadata(job_dir, cfg, overrides)
        else:
            # An absolute save_dir pins the output location (Hydra's logger
            # would do the same); fall back to a version suffix so every
            # sweep point still gets a unique log/checkpoint dir even when
            # the swept parameter isn't part of the version interpolation.
            cfg.logger["version"] = f"{cfg.logger.version}_job{job_index}"
    return run(cfg)


def partition_jobs(
    jobs: list[list[str]], host_index: int, num_hosts: int
) -> list[list[str]]:
    """Round-robin shard of sweep points for multi-host dispatch.

    Each host of a pod runs the same multirun command with its own
    ``launcher.host_index`` and trains every ``num_hosts``-th sweep point —
    the multi-host equivalent of the reference's joblib process-per-job
    launcher (reference: configs/config.yaml:6,17-19).
    """
    if not (0 <= host_index < num_hosts):
        raise ValueError(
            f"host_index {host_index} out of range for {num_hosts} hosts"
        )
    return jobs[host_index::num_hosts]


def main(argv: list[str] | None = None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("overrides", nargs="*", help="key=value config overrides")
    parser.add_argument(
        "-m", "--multirun", action="store_true",
        help="expand comma-separated override values into a sweep",
    )
    args = parser.parse_args(argv)

    if not args.multirun:
        _run_job(str(CONFIG_DIR), args.overrides)
        return

    jobs = expand_multirun(args.overrides)
    cfg0 = compose(str(CONFIG_DIR), overrides=jobs[0])
    launcher_name = cfg0.launcher.get("name", "sequential")
    n_jobs = int(cfg0.launcher.get("n_jobs", 1))
    num_hosts = int(
        os.environ.get("MT_NUM_HOSTS", cfg0.launcher.get("num_hosts", 1))
    )
    host_index = int(
        os.environ.get("MT_HOST_INDEX", cfg0.launcher.get("host_index", 0))
    )
    # Numbered sweep output root (Hydra's multirun/<date>/<time>); pin it
    # via launcher.sweep_dir or MT_SWEEP_DIR when sharding across hosts.
    sweep_dir = os.environ.get("MT_SWEEP_DIR") or cfg0.launcher.get("sweep_dir")
    if not sweep_dir:
        import datetime

        now = datetime.datetime.now()
        sweep_dir = f"multirun/{now:%Y-%m-%d}/{now:%H-%M-%S}"
    total = len(jobs)
    # Jobs keep their GLOBAL sweep index across host partitions so the
    # numbered job dir (or _job<N> suffix) is collision-free fleet-wide.
    indexed = list(enumerate(jobs))
    if num_hosts > 1:
        indexed = partition_jobs(indexed, host_index, num_hosts)
        print(
            f"multirun: host {host_index}/{num_hosts} takes "
            f"{len(indexed)}/{total} jobs"
        )
    print(f"multirun: {len(indexed)} jobs, n_jobs={n_jobs}")
    if n_jobs == 1 and launcher_name != "joblib":
        # launcher=sequential: jobs share this process (and its one TPU
        # client + warm compile cache).
        for i, ov in indexed:
            print(f"--- job {i}: {ov}")
            _run_job(str(CONFIG_DIR), ov, job_index=i, sweep_dir=sweep_dir)
    else:
        # launcher=joblib (or n_jobs>1): process-per-job, like the
        # reference's joblib launcher (reference: configs/config.yaml:6,17-19).
        import joblib

        joblib.Parallel(n_jobs=n_jobs, verbose=10)(
            joblib.delayed(_run_job)(
                str(CONFIG_DIR), ov, job_index=i, sweep_dir=sweep_dir
            )
            for i, ov in indexed
        )


if __name__ == "__main__":
    main()
