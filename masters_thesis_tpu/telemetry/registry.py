"""In-process metrics registry: counters, gauges, histograms.

The registry is the run's live aggregate state; the JSONL event stream
(:mod:`events`) is its durable form. Both are host-side and stdlib-only —
nothing here may import jax, because the ``summarize`` CLI loads this
package on machines where touching the backend can hang forever (the
wedged-relay failure mode, docs/OPERATIONS.md).

Instruments are tagged with host/process identity so multi-host runs can
merge event streams without ambiguity. Histograms keep a bounded,
deterministic sample (no RNG — stride-decimation, not reservoir sampling)
plus exact count/sum/min/max; report-grade quantiles come from the event
stream, the in-registry quantiles are a cheap live approximation.
"""

from __future__ import annotations

import os
import socket
import threading


def default_tags() -> dict:
    """Host/process identity tags stamped on every instrument snapshot.

    ``process_index`` is filled by :class:`~.run.TelemetryRun` once a
    backend exists; this module never imports jax to find out.
    """
    return {"host": socket.gethostname(), "pid": os.getpid()}


class Counter:
    """Monotonic accumulator (float increments allowed: seconds, bytes)."""

    def __init__(self, name: str):
        self.name = name
        self.value: float = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment")
        self.value += amount

    def snapshot(self) -> dict:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Last-write-wins instantaneous value."""

    def __init__(self, name: str):
        self.name = name
        self.value: float | None = None

    def set(self, value: float) -> None:
        self.value = float(value)

    def snapshot(self) -> dict:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Summary stats + a bounded deterministic sample of observations.

    Once ``max_samples`` is reached the sample is decimated by dropping
    every other kept value and the keep-stride doubles — bounded memory,
    no randomness, and the kept points stay spread over the whole run
    rather than clustered at the start.

    Thread-safe: fleet replicas observe the shared ``serve/latency_s``
    histogram from concurrent dispatch threads, so the summary state and
    the kept sample mutate under a lock (uncontended host-side acquire;
    nothing here runs on the device hot path).
    """

    def __init__(self, name: str, max_samples: int = 2048):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._samples: list[float] = []
        self._max_samples = max_samples
        self._stride = 1
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)
            if (self.count - 1) % self._stride == 0:
                self._samples.append(value)
                if len(self._samples) >= self._max_samples:
                    self._samples = self._samples[::2]
                    self._stride *= 2

    @staticmethod
    def _rank(ordered: list[float], q: float) -> float | None:
        if not ordered:
            return None
        idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
        return ordered[idx]

    def quantile(self, q: float) -> float | None:
        """Nearest-rank quantile over the kept sample (live approximation)."""
        with self._lock:
            samples = list(self._samples)
        return self._rank(sorted(samples), q)

    def snapshot(self) -> dict:
        with self._lock:
            count, total = self.count, self.sum
            lo, hi = self.min, self.max
            samples = list(self._samples)
        ordered = sorted(samples)
        return {
            "type": "histogram",
            "count": count,
            "sum": total,
            "min": lo,
            "max": hi,
            "mean": (total / count) if count else None,
            "p50": self._rank(ordered, 0.50),
            "p99": self._rank(ordered, 0.99),
        }


class MetricsRegistry:
    """Named instruments, created on first use, snapshot-able as one dict."""

    def __init__(self, tags: dict | None = None):
        self.tags = dict(default_tags())
        if tags:
            self.tags.update(tags)
        self._lock = threading.Lock()
        self._instruments: dict[str, Counter | Gauge | Histogram] = {}

    def _get(self, name: str, cls):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(inst).__name__}, requested {cls.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "tags": dict(self.tags),
                "metrics": {
                    name: inst.snapshot()
                    for name, inst in sorted(self._instruments.items())
                },
            }
