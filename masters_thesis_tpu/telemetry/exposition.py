"""Stdlib-only HTTP exposition: ``/metrics``, ``/healthz``, ``/slo``.

One tiny :class:`ExpositionServer` per process renders the run's live
state for pull-based monitoring:

- ``/metrics`` — the :class:`~.registry.MetricsRegistry` snapshot in
  Prometheus text exposition format (counters and gauges as-is;
  histograms as summaries with ``quantile`` labels plus ``_sum`` /
  ``_count``), with the registry's host/pid identity as labels and the
  SLO engine's firing alerts as ``mtt_slo_firing`` gauges so a plain
  Prometheus scrape sees alert state without parsing JSON;
- ``/healthz`` — liveness JSON (the process answering IS the signal),
  with the firing-alert list for load balancers that want degradation;
- ``/slo`` — the :class:`~.slo.SLOEngine`'s full published state.

Threading contract (the CL501–CL505 shape): the listener thread is
spawned in :meth:`start` — never in ``__init__`` — and joined with a
bounded timeout in :meth:`close`. Request handlers hold NO locks of
ours: they call providers that copy state under their own short
internal locks (``registry.snapshot()``, ``engine.state()``) and do all
rendering on the handler thread afterwards. Routes are frozen before
``start()``, so the handler reads the routing table without
synchronization.

Deliberately dependency-free (``http.server``): the container bakes in
no prometheus client, and the text format is lines of ASCII.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_NAME_OK = set(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"
)


def sanitize_metric_name(name: str, prefix: str = "mtt_") -> str:
    """Map a registry name (``serve/request_wall_s``) onto the Prometheus
    grammar ``[a-zA-Z_:][a-zA-Z0-9_:]*`` with a stable ``mtt_`` prefix."""
    cleaned = "".join(c if c in _NAME_OK else "_" for c in name)
    if cleaned and cleaned[0].isdigit():
        cleaned = "_" + cleaned
    return prefix + cleaned


def escape_label_value(value) -> str:
    """Label-value escaping per the text format: backslash, quote, LF."""
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def escape_help(text: str) -> str:
    """HELP-line escaping: backslash and LF only (quotes are literal)."""
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _labels(tags: dict, extra: dict | None = None) -> str:
    merged = dict(tags)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"'
        for k, v in sorted(merged.items())
        if v is not None
    )
    return "{" + inner + "}"


def _num(value) -> str:
    if value is None:
        return "NaN"
    value = float(value)
    if value != value:
        return "NaN"
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    return repr(value)


def render_prometheus(
    snapshot: dict, slo_state: dict | None = None
) -> str:
    """The registry snapshot (``MetricsRegistry.snapshot()`` shape) as
    Prometheus text exposition format, plus ``mtt_slo_firing`` gauges
    from an optional SLO state dict."""
    tags = snapshot.get("tags") or {}
    lines: list[str] = []
    for name, inst in sorted((snapshot.get("metrics") or {}).items()):
        kind = inst.get("type")
        pname = sanitize_metric_name(name)
        help_line = f"# HELP {pname} {escape_help(name)}"
        if kind == "counter":
            lines += [
                help_line,
                f"# TYPE {pname} counter",
                f"{pname}{_labels(tags)} {_num(inst.get('value'))}",
            ]
        elif kind == "gauge":
            lines += [
                help_line,
                f"# TYPE {pname} gauge",
                f"{pname}{_labels(tags)} {_num(inst.get('value'))}",
            ]
        elif kind == "histogram":
            lines += [help_line, f"# TYPE {pname} summary"]
            for q, key in (("0.5", "p50"), ("0.99", "p99")):
                lines.append(
                    f"{pname}{_labels(tags, {'quantile': q})} "
                    f"{_num(inst.get(key))}"
                )
            lines.append(
                f"{pname}_sum{_labels(tags)} {_num(inst.get('sum'))}"
            )
            lines.append(
                f"{pname}_count{_labels(tags)} "
                f"{_num(inst.get('count') or 0)}"
            )
    if slo_state:
        lines += [
            "# HELP mtt_slo_firing 1 while the named SLO rule is firing",
            "# TYPE mtt_slo_firing gauge",
        ]
        for rule, row in sorted((slo_state.get("rules") or {}).items()):
            lines.append(
                f"mtt_slo_firing{_labels(tags, {'rule': rule})} "
                f"{1 if row.get('firing') else 0}"
            )
            if row.get("value") is not None:
                lines.append(
                    f"mtt_slo_value{_labels(tags, {'rule': rule})} "
                    f"{_num(row.get('value'))}"
                )
    return "\n".join(lines) + "\n"


class ExpositionServer:
    """Owns one listener thread serving /metrics, /healthz, /slo."""

    def __init__(
        self,
        registry=None,
        slo=None,
        bind_host: str = "127.0.0.1",
        port: int = 0,
    ):
        self._registry = registry
        self._slo = slo
        self._bind_host = bind_host
        self._requested_port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self.port: int | None = None

    # ---------------------------------------------------------- handlers
    # Called on http.server worker threads; they must copy state through
    # the providers' own internal locks and render lock-free here.

    def _get(self, path: str) -> tuple[int, str, str]:
        path = path.split("?", 1)[0]
        if path == "/metrics":
            snap = (
                self._registry.snapshot()
                if self._registry is not None
                else {"tags": {}, "metrics": {}}
            )
            state = self._slo.state() if self._slo is not None else None
            return 200, "text/plain; version=0.0.4", render_prometheus(
                snap, state
            )
        if path == "/healthz":
            state = self._slo.state() if self._slo is not None else {}
            body = json.dumps(
                {
                    "ok": True,
                    "ts": time.time(),
                    "firing": state.get("firing") or [],
                }
            )
            return 200, "application/json", body
        if path == "/slo":
            state = self._slo.state() if self._slo is not None else {}
            return 200, "application/json", json.dumps(state, default=str)
        return 404, "text/plain", f"no route {path!r}\n"

    # --------------------------------------------------------- lifecycle

    def start(self) -> "ExpositionServer":
        if self._httpd is not None:
            return self
        owner = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 -- http.server API
                try:
                    status, ctype, body = owner._get(self.path)
                except Exception as exc:  # noqa: BLE001 -- a provider
                    # error must answer 500, not kill the worker thread
                    status, ctype, body = 500, "text/plain", f"{exc}\n"
                payload = body.encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = ThreadingHTTPServer(
            (self._bind_host, self._requested_port), _Handler
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="exposition-http",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def url(self) -> str | None:
        if self.port is None:
            return None
        return f"http://{self._bind_host}:{self.port}"

    def close(self) -> None:
        httpd, thread = self._httpd, self._thread
        self._httpd = self._thread = None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=10.0)

    def __enter__(self) -> "ExpositionServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def start_telemetry_plane(
    telemetry,
    metrics_port: int | None,
    rules=None,
    slo_interval_s: float = 2.0,
    root=None,
):
    """The one-call attach point components share: an SLO engine tailing
    the run dir's streams plus an exposition server over the run's
    registry. Returns ``(server, engine)`` — both ``None`` when the
    component has no telemetry or no port was requested (``port=0``
    binds an ephemeral port; ``None`` disables the plane). ``root``
    points the SLO engine at a different stream tree than the run dir —
    supervisors watch their CHILDREN's streams while exposing their own
    registry."""
    if telemetry is None or metrics_port is None:
        return None, None
    from masters_thesis_tpu.telemetry.slo import SLOEngine

    engine = SLOEngine(
        root or telemetry.run_dir, rules=rules, sink=telemetry.sink
    )
    engine.start(interval_s=slo_interval_s)
    server = attach_exposition(telemetry, port=metrics_port, slo=engine)
    return server, engine


def stop_telemetry_plane(server, engine) -> None:
    """Tear down what :func:`start_telemetry_plane` built (idempotent)."""
    if server is not None:
        server.close()
    if engine is not None:
        engine.stop()


def attach_exposition(
    telemetry, port: int = 0, bind_host: str = "127.0.0.1", slo=None
) -> ExpositionServer:
    """Start an exposition server over a :class:`~.run.TelemetryRun`'s
    registry (plus an optional SLO engine) and record the bound URL in
    the event stream so operators and the watch console can find it."""
    server = ExpositionServer(
        registry=telemetry.registry, slo=slo, bind_host=bind_host,
        port=port,
    ).start()
    telemetry.event(
        "exposition_started",
        url=server.url,
        port=server.port,
        bind_host=bind_host,
    )
    return server
