"""Declarative SLO rules with multi-window burn-rate alerting.

Every observability surface before this module was post-hoc — summarize,
aggregate, postmortem, and trace all read ``events.jsonl`` after the run
ends. The SLO engine reads the SAME streams *while they are being
written* (via the tail-cursor reader, :func:`~.events.read_new_lines`)
and folds them into a small set of live signals:

- per-request latency/outcome from ``serve.request`` span events (the
  request path closes one span per request with status ∈ {ok, shed,
  rejected_late, error} and its wall duration — serve/spans.py);
- epoch health (starvation, recompiles, divergence) from ``epoch`` /
  ``run_finished`` events;
- liveness from the flight recorder's ``heartbeat.json`` sidecars and
  each stream's last event timestamp.

A :class:`SLORule` names a signal kind, a threshold, and a fast/slow
window pair. The *burn rate* rule follows the multi-window form used by
production SLO alerting: with availability target T, the error budget is
``1 − T`` and the burn rate is ``error_rate / (1 − T)`` — burn 1.0 means
the budget is consumed exactly at sustainment rate; burn N means the
budget dies N× too fast. The rule fires only when BOTH windows breach:
the fast window makes the alert responsive, the slow window stops a
brief blip from paging. Alert transitions are debounced (``for_ticks``
consecutive breaches to fire, ``clear_ticks`` consecutive clean ticks to
resolve — a flapping signal fires ONCE and stays firing) and emitted
back into the event stream as ``alert_fired`` / ``alert_resolved``
events, so the post-hoc report confirms exactly what the live plane saw.

Evaluation is strictly reader-side: the engine touches the serve/train
hot paths nowhere — it tails their streams. The ``slo.evaluate`` fault
point lets chaos plans wedge the evaluator (ticks become no-ops, the
published state goes stale) without touching serving.

Stdlib-only by contract, like the rest of the telemetry CLI surface.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path

from masters_thesis_tpu.resilience.faults import fire
from masters_thesis_tpu.telemetry.events import read_new_lines
from masters_thesis_tpu.telemetry.flightrec import HEARTBEAT_FILENAME
from masters_thesis_tpu.telemetry.report import EVENTS_FILENAME

#: Rule kinds and the signal each one compares against its threshold.
RULE_KINDS = frozenset(
    {
        "p99_latency",  # p99 request wall seconds over the fast window
        "shed_pct",  # % of requests shed/rejected over the fast window
        "burn_rate",  # error-budget burn; fires when BOTH windows breach
        "heartbeat_staleness",  # seconds since the quietest live stream
        "starvation_pct",  # input-pipeline starvation % (slow window)
        "recompile",  # epoch-program compiles beyond the contract's one
        "divergence",  # a run halted on a non-finite loss
        "input_drift",  # max feature PSI from quality_sample events
        "prediction_drift",  # max predicted-(α,β) PSI from quality_sample
        "shadow_disagreement",  # |model − shadow-OLS| EWMA from
        # quality_sample events (telemetry/quality.py)
    }
)

#: The model-quality rule kinds and the ``quality_sample`` field each one
#: reads (the monitor emits thresholds too, but the RULE owns its own).
QUALITY_RULE_FIELDS = {
    "input_drift": "input_psi",
    "prediction_drift": "pred_psi",
    "shadow_disagreement": "shadow_err",
}

#: Request statuses that consume error budget (a shed IS a user-visible
#: non-answer; the no-late-answers invariant makes rejected_late one too).
ERROR_STATUSES = frozenset({"shed", "rejected_late", "error"})


@dataclass(frozen=True)
class SLORule:
    """One declarative objective; see :data:`RULE_KINDS` for semantics."""

    name: str
    kind: str
    threshold: float = 0.0
    #: Availability objective for ``burn_rate`` (budget = 1 − target).
    target: float = 0.99
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    #: Consecutive breaching ticks before the alert fires.
    for_ticks: int = 1
    #: Consecutive clean ticks before a firing alert resolves.
    clear_ticks: int = 2

    def __post_init__(self) -> None:
        if self.kind not in RULE_KINDS:
            raise ValueError(
                f"unknown SLO rule kind: {self.kind!r} "
                f"(valid kinds: {', '.join(sorted(RULE_KINDS))})"
            )
        if self.fast_window_s > self.slow_window_s:
            raise ValueError(
                f"rule {self.name}: fast window {self.fast_window_s}s "
                f"exceeds slow window {self.slow_window_s}s"
            )


def burn_rate(error_rate: float, target: float) -> float:
    """Error-budget burn: how many times faster than sustainable the
    budget is being consumed. Burn 1.0 = the budget lasts exactly the
    SLO period; an exhausted budget (target ≥ 1) burns infinitely fast
    the moment anything errors."""
    budget = 1.0 - target
    if budget <= 0.0:
        return math.inf if error_rate > 0.0 else 0.0
    return error_rate / budget


def window_stats(
    requests, now: float, window_s: float
) -> dict:
    """Fold ``(ts, status, dur_s)`` request samples inside the window.

    Returns n / ok / errored / shed counts, the error rate, nearest-rank
    p99 latency over samples that carried a duration, and the offered
    QPS (n over the window span)."""
    n = ok = shed = errored = 0
    durs: list[float] = []
    cutoff = now - window_s
    for ts, status, dur_s in requests:
        if ts < cutoff:
            continue
        n += 1
        if status == "ok":
            ok += 1
        if status in ("shed", "rejected_late"):
            shed += 1
        if status in ERROR_STATUSES:
            errored += 1
        if dur_s is not None:
            durs.append(dur_s)
    durs.sort()
    p99 = None
    if durs:
        idx = min(len(durs) - 1, max(0, round(0.99 * (len(durs) - 1))))
        p99 = durs[idx]
    return {
        "n": n,
        "ok": ok,
        "shed": shed,
        "errored": errored,
        "error_rate": (errored / n) if n else 0.0,
        "shed_pct": (100.0 * shed / n) if n else 0.0,
        "p99_s": p99,
        "qps": (n / window_s) if window_s > 0 else 0.0,
    }


def default_serve_rules(
    deadline_s: float = 0.05,
    availability_target: float = 0.99,
    fast_window_s: float = 60.0,
    slow_window_s: float = 300.0,
) -> list[SLORule]:
    """The serving-path objectives ROADMAP item 3 gates capacity on."""
    return [
        SLORule(
            "p99-latency", "p99_latency", threshold=deadline_s,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            for_ticks=2,
        ),
        SLORule(
            "shed-rate", "shed_pct", threshold=10.0,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            for_ticks=2,
        ),
        SLORule(
            "error-budget-burn", "burn_rate", threshold=2.0,
            target=availability_target,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
        ),
        SLORule(
            "heartbeat-stale", "heartbeat_staleness", threshold=30.0,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
        ),
    ]


def default_train_rules(
    fast_window_s: float = 60.0, slow_window_s: float = 300.0
) -> list[SLORule]:
    """Training-run objectives: liveness + the runtime TA201 contract."""
    return [
        SLORule(
            "heartbeat-stale", "heartbeat_staleness", threshold=30.0,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
        ),
        SLORule(
            "input-starvation", "starvation_pct", threshold=25.0,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            for_ticks=2,
        ),
        SLORule(
            "recompile", "recompile", threshold=0.0,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
        ),
        SLORule(
            "divergence", "divergence", threshold=0.0,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
        ),
    ]


def default_quality_rules(
    input_threshold: float = 0.25,
    prediction_threshold: float = 0.25,
    shadow_threshold: float = 0.5,
    fast_window_s: float = 60.0,
    slow_window_s: float = 300.0,
) -> list[SLORule]:
    """Model-quality objectives over ``quality_sample`` events (the
    serve-side 1-in-K sampler in telemetry/quality.py). PSI thresholds
    read on the usual industry scale; the shadow threshold is a mean
    |model − OLS| disagreement in (α, β) units."""
    return [
        SLORule(
            "input-drift", "input_drift", threshold=input_threshold,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            for_ticks=2,
        ),
        SLORule(
            "prediction-drift", "prediction_drift",
            threshold=prediction_threshold,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            for_ticks=2,
        ),
        SLORule(
            "shadow-disagreement", "shadow_disagreement",
            threshold=shadow_threshold,
            fast_window_s=fast_window_s, slow_window_s=slow_window_s,
            for_ticks=2,
        ),
    ]


@dataclass
class _AlertState:
    """Debounced per-rule state machine: pending → firing → resolved."""

    rule: SLORule
    firing: bool = False
    breach_streak: int = 0
    clear_streak: int = 0
    fired_ts: float | None = None
    fired_count: int = 0
    value: float | None = None
    detail: dict = field(default_factory=dict)

    def update(self, breached: bool, now: float) -> str | None:
        """Advance one tick; returns "fired"/"resolved" on a transition."""
        if breached:
            self.breach_streak += 1
            self.clear_streak = 0
            if not self.firing and self.breach_streak >= self.rule.for_ticks:
                self.firing = True
                self.fired_ts = now
                self.fired_count += 1
                return "fired"
        else:
            self.clear_streak += 1
            self.breach_streak = 0
            if self.firing and self.clear_streak >= self.rule.clear_ticks:
                self.firing = False
                return "resolved"
        return None


class SLOEngine:
    """Incremental SLO evaluation over the event streams under a root.

    Single-writer by design: :meth:`tick` is called either by the owner
    directly (tests, the bench's per-stage loop) or by the monitor
    thread :meth:`start` spawns — never both at once. Cross-thread
    readers (the ``/slo`` exposition endpoint, the watch console) see
    only the published snapshot, swapped under ``_state_lock`` at the
    end of each tick; no file I/O ever happens under that lock.
    """

    def __init__(
        self,
        root: str | Path,
        rules: list[SLORule] | None = None,
        sink=None,
        grace_s: float = 5.0,
    ):
        self.root = Path(root)
        self.rules = (
            list(rules) if rules is not None else default_serve_rules()
        )
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names: {names}")
        self._sink = sink
        self._grace_s = grace_s
        self._retain_s = max(
            [r.slow_window_s for r in self.rules] or [300.0]
        ) + 60.0
        # Tail cursors + accumulated signal state (single writer: tick).
        self._cursors: dict[Path, int] = {}
        self._requests: deque = deque()  # (ts, status, dur_s)
        self._epochs: deque = deque()  # (ts, wall_s, data_wait_s)
        # (ts, scored, input_psi, pred_psi, shadow_err) quality samples.
        self._quality: deque = deque()
        self._epoch_compiles = 0
        self._diverged = False
        self._divergence_detail: str | None = None
        self._stream_last_ts: dict[Path, float] = {}
        self._stream_finished: dict[Path, bool] = {}
        self._alerts = {r.name: _AlertState(r) for r in self.rules}
        self._events_seen = 0
        self._ticks = 0
        # Published snapshot for cross-thread readers.
        self._state_lock = threading.Lock()
        self._published: dict = {
            "ts": None, "ticks": 0, "rules": {}, "firing": [],
        }
        # Monitor-thread lifecycle (spawned in start, joined in stop).
        self._stop_event = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ ingest

    def _discover(self) -> list[Path]:
        if self.root.is_file():
            return [self.root]
        return sorted(self.root.rglob(EVENTS_FILENAME))

    def _ingest(self) -> None:
        for path in self._discover():
            cursor = self._cursors.get(path, 0)
            events, cursor = read_new_lines(path, cursor)
            self._cursors[path] = cursor
            for ev in events:
                self._fold(path, ev)
            # Single-writer: tick() runs on exactly one thread (the owner
            # before start(), the monitor thread after).
            self._events_seen += len(events)  # mtt: disable=CL502 -- single-writer tick

    def _fold(self, path: Path, ev: dict) -> None:
        ts = ev.get("ts")
        if ts is not None:
            prev = self._stream_last_ts.get(path)
            self._stream_last_ts[path] = ts if prev is None else max(
                prev, ts
            )
        kind = ev.get("kind")
        if kind == "span" and ev.get("name") == "serve.request":
            if ts is not None:
                self._requests.append(
                    (ts, ev.get("status"), ev.get("dur_s"))
                )
        elif kind == "epoch":
            if ts is not None and ev.get("wall_s") is not None:
                self._epochs.append(
                    (ts, float(ev["wall_s"]),
                     float(ev.get("data_wait_s") or 0.0))
                )
            self._epoch_compiles += int(ev.get("compile_events") or 0)  # mtt: disable=CL502 -- single-writer tick
        elif kind == "quality_sample":
            if ts is not None:
                self._quality.append(
                    (
                        ts,
                        bool(ev.get("scored")),
                        float(ev.get("input_psi") or 0.0),
                        float(ev.get("pred_psi") or 0.0),
                        float(ev.get("shadow_err") or 0.0),
                    )
                )
        elif kind == "run_finished":
            self._stream_finished[path] = True
            if ev.get("diverged"):
                self._diverged = True
                self._divergence_detail = "run halted on a non-finite loss"
        elif kind in (
            "serve_finished", "fleet_finished", "fleet_verdict",
            "supervisor_verdict",
        ):
            self._stream_finished[path] = True

    def _trim(self, now: float) -> None:
        cutoff = now - self._retain_s
        while self._requests and self._requests[0][0] < cutoff:
            self._requests.popleft()
        while self._epochs and self._epochs[0][0] < cutoff:
            self._epochs.popleft()
        while self._quality and self._quality[0][0] < cutoff:
            self._quality.popleft()

    # ---------------------------------------------------------- signals

    def _staleness(self, now: float) -> float | None:
        """Seconds since the quietest *live* stream's last sign of life
        (heartbeat sidecar or last flushed event); finished streams are
        excluded — a cleanly ended run must not go stale forever."""
        worst = None
        for path, last_ts in self._stream_last_ts.items():
            if self._stream_finished.get(path):
                continue
            hb = _heartbeat_ts(path.parent / HEARTBEAT_FILENAME)
            last = max(last_ts, hb) if hb is not None else last_ts
            gap = now - last
            worst = gap if worst is None else max(worst, gap)
        return worst

    def _starvation(self, now: float, window_s: float) -> float | None:
        cutoff = now - window_s
        wall = wait = 0.0
        for ts, wall_s, data_wait_s in self._epochs:
            if ts < cutoff:
                continue
            wall += wall_s
            wait += data_wait_s
        if wall <= 0:
            return None
        return 100.0 * wait / wall

    def _evaluate(self, rule: SLORule, now: float) -> tuple[
        float | None, bool, dict
    ]:
        """One rule's (value, breached, detail) at ``now``."""
        if rule.kind == "burn_rate":
            fast = window_stats(self._requests, now, rule.fast_window_s)
            slow = window_stats(self._requests, now, rule.slow_window_s)
            burn_fast = burn_rate(fast["error_rate"], rule.target)
            burn_slow = burn_rate(slow["error_rate"], rule.target)
            value = min(burn_fast, burn_slow)
            breached = (
                fast["n"] > 0
                and burn_fast > rule.threshold
                and burn_slow > rule.threshold
            )
            return value, breached, {
                "burn_fast": burn_fast, "burn_slow": burn_slow,
                "error_rate_fast": fast["error_rate"],
                "requests_fast": fast["n"],
            }
        if rule.kind == "p99_latency":
            stats = window_stats(self._requests, now, rule.fast_window_s)
            value = stats["p99_s"]
            return value, (
                value is not None and value > rule.threshold
            ), {"requests_fast": stats["n"]}
        if rule.kind == "shed_pct":
            stats = window_stats(self._requests, now, rule.fast_window_s)
            value = stats["shed_pct"] if stats["n"] else None
            return value, (
                value is not None and value > rule.threshold
            ), {"requests_fast": stats["n"]}
        if rule.kind == "heartbeat_staleness":
            value = self._staleness(now)
            return value, (
                value is not None and value > rule.threshold
            ), {}
        if rule.kind == "starvation_pct":
            value = self._starvation(now, rule.slow_window_s)
            return value, (
                value is not None and value > rule.threshold
            ), {}
        if rule.kind == "recompile":
            value = float(max(0, self._epoch_compiles - 1))
            return value, value > rule.threshold, {
                "compile_events": self._epoch_compiles
            }
        if rule.kind == "divergence":
            value = 1.0 if self._diverged else 0.0
            return value, value > rule.threshold, {
                "detail": self._divergence_detail
            }
        if rule.kind in QUALITY_RULE_FIELDS:
            # Drift signals are cumulative-sketch scores: the LATEST
            # sample in the window is the current state (older samples
            # were computed from a strictly smaller sketch). Shadow
            # disagreement is an EWMA — same story. Drift kinds only
            # consider scored samples (a reference fingerprint was
            # loaded and the warm-up count was met).
            idx = {
                "input_drift": 2,
                "prediction_drift": 3,
                "shadow_disagreement": 4,
            }[rule.kind]
            cutoff = now - rule.fast_window_s
            value = None
            n = 0
            for ts, scored, *vals in self._quality:
                if ts < cutoff:
                    continue
                if rule.kind != "shadow_disagreement" and not scored:
                    continue
                n += 1
                value = vals[idx - 2]
            return value, (
                value is not None and value > rule.threshold
            ), {"samples_fast": n}
        raise AssertionError(f"unreachable rule kind {rule.kind!r}")

    # -------------------------------------------------------------- tick

    def tick(self, now: float | None = None) -> dict:
        """Ingest new events, evaluate every rule, publish the state.

        The chaos harness can wedge this evaluator (``slo.evaluate`` /
        kind ``wedge``): the tick becomes a no-op and the published
        state goes stale — serving is untouched, which is the point.
        """
        if fire("slo.evaluate") == "wedge":
            return self.state()
        now = time.time() if now is None else now
        self._ingest()
        self._trim(now)
        # Single-writer: one thread ticks; _state_lock only guards the
        # published-snapshot swap.
        self._ticks += 1  # mtt: disable=CL502 -- single-writer tick
        fired: list[str] = []
        resolved: list[str] = []
        rules_out: dict[str, dict] = {}
        for rule in self.rules:
            value, breached, detail = self._evaluate(rule, now)
            st = self._alerts[rule.name]
            st.value = value
            st.detail = detail
            transition = st.update(breached, now)
            if transition == "fired":
                fired.append(rule.name)
                self._emit(
                    "alert_fired", rule, st, now, detail
                )
            elif transition == "resolved":
                resolved.append(rule.name)
                self._emit(
                    "alert_resolved", rule, st, now, detail
                )
            rules_out[rule.name] = {
                "kind": rule.kind,
                "value": value,
                "threshold": rule.threshold,
                "breached": breached,
                "firing": st.firing,
                "fired_ts": st.fired_ts,
                "fired_count": st.fired_count,
                **detail,
            }
        window = window_stats(
            self._requests, now,
            max((r.fast_window_s for r in self.rules), default=60.0),
        )
        state = {
            "ts": now,
            "ticks": self._ticks,
            "events_seen": self._events_seen,
            "streams": len(self._cursors),
            "rules": rules_out,
            "firing": sorted(
                n for n, st in self._alerts.items() if st.firing
            ),
            "just_fired": fired,
            "just_resolved": resolved,
            "requests": window,
        }
        with self._state_lock:
            self._published = state
        return state

    def _emit(
        self, kind: str, rule: SLORule, st: _AlertState, now: float,
        detail: dict,
    ) -> None:
        if self._sink is None:
            return
        payload = {
            "rule": rule.name,
            "slo_kind": rule.kind,
            "value": st.value,
            "threshold": rule.threshold,
            "burn_fast": detail.get("burn_fast"),
            "burn_slow": detail.get("burn_slow"),
            "active_s": (
                (now - st.fired_ts)
                if kind == "alert_resolved" and st.fired_ts is not None
                else None
            ),
        }
        # Infinity is honest math but not valid JSON; clamp at emit.
        for key in ("value", "burn_fast", "burn_slow"):
            v = payload[key]
            if v is not None and math.isinf(v):
                payload[key] = 1e308
        if kind == "alert_fired":
            self._sink.emit("alert_fired", **payload)
        else:
            self._sink.emit("alert_resolved", **payload)

    def emit_snapshot(self, state: dict | None = None) -> None:
        """Record the current SLO state into the stream (periodic from
        the monitor thread; per-stage from the bench)."""
        if self._sink is None:
            return
        state = state or self.state()
        self._sink.emit(
            "slo_snapshot",
            firing=state.get("firing") or [],
            ticks=state.get("ticks"),
            events_seen=state.get("events_seen"),
            p99_s=(state.get("requests") or {}).get("p99_s"),
            shed_pct=(state.get("requests") or {}).get("shed_pct"),
            qps=(state.get("requests") or {}).get("qps"),
        )

    def state(self) -> dict:
        """The last published snapshot (safe from any thread)."""
        with self._state_lock:
            return dict(self._published)

    # --------------------------------------------------------- lifecycle

    def start(
        self, interval_s: float = 2.0, snapshot_every: int = 5
    ) -> None:
        """Spawn the monitor thread: tick every ``interval_s``, record a
        ``slo_snapshot`` event every ``snapshot_every`` ticks."""
        if self._thread is not None:
            return
        self._stop_event.clear()

        def _loop() -> None:
            ticks = 0
            while not self._stop_event.wait(interval_s):
                try:
                    state = self.tick()
                    ticks += 1
                    if snapshot_every and ticks % snapshot_every == 0:
                        self.emit_snapshot(state)
                except Exception:  # noqa: BLE001 -- a transient read
                    # error (stream mid-rotation) must not kill the
                    # monitor; the next tick retries from the cursor.
                    pass

        self._thread = threading.Thread(
            target=_loop, name="slo-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Join the monitor thread (bounded) and run one final tick so
        the published state reflects the stream's end."""
        thread = self._thread
        if thread is not None:
            self._stop_event.set()
            thread.join(timeout=10.0)
            self._thread = None
        try:
            self.tick()
        except Exception:  # noqa: BLE001 -- best-effort final fold
            pass

    close = stop

    def __enter__(self) -> "SLOEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def _heartbeat_ts(path: Path) -> float | None:
    try:
        import json

        doc = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    candidates = [
        doc.get(k) for k in ("ts", "last_beat_ts")
        if isinstance(doc.get(k), (int, float))
    ]
    return max(candidates) if candidates else None
