"""Fleet aggregation: merge per-host/per-process event streams into one
view, and reconstruct how a multi-host run actually ended.

A multi-host run produces N disjoint ``events.jsonl`` streams (one per
process), plus the flight recorder's ``heartbeat.json`` and — when a
process died with warning — ``crashdump.json`` next to each. This module
folds all of them into a single fleet report:

- per-host/per-process **epoch-time skew** over the epochs every stream
  shares (the first straggler signal on real hardware);
- **straggler identification** — the process whose epochs run longest,
  with its slowdown vs the fleet median;
- **collective wait attribution** — in a data-parallel psum world the
  fast processes block in the collective for the slowest, so each
  process's wait is the sum over shared epochs of (fleet max wall − own
  wall). This is where "the TPU is slow" decomposes into "host 3 is
  slow and everyone else is waiting on it";
- **heartbeat gaps** — how far each process's last sign of life lags the
  fleet's, which is the only evidence a SIGKILLed process leaves;
- **exit-status reconstruction** — per process: ``finished`` /
  ``killed`` (crashdump from a signal) / ``hung`` (crashdump from the
  hang watchdog) / ``running`` (recent activity) / ``dead`` (started,
  never finished, no recent activity — the SIGKILL case) /
  ``superseded`` (an older fleet generation the supervisor already
  relaunched past — history, not a live failure);
- **generation stitching** — a fleet-supervised incident leaves one
  stream per rank PER GENERATION (``g<gen>/p<rank>``) plus the
  supervisor's stream; the report reconstructs the attempt chain across
  relaunches and elastic resizes (``fleet_resized``), judges liveness
  against the LATEST generation's world size only, and rides the ONE
  trace id the supervisor threads through every generation.

Stdlib-only by contract, like :mod:`report`: the ``aggregate`` and
``postmortem`` CLI subcommands run on operator machines where importing a
backend can hang on a wedged relay lease (docs/OPERATIONS.md).
"""

from __future__ import annotations

import json
import time
from pathlib import Path

from masters_thesis_tpu.telemetry.events import read_events
from masters_thesis_tpu.telemetry.flightrec import (
    CRASHDUMP_FILENAME,
    HEARTBEAT_FILENAME,
)
from masters_thesis_tpu.telemetry.report import EVENTS_FILENAME
from masters_thesis_tpu.telemetry.schedule import audit_schedules

# A process whose last activity is within this window of "now" is treated
# as still running rather than dead (live-run inspection vs postmortem).
DEFAULT_GRACE_S = 30.0
# A finished straggler is flagged when its shared-epoch wall exceeds the
# fleet median by more than this fraction.
STRAGGLER_SLOWDOWN = 0.10


def discover_streams(root: str | Path) -> list[Path]:
    """Every ``events.jsonl`` under ``root`` (or ``root`` itself if it is
    one), sorted for deterministic process ordering."""
    root = Path(root)
    if root.is_file():
        return [root]
    if (root / EVENTS_FILENAME).is_file():
        # A single-run dir may still have nested streams (bench roots hold
        # point_*/ subruns); take the lot.
        return sorted(root.rglob(EVENTS_FILENAME))
    return sorted(root.rglob(EVENTS_FILENAME))


def _read_json(path: Path) -> dict | None:
    try:
        obj = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None
    return obj if isinstance(obj, dict) else None


def digest_stream(path: Path, root: Path) -> dict:
    """Fold one process's stream (+ sidecar heartbeat/crashdump) into the
    per-process digest the fleet report is built from."""
    return digest_events(read_events(path), path, root)


def digest_events(events: list[dict], path: Path, root: Path) -> dict:
    """The digest fold over already-loaded events.

    Split out of :func:`digest_stream` so incremental consumers (the
    ``watch`` console's tail-cursor accumulation, telemetry/watch.py)
    share THIS reconstruction rather than re-reading every stream from
    byte zero on each refresh; ``path`` still names the stream's
    location because the heartbeat/crashdump sidecars live next to it.
    """
    by_kind: dict[str, list[dict]] = {}
    proc = nproc = None
    host = pid = None
    generation = None
    for ev in events:
        by_kind.setdefault(ev.get("kind", "?"), []).append(ev)
        if proc is None and ev.get("proc") is not None:
            proc = ev["proc"]
        if ev.get("nproc") is not None:
            nproc = max(nproc or 0, ev["nproc"])
        if ev.get("generation") is not None:
            # Fleet-supervised ranks tag every envelope with their
            # generation; a stream that spans relaunches keeps the max.
            generation = max(generation or 0, int(ev["generation"]))
        host = host or ev.get("host")
        pid = pid or ev.get("pid")
    starts = by_kind.get("run_started", [])
    started = bool(starts)
    # Supervisor streams (fleet or single-run) finish at their VERDICT,
    # not at run_finished — without this the postmortem flags the
    # supervisor's own stream as a dead worker.
    role = (
        "supervisor"
        if ("fleet_started" in by_kind or "supervisor_started" in by_kind)
        else "worker"
    )
    fleet_verdict = (by_kind.get("fleet_verdict") or [None])[-1]
    sup_verdict = (by_kind.get("supervisor_verdict") or [None])[-1]
    verdict = fleet_verdict or sup_verdict
    fleet = None
    if "fleet_started" in by_kind:
        gen_starts = by_kind.get("fleet_generation_started", [])
        fleet = {
            "generations": len(gen_starts),
            "last_nprocs": (
                gen_starts[-1].get("nprocs") if gen_starts else None
            ),
            "resizes": [
                {k: ev.get(k) for k in
                 ("gen", "from_nprocs", "to_nprocs", "reason",
                  "fingerprint")}
                for ev in by_kind.get("fleet_resized", [])
            ],
            "verdict": None if fleet_verdict is None else {
                k: fleet_verdict.get(k) for k in
                ("ok", "verdict", "generations", "final_nprocs",
                 "resized", "trace_id")
            },
        }
    # Attempt linking: a supervised run APPENDS each retry to the same
    # stream, so one events.jsonl can hold several attempts — delimited by
    # run_started (trainer streams) or the envelope's attempt tag. The
    # digest folds them into ONE logical run: `finished` reflects the LAST
    # attempt, and a crashdump from a superseded attempt doesn't fail a
    # stream whose final attempt completed.
    attempts = max(
        len(starts),
        len(by_kind.get("attempt_started", [])),
        max((int(ev.get("attempt") or 1) for ev in events), default=0),
    )
    resumed_from = next(
        (s["resumed_from"] for s in reversed(starts) if s.get("resumed_from")),
        None,
    )
    finished = (by_kind.get("run_finished") or [None])[-1]
    last_start = starts[-1] if starts else {}
    # The hot program's static cost model (telemetry/costs.py payload) —
    # the training program when present, else the last profile emitted.
    profiles = by_kind.get("cost_profile", [])
    cost = next(
        (e for e in profiles if str(e.get("program", "")).startswith("train")),
        profiles[-1] if profiles else None,
    )
    epochs = by_kind.get("epoch", [])
    epoch_walls: dict[int, float] = {}
    for e in epochs:
        if e.get("epoch") is not None and e.get("wall_s") is not None:
            epoch_walls[int(e["epoch"])] = float(e["wall_s"])
    # Trace spans: the trace id the stream rides (one id spans the whole
    # supervised fleet when propagation worked) and per-(span name, epoch)
    # walls, so the fleet report can attribute collective wait to NAMED
    # phases instead of only the epoch total.
    spans = by_kind.get("span", [])
    trace_id = next(
        (s["trace_id"] for s in spans if s.get("trace_id")),
        next(
            (s["trace_id"] for s in starts if s.get("trace_id")),
            next(
                (ev["trace_id"]
                 for ev in by_kind.get("fleet_started", [])
                 if ev.get("trace_id")),
                None,
            ),
        ),
    )
    span_walls: dict[str, dict[int, float]] = {}
    for s in spans:
        epoch = (s.get("attrs") or {}).get("epoch")
        if epoch is None or s.get("dur_s") is None or not s.get("name"):
            continue
        span_walls.setdefault(str(s["name"]), {})[int(epoch)] = float(
            s["dur_s"]
        )
    crash_events = by_kind.get("crashdump", [])
    crashdump = _read_json(path.parent / CRASHDUMP_FILENAME)
    if crashdump is None and crash_events:
        # The dump file may have been reaped; the flushed event survives.
        crashdump = {"reason": crash_events[-1].get("reason"),
                     "path": crash_events[-1].get("path")}
    heartbeat = _read_json(path.parent / HEARTBEAT_FILENAME)
    # Collective-schedule snapshot: prefer whichever record saw the most
    # entries — a crashdump taken after the last heartbeat is fresher,
    # and the flushed event stream survives sidecar reaping.
    schedule = None
    for doc in (heartbeat, crashdump):
        snap = (doc or {}).get("collective_schedule")
        if snap and snap.get("n", 0) > (schedule or {}).get("n", 0):
            schedule = snap
    for ev in by_kind.get("collective_schedule", []):
        snap = {
            "n": ev.get("n"),
            "chain": ev.get("chain"),
            "tail": ev.get("tail") or [],
        }
        if snap["n"] and snap["n"] > (schedule or {}).get("n", 0):
            schedule = snap
    try:
        rel = str(path.parent.relative_to(root))
    except ValueError:
        rel = str(path.parent)
    label = f"p{proc}" if proc is not None else (rel or path.parent.name)
    return {
        "stream": rel or ".",
        "label": label,
        "proc": proc,
        "nproc": nproc,
        "host": host,
        "pid": pid,
        "run": events[0].get("run") if events else None,
        "events": len(events),
        "started": started,
        "attempts": attempts,
        "resumed_from": resumed_from,
        "generation": generation,
        "role": role,
        "fleet": fleet,
        "verdict": None if verdict is None else {
            "ok": bool(verdict.get("ok")),
            "verdict": verdict.get("verdict"),
        },
        "finished": finished is not None or verdict is not None,
        "diverged": bool(finished and finished.get("diverged")),
        "steps_per_sec": finished.get("steps_per_sec") if finished else None,
        "platform": last_start.get("platform"),
        "n_devices": last_start.get("n_devices"),
        "cost_profile": None if cost is None else {
            k: cost.get(k)
            for k in ("program", "available", "flops_per_step",
                      "bytes_per_step", "peak_bytes")
        },
        "epochs": len(epoch_walls),
        "last_epoch": max(epoch_walls) if epoch_walls else None,
        "epoch_walls": epoch_walls,
        "trace_id": trace_id,
        "span_walls": span_walls,
        "first_ts": events[0].get("ts") if events else None,
        "last_ts": events[-1].get("ts") if events else None,
        "crashdump": None if crashdump is None else {
            "reason": crashdump.get("reason"),
            "phase": crashdump.get("phase"),
            "epoch": crashdump.get("epoch"),
            "path": str(path.parent / CRASHDUMP_FILENAME),
        },
        "heartbeat": None if heartbeat is None else {
            "ts": heartbeat.get("ts"),
            "phase": heartbeat.get("phase"),
            "epoch": heartbeat.get("epoch"),
            "beats": heartbeat.get("beats"),
        },
        "schedule": schedule,
    }


def _last_activity(d: dict) -> float | None:
    candidates = [d.get("last_ts")]
    if d.get("heartbeat"):
        candidates.append(d["heartbeat"].get("ts"))
    candidates = [c for c in candidates if c is not None]
    return max(candidates) if candidates else None


def _status(d: dict, now: float, grace_s: float) -> str:
    if d["finished"]:
        return "finished"
    last = _last_activity(d)
    crash = d.get("crashdump")
    if (
        crash
        and (d.get("attempts") or 1) > 1
        and last is not None
        and (now - last) <= grace_s
    ):
        # The crashdump belongs to a superseded attempt; the retry is
        # still making progress.
        return "running"
    if crash and crash.get("reason"):
        reason = str(crash["reason"])
        if reason.startswith("signal"):
            return "killed"
        if reason.startswith("hang"):
            return "hung"
        return "crashed"
    last = _last_activity(d)
    if last is not None and (now - last) <= grace_s:
        return "running"
    return "dead"


def aggregate_streams(
    digests: list[dict],
    now: float | None = None,
    grace_s: float = DEFAULT_GRACE_S,
) -> dict:
    """The fleet report over per-process digests (see module docstring)."""
    now = time.time() if now is None else now
    for d in digests:
        d["status"] = _status(d, now, grace_s)

    # --- generation stitching (fleet supervisor relaunch / resize) ---
    # A fleet-supervised incident leaves one stream per rank PER
    # GENERATION under the same root, plus the supervisor's own stream.
    # Everything below reconstructs ONE logical run from that pile: the
    # LATEST generation is the fleet's present; older generations are
    # forensic history, not live failures.
    sups = [d for d in digests if d.get("role") == "supervisor"]
    workers = [d for d in digests if d.get("role") != "supervisor"]
    gens = sorted(
        {d["generation"] for d in workers if d.get("generation") is not None}
    )
    fleet_gen = gens[-1] if gens else None
    if len(gens) > 1:
        # Two generations both contain a "p0": disambiguate every worker
        # label with its generation so rows and attribution keys stay
        # unique across relaunches.
        for d in workers:
            g = d["generation"] if d.get("generation") is not None else 0
            d["label"] = f"g{g}/{d['label']}"
    for d in workers:
        if (
            fleet_gen is not None
            and d.get("generation") is not None
            and d["generation"] < fleet_gen
            and d["status"] != "finished"
        ):
            # The fleet was relaunched past this stream: an unfinished
            # older-generation rank is SUPERSEDED evidence — the relaunch
            # already healed it, so it must not read as dead forever.
            d["status"] = "superseded"
    current = [
        d for d in workers
        if fleet_gen is None or d.get("generation") in (None, fleet_gen)
    ]
    fleet_info = next((d["fleet"] for d in sups if d.get("fleet")), None)
    resizes = fleet_info["resizes"] if fleet_info else []
    fleet_verdict = fleet_info["verdict"] if fleet_info else None
    # Epoch statistics (skew, wait, straggler) compare only the CURRENT
    # generation — a superseded rank's partial epochs would poison the
    # shared-epoch intersection and the wait attribution.
    stat_digests = current if fleet_gen is not None else digests

    # Expected world size is the LATEST generation's: after an elastic
    # resize the retired rank is gone by design, not missing.
    expected_src = [d["nproc"] for d in current if d.get("nproc")]
    if fleet_info and fleet_info.get("last_nprocs"):
        expected_src.append(fleet_info["last_nprocs"])
    expected = max(expected_src or [len(current) or len(digests)])
    present = {d["proc"] for d in current if d.get("proc") is not None}
    missing = (
        sorted(set(range(expected)) - present)
        if present and expected > len(current)
        else []
    )

    # Skew + wait attribution over the epochs EVERY stream shares — a
    # process that died at epoch 3 must not make the survivors' epochs
    # 4..N look like infinite skew.
    walls = [d["epoch_walls"] for d in stat_digests if d["epoch_walls"]]
    shared = sorted(set.intersection(*map(set, walls))) if len(walls) > 1 else []
    per_epoch_skew = {
        e: max(w[e] for w in walls) - min(w[e] for w in walls)
        for e in shared
    }
    slowest_count: dict[str, int] = {}
    for e in shared:
        slowest = max(
            (d for d in stat_digests if e in d["epoch_walls"]),
            key=lambda d: d["epoch_walls"][e],
        )
        slowest_count[slowest["label"]] = (
            slowest_count.get(slowest["label"], 0) + 1
        )
    collective_wait = {
        d["label"]: sum(
            max(w[e] for w in walls) - d["epoch_walls"][e] for e in shared
        )
        for d in stat_digests
        if d["epoch_walls"]
    }

    # Named-span wait attribution: the same (fleet max − own) fold, but per
    # span name over the epochs every emitting stream shares — so "p1 waits
    # 2s" decomposes into WHICH phase the fleet serializes on.
    span_names = sorted(
        {n for d in stat_digests for n in (d.get("span_walls") or {})}
    )
    collective_wait_by_span: dict[str, dict[str, float]] = {}
    for name in span_names:
        swalls = [
            d["span_walls"][name]
            for d in stat_digests
            if (d.get("span_walls") or {}).get(name)
        ]
        if len(swalls) < 2:
            continue
        shared_e = set.intersection(*map(set, swalls))
        if not shared_e:
            continue
        collective_wait_by_span[name] = {
            d["label"]: sum(
                max(w[e] for w in swalls) - d["span_walls"][name][e]
                for e in shared_e
            )
            for d in stat_digests
            if (d.get("span_walls") or {}).get(name)
        }
    trace_ids = sorted(
        {d["trace_id"] for d in digests if d.get("trace_id")}
    )

    straggler = None
    if shared:
        totals = {
            d["label"]: sum(d["epoch_walls"][e] for e in shared)
            for d in stat_digests
            if d["epoch_walls"]
        }
        worst_label = max(totals, key=totals.get)
        ordered = sorted(totals.values())
        median = ordered[len(ordered) // 2]
        slowdown = (totals[worst_label] / median - 1.0) if median > 0 else 0.0
        worst = next(d for d in stat_digests if d["label"] == worst_label)
        straggler = {
            "label": worst_label,
            "proc": worst["proc"],
            "host": worst["host"],
            "shared_epoch_wall_s": totals[worst_label],
            "slowdown_pct": 100.0 * slowdown,
            "slowest_epochs": slowest_count.get(worst_label, 0),
            "significant": slowdown > STRAGGLER_SLOWDOWN,
        }

    per_host_wall: dict[str, list[float]] = {}
    for d in stat_digests:
        if d["epoch_walls"] and d.get("host"):
            per_host_wall.setdefault(d["host"], []).extend(
                d["epoch_walls"][e] for e in (shared or d["epoch_walls"])
            )

    fleet_last = max(
        (t for t in (_last_activity(d) for d in digests) if t is not None),
        default=None,
    )
    heartbeat_gaps = {}
    for d in digests:
        last = _last_activity(d)
        if last is not None and fleet_last is not None:
            heartbeat_gaps[d["label"]] = fleet_last - last

    failures: list[str] = []
    for d in sups:
        v = d.get("verdict")
        if v is not None and not v["ok"]:
            detail = ""
            if fleet_verdict is not None:
                detail = (
                    f" after {fleet_verdict.get('generations')} "
                    f"generation(s), final "
                    f"{fleet_verdict.get('final_nprocs')} rank(s)"
                )
            failures.append(
                f"{d['label']} supervisor verdict "
                f"{v['verdict'].upper()}{detail}"
            )
    for d in digests:
        if d.get("role") == "supervisor" or d["status"] == "superseded":
            # Supervisors fail via their verdict (above); superseded
            # generations already paid their failure as a relaunch.
            continue
        if d["status"] in ("killed", "hung", "crashed", "dead"):
            crash = d.get("crashdump") or {}
            where = (
                f"epoch {d['last_epoch']}" if d["last_epoch"] is not None
                else f"phase {crash.get('phase') or '?'}"
            )
            detail = crash.get("reason") or (
                "no crashdump; last activity "
                f"{heartbeat_gaps.get(d['label'], 0.0):.1f}s behind the fleet"
            )
            failures.append(
                f"{d['label']} (host {d['host']}, pid {d['pid']}) "
                f"{d['status'].upper()} at {where} — {detail}"
            )
        elif d["diverged"]:
            failures.append(
                f"{d['label']} diverged (halted on a non-finite loss)"
            )
    for proc in missing:
        failures.append(
            f"p{proc} left no event stream ({expected} processes expected, "
            f"{len(digests)} streams found)"
        )
    if straggler and straggler["significant"]:
        failures_note = (
            f"{straggler['label']} straggles: "
            f"{straggler['slowdown_pct']:.0f}% over the fleet median, "
            f"slowest in {straggler['slowest_epochs']}/{len(shared)} epochs"
        )
        # A slow-but-finished straggler is a warning, not a failure.
        if any(d["label"] == straggler["label"]
               and d["status"] != "finished" for d in digests):
            failures.append(failures_note)

    # --- collective-schedule audit (runtime half of analysis Pass 4) ---
    # Bitwise cross-check of each CURRENT-generation rank's schedule
    # hash chain: a wedged fleet whose ranks issued different collective
    # schedules gets a diagnosis (divergent rank, step, both schedules)
    # instead of a heartbeat timeout.
    schedule_audit = audit_schedules(
        {
            d["label"]: d.get("schedule")
            for d in (current if fleet_gen is not None else workers)
        }
    )
    if not schedule_audit["ok"]:
        chains = ", ".join(
            f"{label} {v['chain'][:16]}…({v['n']} entries)"
            for label, v in sorted(schedule_audit["ranks"].items())
        )
        scheds = "; ".join(
            f"{label}: [{', '.join(entries[-4:])}]"
            for label, entries in sorted(
                (schedule_audit.get("schedules") or {}).items()
            )
        )
        failures.append(
            f"collective schedule DIVERGED — {schedule_audit['detail']} "
            f"| chains: {chains}" + (f" | tails: {scheds}" if scheds else "")
        )

    # Fleet utilization: the hot program's static cost × the fleet's step
    # rate, with the comms side fed by the wait attribution above — the
    # mean fraction of shared-epoch wall each process spent blocked in the
    # collective. This is the ONLY place comms-bound can be diagnosed (a
    # single stream cannot see the fleet max), so summarize splits only
    # compute/memory and the aggregate view owns the third regime.
    fleet_util = None
    cost_digest = next((d for d in digests if d.get("cost_profile")), None)
    if cost_digest is not None:
        from masters_thesis_tpu.telemetry.costs import utilization

        cost = cost_digest["cost_profile"]
        rates = [d["steps_per_sec"] for d in digests
                 if d.get("steps_per_sec")]
        mean_sps = sum(rates) / len(rates) if rates else None
        comms_frac = None
        if shared and len(collective_wait) > 1:
            fleet_wall = sum(max(w[e] for w in walls) for e in shared)
            if fleet_wall > 0:
                comms_frac = sum(collective_wait.values()) / (
                    fleet_wall * len(collective_wait)
                )
        fleet_util = {
            "program": cost.get("program"),
            "available": bool(cost.get("available")),
            "flops_per_step": cost.get("flops_per_step"),
            "bytes_per_step": cost.get("bytes_per_step"),
            "processes_profiled": sum(
                1 for d in digests if d.get("cost_profile")
            ),
        }
        fleet_util.update(
            utilization(
                cost.get("flops_per_step"),
                cost.get("bytes_per_step"),
                mean_sps,
                cost_digest.get("platform"),
                cost_digest.get("n_devices"),
                comms_frac,
            )
        )

    return {
        "processes": digests,
        "expected_processes": expected,
        "finished_processes": sum(
            d["status"] == "finished"
            for d in digests
            if d.get("role") != "supervisor"
        ),
        "missing_processes": missing,
        "fleet_generation": fleet_gen,
        "generations": (
            fleet_info["generations"]
            if fleet_info and fleet_info.get("generations")
            else (fleet_gen + 1 if fleet_gen is not None else None)
        ),
        "resizes": resizes,
        "fleet_verdict": fleet_verdict,
        "epoch_skew": {
            "epochs_compared": len(shared),
            "mean_s": (
                sum(per_epoch_skew.values()) / len(per_epoch_skew)
                if per_epoch_skew
                else None
            ),
            "max_s": max(per_epoch_skew.values()) if per_epoch_skew else None,
            "max_epoch": (
                max(per_epoch_skew, key=per_epoch_skew.get)
                if per_epoch_skew
                else None
            ),
        },
        "per_host_mean_epoch_wall_s": {
            h: sum(v) / len(v) for h, v in sorted(per_host_wall.items())
        },
        "collective_wait_s": collective_wait,
        "collective_wait_by_span_s": collective_wait_by_span,
        "trace_ids": trace_ids,
        "utilization": fleet_util,
        "straggler": straggler,
        "heartbeat_gaps_s": heartbeat_gaps,
        "collective_schedule": schedule_audit,
        "failures": failures,
        "healthy": not failures,
    }


def aggregate_path(
    root: str | Path,
    now: float | None = None,
    grace_s: float = DEFAULT_GRACE_S,
) -> dict:
    root = Path(root)
    streams = discover_streams(root)
    if not streams:
        raise FileNotFoundError(f"no {EVENTS_FILENAME} under {root}")
    report = aggregate_streams(
        [digest_stream(p, root) for p in streams], now=now, grace_s=grace_s
    )
    report["root"] = str(root)
    return report


def postmortem_path(
    root: str | Path,
    now: float | None = None,
    grace_s: float = DEFAULT_GRACE_S,
) -> dict:
    """The fleet report plus the one-line verdict an operator (or a sweep
    runner's failed-cell row) wants first."""
    report = aggregate_path(root, now=now, grace_s=grace_s)
    report["headline"] = _headline(report)
    report["exit_code"] = 0 if report["healthy"] else 2
    return report


def _headline(report: dict) -> str:
    n = len(report["processes"])
    if report["healthy"]:
        extra = ""
        gens = report.get("generations")
        if gens and gens > 1:
            extra = f" (fleet healed across {gens} generations" + (
                f", {len(report['resizes'])} resize(s))"
                if report.get("resizes") else ")"
            )
            return (
                f"latest generation finished clean; no live failures"
                + extra
            )
        return (
            f"all {n} process(es) finished; no failures detected" + extra
        )
    return report["failures"][0] + (
        f" [{len(report['failures'])} finding(s); "
        f"{report['finished_processes']}/{report['expected_processes']} "
        "finished]"
    )


# ------------------------------------------------------------- rendering


def _fmt(value, spec: str = ".3g") -> str:
    return "n/a" if value is None else format(value, spec)


def render_fleet_text(report: dict, postmortem: bool = False) -> str:
    lines = []
    if postmortem:
        lines.append(f"postmortem     : {report['headline']}")
    lines += [
        f"fleet          : {len(report['processes'])} stream(s), "
        f"{report['finished_processes']}/{report['expected_processes']} "
        "finished",
    ]
    if report.get("fleet_generation") is not None:
        gen_line = f"generations    : {report.get('generations')}"
        for r in report.get("resizes") or []:
            gen_line += (
                f" | resized {r.get('from_nprocs')}->{r.get('to_nprocs')}"
                f" @ g{r.get('gen')} ({r.get('reason')})"
            )
        lines.append(gen_line)
    if report.get("fleet_verdict"):
        v = report["fleet_verdict"]
        lines.append(
            f"fleet verdict  : {'ok' if v.get('ok') else 'FAILED'} "
            f"({v.get('verdict')}, final {v.get('final_nprocs')} rank(s), "
            f"trace {v.get('trace_id')})"
        )
    for d in report["processes"]:
        hb = report["heartbeat_gaps_s"].get(d["label"])
        lines.append(
            f"  {d['label']:<8s} {d['status']:<9s} host={d['host']} "
            f"pid={d['pid']} epochs={d['epochs']} "
            f"last_epoch={_fmt(d['last_epoch'], 'd') if d['last_epoch'] is not None else 'n/a'} "
            f"sps={_fmt(d['steps_per_sec'], '.2f')} "
            f"gap={_fmt(hb, '.1f')}s"
            + (
                f" attempts={d['attempts']}"
                + (" (resumed)" if d.get("resumed_from") else "")
                if (d.get("attempts") or 1) > 1
                else ""
            )
        )
    skew = report["epoch_skew"]
    lines.append(
        f"epoch skew     : mean {_fmt(skew['mean_s'], '.4f')}s | "
        f"max {_fmt(skew['max_s'], '.4f')}s"
        + (
            f" @ epoch {skew['max_epoch']}"
            if skew["max_epoch"] is not None
            else ""
        )
        + f" ({skew['epochs_compared']} shared epochs)"
    )
    for host, wall in report["per_host_mean_epoch_wall_s"].items():
        lines.append(f"  host {host:<12s} mean epoch wall {wall:.4f}s")
    if report["collective_wait_s"]:
        waits = ", ".join(
            f"{label} {wait:.3f}s"
            for label, wait in sorted(report["collective_wait_s"].items())
        )
        lines.append(f"collective wait: {waits}")
    for name, waits in sorted(
        (report.get("collective_wait_by_span_s") or {}).items()
    ):
        per = ", ".join(
            f"{label} {wait:.3f}s" for label, wait in sorted(waits.items())
        )
        lines.append(f"  span {name:<13s} {per}")
    if report.get("trace_ids"):
        ids = report["trace_ids"]
        lines.append(
            f"trace          : {ids[0]}"
            + (f" (+{len(ids) - 1} more — propagation split the fleet!)"
               if len(ids) > 1 else " (one trace across the fleet)")
        )
    util = report.get("utilization")
    if util is not None:
        if util.get("available"):
            frac = util.get("comms_wait_frac")
            lines.append(
                f"utilization    : {util.get('program')} | "
                f"AI {_fmt(util.get('arithmetic_intensity'), '.3g')} | "
                f"{_fmt(util.get('flops_utilization_pct'), '.4g')}% of peak "
                f"FLOP/s | {util.get('regime') or 'n/a'}"
                + (
                    f" (comms wait {100.0 * frac:.1f}% of fleet wall)"
                    if frac is not None
                    else ""
                )
            )
        else:
            lines.append(
                "utilization    : n/a (backend reported no cost model)"
            )
    sched = report.get("collective_schedule")
    if sched is not None and sched.get("verdict") != "insufficient":
        per_rank = ", ".join(
            f"{label} {v['chain'][:12]}…({v['n']})"
            for label, v in sorted((sched.get("ranks") or {}).items())
        )
        lines.append(
            f"collectives    : {sched['verdict']}"
            + (f" | {per_rank}" if per_rank else "")
        )
        if sched.get("verdict") in ("diverged", "lagging"):
            lines.append(f"  {sched.get('detail')}")
        for label, entries in sorted(
            (sched.get("schedules") or {}).items()
        ):
            tail = ", ".join(entries[-4:]) if entries else "<empty>"
            lines.append(f"  {label} schedule tail: {tail}")
    s = report["straggler"]
    if s is not None:
        lines.append(
            f"straggler      : {s['label']} (host {s['host']}) "
            f"+{s['slowdown_pct']:.1f}% vs fleet median, slowest in "
            f"{s['slowest_epochs']} epoch(s)"
            + ("" if s["significant"] else " [not significant]")
        )
    if report["failures"]:
        lines.append("FAILURES:")
        lines.extend(f"  - {f}" for f in report["failures"])
    else:
        lines.append("fleet health   : ok")
    return "\n".join(lines)
