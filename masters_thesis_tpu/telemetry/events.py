"""Structured JSONL event sink — one append-only stream per run.

Each event is one JSON object per line with a fixed envelope
(``ts``/``kind``/``run``/``seq``/``host``/``pid``/``proc``/``nproc``/
``attempt``, plus ``generation`` inside a supervised fleet) and a
flat, kind-specific payload (schema: docs/telemetry.md). The file is flushed
after every line: a SIGKILL mid-run (the grid runner's budget cap, a relay
wedge watchdog) loses at most the event being written, and a resumed run
appends to the same stream rather than clobbering it.

Stdlib-only — the summarize CLI reads these files on machines where
importing a backend is unsafe.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from pathlib import Path

# Envelope keys; payload keys must not collide (enforced at emit time).
RESERVED_KEYS = (
    "ts", "kind", "run", "seq", "host", "pid", "proc", "nproc", "attempt",
    "generation",
)

#: Fleet generation counter (``MTT_GENERATION``), exported by the fleet
#: supervisor for each launch: generation 0 is the first whole-fleet
#: launch, each all-rank relaunch (same or resized world) increments it.
GENERATION_ENV = "MTT_GENERATION"


def current_generation() -> int | None:
    """Fleet generation from the env; ``None`` outside a supervised
    fleet (single-process runs never carry the key)."""
    raw = os.environ.get(GENERATION_ENV)
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def current_attempt() -> int:
    """Supervisor attempt number (``MTT_ATTEMPT``); 1 when unsupervised.

    The resilience supervisor exports the env for each child launch so
    every event a resumed run appends to the shared stream is tagged with
    which attempt produced it — that is what lets summarize/postmortem
    link attempts into one logical run.
    """
    try:
        return int(os.environ.get("MTT_ATTEMPT", "1") or 1)
    except ValueError:
        return 1


class EventSink:
    """Thread-safe append-only JSONL writer with per-line flush."""

    def __init__(
        self,
        path: str | Path,
        run_id: str,
        proc: int | None = None,
        nproc: int | None = None,
        attempt: int | None = None,
        generation: int | None = None,
    ):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.run_id = run_id
        self.proc = proc
        self.nproc = nproc
        self.attempt = current_attempt() if attempt is None else attempt
        self.generation = (
            current_generation() if generation is None else generation
        )
        self._host = socket.gethostname()
        self._pid = os.getpid()
        self._seq = 0
        self._lock = threading.Lock()
        self._file = None

    def emit(self, kind: str, **payload) -> dict:
        clashes = [k for k in payload if k in RESERVED_KEYS]
        if clashes:
            raise ValueError(f"payload keys clash with envelope: {clashes}")
        with self._lock:
            return self._emit_locked(kind, payload)  # mtt: disable=CL503 -- the serialized append IS the sink's contract; the lock exists to order writers

    def try_emit(
        self, kind: str, timeout: float = 0.25, **payload
    ) -> dict | None:
        """Bounded-acquire emit for signal-handler paths.

        A handler that interrupted a frame already holding the sink lock
        must give up after ``timeout`` rather than self-deadlock the
        process (CPython runs handlers on the main thread). Returns None
        when the event was dropped.
        """
        clashes = [k for k in payload if k in RESERVED_KEYS]
        if clashes:
            raise ValueError(f"payload keys clash with envelope: {clashes}")
        if not self._lock.acquire(timeout=timeout):
            return None
        try:
            return self._emit_locked(kind, payload)  # mtt: disable=CL503 -- bounded handler-path append; same serialized-writer contract as emit()
        finally:
            self._lock.release()

    def _emit_locked(self, kind: str, payload: dict) -> dict:
        event = {
            "ts": time.time(),
            "kind": kind,
            "run": self.run_id,
            "seq": self._seq,
            "host": self._host,
            "pid": self._pid,
            "proc": self.proc,
            "nproc": self.nproc,
            "attempt": self.attempt,
        }
        # Only fleet-supervised streams carry a generation: keeping the
        # key absent elsewhere leaves single-process streams byte-stable.
        if self.generation is not None:
            event["generation"] = self.generation
        event.update(payload)
        self._seq += 1  # mtt: disable=CL502 -- _emit_locked runs only with _lock held (emit/try_emit are the sole callers)
        if self._file is None:
            self._file = open(self.path, "a", encoding="utf-8")
        self._file.write(json.dumps(event, default=_jsonable) + "\n")
        self._file.flush()
        return event

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def _jsonable(obj):
    """Last-resort coercion: numpy scalars, Paths, anything with float()."""
    if isinstance(obj, Path):
        return str(obj)
    for cast in (float, int):
        try:
            return cast(obj)
        except (TypeError, ValueError):
            continue
    return str(obj)


def read_events(path: str | Path) -> list[dict]:
    """Load a JSONL event stream; tolerates a torn final line (SIGKILL)."""
    events: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail from a killed writer
    return events


def read_new_lines(
    path: str | Path, cursor: int = 0
) -> tuple[list[dict], int]:
    """Incremental tail read: the events appended since ``cursor``.

    Returns ``(events, new_cursor)`` where ``new_cursor`` is the byte
    offset just past the last newline-terminated line. A torn final line
    (a writer killed mid-append, or simply caught mid-write) is NOT
    consumed: the cursor stays in front of it so the next call re-reads
    the line once its newline lands — unlike :func:`read_events`, which
    drops the torn tail, the incremental reader must not lose the event
    a live writer is still flushing. A terminated-but-unparseable line
    is skipped and consumed (it will never become valid). A file shorter
    than the cursor (stream replaced or truncated) resets to the top.
    A missing file returns ``([], cursor)`` unchanged.
    """
    cursor = max(0, int(cursor))
    try:
        f = open(path, "rb")
    except OSError:
        return [], cursor
    with f:
        size = f.seek(0, os.SEEK_END)
        if cursor > size:
            cursor = 0  # the stream shrank under us: re-read from the top
        f.seek(cursor)
        chunk = f.read()
    end = chunk.rfind(b"\n")
    if end < 0:
        return [], cursor  # nothing terminated yet
    events: list[dict] = []
    for raw in chunk[:end].split(b"\n"):
        raw = raw.strip()
        if not raw:
            continue
        try:
            ev = json.loads(raw.decode("utf-8"))
        except (json.JSONDecodeError, UnicodeDecodeError):
            continue  # terminated but corrupt: consumed, never retried
        if isinstance(ev, dict):
            events.append(ev)
    return events, cursor + end + 1
