"""Static cost models and roofline attribution for compiled programs.

The telemetry stack measures WHEN a run is slow (epoch timing, compile
counts, collective wait); this module explains WHY the hardware is idle.
Every executable the framework produces — the scan-epoch program, the
stream train step, the Pallas-routed recurrences, and each AOT serve
bucket — has a static cost model the compiler already computed:

- ``Lowered.cost_analysis()`` / ``Compiled.cost_analysis()`` — FLOPs,
  bytes accessed, transcendentals. jax 0.4.x returns a LIST of one dict
  whose keys are space-separated strings, and backends may omit keys —
  everything here reads defensively and degrades to a warn-once
  ``cost_unavailable`` event instead of crashing or silently omitting.
- ``Compiled.memory_analysis()`` — argument/output/temp/alias bytes from
  the buffer assignment; peak ≈ argument + output + temp − alias (the
  aliased donation bytes are counted on both sides).

Static cost × the async-aware epoch timing (telemetry/run.py) gives the
utilization story: achieved FLOP/s, achieved bytes/s, arithmetic
intensity, and a roofline regime (compute- / memory- / comms-bound; the
comms side is fed by the aggregator's collective-wait attribution).

Import contract: NO top-level jax import. The pure pieces (roofline
math, regime classification, CP401–CP403 rule evaluation) are consumed
by the jax-free ``summarize``/``postmortem``/``ledger`` CLIs; only the
extraction entry points touch jax, lazily.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

from masters_thesis_tpu.analysis.findings import Finding

# ------------------------------------------------------------- roofline
#
# Nominal per-device peaks used for utilization percentages and the
# compute-vs-memory ridge point. These are ORDER-OF-MAGNITUDE anchors
# (the CP403 floor is 1%, far below any generation-to-generation spread),
# not a calibrated model of a specific chip: the repo runs on whatever
# TPU the relay leases plus an 8-device virtual CPU mesh, and an honest
# ridge matters more than a flattering MFU. Override per deployment with
# MT_PEAK_FLOPS / MT_PEAK_BYTES_PER_S (floats, per device).
PLATFORM_PEAKS: dict[str, dict[str, float]] = {
    # Dense f32-equivalent MXU throughput and HBM bandwidth, TPU v4-ish.
    "tpu": {"flops_per_sec": 137.5e12, "bytes_per_sec": 1.2e12},
    # Data-center GPU ballpark (A100-class f32 tensor / HBM2e).
    "gpu": {"flops_per_sec": 19.5e12, "bytes_per_sec": 1.5e12},
    # One host core of the virtual mesh (XLA:CPU, AVX f32 FMA + DRAM).
    "cpu": {"flops_per_sec": 5e10, "bytes_per_sec": 2e10},
}

#: Collective-wait fraction of wall time past which a program is
#: classified comms-bound regardless of its arithmetic intensity — the
#: chip is idle waiting on the fabric, not on FLOPs or HBM.
COMMS_BOUND_FRAC = 0.25

#: CP403: on a real TPU backend, achieved-FLOP/s utilization below this
#: fraction of nominal peak means the program structurally cannot feed
#: the MXU (ROADMAP: "the H=64 LSTM leaves the MXU mostly idle") — a
#: finding, so scale-out work sees it before multiplying the waste.
TPU_UTILIZATION_FLOOR = 0.01


def platform_peaks(platform: str | None) -> dict[str, float] | None:
    """Per-device nominal peaks for a platform; env-overridable."""
    peaks = PLATFORM_PEAKS.get((platform or "").lower())
    if peaks is None:
        return None
    out = dict(peaks)
    for key, env in (
        ("flops_per_sec", "MT_PEAK_FLOPS"),
        ("bytes_per_sec", "MT_PEAK_BYTES_PER_S"),
    ):
        raw = os.environ.get(env)
        if raw:
            try:
                out[key] = float(raw)
            except ValueError:
                pass
    return out


def roofline_regime(
    intensity: float | None,
    platform: str | None,
    comms_frac: float | None = None,
) -> str | None:
    """compute-bound / memory-bound / comms-bound, or None when unknowable.

    The compute/memory split compares arithmetic intensity (flops per
    byte accessed) against the platform's ridge point; the comms verdict
    overrides both when the aggregator attributes more than
    COMMS_BOUND_FRAC of wall time to collective wait.
    """
    if comms_frac is not None and comms_frac > COMMS_BOUND_FRAC:
        return "comms-bound"
    peaks = platform_peaks(platform)
    if intensity is None or peaks is None:
        return None
    ridge = peaks["flops_per_sec"] / peaks["bytes_per_sec"]
    return "compute-bound" if intensity >= ridge else "memory-bound"


def utilization(
    flops_per_step: float | None,
    bytes_per_step: float | None,
    steps_per_sec: float | None,
    platform: str | None,
    n_devices: int | None = 1,
    comms_frac: float | None = None,
) -> dict:
    """Achieved rates + roofline verdict from static cost × measured rate.

    All fields are None-tolerant: a report renders "n/a" for whatever the
    backend or the run failed to produce, never a crash.
    """
    achieved_flops = achieved_bytes = None
    if steps_per_sec is not None and steps_per_sec > 0:
        if flops_per_step is not None:
            achieved_flops = flops_per_step * steps_per_sec
        if bytes_per_step is not None:
            achieved_bytes = bytes_per_step * steps_per_sec
    intensity = None
    if flops_per_step and bytes_per_step:
        intensity = flops_per_step / bytes_per_step
    peaks = platform_peaks(platform)
    n = max(1, int(n_devices or 1))
    flops_util = bytes_util = None
    if peaks is not None:
        if achieved_flops is not None:
            flops_util = achieved_flops / (peaks["flops_per_sec"] * n)
        if achieved_bytes is not None:
            bytes_util = achieved_bytes / (peaks["bytes_per_sec"] * n)
    return {
        "achieved_flops_per_sec": achieved_flops,
        "achieved_bytes_per_sec": achieved_bytes,
        "arithmetic_intensity": intensity,
        "flops_utilization_pct": (
            None if flops_util is None else 100.0 * flops_util
        ),
        "bytes_utilization_pct": (
            None if bytes_util is None else 100.0 * bytes_util
        ),
        "regime": roofline_regime(intensity, platform, comms_frac),
        "comms_wait_frac": comms_frac,
        "nominal_peaks": peaks,
    }


# ------------------------------------------------------ cost extraction


@dataclasses.dataclass(frozen=True)
class CostModel:
    """One program's static cost model, normalized across jax versions.

    ``source`` records where the numbers came from: ``"compiled"`` (post-
    optimization — authoritative), ``"lowered"`` (pre-optimization HLO —
    cheap, no XLA compile), or ``"unavailable"``.
    """

    program: str
    flops: float | None = None
    bytes_accessed: float | None = None
    transcendentals: float | None = None
    argument_bytes: int | None = None
    output_bytes: int | None = None
    temp_bytes: int | None = None
    alias_bytes: int | None = None
    generated_code_bytes: int | None = None
    source: str = "unavailable"
    #: Steps of training the program performs per execution (the scan
    #: epoch runs steps_per_epoch optimizer steps in one call; the stream
    #: step and a serve bucket run 1).
    steps_per_execution: int = 1
    meta: dict = dataclasses.field(default_factory=dict)

    @property
    def available(self) -> bool:
        return self.flops is not None or self.bytes_accessed is not None

    @property
    def peak_bytes(self) -> int | None:
        """Device-memory high-water estimate from the buffer assignment.

        Donated inputs alias their outputs, so alias bytes are subtracted
        once (they would otherwise be double-counted on both sides).
        """
        parts = [self.argument_bytes, self.output_bytes, self.temp_bytes]
        if all(p is None for p in parts):
            return None
        total = sum(p or 0 for p in parts) - (self.alias_bytes or 0)
        return max(0, total)

    @property
    def flops_per_step(self) -> float | None:
        if self.flops is None:
            return None
        return self.flops / max(1, self.steps_per_execution)

    @property
    def bytes_per_step(self) -> float | None:
        if self.bytes_accessed is None:
            return None
        return self.bytes_accessed / max(1, self.steps_per_execution)

    def to_payload(self) -> dict:
        """Flat dict for a ``cost_profile`` event / bench detail block."""
        return {
            "program": self.program,
            "source": self.source,
            "available": self.available,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "transcendentals": self.transcendentals,
            "steps_per_execution": self.steps_per_execution,
            "flops_per_step": self.flops_per_step,
            "bytes_per_step": self.bytes_per_step,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "alias_bytes": self.alias_bytes,
            "peak_bytes": self.peak_bytes,
            "meta": self.meta,
        }


def _scalar_costs(analysis: Any) -> dict[str, float] | None:
    """Normalize ``cost_analysis()`` output across jax versions.

    jax 0.4.x returns ``[{...}]`` with space-separated keys (plus
    per-operand ``bytes accessed0{}`` entries we fold away); older/newer
    versions return a bare dict. Unknown shapes -> None, never a raise.
    """
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    out: dict[str, float] = {}
    for key in ("flops", "transcendentals", "bytes accessed"):
        value = analysis.get(key)
        if isinstance(value, (int, float)) and value >= 0:
            out[key] = float(value)
    return out or None


def extract_cost(
    compiled: Any = None,
    lowered: Any = None,
    *,
    program: str,
    steps_per_execution: int = 1,
    meta: dict | None = None,
) -> CostModel:
    """Build a :class:`CostModel` from AOT stage objects, defensively.

    Prefers the compiled executable's post-optimization numbers; falls
    back to the lowering's pre-optimization estimate; returns an
    ``unavailable`` model (never raises) when the backend offers neither.
    """
    meta = dict(meta or {})
    scalars = None
    source = "unavailable"
    for obj, label in ((compiled, "compiled"), (lowered, "lowered")):
        if obj is None:
            continue
        try:
            scalars = _scalar_costs(obj.cost_analysis())
        except Exception:  # noqa: BLE001 — backend-dependent API surface
            scalars = None
        if scalars is not None:
            source = label
            break
    mem: dict[str, int | None] = {}
    if compiled is not None:
        try:
            stats = compiled.memory_analysis()
        except Exception:  # noqa: BLE001
            stats = None
        for field, attr in (
            ("argument_bytes", "argument_size_in_bytes"),
            ("output_bytes", "output_size_in_bytes"),
            ("temp_bytes", "temp_size_in_bytes"),
            ("alias_bytes", "alias_size_in_bytes"),
            ("generated_code_bytes", "generated_code_size_in_bytes"),
        ):
            value = getattr(stats, attr, None)
            if isinstance(value, int) and value >= 0:
                mem[field] = value
    scalars = scalars or {}
    return CostModel(
        program=program,
        flops=scalars.get("flops"),
        bytes_accessed=scalars.get("bytes accessed"),
        transcendentals=scalars.get("transcendentals"),
        source=source if scalars or mem else "unavailable",
        steps_per_execution=steps_per_execution,
        meta=meta,
        **mem,
    )


def profile_jit(
    fn: Any,
    *args: Any,
    program: str,
    steps_per_execution: int = 1,
    meta: dict | None = None,
    compile: bool = True,
    **kwargs: Any,
) -> CostModel:
    """Lower (and optionally AOT-compile) a jitted callable for its cost.

    ``fn.lower()`` only traces — it neither executes nor consumes donated
    buffers, and it does NOT touch the jit dispatch cache (CompileTracker
    / TA201 counts are unaffected; verified by tests). ``compile=True``
    additionally runs the XLA compile to get ``memory_analysis()`` — one
    extra compile, paid only where a caller asked for the memory story.
    """
    lowered = fn.lower(*args, **kwargs)
    compiled = lowered.compile() if compile else None
    return extract_cost(
        compiled,
        lowered,
        program=program,
        steps_per_execution=steps_per_execution,
        meta=meta,
    )


# ------------------------------------------------------- event emission


def emit_cost_profile(tel: Any, cost: CostModel, **extra: Any) -> dict:
    """Emit one ``cost_profile`` event for a program's compile.

    When the backend produced no cost model at all, emit a single
    warn-once ``cost_unavailable`` event per run instead — repeated
    unavailable programs must not spam the stream, and ``summarize``
    renders the utilization section as "n/a" rather than omitting it.
    """
    payload = {**cost.to_payload(), **extra}
    if not cost.available and cost.peak_bytes is None:
        warned = getattr(tel, "_cost_unavailable_warned", False)
        if not warned:
            tel._cost_unavailable_warned = True
            return tel.event(
                "cost_unavailable",
                program=cost.program,
                source=cost.source,
                note="backend returned no cost_analysis/memory_analysis; "
                "utilization reports will render n/a",
            )
        return {}
    return tel.event("cost_profile", **payload)


# --------------------------------------------------------- device budget


def device_memory_budget(mesh: Any = None) -> int | None:
    """Per-device memory budget in bytes, from the backend's own report.

    TPU/GPU runtimes expose ``memory_stats()['bytes_limit']``; the CPU
    host platform reports nothing (None — budget checks are skipped on
    the virtual mesh rather than invented).
    """
    try:
        import jax

        devices = (
            list(mesh.devices.flat) if mesh is not None else jax.devices()
        )
        stats = devices[0].memory_stats() if devices else None
    except Exception:  # noqa: BLE001 — probing must never break a run
        return None
    if not isinstance(stats, dict):
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if isinstance(limit, (int, float)) and limit > 0 else None


# ------------------------------------------------------ CP401–403 rules


def cost_findings(
    cost: CostModel | None,
    *,
    platform: str | None,
    budget_bytes: int | None = None,
    flops_utilization_pct: float | None = None,
) -> list[Finding]:
    """Evaluate the cost-observability findings rules for one program.

    - **CP401** — the backend is one where cost models ARE extractable
      (cpu/tpu/gpu XLA backends all implement cost_analysis) but
      extraction produced nothing: the observability contract is broken.
    - **CP402** — the compiled program's peak memory estimate exceeds the
      backend's own reported device budget: the program is OOM-bound
      before it runs.
    - **CP403** — on a real TPU backend, achieved-FLOP/s utilization sits
      below the floor: the program structurally cannot feed the chip and
      scaling it out multiplies idle silicon.
    """
    findings: list[Finding] = []
    plat = (platform or "").lower()
    program = cost.program if cost is not None else "?"
    if plat in ("cpu", "tpu", "gpu") and (cost is None or not cost.available):
        findings.append(
            Finding(
                rule="CP401",
                message=f"no static cost model extractable for program "
                f"{program!r} on backend {plat!r} (cost_analysis and "
                "memory_analysis both empty)",
            )
        )
    if (
        cost is not None
        and budget_bytes
        and cost.peak_bytes is not None
        and cost.peak_bytes > budget_bytes
    ):
        findings.append(
            Finding(
                rule="CP402",
                message=f"program {program!r} peak memory estimate "
                f"{cost.peak_bytes} B exceeds the device budget "
                f"{budget_bytes} B (arguments {cost.argument_bytes} + "
                f"outputs {cost.output_bytes} + temps {cost.temp_bytes} "
                f"- aliased {cost.alias_bytes})",
            )
        )
    if (
        plat == "tpu"
        and flops_utilization_pct is not None
        and flops_utilization_pct < 100.0 * TPU_UTILIZATION_FLOOR
    ):
        findings.append(
            Finding(
                rule="CP403",
                message=f"program {program!r} achieved "
                f"{flops_utilization_pct:.3f}% of nominal TPU FLOP/s "
                f"(floor {100.0 * TPU_UTILIZATION_FLOOR:.1f}%) — the "
                "program cannot feed the MXU; see docs/telemetry.md "
                "roofline playbook before scaling it out",
            )
        )
    return findings


# ------------------------------------------- Pallas recurrence routing


def lstm_route_cost(
    n_t: int,
    rows: int,
    hidden: int,
    n_layers: int = 2,
    *,
    has_mask: bool = False,
    window_rows: int | None = None,
    itemsize: int = 4,
    compile: bool = True,
) -> CostModel:
    """Cost-profile the LSTM recurrence the router would actually run.

    Builds the recurrence program at the given shape with ``impl="auto"``
    (the same routing the trainer takes on this backend), lowers/compiles
    it, and annotates the result with the router's plan — predicted VMEM
    bytes from the byte model (ops/lstm_kernel.py) next to the
    compiler-reported actual temp bytes, so the byte model is auditable
    against the compiler instead of trusted blindly. On non-TPU backends
    the route is the XLA scan and the prediction records what the Pallas
    path WOULD have budgeted.
    """
    import jax
    import jax.numpy as jnp

    from masters_thesis_tpu.ops import lstm_kernel as lk

    plan = lk.route_plan(
        n_t,
        rows,
        hidden,
        n_layers,
        has_mask=has_mask,
        itemsize=itemsize,
        window_rows=window_rows,
    )
    dtype = jnp.float32 if itemsize == 4 else jnp.bfloat16
    four_h = 4 * hidden
    x_struct = jax.ShapeDtypeStruct((n_t, rows, four_h), dtype)
    if n_layers == 1:
        w_struct = jax.ShapeDtypeStruct((hidden, four_h), dtype)

        def run(x, w):
            return lk.lstm_recurrence(x, w, window_rows=window_rows)

        args = (x_struct, w_struct)
    else:
        weights = (
            tuple(
                jax.ShapeDtypeStruct((hidden, four_h), dtype)
                for _ in range(n_layers)
            ),
            tuple(
                jax.ShapeDtypeStruct((hidden, four_h), dtype)
                for _ in range(n_layers - 1)
            ),
            tuple(
                jax.ShapeDtypeStruct((four_h,), dtype)
                for _ in range(n_layers - 1)
            ),
        )
        if has_mask:
            masks = tuple(
                jax.ShapeDtypeStruct((n_t, rows, hidden), dtype)
                for _ in range(n_layers - 1)
            )

            def run(x, w, m):
                return lk.lstm_stack_recurrence(
                    x, w, masks=m, window_rows=window_rows
                )

            args = (x_struct, weights, masks)
        else:

            def run(x, w):
                return lk.lstm_stack_recurrence(
                    x, w, masks=None, window_rows=window_rows
                )

            args = (x_struct, weights)
    cost = profile_jit(
        jax.jit(run),
        *args,
        program=f"lstm_recurrence_L{n_layers}",
        compile=compile,
        meta=plan,
    )
    if cost.temp_bytes is not None and plan.get("predicted_vmem_bytes"):
        ratio = cost.temp_bytes / plan["predicted_vmem_bytes"]
        cost.meta["temp_vs_predicted_ratio"] = round(ratio, 4)
    return cost
