"""Typed autoscaling signals: the feed a capacity controller consumes.

ROADMAP item 5 (load-driven bidirectional elasticity) needs one struct
answering "how loaded is the fleet right now, and how much headroom is
left?". This module packages the three signal families the rest of the
telemetry plane already produces:

- **measured capacity** — the knee QPS from the perf ledger's
  ``serve/knee_qps`` rows (bench.py --serve-sustained appends one per
  round: the last offered rate the 4-replica fleet sustained inside the
  deadline SLO);
- **live load** — offered QPS / p99 / shed% over the recent window, and
  the SLO alerts currently firing, from the event stream (shared fold
  with :mod:`watch` and :mod:`slo`);
- **per-replica service time** — each replica's EWMA batch seconds
  (``ServiceTimeModel``), from a live ``FleetServer.stats()`` dict when
  the caller has one, else from the stream's last ``fleet_finished``
  stats.

``utilization`` is offered/knee (how much of measured capacity is in
use) and ``headroom_qps`` is what is left — the two numbers a
scale-up/scale-down decision hinges on. Stdlib-only, jax-free.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from pathlib import Path

from masters_thesis_tpu.telemetry.events import read_events
from masters_thesis_tpu.telemetry.ledger import read_ledger
from masters_thesis_tpu.telemetry.report import EVENTS_FILENAME
from masters_thesis_tpu.telemetry.slo import window_stats
from masters_thesis_tpu.telemetry.watch import alert_state

DEFAULT_LEDGER = "results/perf_ledger.jsonl"


@dataclass(frozen=True)
class AutoscaleSignals:
    """One consistent snapshot of load vs capacity."""

    ts: float
    #: Offered request rate over the window (None: nothing served yet).
    qps: float | None
    p99_s: float | None
    shed_pct: float | None
    #: Measured capacity: last knee from the perf ledger.
    knee_qps: float | None
    #: qps / knee_qps — fraction of measured capacity in use.
    utilization: float | None
    #: knee_qps − qps (clamped at 0) — capacity left before the knee.
    headroom_qps: float | None
    #: Per-replica EWMA service seconds (ServiceTimeModel.batch_s).
    replica_service_s: dict = field(default_factory=dict)
    live_replicas: int | None = None
    #: SLO rules firing right now — a controller should never scale
    #: DOWN while any of these is active.
    active_alerts: tuple = ()

    def wants_scale_up(self) -> bool:
        """High-utilization or actively-breaching: add capacity."""
        return bool(self.active_alerts) or (
            self.utilization is not None and self.utilization > 0.8
        )

    def wants_scale_down(self) -> bool:
        """Quiet and alert-free: capacity can be returned."""
        return (
            not self.active_alerts
            and self.utilization is not None
            and self.utilization < 0.3
        )


def knee_from_ledger(path: str | Path = DEFAULT_LEDGER) -> float | None:
    """The most recent measured knee QPS (None: never benched)."""
    knee = None
    for row in read_ledger(path):
        if row.get("point") == "serve/knee_qps" and row.get("knee_qps"):
            knee = float(row["knee_qps"])
    return knee


def _replica_service(
    fleet_stats: dict | None, events: list[dict]
) -> tuple[dict, int | None]:
    """(per-replica EWMA seconds, live count) from live stats when the
    caller holds a FleetServer, else from the stream's last stats."""
    per = (fleet_stats or {}).get("replicas")
    n_live = (fleet_stats or {}).get("n_live")
    if not per:
        for ev in events:
            if ev.get("kind") == "fleet_finished" and isinstance(
                ev.get("replicas"), dict
            ):
                per = ev["replicas"]
                n_live = ev.get("n_live", n_live)
    if not per:
        return {}, n_live
    service = {}
    for name, row in sorted(per.items()):
        batch_ms = (row or {}).get("batch_ms")
        if batch_ms is not None:
            service[name] = float(batch_ms) / 1e3
    return service, n_live


def collect_signals(
    root: str | Path,
    ledger_path: str | Path = DEFAULT_LEDGER,
    fleet_stats: dict | None = None,
    now: float | None = None,
    window_s: float = 60.0,
) -> AutoscaleSignals:
    """Build the feed from a run root's event streams + the perf ledger.

    ``fleet_stats`` (a live ``FleetServer.stats()`` dict) sharpens the
    per-replica service times when the caller is in-process with the
    fleet; everything else comes from the durable streams, so a
    controller on another host needs only the filesystem.
    """
    now = time.time() if now is None else now
    root = Path(root)
    streams = (
        [root] if root.is_file() else sorted(root.rglob(EVENTS_FILENAME))
    )
    events: list[dict] = []
    for path in streams:
        events.extend(read_events(path))
    events.sort(key=lambda e: (e.get("ts") or 0.0))
    requests = [
        (ev["ts"], ev.get("status"), ev.get("dur_s"))
        for ev in events
        if ev.get("kind") == "span"
        and ev.get("name") == "serve.request"
        and ev.get("ts") is not None
    ]
    window = window_stats(requests, now, window_s) if requests else None
    alerts = alert_state(events)
    knee = knee_from_ledger(ledger_path)
    qps = window["qps"] if window else None
    utilization = (
        qps / knee if (qps is not None and knee) else None
    )
    service, n_live = _replica_service(fleet_stats, events)
    return AutoscaleSignals(
        ts=now,
        qps=qps,
        p99_s=window["p99_s"] if window else None,
        shed_pct=window["shed_pct"] if window else None,
        knee_qps=knee,
        utilization=utilization,
        headroom_qps=(
            max(0.0, knee - qps)
            if (knee is not None and qps is not None)
            else None
        ),
        replica_service_s=service,
        live_replicas=n_live,
        active_alerts=tuple(alerts.get("active") or ()),
    )
