"""Live fleet watch console: incremental merge of running event streams.

``python -m masters_thesis_tpu.telemetry watch <root>`` tails every
``events.jsonl`` under a root *while the fleet is writing them* and
renders one screen per refresh: per-rank/per-replica status, offered
QPS / p99 / shed over the recent window, the fleet generation, and the
SLO alerts currently firing.

The console shares the fleet reconstruction with the post-hoc tools
rather than duplicating it: each stream's accumulated events are folded
through :func:`~.aggregate.digest_events` (the same digest the
``aggregate`` / ``postmortem`` CLIs build from a full read) and merged
with :func:`~.aggregate.aggregate_streams` — so what the live console
says about a rank is, by construction, what the postmortem will say
once the run ends. The only difference is HOW the events arrive: the
tail-cursor reader (:func:`~.events.read_new_lines`) feeds each refresh
only the bytes appended since the last one, so a refresh over a
long-running fleet costs the tail, not the history.

Jax-free by contract, like every CLI in this package: the watch runs on
operator machines where touching the backend can hang on a wedged relay
lease (docs/OPERATIONS.md).
"""

from __future__ import annotations

import sys
import time
from pathlib import Path

from masters_thesis_tpu.telemetry.aggregate import (
    DEFAULT_GRACE_S,
    aggregate_streams,
    digest_events,
)
from masters_thesis_tpu.telemetry.events import read_new_lines
from masters_thesis_tpu.telemetry.quality import (
    quality_report,
    render_quality,
)
from masters_thesis_tpu.telemetry.report import EVENTS_FILENAME, alert_state
from masters_thesis_tpu.telemetry.slo import window_stats

#: Replica lifecycle kinds the per-replica panel is folded from.
_REPLICA_KINDS = ("replica_started", "replica_dead", "replica_halted")


class FleetWatch:
    """Incremental fleet state: cursors + accumulated events per stream.

    Single-threaded by design (one console, one reader); every refresh
    re-digests only the streams whose cursor moved.
    """

    def __init__(
        self,
        root: str | Path,
        grace_s: float = DEFAULT_GRACE_S,
        window_s: float = 60.0,
    ):
        self.root = Path(root)
        self.grace_s = grace_s
        self.window_s = window_s
        self._cursors: dict[Path, int] = {}
        self._events: dict[Path, list[dict]] = {}
        self._digests: dict[Path, dict] = {}

    def _discover(self) -> list[Path]:
        if self.root.is_file():
            return [self.root]
        return sorted(self.root.rglob(EVENTS_FILENAME))

    def refresh(self, now: float | None = None) -> dict:
        """Tail every stream, re-digest what changed, build the snapshot."""
        now = time.time() if now is None else now
        for path in self._discover():
            cursor = self._cursors.get(path, 0)
            new, moved = read_new_lines(path, cursor)
            acc = self._events.setdefault(path, [])
            if new:
                acc.extend(new)
            if new or path not in self._digests:
                self._digests[path] = digest_events(acc, path, self.root)
            self._cursors[path] = moved
        # aggregate_streams stamps status and (for multi-generation
        # fleets) rewrites labels in place — feed it copies so the cached
        # digests stay pristine across refreshes.
        report = aggregate_streams(
            [dict(d) for d in self._digests.values()],
            now=now,
            grace_s=self.grace_s,
        ) if self._digests else None
        merged = self._merged_events()
        return {
            "ts": now,
            "root": str(self.root),
            "streams": len(self._digests),
            "report": report,
            "serve": self._serve_window(merged, now),
            "alerts": alert_state(merged),
            "replicas": replica_state(merged),
            "quality": quality_report(merged),
        }

    def _merged_events(self) -> list[dict]:
        merged = [
            ev for events in self._events.values() for ev in events
        ]
        merged.sort(key=lambda e: (e.get("ts") or 0.0))
        return merged

    def _serve_window(self, merged: list[dict], now: float) -> dict | None:
        requests = [
            (ev["ts"], ev.get("status"), ev.get("dur_s"))
            for ev in merged
            if ev.get("kind") == "span"
            and ev.get("name") == "serve.request"
            and ev.get("ts") is not None
        ]
        if not requests:
            return None
        return window_stats(requests, now, self.window_s)


def replica_state(events: list[dict]) -> dict | None:
    """Per-replica serving status from the fleet's lifecycle events."""
    per: dict[str, dict] = {}
    for ev in events:
        kind = ev.get("kind")
        if kind not in _REPLICA_KINDS:
            continue
        name = ev.get("replica")
        if not name:
            continue
        row = per.setdefault(
            name,
            {"replica": name, "state": "unknown", "restarts": 0,
             "cause": None},
        )
        if kind == "replica_started":
            row["state"] = "live"
            if ev.get("restart"):
                row["restarts"] += 1
        elif kind == "replica_dead":
            row["state"] = "dead"
            row["cause"] = ev.get("cause")
        elif kind == "replica_halted":
            row["state"] = "halted"
    if not per:
        return None
    return {
        "replicas": {name: per[name] for name in sorted(per)},
        "live": sum(1 for r in per.values() if r["state"] == "live"),
    }


# ------------------------------------------------------------- rendering


def _fmt(value, spec: str = ".3g") -> str:
    return "n/a" if value is None else format(value, spec)


def render_watch(snapshot: dict) -> str:
    """One console frame from a :meth:`FleetWatch.refresh` snapshot."""
    lines = [
        f"watch          : {snapshot['root']} | "
        f"{snapshot['streams']} stream(s) | "
        f"{time.strftime('%H:%M:%S', time.localtime(snapshot['ts']))}"
    ]
    report = snapshot.get("report")
    if report is None:
        lines.append("  (no event streams yet)")
        return "\n".join(lines)
    if report.get("fleet_generation") is not None:
        lines.append(
            f"generation     : g{report['fleet_generation']} "
            f"({report.get('generations')} generation(s), "
            f"{len(report.get('resizes') or [])} resize(s))"
        )
    for d in report["processes"]:
        gap = (report.get("heartbeat_gaps_s") or {}).get(d["label"])
        lines.append(
            f"  {d['label']:<8s} {d['status']:<10s} host={d['host']} "
            f"epochs={d['epochs']} "
            f"sps={_fmt(d.get('steps_per_sec'), '.2f')} "
            f"gap={_fmt(gap, '.1f')}s"
        )
    serve = snapshot.get("serve")
    if serve:
        lines.append(
            f"serving        : qps {serve['qps']:.1f} | "
            f"p99 {_fmt(None if serve['p99_s'] is None else serve['p99_s'] * 1e3, '.2f')}ms | "
            f"shed {serve['shed_pct']:.1f}% "
            f"({serve['n']} request(s) in window)"
        )
    replicas = snapshot.get("replicas")
    if replicas:
        per = ", ".join(
            f"{name} {row['state']}"
            + (f" x{row['restarts']} restart(s)" if row["restarts"] else "")
            for name, row in replicas["replicas"].items()
        )
        lines.append(
            f"replicas       : {replicas['live']}/"
            f"{len(replicas['replicas'])} live | {per}"
        )
    quality = snapshot.get("quality")
    if quality and (
        quality.get("samples") or quality.get("swaps_rejected_quality")
    ):
        lines.append(render_quality(quality))
    alerts = snapshot.get("alerts") or {}
    active = alerts.get("active") or []
    if active:
        lines.append(f"ALERTS FIRING  : {', '.join(active)}")
        for name in active:
            row = alerts["rules"][name]
            since = row.get("since_ts")
            age = (
                f"{snapshot['ts'] - since:.0f}s ago"
                if since is not None else "n/a"
            )
            lines.append(
                f"  - {name} ({row.get('slo_kind')}): value "
                f"{_fmt(row.get('last_value'), '.4g')} > threshold "
                f"{_fmt(row.get('threshold'), '.4g')}, fired {age}"
            )
    else:
        lines.append(
            "alerts         : none firing"
            + (
                f" ({alerts.get('resolved')} resolved)"
                if alerts.get("resolved")
                else ""
            )
        )
    if report.get("failures"):
        lines.append("failures       :")
        lines.extend(f"  - {f}" for f in report["failures"][:4])
    else:
        lines.append("fleet health   : ok")
    return "\n".join(lines)


def run_watch(
    root: str | Path,
    once: bool = False,
    interval_s: float = 2.0,
    grace_s: float = DEFAULT_GRACE_S,
    out=None,
) -> int:
    """The ``watch`` CLI loop; ``once`` renders a single snapshot."""
    out = sys.stdout if out is None else out
    watch = FleetWatch(root, grace_s=grace_s)
    if once:
        print(render_watch(watch.refresh()), file=out)
        return 0
    try:
        while True:
            frame = render_watch(watch.refresh())
            # Clear + home between frames so the console reads as one
            # live screen rather than a scroll.
            print("\x1b[2J\x1b[H" + frame, file=out, flush=True)
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return 0


def selfcheck() -> int:
    """Hermetic watch smoke: fabricate a 2-process fleet (one rank
    behind), a serve window, and a fired-then-unresolved alert; the
    rendered snapshot must show all three. The tools/check.sh gate."""
    import os
    import tempfile

    from masters_thesis_tpu.telemetry.run import TelemetryRun

    saved = {
        k: os.environ.get(k)
        for k in ("JAX_PROCESS_INDEX", "JAX_PROCESS_COUNT")
    }
    failures: list[str] = []
    try:
        with tempfile.TemporaryDirectory() as tmp:
            root = Path(tmp)
            now = time.time()
            for rank in range(2):
                os.environ["JAX_PROCESS_INDEX"] = str(rank)
                os.environ["JAX_PROCESS_COUNT"] = "2"
                tel = TelemetryRun(
                    root / f"p{rank}", run_id=f"watch-p{rank}"
                )
                tel.event(
                    "run_started", platform="cpu", n_devices=1,
                    strategy="selfcheck", epoch_mode="scan",
                    steps_per_epoch=4,
                )
                for epoch in range(3):
                    tel.event(
                        "epoch", epoch=epoch, steps=4,
                        wall_s=0.4 + 0.2 * rank, dispatch_s=0.01,
                        device_s=None, data_wait_s=0.0, compile_events=0,
                        compiled=False, fenced=False, steps_per_sec=8.0,
                    )
                if rank == 0:
                    for i in range(10):
                        tel.event(
                            "span", name="serve.request", cat="serve",
                            span_id=f"r{i}", start_ts=now - 1.0,
                            dur_s=0.01,
                            status="ok" if i < 9 else "shed",
                        )
                    tel.event(
                        "alert_fired", rule="error-budget-burn",
                        slo_kind="burn_rate", value=5.0, threshold=2.0,
                        burn_fast=5.0, burn_slow=4.0, active_s=None,
                    )
                    tel.event(
                        "quality_sample", sampled=7, scored=True,
                        input_psi=0.31, input_ks=0.2, pred_psi=0.05,
                        pred_ks=0.04, shadow_err=0.12, shadow_thr=0.5,
                        input_thr=0.25, pred_thr=0.25,
                        input_breached=True, pred_breached=False,
                        shadow_breached=False,
                    )
                    tel.event(
                        "run_finished", epochs=3, total_steps=12,
                        steps_per_sec=8.0, diverged=False, best_val=0.5,
                        epoch_compiles=1, eval_compiles=0,
                    )
                tel.close()
            snap = FleetWatch(root).refresh()
            frame = render_watch(snap)
            if snap["streams"] != 2:
                failures.append(f"saw {snap['streams']} streams, wanted 2")
            if (snap["alerts"] or {}).get("active") != [
                "error-budget-burn"
            ]:
                failures.append(
                    f"active alerts {snap['alerts'].get('active')!r}"
                )
            if snap["serve"] is None or snap["serve"]["n"] != 10:
                failures.append(f"serve window {snap['serve']!r}")
            if (snap.get("quality") or {}).get("samples") != 1:
                failures.append(f"quality section {snap.get('quality')!r}")
            for needle in ("ALERTS FIRING", "error-budget-burn", "p0",
                           "p1", "serving", "QUALITY"):
                if needle not in frame:
                    failures.append(f"frame missing {needle!r}")
            # A second refresh must be incremental: cursors already at
            # EOF, nothing re-read, identical fleet view.
            watch2 = FleetWatch(root)
            watch2.refresh()
            cursors = dict(watch2._cursors)
            snap2 = watch2.refresh()
            if watch2._cursors != cursors:
                failures.append("cursors moved with no new events")
            if snap2["streams"] != 2:
                failures.append("incremental refresh lost streams")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if failures:
        print("telemetry: watch selfcheck FAILED: " + "; ".join(failures))
        return 1
    print("telemetry: watch selfcheck ok")
    return 0
