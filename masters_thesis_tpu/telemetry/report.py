"""Run reports: fold a JSONL event stream into the numbers that matter.

Jax-free by contract (stdlib + numpy) — this module is what ``python -m
masters_thesis_tpu.telemetry summarize`` runs on operator machines, where
importing jax can acquire (and hang on) the TPU relay lease. Everything
here is arithmetic over dicts.

The report answers the ROADMAP's standing perf questions from one file:

- throughput: steps/sec over post-compile epochs, p50/p99 step time;
- the TA201 contract at runtime: how many times did the epoch program
  actually compile (exactly 1 is the contract; >1 is a violation that
  makes the CLI exit nonzero);
- where the wall time went: compile / device / host dispatch / data wait;
- input pipeline health: starvation fraction (stream mode);
- peak device memory and live buffers;
- the run's preflight verdict, recorded as an event by the Trainer.
"""

from __future__ import annotations

import json
from pathlib import Path

from masters_thesis_tpu.telemetry.costs import (
    TPU_UTILIZATION_FLOOR,
    utilization as _roofline_utilization,
)
from masters_thesis_tpu.telemetry.events import read_events
from masters_thesis_tpu.telemetry.quality import (
    quality_report,
    quality_violations,
)

EVENTS_FILENAME = "events.jsonl"


def resolve_events_path(target: str | Path) -> Path:
    """Accept a run dir, a dir containing one run, or an events file."""
    target = Path(target)
    if target.is_file():
        return target
    direct = target / EVENTS_FILENAME
    if direct.is_file():
        return direct
    nested = sorted(target.glob(f"*/{EVENTS_FILENAME}"))
    if len(nested) == 1:
        return nested[0]
    if len(nested) > 1:
        raise FileNotFoundError(
            f"{target} holds {len(nested)} event streams; pass one of: "
            + ", ".join(str(p.parent) for p in nested)
        )
    raise FileNotFoundError(f"no {EVENTS_FILENAME} under {target}")


def _quantile(sorted_values: list[float], q: float) -> float | None:
    if not sorted_values:
        return None
    idx = min(len(sorted_values) - 1, max(0, round(q * (len(sorted_values) - 1))))
    return sorted_values[idx]


def summarize_events(events: list[dict]) -> dict:
    """Fold an event stream into the run report dict (see render_text)."""
    by_kind: dict[str, list[dict]] = {}
    for ev in events:
        by_kind.setdefault(ev.get("kind", "?"), []).append(ev)

    started = (by_kind.get("run_started") or [{}])[-1]
    finished = (by_kind.get("run_finished") or [{}])[-1]
    epochs = by_kind.get("epoch", [])
    steady = [e for e in epochs if not e.get("compiled")]
    compile_epochs = [e for e in epochs if e.get("compiled")]

    # Throughput: prefer the trainer's own post-compile figure (measured
    # fence-to-fence over the whole run); fall back to summing epoch events.
    steps_per_sec = finished.get("steps_per_sec")
    steady_steps = sum(e.get("steps") or 0 for e in steady)
    steady_wall = sum(e.get("wall_s") or 0.0 for e in steady)
    if steps_per_sec is None and steady_wall > 0:
        steps_per_sec = steady_steps / steady_wall

    step_times = sorted(
        (e["wall_s"] / e["steps"])
        for e in steady
        if e.get("wall_s") and e.get("steps")
    )

    # Compile accounting: epoch events carry per-epoch cache-miss deltas;
    # run_finished carries the totals (authoritative when present).
    epoch_compiles = finished.get("epoch_compiles")
    if epoch_compiles is None:
        epoch_compiles = sum(e.get("compile_events") or 0 for e in epochs)
    eval_compiles = finished.get("eval_compiles")
    if eval_compiles is None:
        eval_compiles = sum(
            e.get("compile_events") or 0 for e in by_kind.get("eval", [])
        )
    first_compile_s = (
        compile_epochs[0].get("wall_s") if compile_epochs else None
    )

    compile_s = sum(e.get("wall_s") or 0.0 for e in compile_epochs)
    device_s = sum(
        e["device_s"] for e in steady if e.get("device_s") is not None
    )
    dispatch_s = sum(
        e["dispatch_s"] for e in steady if e.get("dispatch_s") is not None
    )
    data_wait_s = sum(e.get("data_wait_s") or 0.0 for e in epochs)
    total_wall = compile_s + steady_wall

    # Starvation: fraction of steady-state wall the host spent PRODUCING
    # the next batch instead of overlapping device compute. Scan-mode runs
    # (device-resident split) are structurally 0.
    steady_data_wait = sum(e.get("data_wait_s") or 0.0 for e in steady)
    starvation_pct = (
        100.0 * steady_data_wait / steady_wall if steady_wall > 0 else 0.0
    )

    mem_events = by_kind.get("memory", [])
    peak_bytes = _max_of(mem_events, "peak_bytes_in_use")
    bytes_in_use = _max_of(mem_events, "bytes_in_use")
    live_bytes = _max_of(mem_events, "live_buffer_bytes")
    peak = next(
        (v for v in (peak_bytes, bytes_in_use, live_bytes) if v is not None),
        None,
    )

    restarts = _restart_stats(events, by_kind)
    serve = _serve_stats(by_kind)
    fleet = _fleet_stats(by_kind)
    replicas = _replica_stats(by_kind)
    util = _utilization_stats(
        by_kind,
        steps_per_sec,
        started.get("platform"),
        started.get("n_devices"),
    )

    # Window-store epochs (memory-mapped shards): how many bytes paged
    # through the store and how much of the data wait was page-fault wait —
    # the "slow disk vs slow producer" split for universe-scale runs.
    ws_events = by_kind.get("window_store", [])
    window_store = None
    if ws_events:
        ws_bytes = sum(e.get("bytes_read") or 0 for e in ws_events)
        ws_fault = sum(e.get("fault_wait_s") or 0.0 for e in ws_events)
        window_store = {
            "epochs": len(ws_events),
            "bytes_read": ws_bytes,
            "fault_wait_s": ws_fault,
            "fault_share_pct": (
                100.0 * ws_fault / data_wait_s if data_wait_s > 0 else 0.0
            ),
        }

    preflight = (by_kind.get("preflight") or [{}])[-1]
    # Gradient-sync footprint (flat update path, train/flatparams.py): the
    # trainer records one grad_sync event per run — collectives per step
    # (the TA206-pinned count) and bytes moved by the flat-buffer pmean.
    grad_sync = (by_kind.get("grad_sync") or [{}])[-1]
    profile_windows = [
        {k: e.get(k) for k in ("start_epoch", "end_epoch", "trace_dir")}
        for e in by_kind.get("profile_window", [])
    ]

    report = {
        "run": started.get("run") or (events[0].get("run") if events else None),
        "platform": started.get("platform"),
        "n_devices": started.get("n_devices"),
        "strategy": started.get("strategy"),
        "epoch_mode": started.get("epoch_mode"),
        "epochs": len(epochs),
        "total_steps": sum(e.get("steps") or 0 for e in epochs),
        "steps_per_sec": steps_per_sec,
        "step_time_ms": {
            "p50": _scale(_quantile(step_times, 0.50), 1e3),
            "p99": _scale(_quantile(step_times, 0.99), 1e3),
            "mean": _scale(
                (sum(step_times) / len(step_times)) if step_times else None,
                1e3,
            ),
            "samples": len(step_times),
        },
        "compiles": {
            "train_epoch": epoch_compiles,
            "eval": eval_compiles,
            "first_compile_s": first_compile_s,
        },
        "time_split_s": {
            "total": total_wall,
            "compile": compile_s,
            "device": device_s,
            "dispatch": dispatch_s,
            "data_wait": data_wait_s,
        },
        "data": {
            "data_wait_s": data_wait_s,
            "starvation_pct": starvation_pct,
        },
        "window_store": window_store,
        "memory": {
            "peak_bytes": peak,
            "peak_bytes_in_use": peak_bytes,
            "live_buffer_bytes": live_bytes,
            "source": mem_events[-1].get("source") if mem_events else None,
        },
        "grad_sync": {
            "collectives_per_step": grad_sync.get("collectives_per_step"),
            "grad_reduce_bytes": grad_sync.get("grad_reduce_bytes"),
            "flat_buffers": grad_sync.get("flat_buffers"),
        },
        "restarts": restarts,
        "serve": serve,
        "fleet": fleet,
        "replicas": replicas,
        "utilization": util,
        "preflight": preflight.get("status"),
        "diverged": finished.get("diverged"),
        "profile_windows": profile_windows,
        "best_val": finished.get("best_val"),
        "alerts": (
            alert_state(events)
            if (by_kind.get("alert_fired") or by_kind.get("alert_resolved"))
            else None
        ),
        # Model-quality plane (telemetry/quality.py): drift-sample folding,
        # breach counts, quality-rejected swaps. None for runs that never
        # sampled — the section only appears once a monitor was attached.
        "quality": (
            quality_report(events)
            if (
                by_kind.get("quality_sample")
                or any(
                    str(e.get("reason") or "").startswith("quality")
                    for e in by_kind.get("swap_rejected", [])
                )
            )
            else None
        ),
    }
    report["violations"] = contract_violations(report) + quality_violations(
        events, report["quality"]
    )
    return report


def alert_state(events: list[dict]) -> dict:
    """Fold ``alert_fired``/``alert_resolved`` (stream-ordered) into the
    per-rule alert state. Shared by the live watch console and the
    post-hoc ``alerts`` report section, so what the console showed while
    the run was alive is — by construction — what summarize confirms
    after it ends."""
    rules: dict[str, dict] = {}
    fired = resolved = 0
    for ev in events:
        kind = ev.get("kind")
        if kind not in ("alert_fired", "alert_resolved"):
            continue
        name = ev.get("rule") or "?"
        row = rules.setdefault(
            name,
            {
                "rule": name,
                "slo_kind": ev.get("slo_kind"),
                "firing": False,
                "since_ts": None,
                "fired": 0,
                "resolved": 0,
                "last_value": None,
                "threshold": ev.get("threshold"),
            },
        )
        row["last_value"] = ev.get("value")
        if kind == "alert_fired":
            fired += 1
            row["fired"] += 1
            row["firing"] = True
            row["since_ts"] = ev.get("ts")
        else:
            resolved += 1
            row["resolved"] += 1
            row["firing"] = False
    return {
        "rules": rules,
        "active": sorted(
            name for name, row in rules.items() if row["firing"]
        ),
        "fired": fired,
        "resolved": resolved,
    }


def _restart_stats(events: list[dict], by_kind: dict) -> dict:
    """Restart accounting over a (possibly multi-attempt) event stream.

    A resumed run APPENDS to the same events.jsonl (telemetry/run.py), so
    one stream can hold several attempts: trainer streams delimit them
    with run_started, supervisor streams with attempt_started. Lost work
    per dead attempt = gap between its last activity and its last
    checkpoint_saved (no checkpoint in the segment -> the whole segment
    was lost); supervisor attempt_finished events carry the figure
    precomputed.
    """
    starts = by_kind.get("run_started", [])
    sup_started = by_kind.get("attempt_started", [])
    attempts = max(len(starts), len(sup_started), 1 if events else 0)

    lost_work_s = 0.0
    # Supervisor streams: attempt_finished carries lost_work_s directly.
    measured = False
    for ev in by_kind.get("attempt_finished", []):
        if ev.get("lost_work_s") is not None and not ev.get("ok"):
            lost_work_s += float(ev["lost_work_s"])
            measured = True
    if not measured and len(starts) > 1:
        # Trainer streams: split into segments at each run_started; a
        # segment without a run_finished died mid-flight.
        segments: list[list[dict]] = []
        for ev in events:
            if ev.get("kind") == "run_started":
                segments.append([])
            if segments:
                segments[-1].append(ev)
        for seg in segments:
            if any(e.get("kind") == "run_finished" for e in seg):
                continue
            last_ts = max((e.get("ts") or 0.0) for e in seg)
            saves = [
                e.get("ts") or 0.0
                for e in seg
                if e.get("kind") == "checkpoint_saved"
            ]
            floor_ts = max(saves) if saves else min(
                (e.get("ts") or 0.0) for e in seg
            )
            lost_work_s += max(0.0, last_ts - floor_ts)

    # The trace id the attempt chain rides: the supervisor propagates ONE
    # id forward through every retry (trace.py), so the restarts line can
    # name the trace that stitches the attempts together.
    trace_id = next(
        (
            ev["trace_id"]
            for ev in (sup_started + starts)
            if ev.get("trace_id")
        ),
        None,
    )

    return {
        "attempts": attempts,
        "restarts": max(0, attempts - 1),
        "lost_work_s": lost_work_s,
        "degradations": len(by_kind.get("degradation", [])),
        "rollbacks": len(by_kind.get("rollback", [])),
        "resumed": any(e.get("resumed_from") for e in starts),
        "trace_id": trace_id,
    }


def _serve_stats(by_kind: dict) -> dict | None:
    """Serving-path accounting; None for runs that never served.

    ``serve_finished`` (server.py stop()) is authoritative for the
    totals; the raw shed / swap / degradation events keep the section
    usable for a replica that died before a clean stop.
    """
    finished = by_kind.get("serve_finished", [])
    raw_sheds = len(by_kind.get("request_shed", []))
    swaps_committed = len(by_kind.get("swap_committed", []))
    swaps_rejected = len(by_kind.get("swap_rejected", []))
    lane_swaps_committed = len(by_kind.get("lane_swap_committed", []))
    lane_swaps_rejected = len(by_kind.get("lane_swap_rejected", []))
    if not (
        finished
        or by_kind.get("serve_started")
        or raw_sheds
        or swaps_committed
        or swaps_rejected
        or lane_swaps_committed
        or lane_swaps_rejected
    ):
        return None
    last = finished[-1] if finished else {}
    return {
        "requests": last.get("requests"),
        "completed": last.get("completed"),
        "shed": last.get("shed", raw_sheds),
        "errors": last.get("errors"),
        "late_converted": last.get("late_converted"),
        "late_deliveries": last.get("late_deliveries"),
        "p50_ms": last.get("p50_ms"),
        "p99_ms": last.get("p99_ms"),
        "qps": last.get("qps"),
        # Multi-tenant stacked serving: per-tenant admission accounting
        # and lane count ride along on serve_finished when present.
        "tenants": last.get("tenants"),
        "lanes": last.get("lanes"),
        "swaps_committed": swaps_committed,
        "swaps_rejected": swaps_rejected,
        "lane_swaps_committed": lane_swaps_committed,
        "lane_swaps_rejected": lane_swaps_rejected,
        "degradations": len(by_kind.get("degradation", [])),
        "clean_stop": bool(finished),
    }


def _fleet_stats(by_kind: dict) -> dict | None:
    """Serving-fleet accounting (serve/fleet.py): replica lifecycle,
    failover, and exported-program cache behaviour. None for runs that
    never ran a fleet or touched the program cache.

    ``fleet_finished`` (stop()) is authoritative for the totals; the raw
    lifecycle events (replica_dead / replica_started / redispatch /
    cache_*) keep the section usable for a fleet that died before a
    clean stop.
    """
    finished = by_kind.get("fleet_finished", [])
    deaths = by_kind.get("replica_dead", [])
    boots = by_kind.get("replica_started", [])
    cache = {
        "hits": len(by_kind.get("cache_hit", [])),
        "misses": len(by_kind.get("cache_miss", [])),
        "stores": len(by_kind.get("cache_store", [])),
        "rejections": len(by_kind.get("cache_rejected", [])),
    }
    has_fleet = bool(finished or by_kind.get("fleet_started") or deaths
                     or boots)
    if not has_fleet and not any(cache.values()):
        return None
    last = finished[-1] if finished else {}
    per = last.get("replicas") if isinstance(last.get("replicas"), dict) \
        else {}
    restart_boots = [b for b in boots if b.get("restart")]
    return {
        "replicas": sorted(per) or sorted(
            {b.get("replica") for b in boots if b.get("replica")}
        ),
        "n_live": last.get("n_live"),
        # stop() drains serving replicas before the final stats, so
        # n_live is 0 at every clean stop by construction; draining means
        # the replica was alive when the fleet shut down. Only dead /
        # halted states count as losses.
        "alive_at_stop": sum(
            1 for rep in per.values()
            if (rep or {}).get("state") in ("live", "degraded", "draining")
        ),
        "deaths": last.get("deaths", len(deaths)),
        "death_causes": sorted(
            {d.get("cause") for d in deaths if d.get("cause")}
        ),
        "restarts": len(restart_boots),
        "halted": sorted({
            h.get("replica")
            for h in by_kind.get("replica_halted", [])
            if h.get("replica")
        }),
        "redispatched": last.get(
            "redispatched", len(by_kind.get("redispatch", []))
        ),
        "late_deliveries": last.get("late_deliveries"),
        # Restart boots must come from the exported-program cache: a
        # restarted replica that compiled anything took the cold path.
        "restart_boot_compiles": sum(
            int(b.get("compile_events") or 0) for b in restart_boots
        ),
        "restart_boot_cache_hits": sum(
            int(b.get("cache_hits") or 0) for b in restart_boots
        ),
        "cache": cache,
        "utilization": {
            name: rep.get("utilization") for name, rep in per.items()
        },
        "clean_stop": bool(finished),
    }


def _replica_stats(by_kind: dict) -> dict | None:
    """Per-replica accounting for stacked runs; None for solo runs.

    Folds the stacked trainer's per-replica sub-streams: ``replica_epoch``
    (one per replica per epoch: loss, lr, status), ``replica_status``
    (transition events: active -> recovering -> masked, with rollback
    counts) and ``replica_eval`` (per-replica validation losses).
    """
    epochs = by_kind.get("replica_epoch", [])
    transitions = by_kind.get("replica_status", [])
    evals = by_kind.get("replica_eval", [])
    if not epochs and not transitions:
        return None
    per: dict[int, dict] = {}
    for ev in epochs:
        r = ev.get("replica")
        row = per.setdefault(
            r,
            {
                "replica": r,
                "name": ev.get("name"),
                "epochs": 0,
                "last_loss": None,
                "last_lr": None,
                "status": "active",
                "rollbacks": 0,
                "best_val": None,
            },
        )
        row["epochs"] += 1
        row["last_loss"] = ev.get("loss")
        row["last_lr"] = ev.get("lr")
        row["status"] = ev.get("status", row["status"])
    for ev in transitions:
        row = per.get(ev.get("replica"))
        if row is None:
            continue
        row["status"] = ev.get("status", row["status"])
        row["rollbacks"] = max(
            row["rollbacks"], ev.get("rollbacks") or 0
        )
    for ev in evals:
        row = per.get(ev.get("replica"))
        if row is None or ev.get("val_loss") is None:
            continue
        if row["best_val"] is None or ev["val_loss"] < row["best_val"]:
            row["best_val"] = ev["val_loss"]
    rows = [per[r] for r in sorted(per, key=lambda x: (x is None, x))]
    return {
        "count": len(rows),
        "masked": sum(1 for r in rows if r["status"] == "masked"),
        "rollbacks": sum(r["rollbacks"] for r in rows),
        "per_replica": rows,
    }


def _utilization_stats(
    by_kind: dict,
    steps_per_sec: float | None,
    platform: str | None,
    n_devices: int | None,
) -> dict | None:
    """Roofline section from cost_profile events; None for pre-cost runs.

    Static cost (FLOPs/bytes per step from the compiler) × the measured
    post-compile step rate gives achieved FLOP/s and the roofline regime.
    A stream that recorded only ``cost_unavailable`` (backend reported no
    cost model) still gets a section — rendered "n/a", never omitted.
    The comms-bound verdict needs the aggregator's collective-wait
    attribution, so a single-stream summarize only splits compute/memory.
    """
    profiles = by_kind.get("cost_profile", [])
    unavailable = by_kind.get("cost_unavailable", [])
    if not profiles and not unavailable:
        return None
    # Hot program = the training program when present (authoritative for
    # steps/sec); otherwise the last profile seen (e.g. a serve-only run).
    hot = next(
        (e for e in profiles if str(e.get("program", "")).startswith("train")),
        profiles[-1] if profiles else None,
    )
    serve_buckets = {
        e.get("program"): e
        for e in profiles
        if str(e.get("program", "")).startswith("serve_bucket")
    }
    section = {
        "program": hot.get("program") if hot else None,
        "available": bool(hot and hot.get("available")),
        "source": hot.get("source") if hot else None,
        "flops_per_step": hot.get("flops_per_step") if hot else None,
        "bytes_per_step": hot.get("bytes_per_step") if hot else None,
        "peak_bytes": hot.get("peak_bytes") if hot else None,
        "serve_buckets": len(serve_buckets),
        "cost_unavailable_events": len(unavailable),
    }
    section.update(
        _roofline_utilization(
            section["flops_per_step"],
            section["bytes_per_step"],
            steps_per_sec,
            platform,
            n_devices,
        )
    )
    return section


def contract_violations(report: dict) -> list[str]:
    """The runtime contracts a run report is gated on (CLI exits 2)."""
    violations = []
    compiles = report["compiles"]["train_epoch"] or 0
    if compiles > 1:
        violations.append(
            f"recompile: the train epoch program compiled {compiles} times "
            "across the run (contract: exactly once — TA201 at runtime)"
        )
    if report.get("preflight") == "failed":
        violations.append("preflight: the tracelint trace audit failed")
    if report.get("diverged"):
        violations.append("divergence: the run halted on a non-finite loss")
    serve = report.get("serve")
    if serve and (serve.get("late_deliveries") or 0) > 0:
        violations.append(
            f"serve: {serve['late_deliveries']} response(s) delivered past "
            "their deadline (contract: late answers are rejected, never "
            "delivered)"
        )
    fleet = report.get("fleet")
    if fleet:
        if (
            fleet.get("clean_stop")
            and fleet.get("replicas")
            and fleet.get("alive_at_stop") == 0
        ):
            violations.append(
                "fleet: finished with ZERO live replicas (every replica "
                "dead or halted — the fleet was serving explicit sheds, "
                "not answers)"
            )
        if (fleet.get("late_deliveries") or 0) > 0:
            violations.append(
                f"fleet: {fleet['late_deliveries']} response(s) delivered "
                "past their deadline during fleet serving (the no-late-"
                "answers invariant must hold fleet-wide, failover included)"
            )
        # Only gate restart compiles when a program cache was actually in
        # play (cache events in-stream, or restart boots reporting hits):
        # a cacheless fleet legitimately recompiles on restart.
        cache_active = any((fleet.get("cache") or {}).values()) or (
            fleet.get("restart_boot_cache_hits") or 0
        ) > 0
        if cache_active and (fleet.get("restart_boot_compiles") or 0) > 0:
            violations.append(
                f"fleet: restarted replica(s) compiled "
                f"{fleet['restart_boot_compiles']} program(s) at boot "
                "(contract: restarts load from the exported-program cache "
                "with zero compiles)"
            )
    util = report.get("utilization")
    if util and (report.get("platform") or "").lower() == "tpu":
        pct = util.get("flops_utilization_pct")
        floor_pct = 100.0 * TPU_UTILIZATION_FLOOR
        if pct is not None and pct < floor_pct:
            violations.append(
                f"utilization: {util.get('program')} achieved {pct:.3f}% of "
                f"nominal TPU FLOP/s (floor {floor_pct:.1f}% — CP403); the "
                "program cannot feed the MXU, see docs/telemetry.md"
            )
    return violations


def summarize_path(target: str | Path) -> dict:
    return summarize_events(read_events(resolve_events_path(target)))


def _max_of(events: list[dict], key: str) -> float | None:
    values = [e[key] for e in events if e.get(key) is not None]
    return max(values) if values else None


def _scale(value, factor):
    return None if value is None else value * factor


def _fmt_bytes(n) -> str:
    if n is None:
        return "n/a"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def _fmt(value, spec: str = ".3g") -> str:
    return "n/a" if value is None else format(value, spec)


def render_text(report: dict) -> str:
    """Human-readable run report (the CLI's default output)."""
    t = report["time_split_s"]
    mem = report["memory"]
    st = report["step_time_ms"]
    lines = [
        f"run            : {report.get('run') or 'n/a'}",
        f"platform       : {report.get('platform') or 'n/a'} "
        f"x{report.get('n_devices') or '?'} "
        f"({report.get('strategy') or '?'}, {report.get('epoch_mode') or '?'})",
        f"epochs / steps : {report['epochs']} / {report['total_steps']}",
        f"steps/sec      : {_fmt(report['steps_per_sec'], '.2f')}",
        f"step time (ms) : p50 {_fmt(st['p50'], '.3f')} | "
        f"p99 {_fmt(st['p99'], '.3f')} | mean {_fmt(st['mean'], '.3f')} "
        f"({st['samples']} samples)",
        f"compiles       : train_epoch={report['compiles']['train_epoch']} "
        f"eval={report['compiles']['eval']} "
        f"(first compile {_fmt(report['compiles']['first_compile_s'], '.2f')}s)",
        f"time split (s) : compile {t['compile']:.2f} | device {t['device']:.2f}"
        f" | dispatch {t['dispatch']:.2f} | data-wait {t['data_wait']:.2f}"
        f" | total {t['total']:.2f}",
        f"input pipeline : data-wait {report['data']['data_wait_s']:.3f}s, "
        f"starvation {report['data']['starvation_pct']:.1f}%",
        *(
            [
                f"window store   : {_fmt_bytes(ws['bytes_read'])} paged over "
                f"{ws['epochs']} epoch(s), fault-wait {ws['fault_wait_s']:.3f}s"
                f" ({ws['fault_share_pct']:.1f}% of data-wait)"
            ]
            if (ws := report.get("window_store"))
            else []
        ),
        f"device memory  : peak {_fmt_bytes(mem['peak_bytes'])} "
        f"(live buffers {_fmt_bytes(mem['live_buffer_bytes'])}, "
        f"source: {mem['source'] or 'n/a'})",
        _render_restarts(report.get("restarts") or {}),
        f"preflight      : {report.get('preflight') or 'not recorded'}",
    ]
    sv = report.get("serve")
    if sv:
        lines.insert(
            len(lines) - 1,
            f"serve          : {sv.get('completed') or 0}/"
            f"{sv.get('requests') or 0} ok, shed {sv.get('shed') or 0}, "
            f"late-rejected {sv.get('late_converted') or 0}, "
            f"p50 {_fmt(sv.get('p50_ms'), '.2f')}ms / "
            f"p99 {_fmt(sv.get('p99_ms'), '.2f')}ms, "
            f"qps {_fmt(sv.get('qps'), '.1f')}, "
            f"swaps {sv.get('swaps_committed', 0)}+/"
            f"{sv.get('swaps_rejected', 0)}-, "
            f"{sv.get('degradations', 0)} degradation(s)",
        )
    q = report.get("quality")
    if q:
        last = q.get("last") or {}
        br = q.get("breaches") or {}
        qline = (
            f"quality        : {q.get('samples', 0)} sample(s), "
            f"input_psi {_fmt(last.get('input_psi'), '.3f')}, "
            f"pred_psi {_fmt(last.get('pred_psi'), '.3f')}, "
            f"shadow_err {_fmt(last.get('shadow_err'), '.3f')} | "
            f"breaches input {br.get('input', 0)} / "
            f"pred {br.get('prediction', 0)} / shadow {br.get('shadow', 0)}"
        )
        if q.get("swaps_rejected_quality"):
            lr = q.get("last_rejection") or {}
            qline += (
                f" | {q['swaps_rejected_quality']} quality-rejected "
                f"swap(s) (last: {lr.get('tag')} {lr.get('reason')})"
            )
        lines.insert(len(lines) - 1, qline)
    fl = report.get("fleet")
    if fl:
        cache = fl.get("cache") or {}
        util_bits = ", ".join(
            f"{name} {_fmt(u, '.2f')}"
            for name, u in sorted((fl.get("utilization") or {}).items())
        )
        lines.insert(
            len(lines) - 1,
            f"fleet          : {len(fl.get('replicas') or [])} replica(s), "
            f"{fl.get('deaths') or 0} death(s), "
            f"{fl.get('restarts') or 0} restart(s), "
            f"{fl.get('redispatched') or 0} redispatched, "
            f"halted {fl.get('halted') or 'none'} | "
            f"cache {cache.get('hits', 0)} hit(s) / "
            f"{cache.get('stores', 0)} store(s) / "
            f"{cache.get('rejections', 0)} rejection(s)"
            + (f" | util {util_bits}" if util_bits else ""),
        )
    util = report.get("utilization")
    if util is not None:
        if util.get("available"):
            line = (
                f"utilization    : {util.get('program')} | "
                f"flops/step {_fmt(util.get('flops_per_step'))} | "
                f"bytes/step {_fmt(util.get('bytes_per_step'))} | "
                f"AI {_fmt(util.get('arithmetic_intensity'), '.3g')} | "
                f"{_fmt(util.get('flops_utilization_pct'), '.4g')}% of "
                f"{report.get('platform') or '?'} peak FLOP/s | "
                f"{util.get('regime') or 'n/a'}"
            )
        else:
            line = (
                "utilization    : n/a (backend reported no cost model; "
                f"{util.get('cost_unavailable_events', 0)} "
                "cost_unavailable event(s))"
            )
        if util.get("serve_buckets"):
            line += f" | {util['serve_buckets']} serve bucket(s) profiled"
        lines.insert(len(lines) - 1, line)
    reps = report.get("replicas")
    if reps:
        lines.insert(
            len(lines) - 1,
            f"replicas       : {reps['count']} stacked, "
            f"{reps['masked']} masked, {reps['rollbacks']} rollback(s)",
        )
        for row in reps["per_replica"]:
            lines.insert(
                len(lines) - 1,
                f"  - {row.get('name') or row.get('replica')}: "
                f"{row['epochs']} epochs, "
                f"loss {_fmt(row.get('last_loss'), '.4g')}, "
                f"lr {_fmt(row.get('last_lr'), '.3g')}, "
                f"best-val {_fmt(row.get('best_val'), '.4g')}, "
                f"{row['status']}"
                + (
                    f" ({row['rollbacks']} rollback(s))"
                    if row.get("rollbacks")
                    else ""
                ),
            )
    alerts = report.get("alerts")
    if alerts:
        active = alerts.get("active") or []
        line = (
            f"slo alerts     : {alerts.get('fired', 0)} fired, "
            f"{alerts.get('resolved', 0)} resolved"
        )
        if active:
            line += " | STILL FIRING: " + ", ".join(active)
        lines.insert(len(lines) - 1, line)
        for name, row in sorted((alerts.get("rules") or {}).items()):
            lines.insert(
                len(lines) - 1,
                f"  - {name} ({row.get('slo_kind')}): "
                f"{'FIRING' if row.get('firing') else 'resolved'}, "
                f"last value {_fmt(row.get('last_value'), '.4g')} "
                f"vs threshold {_fmt(row.get('threshold'), '.4g')} "
                f"({row.get('fired', 0)} fire(s))",
            )
    gs = report.get("grad_sync") or {}
    if gs.get("collectives_per_step") is not None:
        lines.insert(
            len(lines) - 1,
            f"grad sync      : {gs['collectives_per_step']} collective(s)"
            f"/step, {_fmt_bytes(gs['grad_reduce_bytes'])} reduced/step "
            f"({gs.get('flat_buffers')} flat buffer(s))",
        )
    for w in report.get("profile_windows", []):
        lines.append(
            f"profiler trace : epochs {w['start_epoch']}..{w['end_epoch']} "
            f"-> {w['trace_dir']}"
        )
    if report["violations"]:
        lines.append("CONTRACT VIOLATIONS:")
        lines.extend(f"  - {v}" for v in report["violations"])
    else:
        lines.append("contracts      : ok")
    return "\n".join(lines)


def _render_restarts(r: dict) -> str:
    if not r or (
        not r.get("restarts")
        and not r.get("degradations")
        and not r.get("rollbacks")
    ):
        return "restarts       : none"
    parts = [f"{r.get('restarts', 0)} ({r.get('attempts', 1)} attempts)"]
    parts.append(f"lost work {_fmt(r.get('lost_work_s'), '.1f')}s")
    if r.get("rollbacks"):
        parts.append(f"{r['rollbacks']} rollback(s)")
    parts.append(f"{r.get('degradations', 0)} degradation event(s)")
    if r.get("trace_id"):
        parts.append(f"trace {r['trace_id']}")
    return "restarts       : " + ", ".join(parts)


def render_json(report: dict) -> str:
    return json.dumps(report, indent=2, default=str)
