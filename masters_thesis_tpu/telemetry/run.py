"""Per-run telemetry: run directory, event stream, registry, and the
async-dispatch-aware epoch accounting the Trainer drives.

The central constraint is the framework's own performance contract: the
steady-state hot loop must not gain host fences (tracelint TA202/TL105).
So :class:`EpochRecorder` measures unfenced epochs boundary-to-boundary —
epoch N's wall closes when epoch N+1 is dispatched, which in the pipelined
trainer equals N's device time once the loop self-paces on the deferred
metric readback — and only epochs the trainer fences ANYWAY (val epochs,
the first epoch, profiler-window epochs) carry an exact ``device_s`` from
the fence itself. Compile events are not inferred from timing: they are
counted from jit cache-miss deltas (``train.steps.jit_cache_size``), which
turns tracelint's TA201 "compiles exactly once" from a preflight assertion
into a measured runtime counter.
"""

from __future__ import annotations

import os
import sys
import time
from pathlib import Path
from typing import Callable

from masters_thesis_tpu.telemetry.events import EventSink
from masters_thesis_tpu.telemetry.registry import MetricsRegistry

# Fleet identity env fallbacks, in priority order: the standard JAX cluster
# vars (exported by parallel.mesh.distributed_initialize for child tools),
# then the in-repo multi-host sweep sharding vars.
_IDENTITY_ENV = (
    ("JAX_PROCESS_INDEX", "JAX_PROCESS_COUNT"),
    ("MT_HOST_INDEX", "MT_NUM_HOSTS"),
)


def process_identity() -> tuple[int | None, int | None]:
    """(process_index, process_count) for tagging telemetry streams.

    Prefers a live jax backend iff jax is already imported (never imports
    it: telemetry must stay usable, and hang-free, in host-only tooling);
    falls back to the cluster env (``JAX_PROCESS_INDEX``/``MT_HOST_INDEX``)
    so streams written BEFORE ``jax.distributed`` init — or by jax-free
    simulated workers — still merge unambiguously in the aggregator.
    """
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index()), int(jax.process_count())
        except Exception:  # backend not up yet — fall through to env
            pass
    for index_key, count_key in _IDENTITY_ENV:
        index = os.environ.get(index_key)
        if index is None:
            continue
        try:
            count = os.environ.get(count_key)
            return int(index), (int(count) if count else None)
        except ValueError:  # malformed env is not identity
            continue
    return None, None


def _process_index() -> int | None:
    return process_identity()[0]


class TelemetryRun:
    """One run's telemetry: ``<run_dir>/events.jsonl`` + a live registry.

    Append-semantics: constructing a TelemetryRun over an existing run dir
    continues its event stream (the resumed-training case) — consumers
    group by the ``run`` envelope field when they care about attempts.
    """

    def __init__(
        self,
        run_dir: str | Path,
        run_id: str | None = None,
        meta: dict | None = None,
    ):
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        proc, nproc = process_identity()
        if run_id is None:
            run_id = time.strftime("%Y%m%d-%H%M%S") + f"-p{proc or 0}"
        self.run_id = run_id
        tags = {}
        if proc is not None:
            tags["process_index"] = proc
        if nproc is not None:
            tags["process_count"] = nproc
        self.registry = MetricsRegistry(tags=tags)
        self.sink = EventSink(
            self.run_dir / "events.jsonl", run_id=run_id, proc=proc,
            nproc=nproc,
        )
        # Optional flight recorder (attach_flight_recorder): every emitted
        # event is mirrored into its bounded ring so a crashdump carries the
        # run's recent history without re-reading the stream.
        self.recorder = None
        self._tracer = None
        if meta:
            self.event("run_meta", meta=meta)

    # ------------------------------------------------------------- emitters

    def event(self, kind: str, **payload) -> dict:
        ev = self.sink.emit(kind, **payload)
        if self.recorder is not None:
            self.recorder.record(ev)
        return ev

    @property
    def tracer(self):
        """This run's span writer (lazy; adopts ``MTT_TRACE_ID`` /
        ``MTT_PARENT_SPAN`` from the environment). Its open spans flow
        into the flight recorder's heartbeat/crashdump sidecars so a
        killed process's in-flight work is closed as ``aborted``."""
        if self._tracer is None:
            from masters_thesis_tpu.telemetry.trace import Tracer

            self._tracer = Tracer(self.sink)
        return self._tracer

    def attach_flight_recorder(self, **kwargs):
        """Attach (or return the already-attached) in-process flight
        recorder for this run: crashdump.json on SIGTERM/SIGQUIT/hang,
        heartbeat.json for the fleet aggregator. Idempotent — the first
        attach wins, so a Trainer sharing a caller-owned TelemetryRun does
        not stack recorders."""
        if self.recorder is None:
            from masters_thesis_tpu.telemetry.flightrec import FlightRecorder
            from masters_thesis_tpu.telemetry.trace import (
                adopt_orphaned_spans,
            )

            # A resumed-in-place attempt is about to overwrite the dead
            # predecessor's sidecars — the only record of its open spans.
            # Close them into the stream first, or the predecessor's
            # child spans orphan once the new heartbeat lands.
            adopt_orphaned_spans(self.run_dir, self.sink)
            self.recorder = FlightRecorder(
                self.run_dir, run_id=self.run_id, sink=self.sink, **kwargs
            )
            # Late-bound so the tracer can attach before OR after the
            # recorder without either knowing construction order.
            self.recorder.open_spans_provider = (
                lambda: self._tracer.open_spans()
                if self._tracer is not None else []
            )
        return self.recorder

    def counter(self, name: str):
        return self.registry.counter(name)

    def gauge(self, name: str):
        return self.registry.gauge(name)

    def histogram(self, name: str):
        return self.registry.histogram(name)

    def sample_memory(self, epoch: int | None = None) -> dict | None:
        """Gauge + event for device memory and live buffers (host-side
        metadata reads only — no device sync)."""
        snap = device_memory_snapshot()
        if snap is None:
            return None
        for key in ("bytes_in_use", "peak_bytes_in_use", "live_buffer_bytes"):
            if snap.get(key) is not None:
                self.gauge(f"device/{key}").set(snap[key])
        self.gauge("device/live_buffers").set(snap["live_buffers"])
        self.event("memory", epoch=epoch, **snap)
        return snap

    def snapshot_metrics(self) -> dict:
        """Emit the registry's final aggregate state as a ``metrics`` event."""
        snap = self.registry.snapshot()
        self.event("metrics", **snap)
        return snap

    def close(self) -> None:
        # Spans an exception path left open are closed `aborted` BEFORE
        # the recorder writes its final (closed) heartbeat — a cleanly
        # closed stream claiming open spans is the trace CLI's
        # `unclosed` bug class, and must only mean real tracer misuse.
        if self._tracer is not None:
            self._tracer.close_all(status="aborted")
        if self.recorder is not None:
            self.recorder.close()
        self.sink.close()


def device_memory_snapshot() -> dict | None:
    """Device memory stats summed over devices, with a live-buffer fallback.

    ``memory_stats()`` is backend-dependent (TPU reports bytes_in_use /
    peak_bytes_in_use; the CPU client usually reports nothing), so the
    snapshot always also carries the bytes of live ``jax.Array``\\ s — an
    upper-bound proxy that exists on every backend. Returns None when jax
    was never imported (pure host tooling must not pull it in).
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    in_use = peak = None
    source = "live_arrays"
    try:
        for dev in jax.devices():
            stats = dev.memory_stats()
            if not stats:
                continue
            if "bytes_in_use" in stats:
                in_use = (in_use or 0) + int(stats["bytes_in_use"])
                source = "memory_stats"
            if "peak_bytes_in_use" in stats:
                peak = (peak or 0) + int(stats["peak_bytes_in_use"])
    except Exception:  # a wedged/odd backend must not kill the run
        pass
    live_bytes = 0
    live_count = 0
    try:
        for arr in jax.live_arrays():
            live_count += 1
            live_bytes += int(getattr(arr, "nbytes", 0) or 0)
    except Exception:
        pass
    return {
        "bytes_in_use": in_use,
        "peak_bytes_in_use": peak,
        "live_buffer_bytes": live_bytes,
        "live_buffers": live_count,
        "source": source,
    }


class CompileTracker:
    """Counts XLA compiles of a jitted callable via cache-miss deltas.

    ``poll()`` returns how many new executables the function's jit cache
    gained since the last poll — 1 after the warmup epoch, 0 in steady
    state, >0 exactly when the program's signature leaked (the TA201 bug
    class) and the run silently recompiled.
    """

    def __init__(self, fn, size_fn: Callable | None = None):
        self._fn = fn
        self._size_fn = size_fn or _default_cache_size
        self._last = self._size() or 0
        self.total = 0

    def _size(self) -> int | None:
        try:
            return self._size_fn(self._fn)
        except Exception:
            return None

    def poll(self) -> int:
        size = self._size()
        if size is None:
            return 0
        delta = max(0, size - self._last)
        self._last = size
        self.total += delta
        return delta


def _default_cache_size(fn) -> int | None:
    size = getattr(fn, "_cache_size", None)
    return size() if callable(size) else None


class EpochRecorder:
    """Turns the trainer's loop boundaries into ``epoch`` events.

    Protocol per epoch: ``begin`` (finalizes the previous unfenced epoch
    boundary-to-boundary) -> ``dispatched`` (host dispatch time, compile
    delta, data wait) -> optionally ``fenced`` (exact device wait, only at
    fences the trainer takes anyway) -> ... -> ``finish`` once after the
    loop's closing ``block_until_ready``.
    """

    def __init__(
        self,
        tel: TelemetryRun,
        steps_per_epoch: int,
        on_epoch: Callable[[dict], None] | None = None,
        span_parent=None,
    ):
        self.tel = tel
        self.steps_per_epoch = steps_per_epoch
        # Called with each finalized epoch event payload — the trainer uses
        # it to mirror perf scalars into TensorBoard next to the loss curves.
        self.on_epoch = on_epoch
        # When a parent span is given (the trainer's fit root), every
        # finalized epoch also lands as a retroactive `train.epoch` span —
        # same boundaries, same no-added-fences contract, just addressable
        # by the trace CLI's critical-path attribution.
        self.span_parent = span_parent
        self._open: dict | None = None  # the epoch awaiting its wall close
        self._t0: float | None = None
        self._wall0: float | None = None  # wall clock twin of _t0

    # The trainer calls these in loop order; all are no-throw by design —
    # a telemetry bug must never kill a training run.

    def begin(self, epoch: int) -> None:
        now = time.perf_counter()
        self._finalize(now, fenced=False, device_s=None)
        self._t0 = now
        self._wall0 = time.time()
        self._open = {"epoch": epoch}

    def dispatched(
        self, compiles: int = 0, data_wait_s: float = 0.0
    ) -> None:
        if self._open is None or self._t0 is None:
            return
        self._open["dispatch_s"] = time.perf_counter() - self._t0
        self._open["compile_events"] = compiles
        self._open["data_wait_s"] = data_wait_s
        if compiles:
            self.tel.counter("train/epoch_compiles").inc(compiles)
        if data_wait_s:
            self.tel.counter("data/get_wait_s").inc(data_wait_s)

    def fenced(self, device_s: float) -> None:
        self._finalize(time.perf_counter(), fenced=True, device_s=device_s)

    def finish(self) -> None:
        self._finalize(time.perf_counter(), fenced=True, device_s=None)

    def _finalize(self, now: float, fenced: bool, device_s: float | None):
        if self._open is None or self._t0 is None:
            return
        ev, self._open = self._open, None
        wall = now - self._t0
        wall0, self._t0, self._wall0 = self._wall0, None, None
        steps = self.steps_per_epoch
        compiled = bool(ev.get("compile_events"))
        self.tel.counter("train/epochs").inc()
        self.tel.counter("train/steps").inc(steps)
        self.tel.histogram("train/epoch_wall_s").observe(wall)
        if not compiled and steps > 0:
            self.tel.histogram("train/step_time_s").observe(wall / steps)
        payload = self.tel.event(
            "epoch",
            epoch=ev["epoch"],
            steps=steps,
            wall_s=wall,
            dispatch_s=ev.get("dispatch_s"),
            device_s=device_s,
            data_wait_s=ev.get("data_wait_s", 0.0),
            compile_events=ev.get("compile_events", 0),
            compiled=compiled,
            fenced=fenced,
            steps_per_sec=(steps / wall) if wall > 0 else None,
        )
        if self.span_parent is not None and wall0 is not None:
            self.tel.tracer.emit_span(
                "train.epoch",
                start_ts=wall0,
                dur_s=wall,
                parent=self.span_parent,
                cat="train",
                epoch=ev["epoch"],
                dispatch_s=ev.get("dispatch_s"),
                device_s=device_s,
                data_wait_s=ev.get("data_wait_s", 0.0),
                compiled=compiled,
                fenced=fenced,
            )
        if self.on_epoch is not None:
            try:
                self.on_epoch(payload)
            except Exception:  # mirroring must never kill a training run
                pass
