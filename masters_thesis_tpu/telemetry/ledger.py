"""Persistent perf ledger: append-only JSONL history of bench points.

``results/perf_ledger.jsonl`` turns the loose ``BENCH_r*.json`` trajectory
into a gated, queryable history: one schema-versioned record per measured
bench point (git rev, backend, mesh shape, pack width, FLOPs, steps/s,
utilization), appended by ``bench.py`` every run and diffed by
``python -m masters_thesis_tpu.telemetry ledger`` — which exits 2 when
the latest round regresses any gated metric (steps/s, utilization,
cells/hour, serving knee QPS, or restart time) by more than 15%
against the baseline window AT EQUAL CONFIG (same point, backend, mesh,
batch size, pack width; a CPU-degraded round is never compared against a
TPU baseline).

Stdlib-only by contract, like :mod:`report`: the ledger CLI runs on
operator machines and in CI where importing a backend can hang on a
wedged relay lease (docs/OPERATIONS.md).
"""

from __future__ import annotations

import json
import subprocess
import time
from pathlib import Path

LEDGER_SCHEMA_VERSION = 1
DEFAULT_LEDGER_PATH = Path("results") / "perf_ledger.jsonl"
#: Regression gate: a latest-round gated metric moving more than this
#: far in its bad direction vs the baseline median (equal config) exits 2.
REGRESSION_PCT = 15.0

#: Gated metrics and their good direction: +1 = higher is better (a drop
#: regresses), -1 = lower is better (a rise regresses — restart time).
#: serve/knee_qps and serve/restart_s rows ride the same gate as the
#: training throughput rows.
GATED_METRICS = (
    ("steps_per_sec", +1),
    ("utilization_pct", +1),
    ("cells_per_hour", +1),
    ("knee_qps", +1),
    ("restart_s", -1),
)

#: The fields that define "equal config" — a row is only ever compared
#: against baseline rows agreeing on ALL of these.
CONFIG_KEYS = (
    "point",
    "platform",
    "mesh_shape",
    "batch_size",
    "objective",
    "pack_width",
)


def git_rev(repo_root: Path | None = None) -> str | None:
    """Short git revision of the repo, or None outside a checkout."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=repo_root or Path(__file__).resolve().parents[2],
            capture_output=True,
            text=True,
            timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    rev = out.stdout.strip()
    return rev if out.returncode == 0 and rev else None


def ledger_record(
    *,
    point: str,
    round_id: str,
    platform: str | None,
    steps_per_sec: float | None,
    objective: str | None = None,
    batch_size: int | None = None,
    mesh_shape: list[int] | None = None,
    pack_width: int | None = None,
    flops_per_step: float | None = None,
    bytes_per_step: float | None = None,
    peak_memory_bytes: int | None = None,
    utilization_pct: float | None = None,
    regime: str | None = None,
    rev: str | None = None,
    ts: float | None = None,
    **extra,
) -> dict:
    """One schema-versioned ledger row. Unknown fields ride in ``extra``."""
    rec = {
        "schema": LEDGER_SCHEMA_VERSION,
        "ts": time.time() if ts is None else ts,
        "round": round_id,
        "git_rev": rev if rev is not None else git_rev(),
        "point": point,
        "platform": platform,
        "objective": objective,
        "batch_size": batch_size,
        "mesh_shape": mesh_shape,
        "pack_width": pack_width,
        "steps_per_sec": steps_per_sec,
        "flops_per_step": flops_per_step,
        "bytes_per_step": bytes_per_step,
        "peak_memory_bytes": peak_memory_bytes,
        "utilization_pct": utilization_pct,
        "regime": regime,
    }
    rec.update(extra)
    return rec


def append_record(path: str | Path, record: dict) -> None:
    """Append one row; parents are created, the file never rewritten."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("a", encoding="utf-8") as fh:
        fh.write(json.dumps(record, default=str) + "\n")


def read_ledger(path: str | Path) -> list[dict]:
    """All parseable rows, in file order; torn tails are tolerated (a
    killed bench run must not corrupt the whole history)."""
    path = Path(path)
    if not path.is_file():
        return []
    rows: list[dict] = []
    with path.open("r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict):
                rows.append(obj)
    return rows


def config_key(rec: dict) -> tuple:
    def _norm(v):
        return tuple(v) if isinstance(v, list) else v

    return tuple(_norm(rec.get(k)) for k in CONFIG_KEYS)


def _round_order(rows: list[dict]) -> list[str]:
    """Distinct round ids ordered by first appearance (the file is
    append-only, so file order IS time order)."""
    seen: list[str] = []
    for rec in rows:
        rid = rec.get("round")
        if rid is not None and rid not in seen:
            seen.append(rid)
    return seen


def _median(values: list[float]) -> float | None:
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    mid = len(vals) // 2
    if len(vals) % 2:
        return vals[mid]
    return 0.5 * (vals[mid - 1] + vals[mid])


def ledger_diff(
    rows: list[dict],
    *,
    threshold_pct: float = REGRESSION_PCT,
    baseline_rounds: int | None = None,
) -> dict:
    """Diff the latest round against the baseline window, at equal config.

    For every config measured in the latest round, the baseline is the
    MEDIAN over all earlier rounds' rows with the same config key (or the
    last ``baseline_rounds`` of them). A config with no baseline is
    reported as new, never as a regression. Exit semantics live in
    ``report["regressed"]`` — True when any ``GATED_METRICS`` entry moved
    more than ``threshold_pct`` in its bad direction (a drop for
    throughput-like metrics, a rise for restart time).
    """
    order = _round_order(rows)
    if not order:
        return {
            "rounds": 0,
            "latest_round": None,
            "compared": [],
            "new_configs": [],
            "regressions": [],
            "regressed": False,
            "threshold_pct": threshold_pct,
        }
    latest = order[-1]
    baseline_ids = order[:-1]
    if baseline_rounds is not None:
        baseline_ids = baseline_ids[-baseline_rounds:]
    latest_rows = [r for r in rows if r.get("round") == latest]
    base_rows = [r for r in rows if r.get("round") in set(baseline_ids)]
    by_key: dict[tuple, list[dict]] = {}
    for rec in base_rows:
        by_key.setdefault(config_key(rec), []).append(rec)

    compared: list[dict] = []
    new_configs: list[dict] = []
    regressions: list[dict] = []
    for rec in latest_rows:
        key = config_key(rec)
        baseline = by_key.get(key)
        if not baseline:
            new_configs.append({"point": rec.get("point"), "config": key})
            continue
        row = {
            "point": rec.get("point"),
            "platform": rec.get("platform"),
            "batch_size": rec.get("batch_size"),
            "baseline_rounds": len({b.get("round") for b in baseline}),
        }
        regressed_metrics: list[str] = []
        for metric, direction in GATED_METRICS:
            latest_v = rec.get(metric)
            base_v = _median([b.get(metric) for b in baseline])
            row[metric] = {"latest": latest_v, "baseline": base_v}
            if latest_v is None or base_v is None or base_v <= 0:
                continue
            delta_pct = 100.0 * (latest_v - base_v) / base_v
            row[metric]["delta_pct"] = round(delta_pct, 2)
            if direction * delta_pct < -threshold_pct:
                regressed_metrics.append(metric)
        row["regressed_metrics"] = regressed_metrics
        compared.append(row)
        if regressed_metrics:
            regressions.append(row)
    return {
        "rounds": len(order),
        "latest_round": latest,
        "baseline_window": baseline_ids,
        "compared": compared,
        "new_configs": new_configs,
        "regressions": regressions,
        "regressed": bool(regressions),
        "threshold_pct": threshold_pct,
    }


def diff_path(
    path: str | Path,
    *,
    threshold_pct: float = REGRESSION_PCT,
    baseline_rounds: int | None = None,
) -> dict:
    report = ledger_diff(
        read_ledger(path),
        threshold_pct=threshold_pct,
        baseline_rounds=baseline_rounds,
    )
    report["path"] = str(path)
    return report


def _fmt(value, spec: str = ".3g") -> str:
    return "n/a" if value is None else format(value, spec)


def render_ledger_text(report: dict) -> str:
    lines = [
        f"ledger         : {report.get('path', '?')} "
        f"({report['rounds']} round(s))",
    ]
    if not report["rounds"]:
        lines.append("verdict        : empty ledger — nothing to gate")
        return "\n".join(lines)
    lines.append(
        f"latest round   : {report['latest_round']} vs "
        f"{len(report.get('baseline_window') or [])} baseline round(s), "
        f"threshold {report['threshold_pct']:.0f}%"
    )
    for row in report["compared"]:
        sps = row["steps_per_sec"]
        util = row["utilization_pct"]
        mark = " <-- REGRESSED" if row["regressed_metrics"] else ""
        line = (
            f"  {row['point']:<16s} [{row.get('platform') or '?'}] "
            f"sps {_fmt(sps['latest'], '.2f')} vs {_fmt(sps['baseline'], '.2f')}"
            f" ({_fmt(sps.get('delta_pct'), '+.1f')}%) | "
            f"util {_fmt(util['latest'], '.3f')}% vs "
            f"{_fmt(util['baseline'], '.3f')}%"
            f" ({_fmt(util.get('delta_pct'), '+.1f')}%)"
        )
        for metric, label, spec in (
            ("cells_per_hour", "cells/h", ".1f"),
            ("knee_qps", "knee", ".1f"),
            ("restart_s", "restart", ".3f"),
        ):
            m = row.get(metric) or {}
            if m.get("latest") is not None:
                line += (
                    f" | {label} {_fmt(m['latest'], spec)} vs "
                    f"{_fmt(m['baseline'], spec)}"
                    f" ({_fmt(m.get('delta_pct'), '+.1f')}%)"
                )
        lines.append(line + mark)
    for row in report["new_configs"]:
        lines.append(f"  {row['point']:<16s} new config (no baseline)")
    if report["regressed"]:
        lines.append(
            f"verdict        : REGRESSION — {len(report['regressions'])} "
            f"config(s) dropped >{report['threshold_pct']:.0f}% vs baseline"
        )
    elif report["compared"]:
        lines.append("verdict        : ok — no regression at equal config")
    else:
        lines.append(
            "verdict        : no comparable configs (first round, or "
            "config drift) — nothing to gate"
        )
    return "\n".join(lines)
