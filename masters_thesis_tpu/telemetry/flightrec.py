"""In-process flight recorder: a killed or hung run still explains itself.

The failure modes that dominate TPU operation leave no forensic state by
default: a watchdog SIGKILL erases the child's stdout, a wedged runtime
hangs a process silently inside a dispatch, a straggler stalls the fleet's
psum with nothing in any log. The recorder keeps a bounded ring buffer of
recent telemetry events, the last-known step/compile/memory state, and
short histories of divergence-relevant scalars (loss, lr) — and dumps all
of it, plus every thread's stack, to ``crashdump.json`` under the run dir
when the process is told to die (SIGTERM/SIGQUIT), when a fatal signal
fires (via :mod:`faulthandler` into ``fatal.log``), or when the heartbeat
watchdog sees no progress past ``hang_timeout_s``.

It also writes ``heartbeat.json`` (atomic replace) every few seconds so
the OUTSIDE world can tell a live process from a dead one even after
SIGKILL — the one signal no handler survives. The fleet aggregator
(:mod:`~masters_thesis_tpu.telemetry.aggregate`) reads both files next to
each ``events.jsonl`` stream to reconstruct per-process exit status.

Stdlib-only by the package contract: simulated fleet workers and operator
tooling construct recorders without jax in the process. Everything on the
trainer's hot path (``beat``/``record``/``note``/``track_scalar``) is a
host-memory update — no fences, no I/O; file writes happen on the
heartbeat thread or at dump time.
"""

from __future__ import annotations

import collections
import faulthandler
import json
import os
import signal
import socket
import sys
import threading
import time
import traceback
from pathlib import Path

from masters_thesis_tpu.telemetry.run import process_identity
from masters_thesis_tpu.telemetry.schedule import GLOBAL_SCHEDULE

CRASHDUMP_FILENAME = "crashdump.json"
HEARTBEAT_FILENAME = "heartbeat.json"
FATAL_LOG_FILENAME = "fatal.log"

# Signals that mean "you are being killed; say your last words". SIGKILL is
# uncatchable by design — that case is reconstructed from the heartbeat gap.
_DUMP_SIGNALS = ("SIGTERM", "SIGQUIT")


def _atomic_write_json(path: Path, obj: dict) -> None:
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(obj, indent=2, default=str))
    os.replace(tmp, path)


def _all_thread_stacks() -> list[dict]:
    """Snapshot every thread's Python stack (the hang forensics core)."""
    names = {t.ident: t for t in threading.enumerate()}
    stacks = []
    for ident, frame in sys._current_frames().items():
        thread = names.get(ident)
        stacks.append(
            {
                "ident": ident,
                "name": thread.name if thread else "?",
                "daemon": thread.daemon if thread else None,
                "stack": [
                    line.rstrip()
                    for line in traceback.format_stack(frame)
                ],
            }
        )
    return stacks


class FlightRecorder:
    """Bounded in-memory history + crashdump/heartbeat files for one run.

    Hot-path API (host memory only, safe at any frequency):

    - ``beat(phase=, epoch=)`` — a progress marker; the hang watchdog
      measures staleness from the last beat.
    - ``record(event)`` — mirror a telemetry event into the ring buffer
      (wired automatically by ``TelemetryRun.attach_flight_recorder``).
    - ``note(**state)`` — merge into the last-known state dict (step,
      compile count, memory snapshot, ...).
    - ``track_scalar(name, value)`` — append to a bounded per-name history
      (recent loss / lr: the divergence context of a crashdump).

    ``hang_timeout_s=None`` (the default, overridable via the
    ``MTT_HANG_TIMEOUT_S`` env var) disables the hang watchdog but keeps
    heartbeats and signal dumps.
    """

    def __init__(
        self,
        run_dir: str | Path,
        run_id: str | None = None,
        sink=None,
        ring_size: int = 256,
        scalar_history: int = 64,
        heartbeat_interval_s: float = 2.0,
        hang_timeout_s: float | None = None,
        install_signal_handlers: bool = True,
        enable_faulthandler: bool = True,
    ):
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.run_id = run_id
        self.sink = sink
        proc, nproc = process_identity()
        self.proc = proc
        self.nproc = nproc
        self._host = socket.gethostname()
        self._pid = os.getpid()
        self._ring: collections.deque = collections.deque(maxlen=ring_size)
        self._scalars: dict[str, collections.deque] = {}
        self._scalar_history = scalar_history
        self._state: dict = {}
        self._lock = threading.Lock()
        self._dump_lock = threading.Lock()
        self._dumped_reasons: set[str] = set()
        self.heartbeat_interval_s = max(0.05, float(heartbeat_interval_s))
        if hang_timeout_s is None:
            env = os.environ.get("MTT_HANG_TIMEOUT_S")
            if env:
                try:
                    hang_timeout_s = float(env)
                except ValueError:
                    hang_timeout_s = None
        self.hang_timeout_s = hang_timeout_s
        # Set by TelemetryRun.attach_flight_recorder: a zero-arg callable
        # returning the tracer's open-span snapshots, flushed into every
        # heartbeat and crashdump so the trace CLI can close a killed
        # process's in-flight spans as `aborted` instead of losing them.
        self.open_spans_provider = None
        self._beats = 0
        self._phase = "init"
        self._epoch: int | None = None
        self._last_beat_mono = time.monotonic()
        self._last_beat_ts = time.time()
        self._hang_dumped = False
        self._closed = threading.Event()
        self._prev_handlers: dict[int, object] = {}
        self._fatal_file = None
        if install_signal_handlers:
            self._install_signal_handlers()
        if enable_faulthandler:
            self._enable_faulthandler()
        self._write_heartbeat()
        self._thread = threading.Thread(
            target=self._heartbeat_loop,
            name="flightrec-heartbeat",
            daemon=True,
        )
        self._thread.start()

    # -------------------------------------------------------- hot-path API

    def beat(self, phase: str | None = None, epoch: int | None = None) -> None:
        self._beats += 1  # mtt: disable=CL502 -- single writer: only the training thread beats; readers tolerate staleness
        self._last_beat_mono = time.monotonic()
        self._last_beat_ts = time.time()
        if phase is not None:
            self._phase = phase
        if epoch is not None:
            self._epoch = epoch  # mtt: disable=CL502 -- single-writer int store from the training thread; dump/heartbeat tolerate staleness
        self._hang_dumped = False  # progress resets the hang latch

    def record(self, event: dict) -> None:
        self._ring.append(event)
        kind = event.get("kind")
        # The last-known state a postmortem reader wants at a glance,
        # without digging through the ring.
        if kind in ("epoch", "memory", "eval", "run_started", "run_finished"):
            with self._lock:
                self._state[f"last_{kind}"] = {
                    k: v for k, v in event.items() if k != "kind"
                }

    def note(self, **state) -> None:
        with self._lock:
            self._state.update(state)

    def track_scalar(self, name: str, value: float) -> None:
        hist = self._scalars.get(name)
        if hist is None:
            hist = self._scalars[name] = collections.deque(
                maxlen=self._scalar_history
            )
        try:
            hist.append(float(value))
        except (TypeError, ValueError):
            hist.append(None)

    # ------------------------------------------------------------- dumping

    @property
    def crashdump_path(self) -> Path:
        return self.run_dir / CRASHDUMP_FILENAME

    @property
    def heartbeat_path(self) -> Path:
        return self.run_dir / HEARTBEAT_FILENAME

    def dump(self, reason: str, force: bool = False) -> Path | None:
        """Write ``crashdump.json``; no-throw, reentrancy-safe, first dump
        per reason wins (a SIGTERM arriving during a hang dump must not
        corrupt the file mid-write)."""
        if not self._dump_lock.acquire(blocking=force):
            return None
        try:
            if reason in self._dumped_reasons and not force:
                return self.crashdump_path
            self._dumped_reasons.add(reason)
            now = time.time()
            # Bounded: this runs on the signal path; if the interrupted
            # main-thread frame holds _lock (record()/note() mid-update),
            # a blocking acquire would self-deadlock the process. Fall
            # back to a best-effort racy copy — a slightly torn state
            # map in a crashdump beats no crashdump.
            if self._lock.acquire(timeout=0.25):
                try:
                    state = dict(self._state)
                finally:
                    self._lock.release()
            else:
                try:
                    state = dict(self._state)  # mtt: disable=CL502 -- deliberate racy fallback; see bounded acquire above
                except RuntimeError:
                    state = {}
            dump = {
                "reason": reason,
                "ts": now,
                "run": self.run_id,
                "host": self._host,
                "pid": self._pid,
                "proc": self.proc,
                "nproc": self.nproc,
                "phase": self._phase,
                "epoch": self._epoch,
                "beats": self._beats,
                "age_since_beat_s": time.monotonic() - self._last_beat_mono,
                "state": state,
                "scalars": {k: list(v) for k, v in self._scalars.items()},
                "open_spans": self._open_spans(),
                "threads": _all_thread_stacks(),
                "ring": list(self._ring),
            }
            sched = GLOBAL_SCHEDULE.snapshot()
            if sched["n"]:
                dump["collective_schedule"] = sched
            _atomic_write_json(self.crashdump_path, dump)  # mtt: disable=CL503 -- _dump_lock exists precisely to serialize crashdump I/O
            self._write_heartbeat(crashdump=str(self.crashdump_path))  # mtt: disable=CL503 -- same serialized-forensics contract as the dump write
            if self.sink is not None:
                try:
                    # The stream flushes per line, so this survives the
                    # process dying right after the handler returns.
                    self.sink.try_emit(  # mtt: disable=CL503 -- bounded handler-path emit; _dump_lock serializes forensics I/O by design
                        "crashdump",
                        reason=reason,
                        path=str(self.crashdump_path),
                        phase=self._phase,
                        epoch=self._epoch,
                    )
                except Exception:
                    pass
            return self.crashdump_path
        except Exception:
            return None  # forensics must never kill (or mask) the run
        finally:
            self._dump_lock.release()

    # ----------------------------------------------------------- heartbeat

    def _open_spans(self) -> list[dict]:
        provider = self.open_spans_provider
        if provider is None:
            return []
        try:
            return list(provider())
        except Exception:
            return []  # forensics must never kill the run

    def _write_heartbeat(self, **extra) -> None:
        try:
            # The schedule chain rides the heartbeat: the heartbeat
            # thread keeps publishing while the main thread is wedged in
            # a collective — exactly when the cross-rank audit needs it.
            sched = GLOBAL_SCHEDULE.snapshot()
            if sched["n"]:
                extra.setdefault("collective_schedule", sched)
            _atomic_write_json(
                self.heartbeat_path,
                {
                    "ts": time.time(),
                    "last_beat_ts": self._last_beat_ts,
                    "run": self.run_id,
                    "host": self._host,
                    "pid": self._pid,
                    "proc": self.proc,
                    "nproc": self.nproc,
                    "phase": self._phase,
                    "epoch": self._epoch,  # mtt: disable=CL502 -- advisory heartbeat snapshot; a stale epoch is harmless
                    "beats": self._beats,
                    "interval_s": self.heartbeat_interval_s,
                    "hang_timeout_s": self.hang_timeout_s,
                    "open_spans": self._open_spans(),
                    **extra,
                },
            )
        except OSError:
            pass  # a full disk must not take the run down with it

    def _heartbeat_loop(self) -> None:
        period = self.heartbeat_interval_s
        if self.hang_timeout_s:
            period = min(period, max(0.05, self.hang_timeout_s / 4.0))
        while not self._closed.wait(period):
            self._write_heartbeat()
            if self.hang_timeout_s and not self._hang_dumped:
                age = time.monotonic() - self._last_beat_mono
                if age > self.hang_timeout_s:
                    self._hang_dumped = True
                    self.dump(
                        f"hang: no progress beat for {age:.1f}s "
                        f"(timeout {self.hang_timeout_s:.1f}s, "
                        f"phase {self._phase!r})"
                    )

    # ------------------------------------------------------------- signals

    def _install_signal_handlers(self) -> None:
        if threading.current_thread() is not threading.main_thread():
            return  # signal.signal only works from the main thread
        for name in _DUMP_SIGNALS:
            signum = getattr(signal, name, None)
            if signum is None:
                continue
            try:
                self._prev_handlers[signum] = signal.signal(
                    signum, self._on_signal
                )
            except (ValueError, OSError):
                continue

    def _on_signal(self, signum, frame) -> None:
        self.dump(f"signal:{signal.Signals(signum).name}")
        # Restore whatever was there and re-deliver, so the process dies
        # with the correct wait status (and chained handlers still run).
        prev = self._prev_handlers.get(signum, signal.SIG_DFL)
        try:
            signal.signal(signum, prev if callable(prev) or prev in (
                signal.SIG_DFL, signal.SIG_IGN) else signal.SIG_DFL)
        except (ValueError, OSError):
            pass
        if callable(prev) and prev not in (signal.SIG_DFL, signal.SIG_IGN):
            prev(signum, frame)
        else:
            os.kill(self._pid, signum)

    def _enable_faulthandler(self) -> None:
        """Fatal signals (SIGSEGV/SIGABRT/...) dump all-thread stacks to
        ``fatal.log`` — a C-level crash can't run Python handlers, but
        faulthandler's async-signal-safe writer still gets the stacks out."""
        if faulthandler.is_enabled():
            return  # someone else owns the global fatal handler
        try:
            self._fatal_file = open(
                self.run_dir / FATAL_LOG_FILENAME, "w", encoding="utf-8"
            )
            faulthandler.enable(file=self._fatal_file)
        except (OSError, ValueError):
            self._fatal_file = None

    # --------------------------------------------------------------- close

    def close(self) -> None:
        """Stop the heartbeat thread, restore signal state, final beat."""
        if self._closed.is_set():
            return
        self._closed.set()
        self._thread.join(timeout=2.0)
        self._phase = "closed"
        self._write_heartbeat(closed=True)
        # Publish the final schedule chain into the flushed stream: the
        # heartbeat sidecar can be reaped, the event line survives for
        # the postmortem's cross-rank audit.
        sched = GLOBAL_SCHEDULE.snapshot()
        if sched["n"] and self.sink is not None:
            try:
                self.sink.try_emit(
                    "collective_schedule",
                    n=sched["n"],
                    chain=sched["chain"],
                    tail=sched["tail"],
                )
            except Exception:
                pass  # forensics must never kill the run
        for signum, prev in self._prev_handlers.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, OSError, TypeError):
                pass
        self._prev_handlers.clear()
        if self._fatal_file is not None:
            try:
                faulthandler.disable()
                self._fatal_file.close()
            except (OSError, ValueError):
                pass
            self._fatal_file = None
