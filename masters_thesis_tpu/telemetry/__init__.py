"""Runtime telemetry: structured step-level metrics and run reports.

The static/trace-time contract layer (``analysis``, tracelint) proves what
a program SHOULD do; this package measures what runs actually DO:

- :mod:`registry` — in-process counters/gauges/histograms with host and
  process tagging (stdlib-only, importable anywhere);
- :mod:`events`   — the per-run structured JSONL event sink, flushed per
  line so killed runs still report;
- :mod:`run`      — :class:`TelemetryRun` (one run's sink + registry),
  :class:`CompileTracker` (TA201 as a runtime counter via jit cache-miss
  deltas), :class:`EpochRecorder` (async-dispatch-aware epoch accounting
  that fences only at boundaries the trainer takes anyway), and device
  memory / live-buffer sampling;
- :mod:`profiling` — programmatic ``jax.profiler`` capture windows
  (``profile_steps=(N, M)``) under the run dir;
- :mod:`flightrec` — in-process flight recorder: bounded event ring,
  SIGTERM/SIGQUIT + hang-watchdog crashdumps (``crashdump.json``),
  heartbeat files the fleet aggregator reads past a SIGKILL;
- :mod:`aggregate` — cross-host stream merging: per-host epoch-time skew,
  collective wait attribution, stragglers, exit-status reconstruction;
- :mod:`report` + ``__main__`` — ``python -m masters_thesis_tpu.telemetry
  summarize|aggregate|postmortem <run>``: single-run reports and fleet
  postmortems; exit nonzero on contract violations / dead processes.

Event schema and metric taxonomy: docs/telemetry.md.
"""

from masters_thesis_tpu.telemetry.aggregate import (
    aggregate_path,
    postmortem_path,
)
from masters_thesis_tpu.telemetry.events import EventSink, read_events
from masters_thesis_tpu.telemetry.flightrec import FlightRecorder
from masters_thesis_tpu.telemetry.profiling import ProfilerWindow
from masters_thesis_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from masters_thesis_tpu.telemetry.run import (
    CompileTracker,
    EpochRecorder,
    TelemetryRun,
    device_memory_snapshot,
)

__all__ = [
    "CompileTracker",
    "Counter",
    "EpochRecorder",
    "EventSink",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ProfilerWindow",
    "TelemetryRun",
    "aggregate_path",
    "device_memory_snapshot",
    "postmortem_path",
    "read_events",
]
