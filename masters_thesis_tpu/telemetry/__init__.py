"""Runtime telemetry: structured step-level metrics and run reports.

The static/trace-time contract layer (``analysis``, tracelint) proves what
a program SHOULD do; this package measures what runs actually DO:

- :mod:`registry` — in-process counters/gauges/histograms with host and
  process tagging (stdlib-only, importable anywhere);
- :mod:`events`   — the per-run structured JSONL event sink, flushed per
  line so killed runs still report;
- :mod:`run`      — :class:`TelemetryRun` (one run's sink + registry),
  :class:`CompileTracker` (TA201 as a runtime counter via jit cache-miss
  deltas), :class:`EpochRecorder` (async-dispatch-aware epoch accounting
  that fences only at boundaries the trainer takes anyway), and device
  memory / live-buffer sampling;
- :mod:`profiling` — programmatic ``jax.profiler`` capture windows
  (``profile_steps=(N, M)``) under the run dir;
- :mod:`flightrec` — in-process flight recorder: bounded event ring,
  SIGTERM/SIGQUIT + hang-watchdog crashdumps (``crashdump.json``),
  heartbeat files the fleet aggregator reads past a SIGKILL;
- :mod:`aggregate` — cross-host stream merging: per-host epoch-time skew,
  collective wait attribution, stragglers, exit-status reconstruction;
- :mod:`costs`    — static cost models of compiled executables
  (``cost_analysis()`` + ``memory_analysis()``) and roofline attribution
  (achieved FLOP/s vs nominal peaks, compute/memory/comms-bound regime);
  pure math importable without jax, extraction lazy;
- :mod:`ledger`   — append-only ``results/perf_ledger.jsonl`` of measured
  bench points (stdlib-only) + round-over-round regression diffing;
- :mod:`trace`    — distributed tracing: close-only spans on the event
  stream, ``MTT_TRACE_ID``/``MTT_PARENT_SPAN`` env propagation across
  supervisor attempts / grid cells / fleet workers, open-span flushing
  through the flight recorder, and the Perfetto export + critical-path
  attribution behind the ``trace`` CLI;
- :mod:`exposition` — stdlib HTTP exposition: ``/metrics`` (Prometheus
  text format over the registry snapshot), ``/healthz``, ``/slo``; one
  owned listener thread per process, attachable to the trainer, both
  serve servers, and both supervisors;
- :mod:`slo`      — declarative SLO rules (p99 vs deadline, shed%,
  multi-window error-budget burn rate, heartbeat staleness, starvation,
  recompile, divergence) evaluated incrementally over the live event
  streams via the tail-cursor reader; debounced ``alert_fired`` /
  ``alert_resolved`` events flow back into the stream;
- :mod:`watch`    — live fleet console (``watch`` CLI): incremental
  stream merging through aggregate's digest fold, per-rank/per-replica
  status, QPS/p99/shed, generation, firing alerts;
- :mod:`signals`  — the typed autoscaling feed (knee QPS vs offered
  load, headroom, per-replica EWMA service times, active alerts);
- :mod:`report` + ``__main__`` — ``python -m masters_thesis_tpu.telemetry
  summarize|aggregate|postmortem|ledger|watch <run>``: single-run
  reports, fleet postmortems, perf-ledger diffs, and the live console;
  exit nonzero on contract violations / dead processes / >15%
  utilization or throughput regressions.

Event schema and metric taxonomy: docs/telemetry.md.
"""

from masters_thesis_tpu.telemetry.aggregate import (
    aggregate_path,
    postmortem_path,
)
from masters_thesis_tpu.telemetry.costs import (
    CostModel,
    extract_cost,
    profile_jit,
    roofline_regime,
    utilization,
)
from masters_thesis_tpu.telemetry.events import (
    EventSink,
    read_events,
    read_new_lines,
)
from masters_thesis_tpu.telemetry.exposition import (
    ExpositionServer,
    attach_exposition,
    render_prometheus,
)
from masters_thesis_tpu.telemetry.ledger import (
    append_record,
    ledger_diff,
    ledger_record,
    read_ledger,
)
from masters_thesis_tpu.telemetry.flightrec import FlightRecorder
from masters_thesis_tpu.telemetry.profiling import ProfilerWindow
from masters_thesis_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from masters_thesis_tpu.telemetry.run import (
    CompileTracker,
    EpochRecorder,
    TelemetryRun,
    device_memory_snapshot,
)
from masters_thesis_tpu.telemetry.signals import (
    AutoscaleSignals,
    collect_signals,
    knee_from_ledger,
)
from masters_thesis_tpu.telemetry.slo import (
    SLOEngine,
    SLORule,
    burn_rate,
    default_serve_rules,
    default_train_rules,
    window_stats,
)
from masters_thesis_tpu.telemetry.watch import FleetWatch, render_watch
from masters_thesis_tpu.telemetry.trace import (
    PARENT_SPAN_ENV,
    TRACE_ENV,
    Span,
    Tracer,
    build_trace_report,
    child_env,
    current_trace_id,
    new_trace_id,
)

__all__ = [
    "PARENT_SPAN_ENV",
    "Span",
    "TRACE_ENV",
    "Tracer",
    "build_trace_report",
    "child_env",
    "current_trace_id",
    "new_trace_id",
    "AutoscaleSignals",
    "CompileTracker",
    "CostModel",
    "Counter",
    "EpochRecorder",
    "EventSink",
    "ExpositionServer",
    "FleetWatch",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ProfilerWindow",
    "SLOEngine",
    "SLORule",
    "TelemetryRun",
    "aggregate_path",
    "append_record",
    "attach_exposition",
    "burn_rate",
    "collect_signals",
    "default_serve_rules",
    "default_train_rules",
    "device_memory_snapshot",
    "extract_cost",
    "knee_from_ledger",
    "ledger_diff",
    "ledger_record",
    "postmortem_path",
    "profile_jit",
    "read_events",
    "read_ledger",
    "read_new_lines",
    "render_prometheus",
    "render_watch",
    "window_stats",
    "utilization",
]
