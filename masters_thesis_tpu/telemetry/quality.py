"""Model-quality plane: streaming drift sketches + shadow-OLS monitoring.

This module is **jax-free by contract** (stdlib + numpy only) — like the
rest of the telemetry readers it must run on a wedged host, in the
``python -m masters_thesis_tpu.telemetry quality`` CLI, and inside the
serve hot path *after* delivery without touching a device.

Three lifecycle stages share the same sketch format:

- **Train**: the trainer fingerprints the validation set at checkpoint
  time (per-feature sketches + predicted-(α, β) sketches + shadow-OLS
  disagreement stats + a golden-batch section) into a ``quality.json``
  sidecar covered by ``MANIFEST.json``.
- **Serve**: ``QualityMonitor`` samples 1-in-K *delivered* responses
  host-side, runs the closed-form OLS shadow estimate per sampled
  window, and publishes ``quality_sample`` events + ``mtt_quality_*``
  gauges that the SLO engine folds into input-drift / prediction-drift /
  shadow-disagreement rules.
- **Publish**: ``quality_gate`` scores a swap candidate's golden-batch
  outputs against the candidate's own shipped fingerprint AND the live
  serving sketch, so a diverged fine-tune is rejected with a named
  reason while an intentional retrain passes via its fresh fingerprint.

Sketch = Welford moments + min/max + P² quantile estimators on a fixed
probability grid. Two sketches compare via PSI (bins from the reference
quantile grid) and a two-sample KS score (max CDF gap over the union of
both grids). Summaries round-trip through JSON bit-stably (`repr`
shortest-float round-trip).
"""

from __future__ import annotations

import json
import math
import threading
from pathlib import Path

import numpy as np

__all__ = [
    "P2Quantile",
    "StreamSketch",
    "psi",
    "ks",
    "infer_factors",
    "shadow_ols",
    "shadow_error",
    "golden_windows",
    "build_fingerprint",
    "fingerprint_to_json",
    "read_fingerprint",
    "sketch_to_json",
    "sketch_from_json",
    "QualityMonitor",
    "quality_gate",
    "quality_report",
    "render_quality",
    "selfcheck",
    "FINGERPRINT_FILENAME",
]

QUANTILE_GRID = (0.05, 0.10, 0.25, 0.50, 0.75, 0.90, 0.95)
FINGERPRINT_FILENAME = "quality.json"
FINGERPRINT_VERSION = 1

# Detector defaults. PSI reads on the usual industry scale (< 0.1 calm,
# 0.1-0.25 drifting, > 0.25 act); the shadow threshold is a mean |model
# minus OLS| disagreement in (α, β) units.
DEFAULT_INPUT_THRESHOLD = 0.25
DEFAULT_PREDICTION_THRESHOLD = 0.25
DEFAULT_SHADOW_THRESHOLD = 0.50

# Gate defaults (see docs/quality.md for semantics).
GATE_MAX_SELF_KS = 0.35
GATE_SHADOW_SLACK = 4.0
GATE_SHADOW_FLOOR = 0.50
GATE_MAX_LIVE_KS = 0.60


# ------------------------------------------------------------------ sketches


class P2Quantile:
    """Single-quantile streaming estimator (Jain & Chlamtac's P², 1985).

    O(1) memory: five markers whose heights track the min, the p/2, p,
    (1+p)/2 quantiles and the max, nudged toward their desired positions
    with a piecewise-parabolic update on every observation.
    """

    __slots__ = ("p", "_first", "_q", "_n", "_np", "_dn")

    def __init__(self, p: float):
        if not 0.0 < p < 1.0:
            raise ValueError(f"quantile probability must be in (0, 1): {p}")
        self.p = float(p)
        self._first: list[float] = []
        self._q: list[float] | None = None  # marker heights
        self._n: list[float] | None = None  # marker positions (1-based)
        self._np: list[float] | None = None  # desired positions
        self._dn = (0.0, self.p / 2.0, self.p, (1.0 + self.p) / 2.0, 1.0)

    def update(self, x: float) -> None:
        x = float(x)
        if self._q is None:
            self._first.append(x)
            if len(self._first) == 5:
                self._first.sort()
                self._q = list(self._first)
                self._n = [1.0, 2.0, 3.0, 4.0, 5.0]
                self._np = [1.0 + 4.0 * d for d in self._dn]
            return
        q, n, np_ = self._q, self._n, self._np
        if x < q[0]:
            q[0] = x
            k = 0
        elif x >= q[4]:
            if x > q[4]:
                q[4] = x
            k = 3
        else:
            k = next(i for i in range(4) if q[i] <= x < q[i + 1])
        for i in range(k + 1, 5):
            n[i] += 1.0
        for i in range(5):
            np_[i] += self._dn[i]
        for i in (1, 2, 3):
            d = np_[i] - n[i]
            if (d >= 1.0 and n[i + 1] - n[i] > 1.0) or (
                d <= -1.0 and n[i - 1] - n[i] < -1.0
            ):
                d = 1.0 if d > 0 else -1.0
                h = self._parabolic(i, d)
                if not q[i - 1] < h < q[i + 1]:
                    h = self._linear(i, d)
                q[i] = h
                n[i] += d

    def _parabolic(self, i: int, d: float) -> float:
        q, n = self._q, self._n
        return q[i] + d / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + d) * (q[i + 1] - q[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - d) * (q[i] - q[i - 1]) / (n[i] - n[i - 1])
        )

    def _linear(self, i: int, d: float) -> float:
        q, n = self._q, self._n
        j = i + int(d)
        return q[i] + d * (q[j] - q[i]) / (n[j] - n[i])

    def value(self) -> float:
        if self._q is not None:
            return float(self._q[2])
        if not self._first:
            return math.nan
        return float(np.quantile(np.asarray(self._first, dtype=np.float64), self.p))  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced


class StreamSketch:
    """Welford moments + min/max + a P² quantile grid for one scalar stream.

    ``update`` accepts scalars or arrays (non-finite values are dropped).
    ``from_values`` builds the same summary shape from a full sample with
    *exact* numpy quantiles — used for checkpoint-time fingerprints where
    the whole validation set is in hand.
    """

    def __init__(self, grid=QUANTILE_GRID):
        self.grid = tuple(float(p) for p in grid)
        self.count = 0
        self.mean = 0.0
        self._m2 = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._quant = [P2Quantile(p) for p in self.grid]
        self._exact: list[float] | None = None

    def update(self, values) -> None:
        arr = np.asarray(values, dtype=np.float64).ravel()  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
        arr = arr[np.isfinite(arr)]
        for x in arr.tolist():
            self.count += 1  # mtt: disable=CL502 -- single-thread or guarded by the owning QualityMonitor._lock
            delta = x - self.mean
            self.mean += delta / self.count  # mtt: disable=CL502 -- single-thread or guarded by the owning QualityMonitor._lock
            self._m2 += delta * (x - self.mean)  # mtt: disable=CL502 -- single-thread or guarded by the owning QualityMonitor._lock
            if x < self.min:
                self.min = x
            if x > self.max:
                self.max = x
            for q in self._quant:
                q.update(x)

    @classmethod
    def from_values(cls, values, grid=QUANTILE_GRID) -> "StreamSketch":
        arr = np.asarray(values, dtype=np.float64).ravel()  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
        arr = arr[np.isfinite(arr)]
        sk = cls(grid)
        if arr.size == 0:
            return sk
        sk.count = int(arr.size)
        sk.mean = float(arr.mean())
        sk._m2 = float(((arr - arr.mean()) ** 2).sum())
        sk.min = float(arr.min())
        sk.max = float(arr.max())
        sk._exact = [float(np.quantile(arr, p)) for p in sk.grid]
        return sk

    def summary(self) -> dict:
        if self.count == 0:
            quantiles = [math.nan] * len(self.grid)
            lo = hi = math.nan
            var = 0.0
        else:
            if self._exact is not None:
                quantiles = list(self._exact)
            else:
                quantiles = [q.value() for q in self._quant]
            lo, hi = float(self.min), float(self.max)
            var = self._m2 / (self.count - 1) if self.count > 1 else 0.0
        return {
            "count": int(self.count),
            "mean": float(self.mean),
            "var": float(var),
            "min": lo,
            "max": hi,
            "grid": [float(p) for p in self.grid],
            "quantiles": [float(v) for v in quantiles],
        }


def sketch_to_json(summary: dict) -> str:
    """Canonical JSON for a sketch summary — bit-stable round trip."""
    return json.dumps(summary, sort_keys=True, separators=(",", ":"))


def sketch_from_json(text: str) -> dict:
    return json.loads(text)


# ------------------------------------------------------- distribution scores


def _cdf_points(summary: dict):
    """Monotone (x, F(x)) knots from a sketch summary."""
    xs = np.asarray(
        [summary["min"], *summary["quantiles"], summary["max"]], dtype=np.float64  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
    )
    ps = np.asarray([0.0, *summary["grid"], 1.0], dtype=np.float64)  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
    xs = np.maximum.accumulate(xs)
    return xs, ps


def _cdf(summary: dict, at: np.ndarray) -> np.ndarray:
    xs, ps = _cdf_points(summary)
    return np.interp(at, xs, ps, left=0.0, right=1.0)


def psi(reference: dict, live: dict, eps: float = 1e-4) -> float:
    """Population-stability index of ``live`` against ``reference``.

    Bins are the reference quantile grid (plus min/max), so the expected
    mass per bin comes straight from the grid probabilities; the actual
    mass is the live CDF evaluated at the reference edges.
    """
    if not reference.get("count") or not live.get("count"):
        return 0.0
    edges, edge_p = _cdf_points(reference)
    expected = np.diff(edge_p)
    actual = np.diff(_cdf(live, edges))
    keep = expected > 0
    if not keep.any():
        return 0.0
    expected = np.clip(expected[keep], eps, None)
    actual = np.clip(actual[keep], eps, None)
    expected = expected / expected.sum()
    actual = actual / actual.sum()
    return float(np.sum((actual - expected) * np.log(actual / expected)))


def ks(reference: dict, live: dict) -> float:
    """Two-sample KS score: max CDF gap over the union of both grids."""
    if not reference.get("count") or not live.get("count"):
        return 0.0
    rx, _ = _cdf_points(reference)
    lx, _ = _cdf_points(live)
    at = np.union1d(rx, lx)
    return float(np.max(np.abs(_cdf(reference, at) - _cdf(live, at))))


# ------------------------------------------------------------- shadow OLS


def infer_factors(n_features: int) -> int:
    """Factor count K from a window's feature channel count.

    The interaction-only pipeline layout (data/pipeline.py) is
    ``[r_stock, f_1..f_K, r_stock*f_1..r_stock*f_K]`` → ``f = 2K + 1``.
    ``f == 3`` is the scalar-market anchor (K = 1).
    """
    return 1 if n_features == 3 else max(1, (int(n_features) - 1) // 2)


def shadow_ols(x, n_factors: int | None = None):
    """Closed-form per-window OLS (α, β) — the thesis baseline, in numpy.

    Mirrors ``ops/linalg.ols``/``ols_k`` + the ``evaluation.py`` slicing
    convention: regressors = features ``1..K`` of stock 0 (the broadcast
    factor series), regressand = feature 0 of every stock. ``x`` is
    ``(n, k, t, f)`` or one window ``(k, t, f)``; ``n_factors`` overrides
    the channel-count inference (:func:`infer_factors`).

    Returns ``(alpha, beta)``: ``alpha`` is ``(n, k)``; ``beta`` is
    ``(n, k)`` at K = 1 (the original scalar path, op for op — the
    bitwise parity anchor) and ``(n, k, K)`` for K > 1 (one loading per
    factor, the numpy twin of ``ops/linalg._batched_ols_k``).
    """
    x = np.asarray(x, dtype=np.float64)  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
    if x.ndim == 3:
        x = x[None]
    if n_factors is None:
        n_factors = infer_factors(x.shape[-1])
    rets = x[:, :, :, 0]  # (n, k, t)
    if n_factors == 1:
        # Scalar path kept op for op: K=1 results must stay bit-identical
        # to every fingerprint and test pinned before K-factor support.
        market = x[:, 0, :, 1]  # (n, t)
        design = np.stack([np.ones_like(market), market], axis=-1)
        gram = design.transpose(0, 2, 1) @ design  # (n, 2, 2)
        moment = design.transpose(0, 2, 1) @ rets.transpose(0, 2, 1)
        coef = np.linalg.pinv(gram) @ moment
        return coef[:, 0, :], coef[:, 1, :]
    factors = x[:, 0, :, 1 : 1 + n_factors]  # (n, t, K)
    ones = np.ones(factors.shape[:-1] + (1,), factors.dtype)
    design = np.concatenate([ones, factors], axis=-1)  # (n, t, K+1)
    gram = design.transpose(0, 2, 1) @ design  # (n, K+1, K+1)
    moment = design.transpose(0, 2, 1) @ rets.transpose(0, 2, 1)
    coef = np.linalg.pinv(gram) @ moment  # (n, K+1, k)
    return coef[:, 0, :], np.swapaxes(coef[:, 1:, :], -1, -2)


def shadow_error(x, alpha, beta, n_factors: int | None = None) -> float:
    """Mean |model − shadow-OLS| disagreement over a window batch.

    With K > 1 factors the OLS betas are ``(n, k, K)``; a model that
    serves the full loading matrix is scored loading-for-loading, while
    one that serves a single ``(n, k)`` beta is scored against the FIRST
    factor's loading (the market line — the K = 1 semantics).
    """
    sa, sb = shadow_ols(x, n_factors=n_factors)
    a = np.asarray(alpha, dtype=np.float64).reshape(sa.shape)  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
    b = np.asarray(beta, dtype=np.float64)  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
    if b.size != sb.size and sb.ndim == 3:
        sb = sb[..., 0]
    b = b.reshape(sb.shape)
    return float(0.5 * (np.mean(np.abs(a - sa)) + np.mean(np.abs(b - sb))))


def golden_windows(n: int, n_stocks: int, lookback: int, n_features: int, seed: int = 0):
    """Deterministic standard-normal golden windows ``(n, k, t, f)`` f32.

    numpy-only so the trainer fingerprint and the swap gate agree on the
    exact bytes without a device in the loop.
    """
    rng = np.random.default_rng(int(seed))
    return rng.standard_normal((n, n_stocks, lookback, n_features)).astype(np.float32)


# ------------------------------------------------------------- fingerprints


def build_fingerprint(
    x,
    alpha,
    beta,
    *,
    golden=None,
    golden_seed: int = 0,
    max_windows: int = 256,
) -> dict:
    """Checkpoint-time quality fingerprint.

    ``x`` is validation windows ``(n, k, t, f)``; ``alpha``/``beta`` the
    model's predictions on them ``(n, k)``. ``golden`` is an optional
    ``(gx, galpha, gbeta)`` triple of the model's outputs on
    ``golden_windows(..., seed=golden_seed)`` — the section the swap
    quality gate scores candidates against.
    """
    x = np.asarray(x, dtype=np.float64)[:max_windows]  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
    alpha = np.asarray(alpha, dtype=np.float64)[: x.shape[0]]  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
    beta = np.asarray(beta, dtype=np.float64)[: x.shape[0]]  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
    sa, sb = shadow_ols(x)
    if beta.size != sb.size and sb.ndim == 3:
        # Single-loading model under a K-factor window: fingerprint the
        # first factor's loading, matching shadow_error's convention.
        sb = sb[..., 0]
    fp = {
        "version": FINGERPRINT_VERSION,
        "windows": int(x.shape[0]),
        "window_shape": [int(s) for s in x.shape[1:]],
        "features": {
            str(fi): StreamSketch.from_values(x[..., fi]).summary()
            for fi in range(x.shape[-1])
        },
        "alpha": StreamSketch.from_values(alpha).summary(),
        "beta": StreamSketch.from_values(beta).summary(),
        "shadow": {
            "err_mean": shadow_error(x, alpha, beta),
            "alpha_mae": float(np.mean(np.abs(alpha.reshape(sa.shape) - sa))),
            "beta_mae": float(np.mean(np.abs(beta.reshape(sb.shape) - sb))),
        },
    }
    if golden is not None:
        gx, ga, gb = golden
        gx = np.asarray(gx, dtype=np.float64)  # mtt: disable=TL104 -- host-only sketch/OLS math in f64; never traced
        fp["golden"] = {
            "seed": int(golden_seed),
            "shape": [int(s) for s in gx.shape],
            "alpha": StreamSketch.from_values(ga).summary(),
            "beta": StreamSketch.from_values(gb).summary(),
            "shadow_err": shadow_error(gx, ga, gb),
        }
    return fp


def fingerprint_to_json(fp: dict) -> str:
    return json.dumps(fp, sort_keys=True, separators=(",", ":"))


def read_fingerprint(tree) -> dict | None:
    """Load ``quality.json`` from a checkpoint tree, or None."""
    path = Path(tree) / FINGERPRINT_FILENAME
    if not path.exists():
        return None
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None


# ------------------------------------------------------------ live monitor


class QualityMonitor:
    """1-in-K post-delivery sampler + drift detectors for a serve process.

    ``sample(x, alpha, beta)`` is called by the server strictly *after*
    a response is delivered, with host-side numpy arrays (one window
    ``(k, t, f)`` and its ``(k,)`` outputs) — no fences, no transfers.
    Every ``sample_every``-th call updates the live sketches, runs the
    shadow OLS on that window, and (once ``min_samples`` windows are in)
    scores the live sketches against the reference fingerprint, sets
    ``quality/*`` gauges (exposed as ``mtt_quality_*``) and emits one
    ``quality_sample`` event for the SLO engine and the report readers.
    """

    def __init__(
        self,
        reference: dict | None = None,
        *,
        sample_every: int = 16,
        min_samples: int = 8,
        input_threshold: float = DEFAULT_INPUT_THRESHOLD,
        prediction_threshold: float = DEFAULT_PREDICTION_THRESHOLD,
        shadow_threshold: float = DEFAULT_SHADOW_THRESHOLD,
        shadow_alpha: float = 0.25,
        telemetry=None,
    ):
        self.sample_every = max(1, int(sample_every))
        self.min_samples = max(1, int(min_samples))
        self.input_threshold = float(input_threshold)
        self.prediction_threshold = float(prediction_threshold)
        self.shadow_threshold = float(shadow_threshold)
        self._shadow_alpha = float(shadow_alpha)
        self._telemetry = telemetry
        self._lock = threading.Lock()
        self.reference = reference
        self._reset_locked()

    def _reset_locked(self) -> None:
        self._seen = 0  # mtt: disable=CL502 -- _locked contract: callers hold self._lock (or __init__ pre-share)
        self._sampled = 0
        self._features: dict[int, StreamSketch] = {}
        self._alpha = StreamSketch()
        self._beta = StreamSketch()
        self._shadow = StreamSketch()
        self._shadow_ewm: float | None = None
        self._last: dict | None = None  # mtt: disable=CL502 -- _locked contract: callers hold self._lock (or __init__ pre-share)

    def set_reference(self, fingerprint: dict | None) -> None:
        """Swap in a new baseline (post-commit); live sketches restart."""
        with self._lock:
            self.reference = fingerprint
            self._reset_locked()

    def live_summaries(self) -> dict:
        """Current serving sketches for the swap gate's live check."""
        with self._lock:
            if self._sampled < self.min_samples:
                return {}
            return {
                "sampled": self._sampled,
                "alpha": self._alpha.summary(),
                "beta": self._beta.summary(),
                "shadow_err": self._shadow_ewm,
            }

    def last_scores(self) -> dict | None:
        with self._lock:
            return dict(self._last) if self._last is not None else None

    def sample(self, x, alpha, beta) -> dict | None:
        """Post-delivery hook; returns the scores dict on sampled windows."""
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self.sample_every:
                return None
            scores = self._ingest_locked(
                np.asarray(x), np.asarray(alpha), np.asarray(beta)
            )
            self._last = scores
        self._publish_sample(scores)
        return scores

    def _ingest_locked(self, x, alpha, beta) -> dict:
        for fi in range(x.shape[-1]):
            self._features.setdefault(fi, StreamSketch()).update(x[..., fi])
        self._alpha.update(alpha)
        self._beta.update(beta)
        err = shadow_error(x, alpha, beta)
        self._shadow.update(err)
        if self._shadow_ewm is None:
            self._shadow_ewm = err
        else:
            a = self._shadow_alpha
            self._shadow_ewm = a * err + (1.0 - a) * self._shadow_ewm  # mtt: disable=CL502 -- _locked contract: sample() holds self._lock
        self._sampled += 1  # mtt: disable=CL502 -- _locked contract: sample() holds self._lock
        scores = {
            "sampled": self._sampled,
            "scored": False,
            "shadow_err": float(self._shadow_ewm),
            "shadow_thr": self.shadow_threshold,
            "input_psi": 0.0,
            "input_ks": 0.0,
            "pred_psi": 0.0,
            "pred_ks": 0.0,
            "input_thr": self.input_threshold,
            "pred_thr": self.prediction_threshold,
        }
        ref = self.reference
        if ref is not None and self._sampled >= self.min_samples:
            in_psi = in_ks = 0.0
            ref_features = ref.get("features", {})
            for fi, sk in self._features.items():
                ref_sk = ref_features.get(str(fi))
                if ref_sk is None:
                    continue
                live = sk.summary()
                in_psi = max(in_psi, psi(ref_sk, live))
                in_ks = max(in_ks, ks(ref_sk, live))
            live_a = self._alpha.summary()
            live_b = self._beta.summary()
            pr_psi = max(psi(ref["alpha"], live_a), psi(ref["beta"], live_b))
            pr_ks = max(ks(ref["alpha"], live_a), ks(ref["beta"], live_b))
            scores.update(
                scored=True,
                input_psi=float(in_psi),
                input_ks=float(in_ks),
                pred_psi=float(pr_psi),
                pred_ks=float(pr_ks),
            )
        scores["input_breached"] = bool(
            scores["scored"] and scores["input_psi"] > self.input_threshold
        )
        scores["pred_breached"] = bool(
            scores["scored"] and scores["pred_psi"] > self.prediction_threshold
        )
        scores["shadow_breached"] = bool(
            self._sampled >= self.min_samples
            and scores["shadow_err"] > self.shadow_threshold
        )
        return scores

    def _publish_sample(self, scores: dict) -> None:
        # Outside the monitor lock: the registry and the sink have their
        # own locks and the sink does file IO.
        t = self._telemetry
        if t is None:
            return
        t.counter("quality/sampled").inc(1)
        t.gauge("quality/shadow_err").set(float(scores["shadow_err"]))
        if scores["scored"]:
            t.gauge("quality/input_psi").set(float(scores["input_psi"]))
            t.gauge("quality/input_ks").set(float(scores["input_ks"]))
            t.gauge("quality/prediction_psi").set(float(scores["pred_psi"]))
            t.gauge("quality/prediction_ks").set(float(scores["pred_ks"]))
        t.event(
            "quality_sample",
            sampled=int(scores["sampled"]),
            scored=bool(scores["scored"]),
            input_psi=float(scores["input_psi"]),
            input_ks=float(scores["input_ks"]),
            pred_psi=float(scores["pred_psi"]),
            pred_ks=float(scores["pred_ks"]),
            shadow_err=float(scores["shadow_err"]),
            input_thr=float(scores["input_thr"]),
            pred_thr=float(scores["pred_thr"]),
            shadow_thr=float(scores["shadow_thr"]),
            input_breached=bool(scores["input_breached"]),
            pred_breached=bool(scores["pred_breached"]),
            shadow_breached=bool(scores["shadow_breached"]),
        )


# ---------------------------------------------------------------- swap gate


def quality_gate(
    fingerprint: dict | None,
    x,
    alpha,
    beta,
    *,
    live: dict | None = None,
    max_self_ks: float = GATE_MAX_SELF_KS,
    shadow_slack: float = GATE_SHADOW_SLACK,
    shadow_floor: float = GATE_SHADOW_FLOOR,
    max_live_ks: float = GATE_MAX_LIVE_KS,
):
    """Score candidate golden-batch outputs for the hot-swap canary.

    ``x`` are the golden windows the candidate was evaluated on and
    ``alpha``/``beta`` its outputs. Returns ``(ok, reason, detail,
    checks)`` with reasons named ``quality_self`` (outputs diverge from
    the candidate's own shipped fingerprint — the diverged-fine-tune
    case), ``quality_shadow`` (shadow-OLS disagreement beyond the
    shipped budget), and ``quality_live`` (no fingerprint shipped and
    outputs diverge from the live serving sketch).
    """
    checks: dict[str, float] = {}
    a_sum = StreamSketch.from_values(alpha).summary()
    b_sum = StreamSketch.from_values(beta).summary()
    err = shadow_error(x, alpha, beta)
    checks["quality_shadow_err"] = err
    gold = (fingerprint or {}).get("golden")
    if gold is not None:
        self_ks = max(ks(gold["alpha"], a_sum), ks(gold["beta"], b_sum))
        checks["quality_self_ks"] = self_ks
        budget = max(shadow_floor, shadow_slack * float(gold.get("shadow_err", 0.0)))
        checks["quality_shadow_budget"] = budget
        if self_ks > max_self_ks:
            return (
                False,
                "quality_self",
                f"golden outputs diverge from the shipped fingerprint "
                f"(ks={self_ks:.4f} > {max_self_ks})",
                checks,
            )
        if err > budget:
            return (
                False,
                "quality_shadow",
                f"shadow-OLS disagreement {err:.4f} exceeds the shipped "
                f"budget {budget:.4f}",
                checks,
            )
    if live:
        live_ks = 0.0
        if live.get("alpha"):
            live_ks = max(ks(live["alpha"], a_sum), ks(live["beta"], b_sum))
        checks["quality_live_ks"] = live_ks
        if gold is None and live_ks > max_live_ks:
            return (
                False,
                "quality_live",
                f"no fingerprint shipped and golden outputs diverge from "
                f"the live serving sketch (ks={live_ks:.4f} > {max_live_ks})",
                checks,
            )
    return True, "", "", checks


# ------------------------------------------------------------ event folding


def quality_report(events) -> dict:
    """Fold a merged event stream into the quality section dict.

    Shared by ``report.summarize_events``, the watch console and the
    ``quality`` CLI verb. Input is an iterable of decoded event dicts.
    """
    samples = [e for e in events if e.get("kind") == "quality_sample"]
    out: dict = {"samples": len(samples)}
    if samples:
        last = samples[-1]
        out["last"] = {
            "sampled": last.get("sampled"),
            "scored": bool(last.get("scored")),
            "input_psi": last.get("input_psi"),
            "pred_psi": last.get("pred_psi"),
            "shadow_err": last.get("shadow_err"),
        }
        out["max"] = {
            "input_psi": max(float(e.get("input_psi") or 0.0) for e in samples),
            "pred_psi": max(float(e.get("pred_psi") or 0.0) for e in samples),
            "shadow_err": max(float(e.get("shadow_err") or 0.0) for e in samples),
        }
        out["breaches"] = {
            "input": sum(1 for e in samples if e.get("input_breached")),
            "prediction": sum(1 for e in samples if e.get("pred_breached")),
            "shadow": sum(1 for e in samples if e.get("shadow_breached")),
        }
    rejected = [
        e
        for e in events
        if e.get("kind") == "swap_rejected"
        and str(e.get("reason") or "").startswith("quality")
    ]
    if rejected:
        out["swaps_rejected_quality"] = len(rejected)
        out["last_rejection"] = {
            "tag": rejected[-1].get("tag"),
            "reason": rejected[-1].get("reason"),
        }
    fired = [
        e
        for e in events
        if e.get("kind") == "alert_fired"
        and e.get("slo_kind")
        in ("input_drift", "prediction_drift", "shadow_disagreement")
    ]
    if fired:
        out["alerts_fired"] = len(fired)
    return out


def quality_violations(events, quality: dict | None = None) -> list[str]:
    """Detector-wiring contract: sustained shadow breach must alert.

    Only meaningful when an SLO engine was actually attached (we see
    ``slo_snapshot`` or any ``alert_*`` traffic); a bare serve run with
    no monitor thread is not a violation.
    """
    quality = quality if quality is not None else quality_report(events)
    breaches = (quality.get("breaches") or {}).get("shadow", 0)
    if breaches < 3:
        return []
    slo_attached = any(
        e.get("kind") in ("slo_snapshot", "alert_fired", "alert_resolved")
        for e in events
    )
    if not slo_attached:
        return []
    shadow_alerts = any(
        e.get("kind") == "alert_fired"
        and e.get("slo_kind") == "shadow_disagreement"
        for e in events
    )
    if shadow_alerts:
        return []
    return [
        f"shadow-OLS disagreement breached on {breaches} sampled windows "
        "but no shadow_disagreement alert fired (detector wiring broken)"
    ]


def render_quality(quality: dict) -> str:
    """One-line QUALITY row for the watch console / text report."""
    if not quality or not quality.get("samples"):
        return "QUALITY   (no sampled windows)"
    last = quality.get("last") or {}
    br = quality.get("breaches") or {}

    def _mark(value, breached):
        v = "-" if value is None else f"{float(value):.3f}"
        return v + ("!" if breached else "")

    parts = [
        f"samples={quality['samples']}",
        "input_psi=" + _mark(last.get("input_psi"), br.get("input")),
        "pred_psi=" + _mark(last.get("pred_psi"), br.get("prediction")),
        "shadow=" + _mark(last.get("shadow_err"), br.get("shadow")),
    ]
    if quality.get("swaps_rejected_quality"):
        parts.append(f"swaps_rejected={quality['swaps_rejected_quality']}")
    if quality.get("alerts_fired"):
        parts.append(f"alerts={quality['alerts_fired']}")
    return "QUALITY   " + "  ".join(parts)


# ---------------------------------------------------------------- selfcheck


def _check(ok: bool, label: str, failures: list[str]) -> None:
    print(f"  {'ok' if ok else 'FAIL'}  {label}")
    if not ok:
        failures.append(label)


def selfcheck(verbose: bool = True) -> bool:
    """Hermetic, jax-free fixture: sketch math, detectors, gate."""
    failures: list[str] = []
    rng = np.random.default_rng(7)

    # 1. P² accuracy vs exact quantiles on three stream shapes.
    streams = {
        "normal": rng.standard_normal(4000),
        "student_t": rng.standard_t(3, size=4000),
        "bimodal": np.concatenate(
            [rng.normal(-2.0, 0.5, 2000), rng.normal(2.0, 0.5, 2000)]
        ),
    }
    for name, data in streams.items():
        sk = StreamSketch()
        sk.update(data)
        got = np.asarray(sk.summary()["quantiles"])
        want = np.quantile(data, np.asarray(QUANTILE_GRID))
        # Per-quantile: accept x-space closeness OR probability-space
        # closeness — heavy tails (student-t) blow up x-space error where
        # density is thin, density gaps (bimodal) blow up probability
        # space where the CDF is flat; neither alone is fair to both.
        ecdf = np.asarray([(data <= v).mean() for v in got])
        x_ok = np.abs(got - want) < 0.1 * float(data.std()) + 0.02
        p_ok = np.abs(ecdf - np.asarray(QUANTILE_GRID)) < 0.02
        _check(
            bool(np.all(x_ok | p_ok)),
            f"p2 quantiles ~ exact ({name})",
            failures,
        )

    # 2. PSI/KS: IID halves quiet, injected shift loud.
    base = rng.standard_normal(20_000)
    ref = StreamSketch.from_values(base[:10_000]).summary()
    iid = StreamSketch.from_values(base[10_000:]).summary()
    shifted = StreamSketch.from_values(base[10_000:] * 1.5 + 0.75).summary()
    _check(psi(ref, iid) < 0.02 and ks(ref, iid) < 0.03, "psi/ks ~ 0 on IID halves", failures)
    _check(psi(ref, shifted) > 0.3 and ks(ref, shifted) > 0.2, "psi/ks large under shift", failures)

    # 3. JSON round trip is bit-stable.
    js = sketch_to_json(ref)
    _check(sketch_to_json(sketch_from_json(js)) == js, "sketch JSON round-trip bit-stable", failures)

    # 4. Shadow OLS matches per-window polyfit.
    x = rng.standard_normal((4, 6, 32, 3))
    sa, sb = shadow_ols(x)
    ok = True
    for n in range(4):
        for k_i in range(6):
            b1, b0 = np.polyfit(x[n, 0, :, 1], x[n, k_i, :, 0], 1)
            ok = ok and abs(sa[n, k_i] - b0) < 1e-8 and abs(sb[n, k_i] - b1) < 1e-8
    _check(ok, "shadow OLS == per-window polyfit", failures)

    # 5. Monitor: IID twin stays silent, shifted stream breaches input
    #    drift, garbage predictions breach shadow disagreement.
    def _windows(m, shift_scale=1.0, shift_off=0.0, seed=11):
        g = np.random.default_rng(seed)
        xs = g.standard_normal((m, 6, 32, 3)).astype(np.float32)
        xs = xs * shift_scale + shift_off
        a, b = shadow_ols(xs)
        return xs, a, b

    fx, fa, fb = _windows(64)
    fp = build_fingerprint(fx, fa, fb)

    def _run(monitor, m, **kw):
        xs, a, b = _windows(m, **kw)
        out = []
        for i in range(m):
            s = monitor.sample(xs[i], a[i], b[i])
            if s is not None:
                out.append(s)
        return out

    mon = QualityMonitor(fp, sample_every=1, min_samples=8)
    quiet = _run(mon, 48, seed=12)
    _check(
        not any(s["input_breached"] or s["shadow_breached"] for s in quiet),
        "monitor silent on IID twin",
        failures,
    )
    mon = QualityMonitor(fp, sample_every=1, min_samples=8)
    loud = _run(mon, 48, shift_scale=1.6, shift_off=0.8, seed=13)
    fired_at = next(
        (s["sampled"] for s in loud if s["input_breached"]), None
    )
    _check(
        fired_at is not None and fired_at <= 24,
        "input drift fires within 24 sampled windows under shift",
        failures,
    )
    mon = QualityMonitor(fp, sample_every=1, min_samples=4)
    xs, a, b = _windows(24, seed=14)
    bad = [mon.sample(xs[i], a[i] * 40.0 + 3.0, b[i] * 40.0) for i in range(24)]
    _check(
        any(s["shadow_breached"] for s in bad if s),
        "shadow disagreement fires on garbage predictions",
        failures,
    )

    # 6. Gate: honest fingerprint passes, diverged fine-tune rejected.
    gx = golden_windows(16, 6, 32, 3, seed=0)
    ga, gb = shadow_ols(gx)
    fp_gold = build_fingerprint(fx, fa, fb, golden=(gx, ga, gb), golden_seed=0)
    ok, reason, _, _ = quality_gate(fp_gold, gx, ga, gb)
    _check(ok and not reason, "gate passes the honest candidate", failures)
    ok, reason, _, checks = quality_gate(fp_gold, gx, ga * 50.0 + 5.0, gb * 50.0)
    _check(
        not ok and reason in ("quality_self", "quality_shadow"),
        f"gate rejects the diverged candidate ({reason or 'no reason'})",
        failures,
    )

    # 7. Report folding + violation contract.
    events = [
        {"kind": "quality_sample", "sampled": i + 1, "scored": True,
         "input_psi": 0.01, "pred_psi": 0.01, "shadow_err": 0.9,
         "input_breached": False, "pred_breached": False,
         "shadow_breached": True}
        for i in range(4)
    ]
    events.append({"kind": "slo_snapshot"})
    viol = quality_violations(events)
    _check(len(viol) == 1, "breach-without-alert is a contract violation", failures)
    events.append({"kind": "alert_fired", "slo_kind": "shadow_disagreement"})
    _check(not quality_violations(events), "alerted breach is clean", failures)

    if failures:
        print(f"quality selfcheck: {len(failures)} failure(s)")
        return False
    print("quality selfcheck: all checks passed")
    return True
