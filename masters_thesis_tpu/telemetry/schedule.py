"""Per-rank collective-schedule hash chain and the cross-rank audit.

On a multi-host DCN mesh the dominant failure is not a crash but a
wedge: one rank takes a divergent control path, skips or reorders a
collective, and every other rank blocks in ``sync_global_devices`` until
a watchdog condemns the generation. The wedged fleet leaves no stack
trace that says *which* rank diverged or *where* its schedule forked.

This module closes that gap with a hash chain. Every host-level
collective (``fleet_barrier``, the per-epoch gradient all-reduce)
records a canonical entry ``(kind, name, dtype, shape, axes, step)``;
each entry is chained into a rolling sha256, so two ranks that issued
the same schedule have bitwise-equal chains and the *first* divergent
entry is findable by comparing per-entry chain hashes. The chain rides
the flight-recorder channel (heartbeat.json / crashdump.json) — the
heartbeat thread keeps publishing it while the main thread is wedged in
a collective, which is exactly when the diagnosis is needed.

Stdlib-only by contract: the aggregate/postmortem readers run on hosts
where importing a backend is unsafe.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import deque
from pathlib import Path
from typing import Any

#: Entries kept verbatim (beyond the rolling hash) for the postmortem
#: report — enough tail to show both schedules around the fork point.
TAIL_KEEP = 64


class CollectiveSchedule:
    """Thread-safe rolling hash chain of collective-schedule entries."""

    def __init__(self, keep: int = TAIL_KEEP):
        self._lock = threading.Lock()
        self._keep = keep
        self._n = 0
        self._hash = hashlib.sha256(b"mtt.schedule.v1").hexdigest()
        self._tail: deque[dict[str, Any]] = deque(maxlen=keep)

    def record(
        self,
        kind: str,
        *,
        name: str | None = None,
        dtype: str | None = None,
        shape: tuple | list | None = None,
        axes: tuple | list | None = None,
        step: int | None = None,
    ) -> str:
        """Append one collective entry; returns the chain hash after it.

        The entry is canonicalised (sorted-key JSON) before hashing so
        two ranks that issued the same collective produce byte-equal
        chain links regardless of call-site kwarg order.
        """
        entry = {
            "kind": kind,
            "name": name,
            "dtype": dtype,
            "shape": list(shape) if shape is not None else None,
            "axes": list(axes) if axes is not None else None,
            "step": step,
        }
        canon = json.dumps(entry, sort_keys=True, separators=(",", ":"))
        with self._lock:
            self._hash = hashlib.sha256(
                (self._hash + canon).encode()
            ).hexdigest()
            entry["i"] = self._n
            entry["h"] = self._hash
            self._tail.append(entry)
            self._n += 1
            return self._hash

    def snapshot(self) -> dict[str, Any]:
        """Publishable view: entry count, chain head, and recent tail."""
        with self._lock:
            return {
                "n": self._n,
                "chain": self._hash,
                "tail": [dict(e) for e in self._tail],
            }

    def reset(self) -> None:
        """Restart the chain (tests / a fresh fleet generation)."""
        with self._lock:
            self._n = 0
            self._hash = hashlib.sha256(b"mtt.schedule.v1").hexdigest()
            self._tail.clear()


#: Process-wide chain: mesh.fleet_barrier and the trainer epoch loop
#: record here; the flight recorder snapshots it into every heartbeat.
GLOBAL_SCHEDULE = CollectiveSchedule()


def record_collective(kind: str, **fields) -> str:
    """Record one entry on the process-wide chain (see GLOBAL_SCHEDULE)."""
    return GLOBAL_SCHEDULE.record(kind, **fields)


def _entry_desc(entry: dict) -> str:
    bits = [str(entry.get("kind"))]
    for key in ("name", "dtype", "shape", "axes", "step"):
        val = entry.get(key)
        if val is not None:
            bits.append(f"{key}={val}")
    return " ".join(bits)


def audit_schedules(snaps: dict[str, dict | None]) -> dict[str, Any]:
    """Bitwise cross-check of per-rank schedule snapshots.

    ``snaps`` maps a rank label (``"p0"``) to a ``snapshot()`` dict (or
    None when that rank published nothing). Returns a verdict dict::

        {"ok": bool, "verdict": "match"|"insufficient"|"lagging"|
                                "diverged",
         "ranks": {label: {"n":, "chain":}},
         # on divergence:
         "divergent_rank":, "step":, "index":, "schedules": {label: ...},
         "detail": "<one-line human diagnosis>"}

    - every (n, chain) equal → ``match``.
    - chains agree over the shared prefix but lengths differ →
      ``lagging``: a rank stopped issuing collectives (wedged or dead)
      while peers ran ahead; names the laggard and the first entry it is
      missing. Still ``ok`` — lag is a liveness symptom, not a schedule
      contradiction (the hang watchdog owns liveness).
    - a per-entry chain hash differs at some shared index →
      ``diverged``: names the first divergent index, the minority rank,
      the step recorded there, and both schedules' tails. Never ``ok``.
    """
    usable = {k: v for k, v in snaps.items() if v and v.get("n", 0) > 0}
    ranks = {
        k: {"n": v["n"], "chain": v["chain"]} for k, v in usable.items()
    }
    if len(usable) < 2:
        return {"ok": True, "verdict": "insufficient", "ranks": ranks}

    chains = {(v["n"], v["chain"]) for v in usable.values()}
    if len(chains) == 1:
        return {"ok": True, "verdict": "match", "ranks": ranks}

    # Index the retained tails by entry position: tails are bounded, so
    # the fork is only locatable when it falls inside every rank's
    # retained window — otherwise fall back to the lagging/short check.
    by_index: dict[int, dict[str, dict]] = {}
    for label, snap in usable.items():
        for entry in snap.get("tail", ()):
            by_index.setdefault(entry["i"], {})[label] = entry

    for idx in sorted(by_index):
        at = by_index[idx]
        if len(at) < 2:
            continue
        hashes = {e["h"] for e in at.values()}
        if len(hashes) == 1:
            continue
        # First divergent entry. The minority hash names the diverging
        # rank; on a 50/50 split (the 2-rank case), the side with FEWER
        # total entries diverged — it skipped a collective the other
        # side issued. Lowest label breaks any remaining tie.
        votes: dict[str, list[str]] = {}
        for label, entry in at.items():
            votes.setdefault(entry["h"], []).append(label)
        minority = min(
            votes.values(),
            key=lambda ls: (
                len(ls),
                min(usable[la]["n"] for la in ls),
                sorted(ls),
            ),
        )
        divergent = sorted(minority)[0]
        step = at[divergent].get("step")
        schedules = {
            label: [_entry_desc(e) for e in usable[label].get("tail", ())]
            for label in sorted(at)
        }
        detail = (
            f"collective schedules diverge at entry {idx}: rank "
            f"{divergent} issued [{_entry_desc(at[divergent])}] "
            f"(step={step}), peers issued "
            + "; ".join(
                f"{label} [{_entry_desc(e)}]"
                for label, e in sorted(at.items())
                if label != divergent
            )
        )
        return {
            "ok": False,
            "verdict": "diverged",
            "ranks": ranks,
            "divergent_rank": divergent,
            "step": step,
            "index": idx,
            "schedules": schedules,
            "detail": detail,
        }

    # No contradicting entry in the shared windows: a rank is simply
    # behind (fewer entries, same prefix) — wedged or killed mid-run.
    laggard = min(usable, key=lambda k: (usable[k]["n"], k))
    leader = max(usable, key=lambda k: (usable[k]["n"], k))
    missing = [
        _entry_desc(e)
        for e in usable[leader].get("tail", ())
        if e["i"] >= usable[laggard]["n"]
    ]
    detail = (
        f"rank {laggard} stopped at {usable[laggard]['n']} collectives "
        f"while {leader} reached {usable[leader]['n']}; first missing: "
        + (missing[0] if missing else "<outside retained tail>")
    )
    return {
        "ok": True,
        "verdict": "lagging",
        "ranks": ranks,
        "laggard": laggard,
        "leader": leader,
        "missing": missing,
        "detail": detail,
    }


def read_rank_schedules(gen_dir: str | Path) -> dict[str, dict | None]:
    """Collect per-rank schedule snapshots under a generation directory.

    Scans ``<gen_dir>/p<rank>/`` for the flight-recorder sidecars
    (heartbeat.json, crashdump.json) and any ``collective_schedule``
    events in the stream, keeping whichever snapshot saw the most
    entries — a crashdump taken after the last heartbeat is the fresher
    record. Purely best-effort: unreadable files yield None for that
    rank rather than raising (this runs on the postmortem path).
    """
    gen_dir = Path(gen_dir)
    out: dict[str, dict | None] = {}
    for rank_dir in sorted(gen_dir.glob("p*")):
        if not rank_dir.is_dir():
            continue
        best: dict | None = None
        for name in ("heartbeat.json", "crashdump.json"):
            for path in sorted(rank_dir.rglob(name)):
                try:
                    doc = json.loads(path.read_text())
                except (OSError, ValueError):
                    continue
                snap = doc.get("collective_schedule")
                if snap and snap.get("n", 0) > (best or {}).get("n", -1):
                    best = snap
        for path in sorted(rank_dir.rglob("events.jsonl")):
            try:
                lines = path.read_text().splitlines()
            except OSError:
                continue
            for line in lines:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if ev.get("kind") != "collective_schedule":
                    continue
                snap = {
                    "n": ev.get("n"),
                    "chain": ev.get("chain"),
                    "tail": ev.get("tail") or [],
                }
                if snap["n"] and snap["n"] > (best or {}).get("n", -1):
                    best = snap
        out[rank_dir.name] = best
    return out
