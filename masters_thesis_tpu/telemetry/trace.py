"""Distributed tracing: spans, cross-process propagation, critical path.

The metrics registry answers "how fast", the flight recorder answers
"what died" — this module answers **"where did the time go"** for one
unit of work: a serve request's life across admit → queue → batch →
device → deliver, or a supervised run's attempts across processes.

Writer side (:class:`Tracer`): spans are *close-only* records — nothing
is written when a span opens; one ``span`` event lands in the run's
existing ``events.jsonl`` when it closes, carrying the trace id, span id,
parent id, a wall-clock ``start_ts`` (for cross-process timeline merge)
and a monotonic-clock ``dur_s`` (immune to NTP steps). Open spans are
held in memory and snapshotted into the flight recorder's heartbeat /
crashdump sidecars, so a SIGKILLed process still accounts for its
in-flight work: the reader closes those as ``aborted``, not orphaned.

Trace context crosses process boundaries via env — ``MTT_TRACE_ID``
carries the trace, ``MTT_PARENT_SPAN`` the parent span id (see
:func:`child_env`). The supervisor exports both per attempt, so one
trace id spans every retry of a run and every process of a fleet. A root
span whose parent came from the env is tagged ``ext`` so the reader
never flags it as an orphan when the parent's stream is out of scope.

Everything here is **stdlib-only and host-side**: spans wrap boundaries
the code already has (the fences :class:`~.run.EpochRecorder` already
takes, the serve worker thread, the supervisor's wait loop) — zero
additions to traced/jit code, so TL/TA/SV rules stay green.

Reader side: :func:`build_trace_report` merges every stream under a
root, validates the span forest (orphans / negative durations / spans
left open by a *cleanly closed* process → exit 2), exports a merged
Chrome-trace-event JSON viewable in Perfetto (``chrome_trace``), and
computes critical-path attribution for the p50/p99 serve request and the
median epoch — a breakdown that must sum to measured wall time within
5%. Jax-free by contract, like ``summarize``/``aggregate``.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from pathlib import Path

# Env propagation contract: MTT_TRACE_ID carries the trace id into child
# processes; MTT_PARENT_SPAN names the span the child's roots hang off.
TRACE_ENV = "MTT_TRACE_ID"
PARENT_SPAN_ENV = "MTT_PARENT_SPAN"
# Event kind used on the run's existing events.jsonl stream.
SPAN_KIND = "span"
# Critical-path components must cover the measured wall within this.
SUM_TOLERANCE = 0.05

# Serve request component attrs, in lifecycle order. ``other`` (the
# residual vs the span's own wall) is appended by the reader.
SERVE_COMPONENTS = ("admit_s", "queue_s", "batch_form_s", "device_s",
                    "deliver_s")


def new_trace_id() -> str:
    return os.urandom(8).hex()


def new_span_id() -> str:
    return os.urandom(4).hex()


def current_trace_id(env=None) -> str | None:
    """The trace id this process inherited, if any."""
    return (os.environ if env is None else env).get(TRACE_ENV) or None


def child_env(parent=None, env=None, trace_id: str | None = None) -> dict:
    """A copy of ``env`` (default ``os.environ``) carrying trace context
    for a child process: ensures ``MTT_TRACE_ID`` (adopting the current
    one unless ``trace_id`` overrides) and, when ``parent`` is given (a
    :class:`Span` or span-id string), sets ``MTT_PARENT_SPAN``."""
    base = dict(os.environ if env is None else env)
    base[TRACE_ENV] = trace_id or base.get(TRACE_ENV) or new_trace_id()
    if parent is not None:
        base[PARENT_SPAN_ENV] = (
            parent.span_id if isinstance(parent, Span) else str(parent)
        )
    return base


class Span:
    """An open span. Cheap (slots, no I/O); closed via ``Tracer.end``."""

    __slots__ = (
        "name", "cat", "span_id", "parent_id", "trace_id", "start_ts",
        "t0", "attrs", "ext", "closed",
    )

    def __init__(self, name, cat, span_id, parent_id, trace_id, start_ts,
                 t0, attrs, ext):
        self.name = name
        self.cat = cat
        self.span_id = span_id
        self.parent_id = parent_id
        self.trace_id = trace_id
        self.start_ts = start_ts  # wall clock (cross-process timeline)
        self.t0 = t0              # monotonic (duration)
        self.attrs = attrs
        self.ext = ext            # parent id came from MTT_PARENT_SPAN
        self.closed = False

    def snapshot(self) -> dict:
        """The sidecar form a flight recorder flushes for open spans."""
        return {
            "name": self.name,
            "cat": self.cat,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            "start_ts": self.start_ts,
            "ext": self.ext,
            "attrs": dict(self.attrs),
        }


class Tracer:
    """Thread-safe span writer over an :class:`~.events.EventSink`.

    Adopts ``MTT_TRACE_ID``/``MTT_PARENT_SPAN`` from the environment so a
    supervised child, a grid cell, or a fleet worker lands on the trace
    its parent started. All emission is no-throw by design — a telemetry
    bug must never kill a training run or a serve worker.
    """

    def __init__(self, sink, trace_id: str | None = None, parent=None,
                 env=None):
        env = os.environ if env is None else env
        self.sink = sink
        self.trace_id = trace_id or env.get(TRACE_ENV) or new_trace_id()
        if parent is not None:
            self.root_parent = (
                parent.span_id if isinstance(parent, Span) else str(parent)
            )
            self._root_ext = False
        else:
            self.root_parent = env.get(PARENT_SPAN_ENV) or None
            self._root_ext = self.root_parent is not None
        self._lock = threading.Lock()
        self._open: dict[str, Span] = {}

    # ------------------------------------------------------------ writer

    def start(self, name: str, parent=None, cat: str | None = None,
              **attrs) -> Span:
        """Open a span. ``parent`` is a :class:`Span`, a span-id string,
        or None for a trace root (which hangs off ``MTT_PARENT_SPAN``
        when the env provided one)."""
        if parent is None:
            parent_id, ext = self.root_parent, self._root_ext
        elif isinstance(parent, Span):
            parent_id, ext = parent.span_id, False
        else:
            parent_id, ext = str(parent), False
        span = Span(
            name=name,
            cat=cat or name.split(".", 1)[0],
            span_id=new_span_id(),
            parent_id=parent_id,
            trace_id=self.trace_id,
            start_ts=time.time(),
            t0=time.perf_counter(),
            attrs=dict(attrs),
            ext=ext,
        )
        with self._lock:
            self._open[span.span_id] = span
        return span

    def end(self, span: Span, status: str = "ok",
            dur_s: float | None = None, **attrs) -> None:
        """Close a span and emit its ``span`` event. ``dur_s`` overrides
        the monotonic measurement when the caller owns the exact wall
        (e.g. the EpochRecorder's boundary-to-boundary epoch wall)."""
        if span is None or span.closed:
            return
        span.closed = True
        with self._lock:
            self._open.pop(span.span_id, None)
        if dur_s is None:
            dur_s = time.perf_counter() - span.t0
        if attrs:
            span.attrs.update(attrs)
        self._emit(span, status, dur_s)

    @contextlib.contextmanager
    def span(self, name: str, parent=None, cat: str | None = None, **attrs):
        """``with tracer.span("train.eval", parent=fit): ...`` — closes
        ``ok`` on exit, ``error`` on exception (re-raised)."""
        sp = self.start(name, parent=parent, cat=cat, **attrs)
        try:
            yield sp
        except BaseException:
            self.end(sp, status="error")
            raise
        self.end(sp)

    def emit_span(self, name: str, *, start_ts: float, dur_s: float,
                  parent=None, cat: str | None = None, status: str = "ok",
                  **attrs) -> None:
        """Emit a retroactive span that was never open (the caller timed
        it itself)."""
        if parent is None:
            parent_id, ext = self.root_parent, self._root_ext
        elif isinstance(parent, Span):
            parent_id, ext = parent.span_id, False
        else:
            parent_id, ext = str(parent), False
        span = Span(
            name=name, cat=cat or name.split(".", 1)[0],
            span_id=new_span_id(), parent_id=parent_id,
            trace_id=self.trace_id, start_ts=start_ts, t0=0.0,
            attrs=dict(attrs), ext=ext,
        )
        span.closed = True
        self._emit(span, status, dur_s)

    def _emit(self, span: Span, status: str, dur_s: float) -> None:
        try:
            self.sink.emit(
                SPAN_KIND,
                name=span.name,
                cat=span.cat,
                span_id=span.span_id,
                parent_id=span.parent_id,
                trace_id=span.trace_id,
                start_ts=span.start_ts,
                dur_s=dur_s,
                status=status,
                ext=span.ext,
                attrs=span.attrs,
            )
        except Exception:
            pass  # tracing must never kill the traced work

    # ------------------------------------------------- sidecar interface

    def open_spans(self) -> list[dict]:
        """Snapshot of currently-open spans — the flight recorder flushes
        this into heartbeat.json/crashdump.json so a killed process's
        in-flight work is recoverable."""
        with self._lock:
            spans = list(self._open.values())
        return [s.snapshot() for s in spans]

    def close_all(self, status: str = "aborted") -> int:
        """Close every still-open span (children before parents). Called
        by ``TelemetryRun.close`` so an exception path that skips
        individual ``end`` calls still leaves a well-formed tree."""
        with self._lock:
            spans = sorted(
                self._open.values(), key=lambda s: s.start_ts, reverse=True
            )
        for span in spans:
            self.end(span, status=status)
        return len(spans)


def adopt_orphaned_spans(run_dir: str | Path, sink) -> int:
    """Close the previous attempt's open spans into a re-opened stream.

    A supervised retry that resumes IN PLACE re-opens the same run dir,
    and its fresh flight recorder will overwrite ``heartbeat.json`` /
    ``crashdump.json`` — the only record of the spans the dead attempt
    left open. Called before that overwrite (``attach_flight_recorder``),
    this emits the sidecar's unclosed spans as ``aborted`` span events,
    exactly as the reader would have synthesized them, so the dead
    attempt's child spans keep a parent in the merged tree. No-throw;
    returns the number of spans adopted (0 for a fresh dir).
    """
    try:
        from masters_thesis_tpu.telemetry.aggregate import _read_json
        from masters_thesis_tpu.telemetry.events import read_events
        from masters_thesis_tpu.telemetry.flightrec import (
            CRASHDUMP_FILENAME,
            HEARTBEAT_FILENAME,
        )

        run_dir = Path(run_dir)
        crashdump = _read_json(run_dir / CRASHDUMP_FILENAME)
        heartbeat = _read_json(run_dir / HEARTBEAT_FILENAME)
        closed_cleanly = bool(heartbeat and heartbeat.get("closed"))
        sidecar = _sidecar_open_spans(crashdump) or (
            [] if closed_cleanly else _sidecar_open_spans(heartbeat)
        )
        if not sidecar:
            return 0
        sidecar_ts = (crashdump or {}).get("ts") or (
            heartbeat or {}).get("ts")
        closed_ids = {
            ev.get("span_id")
            for ev in read_events(run_dir / "events.jsonl")
            if ev.get("kind") == SPAN_KIND
        }
        adopted = 0
        for s in sidecar:
            if s.get("span_id") in closed_ids:
                continue
            start_ts = s.get("start_ts")
            dur = 0.0
            if start_ts is not None and sidecar_ts is not None:
                dur = max(0.0, float(sidecar_ts) - float(start_ts))
            sink.emit(
                SPAN_KIND,
                name=s.get("name"),
                cat=s.get("cat"),
                span_id=s.get("span_id"),
                parent_id=s.get("parent_id"),
                trace_id=s.get("trace_id"),
                start_ts=start_ts,
                dur_s=dur,
                status="aborted",
                ext=bool(s.get("ext")),
                attrs={**(s.get("attrs") or {}), "synthesized": True},
            )
            adopted += 1
        return adopted
    except Exception:
        return 0  # crash forensics must never block the new attempt


# ======================================================================
# Reader side: collect, validate, export, attribute. Jax-free.
# ======================================================================


def _sidecar_open_spans(obj: dict | None) -> list[dict]:
    if not obj:
        return []
    spans = obj.get("open_spans")
    return [s for s in spans if isinstance(s, dict)] if isinstance(
        spans, list) else []


def collect_spans(root: str | Path) -> dict:
    """Merge span records from every stream under ``root``.

    Returns ``{"spans": [...], "problems": [...], "streams": n,
    "profile_windows": [...]}`` where each span record carries the event
    envelope (host/pid/proc) plus a ``stream`` label. Open spans found in
    the sidecars of *dead* processes are synthesized as ``aborted``;
    open spans claimed by a *cleanly closed* process are a bug
    (``unclosed``) and land in ``problems``.
    """
    from masters_thesis_tpu.telemetry.aggregate import (
        _read_json,
        discover_streams,
    )
    from masters_thesis_tpu.telemetry.events import read_events
    from masters_thesis_tpu.telemetry.flightrec import (
        CRASHDUMP_FILENAME,
        HEARTBEAT_FILENAME,
    )

    root = Path(root)
    streams = discover_streams(root)
    spans: list[dict] = []
    problems: list[dict] = []
    windows: list[dict] = []
    seen_dirs: set[Path] = set()
    for path in streams:
        if path.parent in seen_dirs:
            continue
        seen_dirs.add(path.parent)
        try:
            rel = str(path.parent.relative_to(root))
        except ValueError:
            rel = str(path.parent)
        stream = rel or "."
        events = read_events(path)
        envelope = {"host": None, "pid": None, "proc": None}
        for ev in events:
            kind = ev.get("kind")
            for key in envelope:
                if envelope[key] is None and ev.get(key) is not None:
                    envelope[key] = ev[key]
            if kind == "profile_window":
                windows.append({**ev, "stream": stream})
            elif kind == SPAN_KIND:
                spans.append({
                    "name": ev.get("name"),
                    "cat": ev.get("cat"),
                    "span_id": ev.get("span_id"),
                    "parent_id": ev.get("parent_id"),
                    "trace_id": ev.get("trace_id"),
                    "start_ts": ev.get("start_ts"),
                    "dur_s": ev.get("dur_s"),
                    "status": ev.get("status", "ok"),
                    "ext": bool(ev.get("ext")),
                    "attrs": ev.get("attrs") or {},
                    "host": ev.get("host"),
                    "pid": ev.get("pid"),
                    "proc": ev.get("proc"),
                    "stream": stream,
                })
        crashdump = _read_json(path.parent / CRASHDUMP_FILENAME)
        heartbeat = _read_json(path.parent / HEARTBEAT_FILENAME)
        closed_cleanly = bool(heartbeat and heartbeat.get("closed"))
        closed_ids = {s["span_id"] for s in spans if s.get("span_id")}
        # Prefer the crashdump snapshot (dump-time truth) over the last
        # periodic heartbeat; a span closed in the stream supersedes both
        # (a SIGTERM dump races the normal close path).
        sidecar = _sidecar_open_spans(crashdump) or (
            [] if closed_cleanly else _sidecar_open_spans(heartbeat)
        )
        sidecar_ts = (crashdump or {}).get("ts") or (
            heartbeat or {}).get("ts")
        if closed_cleanly and not crashdump:
            for s in _sidecar_open_spans(heartbeat):
                if s.get("span_id") in closed_ids:
                    continue
                problems.append({
                    "kind": "unclosed",
                    "span_id": s.get("span_id"),
                    "detail": (
                        f"span {s.get('name')!r} ({s.get('span_id')}) still "
                        f"open after clean close of stream {stream}"
                    ),
                })
            continue
        for s in sidecar:
            if s.get("span_id") in closed_ids:
                continue
            start_ts = s.get("start_ts")
            dur = None
            if start_ts is not None and sidecar_ts is not None:
                dur = max(0.0, float(sidecar_ts) - float(start_ts))
            spans.append({
                "name": s.get("name"),
                "cat": s.get("cat"),
                "span_id": s.get("span_id"),
                "parent_id": s.get("parent_id"),
                "trace_id": s.get("trace_id"),
                "start_ts": start_ts,
                "dur_s": dur if dur is not None else 0.0,
                "status": "aborted",
                "ext": bool(s.get("ext")),
                "attrs": {**(s.get("attrs") or {}), "synthesized": True},
                "host": envelope["host"],
                "pid": envelope["pid"],
                "proc": envelope["proc"],
                "stream": stream,
            })
    return {
        "spans": spans,
        "problems": problems,
        "streams": len(seen_dirs),
        "profile_windows": windows,
    }


def validate_spans(spans: list[dict],
                   problems: list[dict] | None = None) -> list[dict]:
    """Broken-tree findings: orphans (a parent id resolving to no known
    span, unless the link was env-external) and negative durations.
    Extends and returns ``problems``."""
    problems = list(problems or [])
    known = {s["span_id"] for s in spans if s.get("span_id")}
    for s in spans:
        dur = s.get("dur_s")
        if dur is not None and dur < 0:
            problems.append({
                "kind": "negative_duration",
                "span_id": s.get("span_id"),
                "detail": (
                    f"span {s.get('name')!r} ({s.get('span_id')}) has "
                    f"negative duration {dur:.6f}s"
                ),
            })
        parent = s.get("parent_id")
        if parent and not s.get("ext") and parent not in known:
            problems.append({
                "kind": "orphan",
                "span_id": s.get("span_id"),
                "detail": (
                    f"span {s.get('name')!r} ({s.get('span_id')}) names "
                    f"unknown parent {parent} (stream {s.get('stream')})"
                ),
            })
    return problems


# ------------------------------------------------------- Chrome export


def chrome_trace(spans: list[dict],
                 profile_windows: list[dict] | None = None) -> dict:
    """A merged Chrome-trace-event JSON (Perfetto-loadable): one process
    row per stream, one thread row per span category; overlapping serve
    requests as async (b/e) events so concurrent lifetimes render as
    separate tracks instead of garbled nesting."""
    streams = sorted({s["stream"] for s in spans})
    pid_of = {stream: i for i, stream in enumerate(streams)}
    tid_of: dict[tuple[int, str], int] = {}
    events: list[dict] = []

    def tid(pid: int, track: str) -> int:
        key = (pid, track)
        if key not in tid_of:
            tid_of[key] = len([k for k in tid_of if k[0] == pid]) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tid_of[key], "args": {"name": track},
            })
        return tid_of[key]

    for stream in streams:
        first = next(s for s in spans if s["stream"] == stream)
        label = f"{stream}"
        if first.get("proc") is not None:
            label = f"p{first['proc']} · {stream}"
        if first.get("host"):
            label += f" @ {first['host']}"
        events.append({
            "ph": "M", "name": "process_name", "pid": pid_of[stream],
            "tid": 0, "args": {"name": label},
        })
        events.append({
            "ph": "M", "name": "process_sort_index", "pid": pid_of[stream],
            "tid": 0, "args": {"sort_index": pid_of[stream]},
        })

    epoch_index: dict[tuple[str, int], dict] = {}
    for s in spans:
        if s.get("start_ts") is None or s.get("dur_s") is None:
            continue
        pid = pid_of[s["stream"]]
        args = {
            "span_id": s.get("span_id"),
            "parent_id": s.get("parent_id"),
            "trace_id": s.get("trace_id"),
            "status": s.get("status"),
            **{k: v for k, v in (s.get("attrs") or {}).items()},
        }
        ts_us = float(s["start_ts"]) * 1e6
        dur_us = max(0.0, float(s["dur_s"])) * 1e6
        if s.get("name") == "serve.request":
            common = {
                "cat": s.get("cat") or "serve", "name": s["name"],
                "id": str(s.get("span_id")), "pid": pid,
                "tid": tid(pid, "serve.requests"),
            }
            events.append({**common, "ph": "b", "ts": ts_us, "args": args})
            events.append({**common, "ph": "e", "ts": ts_us + dur_us,
                           "args": {}})
        else:
            events.append({
                "ph": "X", "name": s.get("name") or "?",
                "cat": s.get("cat") or "span",
                "ts": ts_us, "dur": dur_us, "pid": pid,
                "tid": tid(pid, s.get("cat") or "span"),
                "args": args,
            })
        if s.get("name") == "train.epoch":
            ep = (s.get("attrs") or {}).get("epoch")
            if ep is not None:
                epoch_index[(s["stream"], int(ep))] = s

    # jax.profiler capture windows, placed on the timeline via the epoch
    # spans they bracket (the window event itself is emitted at close).
    for win in profile_windows or []:
        lo = epoch_index.get((win["stream"], int(win.get("start_epoch", -1))
                              if win.get("start_epoch") is not None else -1))
        hi = epoch_index.get((win["stream"], int(win.get("end_epoch", -1))
                              if win.get("end_epoch") is not None else -1))
        if lo is None or hi is None:
            continue
        start = float(lo["start_ts"])
        end = float(hi["start_ts"]) + float(hi["dur_s"])
        pid = pid_of.get(win["stream"], 0)
        events.append({
            "ph": "X", "name": "jax.profiler window", "cat": "profiler",
            "ts": start * 1e6, "dur": max(0.0, end - start) * 1e6,
            "pid": pid, "tid": tid(pid, "jax.profiler"),
            "args": {"trace_dir": win.get("trace_dir"),
                     "start_epoch": win.get("start_epoch"),
                     "end_epoch": win.get("end_epoch")},
        })

    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ------------------------------------------------- critical-path math


def _breakdown(wall: float, components: dict[str, float]) -> dict:
    """Components + an ``other`` residual, with the ≤5% coverage check.
    ``other`` is clamped at 0 so a small negative residual (overlapping
    host timers) reads as over-coverage, which the check also catches."""
    comp = {k: float(v) for k, v in components.items() if v is not None}
    total = sum(comp.values())
    residual = wall - total
    if residual > 0:
        comp["other"] = residual
    shares = (
        {k: v / wall for k, v in comp.items()} if wall > 0
        else {k: 0.0 for k in comp}
    )
    return {
        "wall_s": wall,
        "components_s": comp,
        "shares": shares,
        "unattributed_frac": (
            max(0.0, residual) / wall if wall > 0 else 0.0
        ),
        "sum_ok": abs(residual) <= SUM_TOLERANCE * wall,
        "gap_s": abs(residual),
    }


def _quantile_item(items: list, q: float):
    if not items:
        return None
    idx = min(len(items) - 1, max(0, round(q * (len(items) - 1))))
    return items[idx]


def serve_attribution(spans: list[dict]) -> dict | None:
    """p50/p99 request breakdowns + aggregate shares over every
    ``serve.request`` span (the bench's ``detail.serve`` source)."""
    requests = [s for s in spans if s.get("name") == "serve.request"]
    if not requests:
        return None
    completed = sorted(
        (s for s in requests if s.get("status") == "ok"
         and s.get("dur_s") is not None),
        key=lambda s: s["dur_s"],
    )
    shed_by_reason: dict[str, int] = {}
    for s in requests:
        if s.get("status") in ("shed", "rejected_late", "error", "aborted"):
            key = (s.get("attrs") or {}).get("reason_category") or s["status"]
            shed_by_reason[key] = shed_by_reason.get(key, 0) + 1

    def request_breakdown(s: dict) -> dict:
        attrs = s.get("attrs") or {}
        b = _breakdown(
            float(s["dur_s"]),
            {k: attrs.get(k) for k in SERVE_COMPONENTS},
        )
        b["rid"] = attrs.get("rid")
        return b

    total_wall = sum(s["dur_s"] for s in completed)
    total_queue = sum(
        (s.get("attrs") or {}).get("queue_s") or 0.0 for s in completed
    )
    total_device = sum(
        (s.get("attrs") or {}).get("device_s") or 0.0 for s in completed
    )
    p50 = _quantile_item(completed, 0.50)
    p99 = _quantile_item(completed, 0.99)
    return {
        "requests": len(requests),
        "completed": len(completed),
        "shed": sum(1 for s in requests if s.get("status") == "shed"),
        "rejected_late": sum(
            1 for s in requests if s.get("status") == "rejected_late"
        ),
        "shed_by_reason": shed_by_reason,
        "queue_wait_share": (
            total_queue / total_wall if total_wall > 0 else None
        ),
        "compute_share": (
            total_device / total_wall if total_wall > 0 else None
        ),
        "p50": request_breakdown(p50) if p50 else None,
        "p99": request_breakdown(p99) if p99 else None,
    }


def epoch_attribution(spans: list[dict]) -> dict | None:
    """Median-epoch breakdown over ``train.epoch`` spans. The epoch wall
    decomposes as host dispatch + (in stream mode) data wait + the
    device/overlap remainder — the boundary-to-boundary semantics the
    EpochRecorder already defines, so components tile the wall exactly."""
    epochs = sorted(
        (s for s in spans if s.get("name") == "train.epoch"
         and s.get("status") == "ok" and s.get("dur_s") is not None),
        key=lambda s: s["dur_s"],
    )
    if not epochs:
        return None

    def breakdown(s: dict) -> dict:
        attrs = s.get("attrs") or {}
        wall = float(s["dur_s"])
        dispatch = min(float(attrs.get("dispatch_s") or 0.0), wall)
        data_wait = min(float(attrs.get("data_wait_s") or 0.0),
                        max(0.0, dispatch))
        comp = {
            "dispatch_s": dispatch - data_wait,
            "data_wait_s": data_wait,
            "device_overlap_s": max(0.0, wall - dispatch),
        }
        b = _breakdown(wall, comp)
        b["epoch"] = attrs.get("epoch")
        b["fenced"] = attrs.get("fenced")
        b["device_s"] = attrs.get("device_s")
        return b

    median = _quantile_item(epochs, 0.50)
    return {
        "epochs": len(epochs),
        "median": breakdown(median),
        "slowest": breakdown(epochs[-1]),
    }


# ------------------------------------------------------------- report


def build_trace_report(root: str | Path,
                       out: str | Path | None = None) -> dict:
    """Collect + validate + attribute + export: the ``trace`` CLI body.
    ``exit_code``: 0 ok, 1 no spans found, 2 broken span tree."""
    root = Path(root)
    collected = collect_spans(root)
    spans = collected["spans"]
    problems = validate_spans(spans, collected["problems"])
    traces: dict[str, dict] = {}
    for s in spans:
        t = traces.setdefault(
            s.get("trace_id") or "?", {"spans": 0, "streams": set()}
        )
        t["spans"] += 1
        t["streams"].add(s["stream"])
    chrome = chrome_trace(spans, collected["profile_windows"])
    chrome_path = None
    if out is not None and spans:
        chrome_path = Path(out)
        chrome_path.parent.mkdir(parents=True, exist_ok=True)
        chrome_path.write_text(json.dumps(chrome))
    report = {
        "root": str(root),
        "streams": collected["streams"],
        "spans": len(spans),
        "aborted": sum(1 for s in spans if s.get("status") == "aborted"),
        "traces": {
            tid: {"spans": t["spans"], "streams": sorted(t["streams"])}
            for tid, t in sorted(traces.items())
        },
        "problems": problems,
        "serve": serve_attribution(spans),
        "epoch": epoch_attribution(spans),
        "chrome_events": len(chrome["traceEvents"]),
        "chrome_path": str(chrome_path) if chrome_path else None,
        "profile_windows": len(collected["profile_windows"]),
    }
    if not spans:
        report["exit_code"] = 1
    elif problems:
        report["exit_code"] = 2
    else:
        report["exit_code"] = 0
    return report


def _fmt_breakdown(b: dict | None) -> str:
    if b is None:
        return "n/a"
    wall = b["wall_s"]
    unit, scale = ("ms", 1e3) if wall < 1.0 else ("s", 1.0)
    parts = " + ".join(
        f"{name.removesuffix('_s')} {100.0 * share:.0f}%"
        for name, share in sorted(
            b["shares"].items(), key=lambda kv: -kv[1]
        )
        if share >= 0.005
    )
    ok = "" if b["sum_ok"] else "  [components do not cover wall]"
    return f"{wall * scale:.3g}{unit} = {parts}{ok}"


def render_trace_text(report: dict) -> str:
    lines = [
        f"trace          : {report['spans']} span(s) across "
        f"{report['streams']} stream(s), {len(report['traces'])} trace(s)"
        + (f", {report['aborted']} aborted" if report["aborted"] else ""),
    ]
    for tid, t in report["traces"].items():
        lines.append(
            f"  {tid}  {t['spans']} span(s) in {', '.join(t['streams'])}"
        )
    serve = report.get("serve")
    if serve:
        lines.append(
            f"serve          : {serve['completed']}/{serve['requests']} "
            f"completed, {serve['shed']} shed, "
            f"{serve['rejected_late']} late-rejected"
        )
        if serve["shed_by_reason"]:
            lines.append(
                "  shed by reason: " + ", ".join(
                    f"{k}={v}" for k, v in sorted(
                        serve["shed_by_reason"].items())
                )
            )
        if serve.get("queue_wait_share") is not None:
            lines.append(
                f"  queue-wait share {100 * serve['queue_wait_share']:.1f}% "
                f"| compute share {100 * (serve['compute_share'] or 0):.1f}%"
            )
        lines.append(f"  p50 request  : {_fmt_breakdown(serve['p50'])}")
        lines.append(f"  p99 request  : {_fmt_breakdown(serve['p99'])}")
    epoch = report.get("epoch")
    if epoch:
        med = epoch["median"]
        lines.append(
            f"epoch median   : {_fmt_breakdown(med)}"
            + (f"  (epoch {med.get('epoch')})"
               if med.get("epoch") is not None else "")
        )
    if report.get("chrome_path"):
        lines.append(
            f"chrome trace   : {report['chrome_path']} "
            f"({report['chrome_events']} events; open in Perfetto)"
        )
    if report["problems"]:
        lines.append("BROKEN SPAN TREE:")
        lines.extend(f"  - {p['detail']}" for p in report["problems"])
    elif report["spans"]:
        lines.append("span tree      : ok")
    else:
        lines.append("span tree      : no spans found")
    return "\n".join(lines)


# ----------------------------------------------------------- selfcheck


def _selfcheck_fixture(root: Path) -> str:
    """A synthetic multi-process trace: a supervisor with two attempts
    (one killed mid-epoch, one finishing), a 2-process fleet of epoch
    spans, and a serve stream with sheds — all through the real writer
    classes so the fixture exercises the same code paths as production."""
    from masters_thesis_tpu.telemetry.events import EventSink

    t0 = time.time() - 100.0
    trace_id = new_trace_id()

    sup_sink = EventSink(root / "sup" / "events.jsonl", run_id="sup")
    sup = Tracer(sup_sink, trace_id=trace_id, env={})
    run_span = sup.start("supervisor.run")
    run_span.start_ts = t0
    a1 = sup.start("supervisor.attempt", parent=run_span, n=1)
    a1.start_ts = t0 + 0.1
    a2 = sup.start("supervisor.attempt", parent=run_span, n=2)
    a2.start_ts = t0 + 4.5
    sup.end(a1, status="error", dur_s=4.0, rc=-15)
    sup.end(a2, status="ok", dur_s=5.0, rc=0)
    sup.end(run_span, status="ok", dur_s=10.0)
    sup_sink.emit("supervisor_verdict", ok=True)
    sup_sink.close()

    # Worker p0: killed mid-epoch — its fit span survives only in the
    # crashdump sidecar and must come back as `aborted`, not orphaned.
    w0_sink = EventSink(root / "w0" / "events.jsonl", run_id="w0", proc=0,
                        nproc=2)
    w0 = Tracer(w0_sink, trace_id=trace_id,
                env={PARENT_SPAN_ENV: a1.span_id})
    fit0 = w0.start("trainer.fit")
    fit0.start_ts = t0 + 0.2
    for ep in range(2):
        w0.emit_span(
            "train.epoch", start_ts=t0 + 0.3 + ep, dur_s=1.0,
            parent=fit0, epoch=ep, dispatch_s=0.12, data_wait_s=0.02,
            fenced=(ep == 0),
        )
    (root / "w0" / "crashdump.json").write_text(json.dumps({
        "reason": "signal: SIGKILL (simulated)", "ts": t0 + 4.0,
        "open_spans": w0.open_spans(),
    }))
    w0_sink.close()

    # Worker p1: the healthy retry, sharing the SAME trace id via env.
    w1_sink = EventSink(root / "w1" / "events.jsonl", run_id="w1", proc=1,
                        nproc=2)
    w1 = Tracer(w1_sink, trace_id=trace_id,
                env={PARENT_SPAN_ENV: a2.span_id})
    fit1 = w1.start("trainer.fit")
    fit1.start_ts = t0 + 4.6
    for ep in range(3):
        w1.emit_span(
            "train.epoch", start_ts=t0 + 4.7 + ep, dur_s=1.0 + 0.1 * ep,
            parent=fit1, epoch=ep, dispatch_s=0.1, data_wait_s=0.0,
            fenced=(ep == 0),
        )
    w1.end(fit1, dur_s=4.8)
    w1_sink.emit("run_finished", epochs=3, total_steps=30)
    w1_sink.close()

    # Serve stream: 20 requests with exhaustive component attribution.
    sv_sink = EventSink(root / "serve" / "events.jsonl", run_id="serve")
    sv = Tracer(sv_sink, trace_id=trace_id, env={})
    server_span = sv.start("serve.server")
    server_span.start_ts = t0 + 20.0
    for i in range(20):
        wall = 0.004 + 0.0005 * i
        queue = 0.4 * wall
        device = 0.5 * wall
        sv.emit_span(
            "serve.request", start_ts=t0 + 20.1 + 0.01 * i, dur_s=wall,
            parent=server_span, rid=i, admit_s=0.02 * wall, queue_s=queue,
            batch_form_s=0.02 * wall, device_s=device,
            deliver_s=0.02 * wall,
        )
    for i, (status, category) in enumerate(
        (("shed", "queue_full"), ("shed", "deadline_infeasible"),
         ("rejected_late", "rejected_late")),
    ):
        sv.emit_span(
            "serve.request", start_ts=t0 + 20.5 + 0.01 * i, dur_s=0.001,
            parent=server_span, status=status, rid=100 + i,
            reason_category=category,
        )
    sv.end(server_span, dur_s=2.0)
    sv_sink.close()
    return trace_id


def selfcheck(echo=print) -> int:
    """Hermetic fixture → report → Chrome JSON → attribution checks,
    plus the negative case (a deliberately broken tree must exit 2).
    Returns a process exit code; gated in tools/check.sh."""
    import tempfile

    failures: list[str] = []

    def check(cond: bool, what: str) -> None:
        if cond:
            echo(f"  ok: {what}")
        else:
            failures.append(what)

    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        trace_id = _selfcheck_fixture(root)
        out = root / "trace.json"
        report = build_trace_report(root, out=out)
        check(report["exit_code"] == 0,
              f"clean fixture exits 0 (got {report['exit_code']}: "
              f"{report['problems']})")
        check(report["aborted"] == 1,
              f"killed worker's open span aborted (got {report['aborted']})")
        check(len(report["traces"]) == 1
              and trace_id in report["traces"],
              "one trace id spans supervisor + both workers + serve")
        if trace_id in report["traces"]:
            check(len(report["traces"][trace_id]["streams"]) == 4,
                  "all 4 process streams joined the trace")
        serve = report["serve"] or {}
        check(serve.get("completed") == 20 and serve.get("shed") == 2,
              "serve request census (20 completed / 2 shed)")
        p99 = serve.get("p99") or {}
        check(bool(p99.get("sum_ok")),
              "p99 request components cover wall within 5%")
        qws = serve.get("queue_wait_share")
        check(qws is not None and abs(qws - 0.4) < 0.01,
              f"queue-wait share ≈ 40% (got {qws})")
        med = (report["epoch"] or {}).get("median") or {}
        check(bool(med.get("sum_ok")),
              "median epoch components cover wall within 5%")
        chrome = json.loads(out.read_text())
        events = chrome.get("traceEvents", [])
        check(bool(events) and all(
            {"ph", "pid"} <= set(e) for e in events),
            "chrome trace events well-formed")
        begins = sum(1 for e in events if e.get("ph") == "b")
        ends = sum(1 for e in events if e.get("ph") == "e")
        check(begins == ends and begins == 23,
              f"async request events balanced ({begins}b/{ends}e)")
        check(any(e.get("ph") == "M" and e.get("name") == "process_name"
                  for e in events), "process_name metadata present")

    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        from masters_thesis_tpu.telemetry.events import EventSink

        sink = EventSink(root / "bad" / "events.jsonl", run_id="bad")
        bad = Tracer(sink, env={})
        bad.emit_span("x.orphan", start_ts=1.0, dur_s=1.0,
                      parent="feedfeed")
        bad.emit_span("x.negative", start_ts=2.0, dur_s=-0.5)
        sink.close()
        (root / "bad" / "heartbeat.json").write_text(json.dumps({
            "ts": 3.0, "closed": True,
            "open_spans": [{"name": "x.unclosed", "span_id": "aa11aa11",
                            "start_ts": 2.5}],
        }))
        report = build_trace_report(root)
        kinds = {p["kind"] for p in report["problems"]}
        check(report["exit_code"] == 2, "broken fixture exits 2")
        check(kinds == {"orphan", "negative_duration", "unclosed"},
              f"all three problem classes detected (got {sorted(kinds)})")

    if failures:
        for f in failures:
            echo(f"  FAIL: {f}")
        echo(f"trace selfcheck: {len(failures)} failure(s)")
        return 1
    echo("trace selfcheck: ok")
    return 0
