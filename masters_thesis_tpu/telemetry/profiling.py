"""Programmatic ``jax.profiler`` capture windows.

``ProfilerWindow((N, M), trace_dir)`` captures epochs N..M (inclusive)
into ``trace_dir`` — the trainer starts the trace before dispatching
epoch N and stops it after epoch M behind a ``block_until_ready`` fence
(a sampling boundary: the fence is what makes the trace end at a clean
program boundary, and it is the ONLY fence profiling adds). A window of
``None`` is a no-op object so the trainer's loop carries no conditionals.

jax is imported lazily at start time: constructing a window (e.g. from
config parsing) must not initialize a backend.
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable


class ProfilerWindow:
    def __init__(
        self,
        window: tuple[int, int] | None,
        trace_dir: str | Path,
        telemetry=None,
    ):
        if window is not None:
            start, end = int(window[0]), int(window[1])
            if start < 0 or end < start:
                raise ValueError(
                    f"profile window must be 0 <= start <= end, got {window!r}"
                )
            window = (start, end)
        self.window = window
        self.trace_dir = Path(trace_dir)
        self.telemetry = telemetry
        self.active = False

    def wants_fence(self, epoch: int) -> bool:
        """True for epochs inside the window: the trainer fences these so
        the captured trace aligns with epoch boundaries."""
        return (
            self.window is not None
            and self.window[0] <= epoch <= self.window[1]
        )

    def maybe_start(self, epoch: int) -> None:
        if self.window is None or self.active or epoch != self.window[0]:
            return
        import jax

        self.trace_dir.mkdir(parents=True, exist_ok=True)
        jax.profiler.start_trace(str(self.trace_dir))
        self.active = True

    def maybe_stop(self, epoch: int, fence: Callable[[], None]) -> None:
        if not self.active or epoch < self.window[1]:
            return
        self._stop(fence)

    def close(self, fence: Callable[[], None]) -> None:
        """Close a still-open trace (divergence break mid-window) so the
        diagnostic data is written out rather than lost."""
        if self.active:
            self._stop(fence)

    def _stop(self, fence: Callable[[], None]) -> None:
        import jax

        fence()
        jax.profiler.stop_trace()
        self.active = False
        if self.telemetry is not None:
            self.telemetry.event(
                "profile_window",
                start_epoch=self.window[0],
                end_epoch=self.window[1],
                trace_dir=str(self.trace_dir),
            )
