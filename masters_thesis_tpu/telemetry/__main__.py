"""``python -m masters_thesis_tpu.telemetry`` — run reports from JSONL.

Subcommands:

- ``summarize <run>`` — render the run report for a run directory (or an
  ``events.jsonl`` file directly). Exit codes: 0 = ok, 1 = could not load,
  2 = the report shows contract violations (recompiles > 1, failed
  preflight, divergence) — so CI and the grid runner can gate on it.
- ``selfcheck`` — hermetic smoke of the whole pipeline (registry ->
  events -> report) in a temp dir; the tools/check.sh telemetry gate.

Deliberately jax-free: summarize runs on operator machines where touching
the backend can hang on a wedged relay lease (docs/OPERATIONS.md).
"""

from __future__ import annotations

import argparse
import sys
import tempfile


def _summarize(args) -> int:
    from masters_thesis_tpu.telemetry.report import (
        render_json,
        render_text,
        summarize_path,
    )

    try:
        report = summarize_path(args.run)
    except FileNotFoundError as exc:
        print(f"summarize: {exc}", file=sys.stderr)
        return 1
    print(render_json(report) if args.json else render_text(report))
    return 2 if report["violations"] else 0


def _selfcheck(args) -> int:
    from masters_thesis_tpu.telemetry.report import summarize_path
    from masters_thesis_tpu.telemetry.run import TelemetryRun

    with tempfile.TemporaryDirectory() as tmp:
        tel = TelemetryRun(tmp, run_id="selfcheck")
        tel.event(
            "run_started", platform="cpu", n_devices=1, strategy="selfcheck",
            epoch_mode="scan", steps_per_epoch=4,
        )
        for epoch in range(3):
            tel.event(
                "epoch", epoch=epoch, steps=4, wall_s=0.4 if epoch else 2.0,
                dispatch_s=0.01, device_s=0.38 if epoch else None,
                data_wait_s=0.0, compile_events=0 if epoch else 1,
                compiled=not epoch, fenced=True, steps_per_sec=10.0,
            )
            tel.histogram("train/epoch_wall_s").observe(0.4)
        tel.event(
            "run_finished", epochs=3, total_steps=12, steps_per_sec=10.0,
            diverged=False, best_val=0.5, epoch_compiles=1, eval_compiles=1,
        )
        tel.snapshot_metrics()
        tel.close()
        report = summarize_path(tmp)
    ok = (
        report["compiles"]["train_epoch"] == 1
        and report["steps_per_sec"] == 10.0
        and report["step_time_ms"]["p50"] is not None
        and not report["violations"]
    )
    print("telemetry: selfcheck " + ("ok" if ok else f"FAILED: {report}"))
    return 0 if ok else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m masters_thesis_tpu.telemetry",
        description="run reports over structured step-level telemetry",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    p_sum = sub.add_parser(
        "summarize", help="render a run report from a run dir's events.jsonl"
    )
    p_sum.add_argument(
        "run", help="run directory (or events.jsonl file) to summarize"
    )
    p_sum.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p_sum.set_defaults(fn=_summarize)
    p_check = sub.add_parser(
        "selfcheck", help="hermetic registry->events->report smoke"
    )
    p_check.set_defaults(fn=_selfcheck)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # summarize | head/less closed the pipe
        sys.exit(0)
