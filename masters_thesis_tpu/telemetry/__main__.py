"""``python -m masters_thesis_tpu.telemetry`` — run reports from JSONL.

Subcommands:

- ``summarize <run>`` — render the run report for a run directory (or an
  ``events.jsonl`` file directly). Exit codes: 0 = ok, 1 = could not load,
  2 = the report shows contract violations (recompiles > 1, failed
  preflight, divergence) — so CI and the grid runner can gate on it.
- ``aggregate <root>`` — merge every per-process ``events.jsonl`` under a
  root into one fleet view: per-host epoch-time skew, collective wait
  attribution, straggler identification, heartbeat gaps. Exit codes as
  above (2 = the fleet has failures).
- ``postmortem <root>`` — the aggregate view led by a one-line verdict on
  how the run ended (which process died/hung/straggled and where). Exit 2
  when any process died, hung, or stalled — so sweep runners and CI can
  gate on it. ``--selfcheck`` runs a hermetic simulated-fleet smoke
  instead (the tools/check.sh gate).
- ``ledger [path]`` — diff the latest perf-ledger round
  (``results/perf_ledger.jsonl``, appended by ``bench.py``) against the
  baseline window at equal config. Exit codes: 0 = ok / nothing to gate,
  1 = could not load, 2 = >threshold steps/s or utilization regression.
  ``--selfcheck`` fabricates a two-round ledger and verifies the gate
  fires (the tools/check.sh gate).
- ``trace <run>`` — merge every process's ``span`` events (plus open
  spans recovered from heartbeat/crashdump sidecars of killed processes)
  into one validated span forest: critical-path attribution for the
  p50/p99 serve request and the median epoch, and a Chrome-trace-event
  JSON (``--out``, default ``<run>/trace.json``) viewable in Perfetto.
  Exit codes: 0 = ok, 1 = no spans found, 2 = broken span tree (orphans,
  negative durations, spans left open by a cleanly closed process).
  ``--selfcheck`` runs the hermetic synthetic-fleet fixture instead (the
  tools/check.sh gate).
- ``watch <root>`` — live fleet console: tail every ``events.jsonl``
  under a root while the fleet writes them and render per-rank status,
  QPS/p99/shed, generation, and firing SLO alerts, refreshing in place.
  ``--once`` prints a single snapshot (tests, cron); ``--selfcheck``
  runs the hermetic 2-process fixture instead (the tools/check.sh gate).
- ``quality <root>`` — model-quality report over a run's event stream:
  drift-sample folding (input/prediction PSI+KS, shadow-OLS
  disagreement), breach counts, quality-rejected swaps, and the
  detector-wiring contract (sustained shadow breach with an SLO engine
  attached must have fired a ``shadow_disagreement`` alert). Exit codes:
  0 = ok, 1 = could not load, 2 = a detector is breached or the wiring
  contract is violated. ``--selfcheck`` runs the hermetic sketch-math +
  detector + gate fixture instead (the tools/check.sh gate).
- ``selfcheck`` — hermetic smoke of the whole pipeline (registry ->
  events -> report) in a temp dir; the tools/check.sh telemetry gate.

Deliberately jax-free: these run on operator machines where touching the
backend can hang on a wedged relay lease (docs/OPERATIONS.md).
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile


def _summarize(args) -> int:
    from masters_thesis_tpu.telemetry.report import (
        render_json,
        render_text,
        summarize_path,
    )

    try:
        report = summarize_path(args.run)
    except FileNotFoundError as exc:
        print(f"summarize: {exc}", file=sys.stderr)
        return 1
    print(render_json(report) if args.json else render_text(report))
    return 2 if report["violations"] else 0


def _aggregate(args) -> int:
    from masters_thesis_tpu.telemetry.aggregate import (
        aggregate_path,
        render_fleet_text,
    )

    try:
        report = aggregate_path(args.root, grace_s=args.grace)
    except FileNotFoundError as exc:
        print(f"aggregate: {exc}", file=sys.stderr)
        return 1
    print(
        json.dumps(report, indent=2, default=str)
        if args.json
        else render_fleet_text(report)
    )
    return 0 if report["healthy"] else 2


def _postmortem(args) -> int:
    if args.selfcheck:
        return _postmortem_selfcheck()
    if args.root is None:
        print("postmortem: a run root is required (or --selfcheck)",
              file=sys.stderr)
        return 1
    from masters_thesis_tpu.telemetry.aggregate import (
        postmortem_path,
        render_fleet_text,
    )

    try:
        report = postmortem_path(args.root, grace_s=args.grace)
    except FileNotFoundError as exc:
        print(f"postmortem: {exc}", file=sys.stderr)
        return 1
    print(
        json.dumps(report, indent=2, default=str)
        if args.json
        else render_fleet_text(report, postmortem=True)
    )
    return report["exit_code"]


def _postmortem_selfcheck() -> int:
    """Hermetic smoke of the fleet pipeline: fabricate a healthy 2-process
    run (must aggregate to exit 0) and a failed one whose p1 hung and
    crash-dumped (postmortem must exit 2 and name p1). Jax-free — this is
    the tools/check.sh gate for the aggregate/postmortem path."""
    import os
    from pathlib import Path

    from masters_thesis_tpu.telemetry.aggregate import postmortem_path
    from masters_thesis_tpu.telemetry.flightrec import FlightRecorder
    from masters_thesis_tpu.telemetry.run import TelemetryRun

    def write_stream(root: Path, rank: int, epochs: int, finish: bool,
                     wall: float) -> TelemetryRun:
        os.environ["JAX_PROCESS_INDEX"] = str(rank)
        os.environ["JAX_PROCESS_COUNT"] = "2"
        tel = TelemetryRun(root / f"p{rank}", run_id=f"selfcheck-p{rank}")
        tel.event("run_started", platform="cpu", n_devices=1,
                  strategy="selfcheck", epoch_mode="scan", steps_per_epoch=4)
        for epoch in range(epochs):
            tel.event("epoch", epoch=epoch, steps=4, wall_s=wall,
                      dispatch_s=0.01, device_s=None, data_wait_s=0.0,
                      compile_events=0, compiled=False, fenced=False,
                      steps_per_sec=4.0 / wall)
        if finish:
            tel.event("run_finished", epochs=epochs, total_steps=4 * epochs,
                      steps_per_sec=4.0 / wall, diverged=False,
                      best_val=0.5, epoch_compiles=1, eval_compiles=0)
        return tel

    saved = {k: os.environ.get(k)
             for k in ("JAX_PROCESS_INDEX", "JAX_PROCESS_COUNT")}
    failures: list[str] = []
    try:
        with tempfile.TemporaryDirectory() as tmp:
            healthy = Path(tmp) / "healthy"
            for rank in range(2):
                write_stream(healthy, rank, epochs=3, finish=True,
                             wall=0.4 + 0.01 * rank).close()
            report = postmortem_path(healthy)
            if report["exit_code"] != 0:
                failures.append(
                    f"healthy fleet exited {report['exit_code']}: "
                    f"{report['failures']}"
                )
            if report["epoch_skew"]["epochs_compared"] != 3:
                failures.append(
                    f"expected 3 shared epochs, got {report['epoch_skew']}"
                )

            wedged = Path(tmp) / "wedged"
            write_stream(wedged, 0, epochs=3, finish=True, wall=0.4).close()
            tel = write_stream(wedged, 1, epochs=2, finish=False, wall=0.4)
            rec = FlightRecorder(
                tel.run_dir, run_id=tel.run_id, sink=tel.sink,
                heartbeat_interval_s=60.0, install_signal_handlers=False,
                enable_faulthandler=False,
            )
            rec.beat(phase="train", epoch=2)
            rec.dump("hang: no progress beat for 9.9s (selfcheck)")
            rec.close()
            tel.close()
            report = postmortem_path(wedged)
            if report["exit_code"] != 2:
                failures.append(
                    f"wedged fleet exited {report['exit_code']}, wanted 2"
                )
            if "p1" not in report["headline"]:
                failures.append(
                    f"headline does not name p1: {report['headline']!r}"
                )
            statuses = {d["label"]: d["status"]
                        for d in report["processes"]}
            if statuses.get("p1") != "hung":
                failures.append(f"p1 status {statuses.get('p1')!r} != 'hung'")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    if failures:
        print("telemetry: postmortem selfcheck FAILED: "
              + "; ".join(failures))
        return 1
    print("telemetry: postmortem selfcheck ok")
    return 0


def _ledger(args) -> int:
    if args.selfcheck:
        return _ledger_selfcheck()
    from masters_thesis_tpu.telemetry.ledger import (
        diff_path,
        render_ledger_text,
    )
    from pathlib import Path

    path = Path(args.path)
    if not path.exists():
        print(f"ledger: {path} does not exist", file=sys.stderr)
        return 1
    report = diff_path(
        path, threshold_pct=args.threshold, baseline_rounds=args.baseline
    )
    print(
        json.dumps(report, indent=2, default=str)
        if args.json
        else render_ledger_text(report)
    )
    return 2 if report["regressed"] else 0


def _ledger_selfcheck() -> int:
    """Hermetic smoke of the perf-ledger gate: fabricate a steady
    two-round ledger (must pass) and a third round 30% slower at equal
    config (the gate must fire). Jax-free — the tools/check.sh gate."""
    from pathlib import Path

    from masters_thesis_tpu.telemetry.ledger import (
        append_record,
        diff_path,
        ledger_record,
        read_ledger,
    )

    failures: list[str] = []
    with tempfile.TemporaryDirectory() as tmp:
        path = Path(tmp) / "perf_ledger.jsonl"

        def point(round_id, sps, util, ts):
            return ledger_record(
                point="scan_bs2", round_id=round_id, platform="cpu",
                steps_per_sec=sps, batch_size=2, mesh_shape=[8],
                pack_width=4, objective="mse", flops_per_step=1.6e5,
                bytes_per_step=7.2e5, utilization_pct=util,
                regime="memory-bound", rev="deadbee", ts=ts,
            )

        append_record(path, point("r1", 100.0, 4.0, 1.0))
        append_record(path, point("r2", 98.0, 3.9, 2.0))
        if len(read_ledger(path)) != 2:
            failures.append("append/read round-trip lost rows")
        report = diff_path(path)
        if report["regressed"] or report["rounds"] != 2:
            failures.append(f"steady ledger flagged regressed: {report}")
        if not report["compared"]:
            failures.append("equal-config rounds were not compared")

        append_record(path, point("r3", 60.0, 2.4, 3.0))
        report = diff_path(path)
        if not report["regressed"]:
            failures.append("30% slower round did not trip the gate")
        else:
            metrics = report["regressions"][0]["regressed_metrics"]
            if set(metrics) != {"steps_per_sec", "utilization_pct"}:
                failures.append(f"unexpected regressed metrics: {metrics}")

        # A config change (different batch size) must NOT be compared
        # against the old baseline — no false regression.
        path2 = Path(tmp) / "drift.jsonl"
        append_record(path2, point("r1", 100.0, 4.0, 1.0))
        rec = point("r2", 10.0, 0.4, 2.0)
        rec["batch_size"] = 64
        append_record(path2, rec)
        report = diff_path(path2)
        if report["regressed"] or not report["new_configs"]:
            failures.append(f"config drift mis-gated: {report}")
    if failures:
        print("telemetry: ledger selfcheck FAILED: " + "; ".join(failures))
        return 1
    print("telemetry: ledger selfcheck ok")
    return 0


def _trace(args) -> int:
    from masters_thesis_tpu.telemetry import trace

    if args.selfcheck:
        return trace.selfcheck()
    if args.run is None:
        print("trace: a run root is required (or --selfcheck)",
              file=sys.stderr)
        return 1
    from pathlib import Path

    root = Path(args.run)
    if not root.exists():
        print(f"trace: {root} does not exist", file=sys.stderr)
        return 1
    out = args.out
    if out is None:
        out = (root.parent if root.is_file() else root) / "trace.json"
    report = trace.build_trace_report(root, out=out)
    print(
        json.dumps(report, indent=2, default=str)
        if args.json
        else trace.render_trace_text(report)
    )
    return report["exit_code"]


def _watch(args) -> int:
    from masters_thesis_tpu.telemetry import watch

    if args.selfcheck:
        return watch.selfcheck()
    if args.root is None:
        print("watch: a run root is required (or --selfcheck)",
              file=sys.stderr)
        return 1
    if args.json:
        w = watch.FleetWatch(args.root, grace_s=args.grace)
        print(json.dumps(w.refresh(), indent=2, default=str))
        return 0
    return watch.run_watch(
        args.root, once=args.once, interval_s=args.interval,
        grace_s=args.grace,
    )


def _quality(args) -> int:
    from masters_thesis_tpu.telemetry import quality as quality_lib

    if args.selfcheck:
        return 0 if quality_lib.selfcheck() else 1
    if args.root is None:
        print("quality: a run root is required (or --selfcheck)",
              file=sys.stderr)
        return 1
    from masters_thesis_tpu.telemetry.events import read_events
    from masters_thesis_tpu.telemetry.report import resolve_events_path

    try:
        events = read_events(resolve_events_path(args.root))
    except FileNotFoundError as exc:
        print(f"quality: {exc}", file=sys.stderr)
        return 1
    report = quality_lib.quality_report(events)
    violations = quality_lib.quality_violations(events, report)
    if args.json:
        print(json.dumps(
            {"quality": report, "violations": violations},
            indent=2, default=str,
        ))
    else:
        print(quality_lib.render_quality(report))
        for v in violations:
            print(f"CONTRACT VIOLATION: {v}")
    breached = any((report.get("breaches") or {}).values())
    return 2 if (violations or breached) else 0


def _selfcheck(args) -> int:
    from masters_thesis_tpu.telemetry.report import summarize_path
    from masters_thesis_tpu.telemetry.run import TelemetryRun

    with tempfile.TemporaryDirectory() as tmp:
        tel = TelemetryRun(tmp, run_id="selfcheck")
        tel.event(
            "run_started", platform="cpu", n_devices=1, strategy="selfcheck",
            epoch_mode="scan", steps_per_epoch=4,
        )
        for epoch in range(3):
            tel.event(
                "epoch", epoch=epoch, steps=4, wall_s=0.4 if epoch else 2.0,
                dispatch_s=0.01, device_s=0.38 if epoch else None,
                data_wait_s=0.0, compile_events=0 if epoch else 1,
                compiled=not epoch, fenced=True, steps_per_sec=10.0,
            )
            tel.histogram("train/epoch_wall_s").observe(0.4)
        tel.event(
            "run_finished", epochs=3, total_steps=12, steps_per_sec=10.0,
            diverged=False, best_val=0.5, epoch_compiles=1, eval_compiles=1,
        )
        tel.snapshot_metrics()
        tel.close()
        report = summarize_path(tmp)
    ok = (
        report["compiles"]["train_epoch"] == 1
        and report["steps_per_sec"] == 10.0
        and report["step_time_ms"]["p50"] is not None
        and not report["violations"]
    )
    print("telemetry: selfcheck " + ("ok" if ok else f"FAILED: {report}"))
    return 0 if ok else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m masters_thesis_tpu.telemetry",
        description="run reports over structured step-level telemetry",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    p_sum = sub.add_parser(
        "summarize", help="render a run report from a run dir's events.jsonl"
    )
    p_sum.add_argument(
        "run", help="run directory (or events.jsonl file) to summarize"
    )
    p_sum.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p_sum.set_defaults(fn=_summarize)
    p_agg = sub.add_parser(
        "aggregate",
        help="merge per-process event streams into one fleet view",
    )
    p_agg.add_argument(
        "root", help="root directory holding per-process run dirs"
    )
    p_agg.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p_agg.add_argument(
        "--grace", type=float, default=30.0, metavar="S",
        help="treat processes active within S seconds as still running",
    )
    p_agg.set_defaults(fn=_aggregate)
    p_post = sub.add_parser(
        "postmortem",
        help="fleet verdict on a dead/wedged run; exit 2 on failures",
    )
    p_post.add_argument(
        "root", nargs="?", default=None,
        help="root directory holding per-process run dirs",
    )
    p_post.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p_post.add_argument(
        "--grace", type=float, default=30.0, metavar="S",
        help="treat processes active within S seconds as still running",
    )
    p_post.add_argument(
        "--selfcheck", action="store_true",
        help="hermetic simulated-fleet smoke instead of reading a run",
    )
    p_post.set_defaults(fn=_postmortem)
    p_led = sub.add_parser(
        "ledger",
        help="diff the perf ledger's latest round vs baseline; exit 2 "
             "on >threshold regression at equal config",
    )
    p_led.add_argument(
        "path", nargs="?", default="results/perf_ledger.jsonl",
        help="perf ledger JSONL (default: results/perf_ledger.jsonl)",
    )
    p_led.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p_led.add_argument(
        "--threshold", type=float, default=15.0, metavar="PCT",
        help="regression threshold in percent (default 15)",
    )
    p_led.add_argument(
        "--baseline", type=int, default=None, metavar="N",
        help="compare against only the last N baseline rounds",
    )
    p_led.add_argument(
        "--selfcheck", action="store_true",
        help="hermetic two-round gate smoke instead of reading a ledger",
    )
    p_led.set_defaults(fn=_ledger)
    p_trace = sub.add_parser(
        "trace",
        help="merged span timeline + critical-path attribution; exit 2 "
             "on a broken span tree",
    )
    p_trace.add_argument(
        "run", nargs="?", default=None,
        help="run root (every events.jsonl under it joins the trace)",
    )
    p_trace.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p_trace.add_argument(
        "--out", default=None, metavar="PATH",
        help="Chrome-trace JSON output (default <run>/trace.json)",
    )
    p_trace.add_argument(
        "--selfcheck", action="store_true",
        help="hermetic synthetic-fleet span fixture instead of a run",
    )
    p_trace.set_defaults(fn=_trace)
    p_watch = sub.add_parser(
        "watch",
        help="live fleet console over running event streams",
    )
    p_watch.add_argument(
        "root", nargs="?", default=None,
        help="root directory holding per-process run dirs",
    )
    p_watch.add_argument(
        "--once", action="store_true",
        help="render one snapshot and exit (tests, cron)",
    )
    p_watch.add_argument(
        "--json", action="store_true",
        help="print one machine-readable snapshot and exit",
    )
    p_watch.add_argument(
        "--interval", type=float, default=2.0, metavar="S",
        help="refresh interval in seconds (default 2)",
    )
    p_watch.add_argument(
        "--grace", type=float, default=30.0, metavar="S",
        help="treat processes active within S seconds as still running",
    )
    p_watch.add_argument(
        "--selfcheck", action="store_true",
        help="hermetic 2-process watch fixture instead of a live root",
    )
    p_watch.set_defaults(fn=_watch)
    p_q = sub.add_parser(
        "quality",
        help="model-quality report (drift, shadow-OLS, gated swaps); "
             "exit 2 on breach or wiring violation",
    )
    p_q.add_argument(
        "root", nargs="?", default=None,
        help="run directory (or events.jsonl file) to score",
    )
    p_q.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p_q.add_argument(
        "--selfcheck", action="store_true",
        help="hermetic sketch-math/detector/gate fixture instead of a run",
    )
    p_q.set_defaults(fn=_quality)
    p_check = sub.add_parser(
        "selfcheck", help="hermetic registry->events->report smoke"
    )
    p_check.set_defaults(fn=_selfcheck)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # summarize | head/less closed the pipe
        sys.exit(0)
