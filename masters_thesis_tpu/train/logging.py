"""TensorBoard logging with the reference's directory and tag taxonomy.

(reference: train.py:143-148 TensorBoardLogger(save_dir, name, version);
scalar tags ``loss/{mse,nll,total}/{train,val}`` at src/model.py:207-208,
254-255, 314-318; LR under ``lr-Adam`` via LearningRateMonitor
train.py:162-165; final hparams + test metrics train.py:204-211; figures
via ``add_figure`` test.py:94-145.)

tensorboardX is optional: it is imported lazily on first write, and when
absent the logger degrades to a warn-once no-op instead of breaking
training — the telemetry event stream (telemetry/) is the durable record;
TensorBoard is a mirror for humans. Scalar writes flush the underlying
writer so curves are visible mid-run and survive a killed process without
waiting for ``close()``.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Any

_MISSING_WARNED = False


def _load_writer_cls():
    """tensorboardX's SummaryWriter, or None (warn once) when unavailable."""
    global _MISSING_WARNED
    try:
        from tensorboardX import SummaryWriter
    except ImportError:
        if not _MISSING_WARNED:
            _MISSING_WARNED = True
            print(
                "masters_thesis_tpu: tensorboardX is not installed — "
                "TensorBoard logging disabled (telemetry events.jsonl is "
                "still written)",
                file=sys.stderr,
            )
        return None
    return SummaryWriter


class TensorBoardLogger:
    """Scalars, hparams, and figures under ``<save_dir>/<name>/<version>``."""

    def __init__(self, save_dir: str | Path, name: str, version: str):
        self.log_dir = Path(save_dir) / name / version
        self.log_dir.mkdir(parents=True, exist_ok=True)
        self._writer = None
        self._disabled = False

    @property
    def writer(self):
        """The lazy SummaryWriter, or None when tensorboardX is missing."""
        if self._writer is None and not self._disabled:
            cls = _load_writer_cls()
            if cls is None:
                self._disabled = True
            else:
                self._writer = cls(logdir=str(self.log_dir))
        return self._writer

    def log_scalar(self, tag: str, value: float, step: int) -> None:
        w = self.writer
        if w is None:
            return
        w.add_scalar(tag, float(value), step)
        w.flush()

    def log_scalars(self, scalars: dict[str, float], step: int) -> None:
        w = self.writer
        if w is None:
            return
        for tag, value in scalars.items():
            w.add_scalar(tag, float(value), step)
        w.flush()

    def log_hparams(self, hparams: dict[str, Any], metrics: dict[str, float]) -> None:
        """Final hparams + metrics table (reference: train.py:204-211)."""
        w = self.writer
        if w is None:
            return
        clean = {
            k: (v if isinstance(v, (int, float, str, bool)) else str(v))
            for k, v in hparams.items()
            if v is not None
        }
        w.add_hparams(clean, {k: float(v) for k, v in metrics.items()})
        w.flush()

    def log_figure(self, tag: str, figure, step: int = 0) -> None:
        w = self.writer
        if w is None:
            return
        w.add_figure(tag, figure, step)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
