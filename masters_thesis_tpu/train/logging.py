"""TensorBoard logging with the reference's directory and tag taxonomy.

(reference: train.py:143-148 TensorBoardLogger(save_dir, name, version);
scalar tags ``loss/{mse,nll,total}/{train,val}`` at src/model.py:207-208,
254-255, 314-318; LR under ``lr-Adam`` via LearningRateMonitor
train.py:162-165; final hparams + test metrics train.py:204-211; figures
via ``add_figure`` test.py:94-145.)
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

from tensorboardX import SummaryWriter


class TensorBoardLogger:
    """Scalars, hparams, and figures under ``<save_dir>/<name>/<version>``."""

    def __init__(self, save_dir: str | Path, name: str, version: str):
        self.log_dir = Path(save_dir) / name / version
        self.log_dir.mkdir(parents=True, exist_ok=True)
        self._writer: SummaryWriter | None = None

    @property
    def writer(self) -> SummaryWriter:
        if self._writer is None:
            self._writer = SummaryWriter(logdir=str(self.log_dir))
        return self._writer

    def log_scalar(self, tag: str, value: float, step: int) -> None:
        self.writer.add_scalar(tag, float(value), step)

    def log_scalars(self, scalars: dict[str, float], step: int) -> None:
        for tag, value in scalars.items():
            self.log_scalar(tag, value, step)

    def log_hparams(self, hparams: dict[str, Any], metrics: dict[str, float]) -> None:
        """Final hparams + metrics table (reference: train.py:204-211)."""
        clean = {
            k: (v if isinstance(v, (int, float, str, bool)) else str(v))
            for k, v in hparams.items()
            if v is not None
        }
        self.writer.add_hparams(clean, {k: float(v) for k, v in metrics.items()})

    def log_figure(self, tag: str, figure, step: int = 0) -> None:
        self.writer.add_figure(tag, figure, step)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
