"""The fit/test orchestration loop — Lightning's Trainer, TPU-native.

(reference: train.py:169-198 constructs Trainer(max_epochs,
gradient_clip_val, precision, check_val_every_n_epoch, ...) then fit + test.)

Two execution strategies, one code path:

- ``single_device`` — a 1-device mesh; psum/pmean degenerate to no-ops.
- ``tpu_xla`` — the full mesh over all visible chips; batch axis sharded,
  grads pmean'd over ICI (BASELINE.json: "pjit + lax.psum over ICI").
  ``auto`` picks tpu_xla iff >1 device is visible.

Two epoch modes:

- ``scan`` (default): the train split is device-resident and each epoch is
  one jitted shard_map+scan program (see steps.py) — the fast path.
- ``stream``: host batch iterator + double-buffered ``device_put`` prefetch
  with a per-step jitted update — the reference-shaped loop, kept for
  datasets that outgrow HBM.
"""

from __future__ import annotations

import dataclasses
import os
import time
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from masters_thesis_tpu.data.pipeline import Batch, FinancialWindowDataModule
from masters_thesis_tpu.data.prefetch import PrefetchStats, prefetch_to_device
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.parallel import (
    DATA_AXIS,
    batch_sharding,
    distributed_run_context,
    global_put,
    make_data_mesh,
)
from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.telemetry import (
    CompileTracker,
    EpochRecorder,
    ProfilerWindow,
    TelemetryRun,
)
from masters_thesis_tpu.telemetry.schedule import record_collective
from masters_thesis_tpu.train import checkpoint as ckpt_lib
from masters_thesis_tpu.train.logging import TensorBoardLogger
from masters_thesis_tpu.train.flatparams import (
    FlatAdam,
    flat_size_bytes,
    flatten_spec,
    num_buffers,
)
from masters_thesis_tpu.train.optim import PlateauScheduler
from masters_thesis_tpu.train.steps import (
    forward_rows,
    jit_cache_size,
    make_eval_fn,
    make_train_epoch,
    make_train_step,
    metric_means,
)
from masters_thesis_tpu.telemetry import quality as quality_lib

EVAL_CHUNK = 32


def device_train_split(
    mesh, arrays: Batch, axis: str = "window"
) -> tuple[Batch, int]:
    """Shard the train split over the mesh; returns (device batch, n_local).

    ``axis='window'`` (default): truncates to a multiple of the mesh size
    (<= n_dev-1 windows dropped; every window still rotates in via the
    per-epoch shard-local shuffle being re-drawn — matches DDP sampler
    semantics). Module-level so the stacked trainer (train/stacked.py)
    prepares data identically to the single-run Trainer — replicas share one
    device-resident split.

    ``axis='asset'`` (universe-scale workloads): shards the ASSET rows
    instead — ``x``/``y``/``inv_psi`` split on axis 1 (truncated to a
    multiple of the mesh, <= n_dev-1 asset rows dropped) while the
    per-window ``factor`` stats, which carry no asset axis, replicate.
    ``n_local`` is then the full window count: every device sees the whole
    window stream over its block of asset rows.
    """
    n_dev = mesh.size
    if axis == "asset":
        from masters_thesis_tpu.parallel import replicated_sharding

        n_assets = arrays.x.shape[1]
        k_local = n_assets // n_dev
        if k_local == 0:
            raise ValueError(
                f"train split has {n_assets} assets < mesh size {n_dev}"
            )
        n_keep = k_local * n_dev
        trunc = Batch(
            arrays.x[:, :n_keep],
            arrays.y[:, :n_keep],
            arrays.factor,
            arrays.inv_psi[:, :n_keep],
        )
        asset_sh = batch_sharding(mesh, batch_dim=1)
        shardings = Batch(
            asset_sh, asset_sh, replicated_sharding(mesh), asset_sh
        )
        dev = Batch(
            *(global_put(a, s) for a, s in zip(trunc, shardings))
        )
        return dev, trunc.x.shape[0]
    if axis != "window":
        raise ValueError(f"unknown shard axis: {axis!r}")
    n = arrays.x.shape[0]
    n_local = n // n_dev
    if n_local == 0:
        raise ValueError(f"train split has {n} windows < mesh size {n_dev}")
    trunc = jax.tree_util.tree_map(lambda a: a[: n_local * n_dev], arrays)
    return global_put(trunc, batch_sharding(mesh)), n_local


def prepare_eval_split(mesh, arrays: Batch) -> tuple[Batch, jax.Array] | None:
    """Pad + reshape a split to (steps, n_dev*chunk, ...) with a mask."""
    n_dev = mesh.size
    n = arrays.x.shape[0]
    if n == 0:
        return None
    global_chunk = n_dev * min(EVAL_CHUNK, max(1, n // n_dev))
    steps = -(-n // global_chunk)
    padded = steps * global_chunk

    def pad_reshape(a):
        a = np.asarray(a)
        widths = [(0, padded - n)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths).reshape(steps, global_chunk, *a.shape[1:])

    mask = np.zeros((padded,), np.float32)
    mask[:n] = 1.0
    mask = mask.reshape(steps, global_chunk)
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = NamedSharding(mesh, PartitionSpec(None, DATA_AXIS))
    batch = global_put(jax.tree_util.tree_map(pad_reshape, arrays), sharding)
    return batch, global_put(mask, sharding)


@dataclasses.dataclass
class TrainResult:
    params: Any
    opt_state: Any
    best_val_loss: float
    history: list[dict]
    steps_per_sec: float
    test_metrics: dict | None = None
    # Static cost model of the hot program (telemetry/costs.py payload):
    # FLOPs/bytes per step + peak memory, None when profiling was off or
    # the backend reported nothing.
    cost_profile: dict | None = None


def _precision_dtype(precision: str):
    if precision in ("32-true", "32", "fp32"):
        return jnp.float32
    if precision in ("bf16-mixed", "bf16"):
        return jnp.bfloat16
    if precision == "auto":
        return None  # resolved per model shape at fit/test time
    raise ValueError(f"unknown precision: {precision!r}")


class Trainer:
    def __init__(
        self,
        max_epochs: int,
        gradient_clip_val: float | None = None,
        precision: str = "32-true",
        check_val_every_n_epoch: int = 1,
        strategy: str = "auto",
        epoch_mode: str = "scan",
        n_devices: int | None = None,
        enable_progress_bar: bool = True,
        enable_model_summary: bool = True,
        profile: bool = False,
        profile_steps: tuple[int, int] | None = None,
        logger: TensorBoardLogger | None = None,
        ckpt_dir: str | Path | None = None,
        seed: int = 0,
        name: str = "fast",
        resume: bool | str = False,
        preflight: bool = False,
        telemetry: TelemetryRun | str | Path | None = None,
        hang_timeout_s: float | None = None,
        checkpoint_every_n_epochs: int | None = None,
        cost_profile: bool | None = None,
        metrics_port: int | None = None,
        slo_rules=None,
        shard_axis: str = "window",
    ):
        if shard_axis not in ("window", "asset"):
            raise ValueError(f"unknown shard_axis: {shard_axis!r}")
        if shard_axis == "asset" and epoch_mode != "scan":
            raise ValueError(
                "shard_axis='asset' requires epoch_mode='scan' (the stream "
                "path prefetches window batches, which shard on windows)"
            )
        self.shard_axis = shard_axis
        self.max_epochs = max_epochs
        self.gradient_clip_val = gradient_clip_val
        # 'auto' defers the dtype to the per-shape measured policy
        # (ops.lstm_kernel.preferred_compute_dtype) once the model and
        # window shapes are known at fit/test time.
        self.compute_dtype = _precision_dtype(precision)
        self.check_val_every_n_epoch = max(1, int(check_val_every_n_epoch))
        if strategy == "auto":
            strategy = "tpu_xla" if len(jax.devices()) > 1 else "single_device"
        self.strategy = strategy
        self.epoch_mode = epoch_mode
        self.mesh = make_data_mesh(
            1 if strategy == "single_device" else n_devices
        )
        self.n_dev = self.mesh.size
        self.enable_progress_bar = enable_progress_bar
        self.enable_model_summary = enable_model_summary
        self.profile = profile
        # profile_steps=(N, M) opens a jax.profiler capture window over
        # epochs N..M (inclusive); the legacy profile=True flag maps to the
        # first post-compile epoch at fit time.
        self.profile_steps = (
            (int(profile_steps[0]), int(profile_steps[1]))
            if profile_steps is not None
            else None
        )
        self.logger = logger
        self.ckpt_dir = Path(ckpt_dir) if ckpt_dir else None
        self.seed = seed
        self.name = name
        # 'auto' (the supervised-run setting) and plain True both mean
        # "continue from <ckpt_dir>/last when it is restorable".
        if isinstance(resume, str):
            resume = resume.lower() in ("true", "auto", "1", "yes")
        self.resume = resume
        # Epoch-granular auto-checkpointing for supervised runs: every N
        # epochs, 'last' is refreshed behind a fence (after the divergence
        # check, so poisoned params never overwrite a good save). None
        # keeps the legacy cadence (val epochs + end of fit only).
        self.checkpoint_every_n_epochs = (
            max(1, int(checkpoint_every_n_epochs))
            if checkpoint_every_n_epochs
            else None
        )
        # Run the tracelint trace-time audit (analysis.traceaudit) on this
        # trainer's mesh before fitting: recompile stability, transfer
        # guard, sharding, dtype policy. Fails fast with a PreflightError
        # instead of training slowly/wrongly for hours.
        self.preflight = preflight
        # Structured step-level telemetry (telemetry/): a run dir gets an
        # events.jsonl stream readable by
        # ``python -m masters_thesis_tpu.telemetry summarize``. A path
        # constructs the run here; a TelemetryRun is shared with the caller
        # (the caller owns close()).
        if isinstance(telemetry, (str, Path)):
            telemetry = TelemetryRun(telemetry)
        self.telemetry = telemetry
        # Static cost-model extraction (telemetry/costs.py) for the hot
        # program: FLOPs, bytes accessed, peak memory, roofline regime —
        # emitted as a `cost_profile` event and stored on TrainResult. None
        # (default) follows telemetry: profile iff a run stream is attached.
        # The extraction AOT-lowers+compiles the hot program once before
        # the loop; the jit dispatch cache is untouched, so TA201's
        # "compiles exactly once" accounting is unaffected.
        self.cost_profile = cost_profile
        # Flight-recorder hang watchdog: with telemetry on, a run that makes
        # no progress for hang_timeout_s dumps crashdump.json (all-thread
        # stacks + recent events) instead of wedging silently. None keeps
        # heartbeats and signal dumps but no hang detection (the default —
        # a legitimate giant compile must not be declared a hang).
        self.hang_timeout_s = hang_timeout_s
        # Live telemetry plane (telemetry/exposition.py): /metrics + /slo
        # over this run's registry while fit() is live. None disables; 0
        # binds an ephemeral port. Reader-side only — the SLO engine tails
        # events.jsonl; nothing runs on the step path (TL105/TA202
        # unchanged).
        self.metrics_port = metrics_port
        self._slo_rules = slo_rules
        self._exposition = None
        self._slo_engine = None

    def _resolve_dtype(self, spec, dm):
        """Concrete compute dtype for this (model, window) shape.

        ``precision=auto`` resolves through the measured per-shape policy:
        bf16 only where the VMEM byte model shows it unlocks a deeper
        wavefront AND the A/B recorded the win on hardware
        (ops.lstm_kernel.MEASURED_BF16_WAVEFRONT_WINS)."""
        if self.compute_dtype is not None:
            return self.compute_dtype
        from masters_thesis_tpu.ops.lstm_kernel import preferred_compute_dtype

        return preferred_compute_dtype(
            spec.num_layers, spec.hidden_size, dm.lookback_window,
            getattr(dm, "n_stocks", None) or 100,
            kernel_impl=spec.kernel_impl,
        )

    # ----------------------------------------------------------- data prep

    def _device_train_split(self, arrays: Batch) -> tuple[Batch, int]:
        return device_train_split(self.mesh, arrays, axis=self.shard_axis)

    def _eval_split(self, arrays: Batch) -> tuple[Batch, jax.Array] | None:
        return prepare_eval_split(self.mesh, arrays)

    # ----------------------------------------------------------------- fit

    def fit(
        self,
        spec: ModelSpec,
        dm: FinancialWindowDataModule,
        init_state: tuple[Any, Any] | None = None,
    ) -> TrainResult:
        """Train; ``init_state=(params, opt_state)`` resumes from a
        checkpoint (reference: train.py:187 passes ckpt_path to fit);
        ``init_state=(params, None)`` warm-starts the weights with a fresh
        optimizer (the thesis' synthetic->real warmup protocol)."""
        tel = self.telemetry
        if self.preflight:
            if self.epoch_mode == "scan":
                from masters_thesis_tpu.analysis.traceaudit import (
                    PreflightError,
                    assert_trace_clean,
                )

                self._print("preflight: trace audit on the fit mesh ...")
                # Audits the configured model/objective on this trainer's
                # mesh with tiny synthetic data — raises PreflightError
                # before any real epoch runs. The verdict is recorded as a
                # telemetry event either way, so a failed preflight shows up
                # in the run report, not only in a dead process' stderr.
                try:
                    assert_trace_clean(
                        spec=spec, mesh=self.mesh,
                        shard_axis=self.shard_axis,
                    )
                except PreflightError as exc:
                    if tel:
                        tel.event(
                            "preflight",
                            status="failed",
                            rules=sorted({f.rule for f in exc.findings}),
                            findings=[f.format() for f in exc.findings],
                        )
                    raise
                if tel:
                    tel.event("preflight", status="ok")
                self._print("preflight: ok")
            else:
                # The stream mode's per-step program has host work (the
                # prefetcher) inside the loop by design; the scan-epoch
                # invariants don't apply.
                self._print(
                    "preflight: skipped (epoch_mode='stream' streams batches "
                    "through the host by design)"
                )
                if tel:
                    tel.event(
                        "preflight", status="skipped",
                        reason="epoch_mode=stream",
                    )
        dm.prepare_data(verbose=self.enable_progress_bar)
        dm.setup("fit")

        module = spec.build_module(compute_dtype=self._resolve_dtype(spec, dm))
        init_rng, dropout_rng = jax.random.split(jax.random.key(self.seed))
        dummy = jnp.zeros(
            (1, dm.lookback_window, dm.n_features), jnp.float32
        )
        params = module.init(init_rng, dummy)["params"]
        if self.enable_model_summary:
            n_params = sum(
                p.size for p in jax.tree_util.tree_leaves(params)
            )
            self._print(f"model: {spec.objective} | params: {n_params:,} "
                        f"| mesh: {self.n_dev}x{DATA_AXIS} | {self.strategy}")

        from masters_thesis_tpu.parallel import replicated_sharding

        # The flat update path (train/flatparams.py): moments live in
        # per-dtype flat buffers, the per-step gradient sync is ONE pmean
        # over the flat buffer (TA206), and the Adam fold is one fused
        # elementwise pass. Same chain semantics as optim.make_optimizer —
        # bit-identical updates, asserted by tests/test_flatparams.py.
        tx = FlatAdam(self.gradient_clip_val, spec.weight_decay)
        opt_state = tx.init(params)
        repl = replicated_sharding(self.mesh)
        if init_state is not None:
            from masters_thesis_tpu.train.checkpoint import restore_opt_state

            params = jax.tree_util.tree_map(jnp.asarray, init_state[0])
            if init_state[1] is not None:  # None = warm start, fresh optimizer
                opt_state = restore_opt_state(
                    jax.device_get(opt_state), init_state[1], params=params
                )
        scheduler = PlateauScheduler(spec.learning_rate)
        start_epoch = 0
        best_val = float("inf")
        # Failure recovery: pick up where the 'last' checkpoint left off —
        # params, optimizer moments, LR-scheduler state, best-val watermark,
        # and epoch counter (the reference's only resume affordance is
        # Lightning's save_last=True, train.py:159; restart semantics there
        # require manually passing ckpt_path).
        # checkpoint_restorable also finishes an interrupted staged swap
        # (kill between publish steps), so a crash at ANY point of a save
        # leaves either the previous or the new checkpoint restorable;
        # only a truly torn state (e.g. pre-staging layouts) falls back to
        # training from scratch rather than dying.
        resumed_from = None
        if (
            self.resume
            and self.ckpt_dir
            and ckpt_lib.checkpoint_restorable(self.ckpt_dir, "last")
        ):
            from masters_thesis_tpu.train.checkpoint import (
                restore_checkpoint,
                restore_opt_state,
            )

            r_params, r_opt, _, r_meta = restore_checkpoint(
                self.ckpt_dir, "last"
            )
            params = jax.tree_util.tree_map(jnp.asarray, r_params)
            opt_state = restore_opt_state(
                jax.device_get(opt_state), r_opt, params=params
            )
            start_epoch = int(r_meta.get("epoch", -1)) + 1
            if r_meta.get("best_val") is not None:
                best_val = float(r_meta["best_val"])
            if r_meta.get("scheduler"):
                scheduler.load_state_dict(r_meta["scheduler"])
            # Divergence rollback (resilience supervisor): the relaunch
            # carries MTT_LR_SCALE so the restored run retries the diverged
            # stretch at a reduced LR instead of replaying the same blow-up.
            lr_scale = float(os.environ.get("MTT_LR_SCALE", "1") or 1.0)
            if lr_scale != 1.0:
                scheduler.lr *= lr_scale
                self._print(
                    f"rollback: LR scaled by {lr_scale:g} -> "
                    f"{scheduler.lr:.3g}"
                )
            resumed_from = str(self.ckpt_dir / "last")
            self._print(
                f"resuming from {resumed_from} at epoch {start_epoch}"
            )
        # Commit to the mesh BEFORE the first epoch: epoch outputs carry
        # mesh-tagged avals, and untagged first-call inputs would otherwise
        # trace+compile the epoch program a second time at epoch 1.
        params = global_put(params, repl)
        opt_state = global_put(opt_state, repl)
        objective = spec.window_objective()

        val_prepared = self._eval_split(dm.val_arrays())
        if val_prepared is None:
            # Without a val split there is no plateau signal and no best-val
            # watermark; warn loudly instead of silently returning inf (a
            # sweep minimizing best_val would rank such runs last without
            # explanation) and fall back to best=last below.
            self._print(
                "warning: val split is empty — LR plateau scheduling is "
                "inactive and 'best' falls back to the final checkpoint, "
                "ranked by final TRAIN loss"
            )
        eval_fn = make_eval_fn(module, objective, self.mesh)

        # Model-quality fingerprint (telemetry/quality.py): at checkpoint
        # time a fixed slice of the val split plus a seeded golden batch is
        # scored through the CURRENT params and the sketches ship as a
        # quality.json sidecar covered by MANIFEST.json. Every rank computes
        # the same fingerprint (SPMD-uniform — no rank-gated device work);
        # only rank 0 writes, inside save_checkpoint's staging protocol.
        self._quality_fp_fn = None
        if self.ckpt_dir and val_prepared:
            qx = np.asarray(dm.val_arrays().x[:128], np.float32)
            gx = quality_lib.golden_windows(32, *qx.shape[1:], seed=0)

            def _fingerprint(fp_params):
                def _predict(x_np):
                    a, b = forward_rows(module, fp_params, jnp.asarray(x_np))
                    return (
                        np.asarray(jax.device_get(a))[..., 0],
                        np.asarray(jax.device_get(b))[..., 0],
                    )

                a_v, b_v = _predict(qx)
                a_g, b_g = _predict(gx)
                return quality_lib.build_fingerprint(
                    qx, a_v, b_v, golden=(gx, a_g, b_g), golden_seed=0
                )

            self._quality_fp_fn = _fingerprint

        # Stream mode fills a fresh PrefetchStats per epoch so telemetry can
        # split epoch wall into device time vs host data-wait; scan mode has
        # no input pipeline (the split is device-resident).
        epoch_stats: dict[str, PrefetchStats | None] = {"cur": None}

        # Armed by the trainer.epoch_start ``shift`` fault below: scan mode
        # rewrites the device-resident split once at the epoch boundary,
        # stream mode shifts each host batch as it is drawn — either way the
        # shift persists for the rest of the run (a regime change, not a
        # one-off glitch).
        data_cell: dict[str, Any] = {}
        shift_cell: dict[str, tuple[float, float] | None] = {"params": None}

        if self.epoch_mode == "scan":
            train_dev, n_local = self._device_train_split(dm.train_arrays())
            b_local = dm.batch_size
            steps_per_epoch = n_local // b_local
            epoch_fn = make_train_epoch(
                module, objective, spec.metric_keys, tx, self.mesh,
                batch_size=b_local, shard_axis=self.shard_axis,
            )
            hot_fn = epoch_fn
            data_cell["train"] = train_dev

            def run_epoch(params, opt_state, lr, epoch_rng, epoch):
                # Shuffle happens on device (steps.py) — no index upload.
                return epoch_fn(
                    params, opt_state, lr, epoch_rng, data_cell["train"]
                )

        elif self.epoch_mode == "stream":
            global_b = dm.batch_size * self.n_dev
            n_train = len(dm.train_range)
            if n_train == 0:
                raise ValueError("train split has 0 windows")
            # The tail partial batch trains too (the reference's DataLoader
            # drop_last defaults to False): it is padded back to global_b by
            # cycling its own windows with zero weight, so every epoch runs
            # ceil(n/global_b) steps through ONE compiled program.
            steps_per_epoch = -(-n_train // global_b)
            step_fn = make_train_step(
                module, objective, tx, self.mesh, weighted=True
            )
            hot_fn = step_fn
            shard = batch_sharding(self.mesh)

            def weighted_batches(batches):
                full_w = np.ones((global_b,), np.float32)
                for b in batches:
                    so = shift_cell["params"]
                    if so is not None:
                        b = b._replace(
                            x=(b.x * so[0] + so[1]).astype(
                                np.asarray(b.x).dtype
                            )
                        )
                    n = b.x.shape[0]
                    if n == global_b:
                        yield b, full_w
                    else:
                        idx = np.arange(global_b) % n
                        yield (
                            Batch(*(np.asarray(a)[idx] for a in b)),
                            (np.arange(global_b) < n).astype(np.float32),
                        )

            def run_epoch(params, opt_state, lr, epoch_rng, epoch):
                sums = None
                stats = PrefetchStats()
                epoch_stats["cur"] = stats
                it = dm._iterate(
                    dm.train_range, global_b, shuffle_seed=(self.seed, epoch)
                )
                for i, (batch, w) in enumerate(
                    prefetch_to_device(
                        weighted_batches(it), sharding=shard, stats=stats
                    )
                ):
                    step_rng = jax.random.fold_in(epoch_rng, i)
                    params, opt_state, step_sums = step_fn(
                        params, opt_state, lr, step_rng, batch, w
                    )
                    sums = (
                        step_sums
                        if sums is None
                        else jax.tree_util.tree_map(jnp.add, sums, step_sums)
                    )
                return params, opt_state, sums

        else:
            raise ValueError(f"unknown epoch_mode: {self.epoch_mode!r}")

        # ---- telemetry wiring: event stream, compile trackers, recorder ----
        # Compile events are measured, not inferred: cache-miss deltas on
        # the hot program (scan epoch / stream step) and on eval_fn turn
        # tracelint's TA201 "compiles exactly once" into a runtime counter.
        epoch_tracker = eval_tracker = rec = flight = fit_span = None
        if tel:
            # Attach the flight recorder BEFORE the first event so the ring
            # buffer holds the whole run and SIGTERM/hang forensics cover the
            # compile phase (where multi-host runs most often wedge).
            flight = tel.attach_flight_recorder(
                hang_timeout_s=self.hang_timeout_s
            )
            flight.beat(phase="setup")
            # The run's root span: hangs off MTT_PARENT_SPAN when a
            # supervisor/grid runner launched us, so every epoch/eval/
            # checkpoint span below joins the cross-process trace. Host
            # bookkeeping only — no fences (TL/TA contract unchanged).
            fit_span = tel.tracer.start(
                "trainer.fit", trainer=self.name, attempt_resume=bool(
                    resumed_from),
            )
            self._fit_span = fit_span
            tel.event(
                "run_started",
                platform=jax.default_backend(),
                n_devices=self.n_dev,
                strategy=self.strategy,
                epoch_mode=self.epoch_mode,
                steps_per_epoch=steps_per_epoch,
                max_epochs=self.max_epochs,
                start_epoch=start_epoch,
                objective=spec.objective,
                trainer=self.name,
                seed=self.seed,
                resumed_from=resumed_from,
                distributed=distributed_run_context(),
                trace_id=tel.tracer.trace_id,
            )
            # Gradient-sync footprint of the flat update path: one collective
            # per dtype buffer per step (TA206 pins exactly this count in the
            # lowered HLO; preflight=True re-verifies it on this very mesh),
            # moving the whole flat gradient. Gauges + an event so `telemetry
            # summarize` and the bench `detail` report the same numbers.
            if isinstance(tx, FlatAdam):
                fspec = flatten_spec(params)
                n_coll = num_buffers(fspec)
                sync_bytes = flat_size_bytes(fspec)
                tel.gauge("train/collectives_per_step").set(n_coll)
                tel.gauge("train/grad_reduce_bytes").set(sync_bytes)
                tel.event(
                    "grad_sync",
                    collectives_per_step=n_coll,
                    grad_reduce_bytes=sync_bytes,
                    flat_buffers=n_coll,
                )
            epoch_tracker = CompileTracker(hot_fn, size_fn=jit_cache_size)
            eval_tracker = CompileTracker(eval_fn, size_fn=jit_cache_size)

            def _mirror_epoch(ev):
                # Perf scalars land next to the loss curves in TensorBoard.
                if self.logger and ev.get("steps_per_sec") is not None:
                    self.logger.log_scalars(
                        {
                            "perf/epoch_wall_s": ev["wall_s"],
                            "perf/steps_per_sec": ev["steps_per_sec"],
                        },
                        ev["epoch"],
                    )

            rec = EpochRecorder(
                tel, steps_per_epoch, on_epoch=_mirror_epoch,
                span_parent=fit_span,
            )
            if self.metrics_port is not None:
                from masters_thesis_tpu.telemetry.exposition import (
                    start_telemetry_plane,
                )
                from masters_thesis_tpu.telemetry.slo import (
                    default_train_rules,
                )

                self._exposition, self._slo_engine = start_telemetry_plane(
                    tel,
                    self.metrics_port,
                    rules=self._slo_rules or default_train_rules(),
                )

        # ---- static cost model of the hot program (telemetry/costs.py) ----
        # AOT lower+compile the exact program the loop runs and pull the
        # compiler's FLOPs / bytes-accessed / peak-memory numbers, plus the
        # Pallas router's plan for the recurrence at this shape (byte-model
        # prediction to audit against the compiler's temp bytes). Lowering
        # with donated args executes nothing and consumes no buffers; the
        # jit dispatch cache is untouched (TA201 still counts one compile).
        cost_payload: dict | None = None
        want_cost = (
            self.cost_profile if self.cost_profile is not None else bool(tel)
        )
        if want_cost:
            from masters_thesis_tpu.telemetry import costs as _costs

            try:
                from masters_thesis_tpu.ops.lstm_kernel import route_plan

                meta = {
                    "platform": jax.default_backend(),
                    "mesh_shape": list(self.mesh.devices.shape),
                    "n_devices": self.n_dev,
                    "epoch_mode": self.epoch_mode,
                    "objective": spec.objective,
                    "batch_size": dm.batch_size,
                    "lstm_route": route_plan(
                        dm.lookback_window,
                        dm.batch_size,
                        spec.hidden_size,
                        spec.num_layers,
                        has_mask=spec.dropout > 0,
                    ),
                }
                if self.epoch_mode == "scan":
                    cost = _costs.profile_jit(
                        epoch_fn,
                        params,
                        opt_state,
                        jnp.float32(scheduler.lr),
                        jax.random.fold_in(dropout_rng, start_epoch),
                        train_dev,
                        program="train_epoch_scan",
                        steps_per_execution=steps_per_epoch,
                        meta=meta,
                    )
                else:
                    shard_c = batch_sharding(self.mesh)
                    arrays = dm.train_arrays()
                    batch_struct = Batch(
                        *(
                            jax.ShapeDtypeStruct(
                                (global_b,) + tuple(a.shape[1:]),
                                a.dtype,
                                sharding=shard_c,
                            )
                            for a in arrays
                        )
                    )
                    w_struct = jax.ShapeDtypeStruct(
                        (global_b,), np.float32, sharding=shard_c
                    )
                    cost = _costs.profile_jit(
                        step_fn,
                        params,
                        opt_state,
                        jnp.float32(scheduler.lr),
                        jax.random.fold_in(dropout_rng, start_epoch),
                        batch_struct,
                        w_struct,
                        program="train_step_stream",
                        meta=meta,
                    )
                cost_payload = cost.to_payload()
                if tel:
                    _costs.emit_cost_profile(tel, cost)
            except Exception as exc:  # never fail a run over observability
                self._print(f"cost profile extraction failed: {exc!r}")
                if tel:
                    tel.event("cost_unavailable", program="train",
                              error=repr(exc))

        window = self.profile_steps
        if window is None and self.profile:
            # Legacy profile=True: capture the first post-compile epoch.
            window = (start_epoch + 1, start_epoch + 1)
        prof = ProfilerWindow(
            window,
            (
                tel.run_dir
                if tel
                else (self.logger.log_dir if self.logger else Path("logs"))
            )
            / "profile",
            telemetry=tel,
        )

        history: list[dict] = []
        total_steps = 0
        t_start = None  # set after first epoch (excludes compile)
        diverged = False
        # Pipelined metric readback: a non-val epoch's (row, device sums) is
        # held here and fetched only after the NEXT epoch has been
        # dispatched, so the host↔device round-trip overlaps compute instead
        # of serializing the loop (worth ~30% wall time on a relay-attached
        # chip). Val epochs are inherently synchronous (the LR scheduler and
        # checkpointing decisions feed the next epoch).
        pending: tuple[dict, Any] | None = None

        def readback(row, sums) -> bool:
            """Fill a row's train metrics from device sums; True = diverged.

            Divergence halts the run (the reference has no such guard,
            SURVEY.md §5; Lightning would loop on NaN to the end) — but the
            poisoned row is still logged so TensorBoard shows WHY the curve
            ends.
            """
            train_metrics = metric_means(jax.device_get(sums))
            row.update(
                {f"loss/{k}/train": v for k, v in train_metrics.items()}
            )
            # Fault point (host-side, post-device-sums): a `nan` fault
            # poisons the readback exactly as a diverged step would, driving
            # the real halt + supervisor-rollback machinery downstream.
            if faults.fire("trainer.loss", epoch=row["epoch"]) == "nan":
                row["loss/total/train"] = float("nan")
            if flight is not None:
                # Divergence context for crashdumps: the recent loss/lr
                # history shows WHETHER the run was blowing up when it died.
                flight.track_scalar(
                    "loss/total/train", row.get("loss/total/train")
                )
                flight.track_scalar("lr", row.get("lr-Adam"))
            return not np.isfinite(row.get("loss/total/train", 0.0))

        def emit(row) -> None:
            if self.logger:
                self.logger.log_scalars(
                    {k: v for k, v in row.items() if k != "epoch"},
                    row["epoch"],
                )
            history.append(row)
            self._print(
                f"epoch {row['epoch']:4d} | "
                + " | ".join(
                    f"{k.split('/')[1]}/{k.split('/')[2]} {v:.5g}"
                    for k, v in row.items()
                    if k.startswith("loss/")
                )
            )

        def halt(row) -> None:
            self._print(
                f"epoch {row['epoch']}: non-finite training loss "
                f"({row['loss/total/train']}); halting (diverged)"
            )

        def drain(pend) -> bool:
            """Readback + emit a deferred epoch; True = diverged (halted)."""
            row, sums = pend
            bad = readback(row, sums)
            emit(row)
            if bad:
                halt(row)
            return bad

        def fence():
            jax.block_until_ready(params)

        for epoch in range(start_epoch, self.max_epochs):
            fired = faults.fire("trainer.epoch_start", epoch=epoch)
            if fired == "shift":
                # Seeded regime shift on this epoch's (and every later
                # epoch's) window features — the deterministic trigger for
                # the quality plane's drift detectors. One device op at the
                # epoch boundary in scan mode; host-side per batch in
                # stream mode. The hot loop itself is untouched.
                scale, offset = faults.shift_params(epoch)
                if self.epoch_mode == "scan":
                    cur = data_cell["train"]
                    data_cell["train"] = cur._replace(
                        x=(cur.x * scale + offset).astype(cur.x.dtype)
                    )
                else:
                    shift_cell["params"] = (scale, offset)
            prof.maybe_start(epoch)
            if flight is not None:
                # Progress marker for the hang watchdog (host memory only —
                # no fence, no I/O; tracelint's hot-loop contract holds).
                flight.beat(phase="train", epoch=epoch)
            if rec:
                # Closes the previous unfenced epoch boundary-to-boundary
                # (the async-dispatch-aware accounting in telemetry/run.py)
                # — never an added fence in the steady-state hot loop.
                rec.begin(epoch)
            epoch_rng = jax.random.fold_in(dropout_rng, epoch)
            lr = jnp.float32(scheduler.lr)
            params, opt_state, sums = run_epoch(
                params, opt_state, lr, epoch_rng, epoch
            )
            # "Mid-epoch" fault point: the epoch's update is dispatched but
            # nothing about it is checkpointed yet — a kill here loses
            # exactly this epoch's work (the chaos tests' preemption site).
            faults.fire("trainer.epoch_dispatched", epoch=epoch)
            # One schedule entry per dispatched epoch program: the flat
            # gradient pmean over the data axis. Host-memory hash update
            # only — no fence, no I/O (hot-loop contract holds).
            record_collective(
                "pmean", name="grads.flat", axes=(DATA_AXIS,), step=epoch
            )
            total_steps += steps_per_epoch
            # 'lr-Adam' matches the reference's LearningRateMonitor scalar
            # tag (reference: train.py:162-165 names it lr-<optimizer>).
            row = {"epoch": epoch, "lr-Adam": scheduler.lr}

            if rec:
                stats = epoch_stats["cur"]
                compiles = epoch_tracker.poll()
                rec.dispatched(
                    compiles=compiles,
                    data_wait_s=stats.get_wait_s if stats else 0.0,
                )
                if flight is not None and compiles:
                    flight.note(epoch_compiles=epoch_tracker.total,
                                last_compile_epoch=epoch)
                if stats:
                    tel.counter("data/batches").inc(stats.gets)
                    tel.gauge("data/prefetch_mean_depth").set(stats.mean_depth)
                    if stats.min_depth is not None:
                        tel.gauge("data/prefetch_min_depth").set(
                            stats.min_depth
                        )
                    if stats.mmap_bytes:
                        # Store-backed epoch: page-in wait vs total data
                        # wait, so `telemetry summarize` can split "slow
                        # disk" from "slow producer" (window_store line).
                        tel.event(
                            "window_store",
                            epoch=epoch,
                            bytes_read=stats.mmap_bytes,
                            fault_wait_s=round(stats.fault_wait_s, 6),
                            get_wait_s=round(stats.get_wait_s, 6),
                        )
                    epoch_stats["cur"] = None

            # Previous epoch's readback overlaps this epoch's execution.
            if pending is not None:
                prev, pending = pending, None
                diverged = drain(prev)
                if diverged:
                    break

            is_val = (
                (epoch + 1) % self.check_val_every_n_epoch == 0
                and val_prepared
            )
            # Epoch-granular auto-checkpoint cadence: forces the fenced
            # path so the divergence check runs BEFORE the save — 'last'
            # must never hold poisoned params (auto-resume would restart
            # from them).
            is_ckpt = bool(
                self.checkpoint_every_n_epochs
                and self.ckpt_dir
                and (epoch + 1) % self.checkpoint_every_n_epochs == 0
            )
            if is_val or is_ckpt or t_start is None or prof.wants_fence(epoch):
                # This readback blocks on the epoch's device sums — the only
                # fences in the loop, and all at boundaries the trainer
                # needs anyway (val sync, compile watermark, profile window).
                t_fence = time.perf_counter()
                diverged = readback(row, sums)
                if rec:
                    rec.fenced(time.perf_counter() - t_fence)
                    tel.sample_memory(epoch)
                if t_start is None:  # first epoch readback = compile done
                    t_start = time.perf_counter()
                if diverged:
                    emit(row)
                    halt(row)
                    break
                if is_val:
                    t_eval_wall = time.time()
                    t_eval = time.perf_counter()
                    val_sums = eval_fn(params, *val_prepared)
                    val_metrics = metric_means(jax.device_get(val_sums))
                    row.update(
                        {f"loss/{k}/val": v for k, v in val_metrics.items()}
                    )
                    val_loss = val_metrics["total"]
                    if rec:
                        tel.event(
                            "eval",
                            epoch=epoch,
                            compile_events=eval_tracker.poll(),
                            val_loss=float(val_loss),
                        )
                        # device_get above already fenced the eval; the
                        # span just names the interval retroactively.
                        tel.tracer.emit_span(
                            "train.eval",
                            start_ts=t_eval_wall,
                            dur_s=time.perf_counter() - t_eval,
                            parent=fit_span,
                            epoch=epoch,
                        )
                    row["lr-Adam"] = scheduler.step(val_loss)
                    if val_loss < best_val:
                        best_val = val_loss
                        self._save("best", params, opt_state, spec, epoch,
                                   val_loss, dm, scheduler, best_val)
                    self._save("last", params, opt_state, spec, epoch,
                               val_loss, dm, scheduler, best_val)
                elif is_ckpt:
                    # Non-val cadence save: the loss is confirmed finite by
                    # the readback above; scheduler/best_val are unchanged
                    # since the last val epoch, so a resume from here is
                    # bit-identical to having never stopped.
                    self._save("last", params, opt_state, spec, epoch,
                               row.get("loss/total/train", float("inf")),
                               dm, scheduler, best_val)
                emit(row)
            else:
                pending = (row, sums)

            prof.maybe_stop(epoch, fence)

        # A divergence break can exit mid-profiled-epoch: close the trace so
        # the diagnostic data is written out rather than lost.
        prof.close(fence)

        if pending is not None and not diverged:
            diverged = drain(pending)

        jax.block_until_ready(params)
        if rec:
            # The loop's closing fence above is the final epoch's boundary.
            rec.finish()
        elapsed = time.perf_counter() - (t_start or time.perf_counter())
        post_compile_steps = total_steps - steps_per_epoch
        steps_per_sec = (
            post_compile_steps / elapsed
            if elapsed > 0 and post_compile_steps > 0
            else 0.0
        )

        # Empty-val fallback: rank the run by its final train loss and make
        # 'best' exist (pointing at the final params) so downstream tooling
        # (test.py, warmup) keeps working.
        if val_prepared is None and not diverged and history:
            best_val = history[-1].get("loss/total/train", best_val)
            self._save("best", params, opt_state, spec, self.max_epochs - 1,
                       best_val, dm, scheduler, best_val)

        # 'last' must hold the FINAL params even when the last epoch wasn't a
        # val epoch (Lightning's save_last=True, train.py:159) — but a
        # diverged run must NOT clobber the last good checkpoint with NaN
        # params (auto-resume would then restart from poison).
        if self.ckpt_dir and not diverged:
            self._save("last", params, opt_state, spec, self.max_epochs - 1,
                       best_val, dm, scheduler, best_val)

        if tel:
            if flight is not None:
                flight.beat(phase="finished")
            tel.sample_memory(None)
            tel.tracer.end(
                fit_span,
                status="error" if diverged else "ok",
                epochs=len(history),
                diverged=diverged,
            )
            self._fit_span = None
            tel.event(
                "run_finished",
                epochs=len(history),
                total_steps=total_steps,
                steps_per_sec=steps_per_sec,
                diverged=diverged,
                best_val=float(best_val) if np.isfinite(best_val) else None,
                epoch_compiles=epoch_tracker.total,
                eval_compiles=eval_tracker.total,
            )
            tel.snapshot_metrics()
            if self.logger:
                self.logger.log_scalars(
                    {"perf/steps_per_sec": steps_per_sec},
                    self.max_epochs - 1,
                )

        if self._exposition is not None or self._slo_engine is not None:
            from masters_thesis_tpu.telemetry.exposition import (
                stop_telemetry_plane,
            )

            stop_telemetry_plane(self._exposition, self._slo_engine)
            self._exposition = self._slo_engine = None

        return TrainResult(
            params=params,
            opt_state=opt_state,
            best_val_loss=best_val,
            history=history,
            steps_per_sec=steps_per_sec,
            cost_profile=cost_payload,
        )

    # ---------------------------------------------------------------- test

    def test(
        self, spec: ModelSpec, params: Any, dm: FinancialWindowDataModule
    ) -> dict:
        """Final test metrics: MAE + NLL + MSE + objective total
        (reference: trainer.test at train.py:198 -> src/model.py:119-141)."""
        dm.setup("test")
        module = spec.build_module(compute_dtype=self._resolve_dtype(spec, dm))
        eval_fn = make_eval_fn(module, spec.window_objective(), self.mesh)
        prepared = self._eval_split(dm.test_arrays())
        if prepared is None:
            return {}
        sums = eval_fn(params, *prepared)
        metrics = metric_means(jax.device_get(sums))
        if self.logger:
            self.logger.log_scalars(
                {f"test/{k}": v for k, v in metrics.items()}, 0
            )
        if self.telemetry:
            self.telemetry.event(
                "test",
                metrics={k: float(v) for k, v in metrics.items()},
            )
        return metrics

    # ------------------------------------------------------------- helpers

    def _save(self, tag, params, opt_state, spec, epoch, val_loss, dm,
              scheduler=None, best_val=None):
        if not self.ckpt_dir:
            return
        t0_wall = time.time()
        t0 = time.perf_counter()
        # Quality fingerprint sidecar: sketches of the val inputs, the
        # predicted (alpha, beta) distributions, and the shadow-OLS
        # disagreement under the params being saved. Best-effort — a
        # fingerprint failure must never lose the checkpoint itself.
        fp = extra = None
        if getattr(self, "_quality_fp_fn", None) is not None:
            try:
                fp = self._quality_fp_fn(params)
                extra = {
                    quality_lib.FINGERPRINT_FILENAME:
                        quality_lib.fingerprint_to_json(fp)
                }
            except Exception as e:
                fp = extra = None
                self._print(f"quality fingerprint failed for {tag!r}: {e}")
        ckpt_lib.save_checkpoint(
            self.ckpt_dir, tag, params, opt_state, spec,
            extra_files=extra,
            meta={
                "epoch": epoch,
                "val_loss": float(val_loss),
                # Resume state: LR-scheduler + best-val watermark.
                "scheduler": scheduler.state_dict() if scheduler else None,
                "best_val": None if best_val is None else float(best_val),
                "trainer": self.name,
                "datamodule": {
                    "lookback_window": dm.lookback_window,
                    "target_window": dm.target_window,
                    "stride": dm.stride,
                    "prediction_task": dm.prediction_task,
                    "interaction_only": dm.interaction_only,
                    "batch_size": dm.batch_size,
                },
            },
        )
        if self.telemetry:
            # Lost-work accounting: `telemetry summarize` measures the gap
            # between a dead attempt's last activity and its last
            # checkpoint_saved to report how much training a restart cost.
            wall_s = time.perf_counter() - t0
            self.telemetry.event(
                "checkpoint_saved",
                tag=tag,
                epoch=epoch,
                wall_s=wall_s,
                path=str(self.ckpt_dir / tag),
            )
            if fp is not None:
                self.telemetry.event(
                    "quality_fingerprint",
                    tag=tag,
                    epoch=int(epoch),
                    windows=int(fp["windows"]),
                    shadow_err=float(fp["shadow"]["err_mean"]),
                )
            self.telemetry.tracer.emit_span(
                "train.checkpoint",
                start_ts=t0_wall,
                dur_s=wall_s,
                parent=getattr(self, "_fit_span", None),
                tag=tag,
                epoch=epoch,
            )

    def _print(self, msg: str) -> None:
        if self.enable_progress_bar and jax.process_index() == 0:
            print(msg, flush=True)
