"""Stacked-replica training: N independent runs inside ONE epoch program.

PR 7's rooflines measured what the grid runner pays per cell-as-subprocess:
a fresh multi-second compile and a chip left nearly empty by the H=64 LSTM
(the CP403 1%-utilization floor exists because of it). This driver
multiplies work per compiled program instead: R replicas — grid cells
differing in lr/seed, ensemble members — train as a leading ``vmap`` axis
over the flat-buffer layout (train/steps.py:make_stacked_train_epoch).
One compile, one host dispatch per epoch, one gradient all-reduce per
dtype buffer per step (TA207), R training runs.

What stays per-replica: init/dropout RNG streams (fold-in per replica
seed), learning rate (an ``[R]`` vector the per-replica plateau schedulers
drive), Adam moments + bias-correction counts, metric readbacks, telemetry
events, checkpoints, and divergence handling — a replica that goes
non-finite is rolled back to the last fenced-clean snapshot (once, with
its LR halved) and masked out (lr=0) if it blows up again, while its
siblings train on untouched. Replica isolation is structural (row r of
every stacked buffer is a function of row r's inputs only) and pinned
bit-exactly by tests/test_stacked.py.

Stack-compatibility: replicas must share the model architecture, loss,
gradient-clip and weight-decay (one program, one clip threshold); lr and
seed are free per replica. The grid runner groups cells by exactly that
key (sweeps/run_grid_canonical.py).
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from masters_thesis_tpu.data.pipeline import FinancialWindowDataModule
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.parallel import (
    DATA_AXIS,
    distributed_run_context,
    global_put,
    make_data_mesh,
    replicated_sharding,
)
from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.telemetry import (
    CompileTracker,
    EpochRecorder,
    TelemetryRun,
)
from masters_thesis_tpu.train import checkpoint as ckpt_lib
from masters_thesis_tpu.train.flatparams import (
    FlatAdam,
    flatten,
    flatten_spec,
    num_buffers,
    replica_flat,
    replica_opt_state,
    stack_flat,
    stack_opt_states,
    stacked_size_bytes,
    unflatten,
)
from masters_thesis_tpu.train.optim import PlateauScheduler
from masters_thesis_tpu.train.steps import (
    jit_cache_size,
    make_eval_fn,
    make_stacked_train_epoch,
    metric_means,
    stacked_metric_means,
)
from masters_thesis_tpu.train.trainer import (
    device_train_split,
    prepare_eval_split,
)


@dataclasses.dataclass(frozen=True)
class ReplicaSpec:
    """One replica riding the stack: its identity and free hyperparameters."""

    name: str
    seed: int
    learning_rate: float


@dataclasses.dataclass
class ReplicaResult:
    name: str
    params: Any  # unflattened final params (rolled-back if masked)
    opt_state: Any  # single-replica FlatOptState
    best_val_loss: float
    history: list[dict]
    status: str  # active | recovering | masked
    rollbacks: int


@dataclasses.dataclass
class StackedResult:
    replicas: list[ReplicaResult]
    steps_per_sec: float  # program steps/sec (each step trains R cells)
    epochs: int

    @property
    def replica_steps_per_sec(self) -> float:
        return self.steps_per_sec * len(self.replicas)


class StackedTrainer:
    """Drive one stacked epoch program over R replicas.

    Deliberately narrower than :class:`Trainer` (scan mode, FlatAdam, no
    stream path): it exists for throughput — packing a sweep's worth of
    runs into one program — not as a second general-purpose fit loop.
    """

    def __init__(
        self,
        max_epochs: int,
        gradient_clip_val: float | None = None,
        check_val_every_n_epoch: int = 1,
        strategy: str = "auto",
        n_devices: int | None = None,
        enable_progress_bar: bool = True,
        ckpt_dir: str | Path | None = None,
        resume: bool | str = False,
        preflight: bool = False,
        telemetry: TelemetryRun | str | Path | None = None,
        max_replica_rollbacks: int = 1,
    ):
        self.max_epochs = max_epochs
        self.gradient_clip_val = gradient_clip_val
        self.check_val_every_n_epoch = max(1, int(check_val_every_n_epoch))
        if strategy == "auto":
            strategy = "tpu_xla" if len(jax.devices()) > 1 else "single_device"
        self.strategy = strategy
        self.mesh = make_data_mesh(
            1 if strategy == "single_device" else n_devices
        )
        self.n_dev = self.mesh.size
        self.enable_progress_bar = enable_progress_bar
        self.ckpt_dir = Path(ckpt_dir) if ckpt_dir else None
        if isinstance(resume, str):
            resume = resume.lower() in ("true", "auto", "1", "yes")
        self.resume = resume
        self.preflight = preflight
        if isinstance(telemetry, (str, Path)):
            telemetry = TelemetryRun(telemetry)
        self.telemetry = telemetry
        # Divergences tolerated per replica before it is masked: each one
        # costs a rollback to the last fenced snapshot + an LR halving
        # (the supervisor's NaN protocol, per replica instead of per run).
        self.max_replica_rollbacks = max(0, int(max_replica_rollbacks))

    # ----------------------------------------------------------------- fit

    def fit(
        self,
        spec: ModelSpec,
        dm: FinancialWindowDataModule,
        replicas: Sequence[ReplicaSpec],
    ) -> StackedResult:
        if not replicas:
            raise ValueError("stacked fit needs at least one ReplicaSpec")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        R = len(replicas)
        tel = self.telemetry

        if self.preflight:
            from masters_thesis_tpu.analysis.traceaudit import (
                PreflightError,
                assert_trace_clean,
            )

            self._print(
                f"preflight: trace audit (single + stacked R={R}) ..."
            )
            try:
                assert_trace_clean(
                    spec=spec, mesh=self.mesh, stacked_replicas=R
                )
            except PreflightError as exc:
                if tel:
                    tel.event(
                        "preflight",
                        status="failed",
                        rules=sorted({f.rule for f in exc.findings}),
                        findings=[f.format() for f in exc.findings],
                    )
                raise
            if tel:
                tel.event("preflight", status="ok", stacked_replicas=R)
            self._print("preflight: ok")

        dm.prepare_data(verbose=self.enable_progress_bar)
        dm.setup("fit")

        module = spec.build_module(compute_dtype=jnp.float32)
        objective = spec.window_objective()
        tx = FlatAdam(self.gradient_clip_val, spec.weight_decay)
        dummy = jnp.zeros((1, dm.lookback_window, dm.n_features), jnp.float32)

        # Per-replica init: each replica draws its own init/dropout streams
        # from its own seed — exactly the streams a solo run would draw.
        dropout_rngs = []
        params_list = []
        for rep in replicas:
            init_rng, dropout_rng = jax.random.split(jax.random.key(rep.seed))
            params_list.append(module.init(init_rng, dummy)["params"])
            dropout_rngs.append(dropout_rng)

        fspec = flatten_spec(params_list[0])
        schedulers = [PlateauScheduler(rep.learning_rate) for rep in replicas]
        opt_list = [tx.init(p) for p in params_list]
        best_vals = [float("inf")] * R
        start_epoch = 0

        # Resume: only when EVERY replica has a restorable 'last' at the
        # same epoch — a mixed-epoch stack would silently train replicas
        # different amounts per program step. Otherwise start fresh.
        resumed = self._try_resume(replicas, tx, params_list)
        if resumed is not None:
            params_list, opt_list, start_epoch, metas = resumed
            for r, meta in enumerate(metas):
                if meta.get("best_val") is not None:
                    best_vals[r] = float(meta["best_val"])
                if meta.get("scheduler"):
                    schedulers[r].load_state_dict(meta["scheduler"])
            self._print(
                f"resuming all {R} replicas at epoch {start_epoch}"
            )

        repl = replicated_sharding(self.mesh)
        pstack = global_put(
            stack_flat([flatten(p, fspec) for p in params_list]), repl
        )
        ostack = global_put(stack_opt_states(opt_list), repl)
        del params_list, opt_list

        train_dev, n_local = device_train_split(self.mesh, dm.train_arrays())
        b_local = dm.batch_size
        steps_per_epoch = n_local // b_local
        epoch_fn = make_stacked_train_epoch(
            module, objective, spec.metric_keys, tx, self.mesh, fspec,
            batch_size=b_local,
        )
        eval_fn = make_eval_fn(module, objective, self.mesh)
        val_prepared = prepare_eval_split(self.mesh, dm.val_arrays())

        statuses = ["active"] * R
        rollbacks = [0] * R
        histories: list[list[dict]] = [[] for _ in range(R)]

        # ---- telemetry wiring (same protocol as Trainer.fit, plus the
        # per-replica sub-streams `replica_epoch` / `replica_status`) ----
        tracker = rec = flight = fit_span = None
        if tel:
            flight = tel.attach_flight_recorder()
            flight.beat(phase="setup")
            fit_span = tel.tracer.start(
                "trainer.fit", trainer="stacked", stacked_replicas=R
            )
            tel.event(
                "run_started",
                platform=jax.default_backend(),
                n_devices=self.n_dev,
                strategy=self.strategy,
                epoch_mode="stacked_scan",
                steps_per_epoch=steps_per_epoch,
                max_epochs=self.max_epochs,
                start_epoch=start_epoch,
                objective=spec.objective,
                trainer="stacked",
                seed=replicas[0].seed,
                resumed_from=(
                    str(self.ckpt_dir) if resumed is not None else None
                ),
                distributed=distributed_run_context(),
                stacked_replicas=R,
                replicas=[dataclasses.asdict(r) for r in replicas],
                trace_id=tel.tracer.trace_id,
            )
            tel.gauge("train/collectives_per_step").set(num_buffers(fspec))
            tel.gauge("train/grad_reduce_bytes").set(
                stacked_size_bytes(fspec, R)
            )
            tel.event(
                "grad_sync",
                collectives_per_step=num_buffers(fspec),
                grad_reduce_bytes=stacked_size_bytes(fspec, R),
                flat_buffers=num_buffers(fspec),
                stacked_replicas=R,
            )
            tracker = CompileTracker(epoch_fn, size_fn=jit_cache_size)
            rec = EpochRecorder(tel, steps_per_epoch, span_parent=fit_span)

        def active_lrs() -> jax.Array:
            # Masked replicas ride along at lr=0: their rows stay exactly
            # at the rolled-back state (u * 0 update) without branching the
            # program or changing its signature.
            return global_put(
                jnp.asarray(
                    [
                        0.0 if statuses[r] == "masked" else schedulers[r].lr
                        for r in range(R)
                    ],
                    jnp.float32,
                ),
                repl,
            )

        def epoch_keys(epoch: int) -> jax.Array:
            return global_put(
                jnp.stack(
                    [jax.random.fold_in(k, epoch) for k in dropout_rngs]
                ),
                repl,
            )

        def snapshot(p, o):
            # Fresh buffers (donation-safe): the snapshot must survive the
            # next epoch call consuming the live stack.
            copy = lambda t: jax.tree_util.tree_map(jnp.copy, t)  # noqa: E731
            return copy(p), copy(o)

        def replica_params(p_stack, r: int):
            return unflatten(replica_flat(p_stack, r), fspec)

        def emit_replica(epoch, r, means_r, diverged):
            row = {
                "epoch": epoch,
                "lr-Adam": (
                    0.0 if statuses[r] == "masked" else schedulers[r].lr
                ),
            }
            row.update({f"loss/{k}/train": v for k, v in means_r.items()})
            if diverged:
                row["loss/total/train"] = float("nan")
            histories[r].append(row)
            if tel:
                tel.event(
                    "replica_epoch",
                    epoch=epoch,
                    replica=r,
                    name=replicas[r].name,
                    loss=row.get("loss/total/train"),
                    lr=row["lr-Adam"],
                    status=statuses[r],
                )
            return row

        def set_status(r, status, epoch, reason):
            if statuses[r] == status:
                return
            statuses[r] = status
            self._print(
                f"epoch {epoch}: replica {replicas[r].name!r} -> {status} "
                f"({reason})"
            )
            if tel:
                tel.event(
                    "replica_status",
                    epoch=epoch,
                    replica=r,
                    name=replicas[r].name,
                    status=status,
                    reason=reason,
                    rollbacks=rollbacks[r],
                )

        last_good = None  # (pstack, ostack) at the last fenced-clean epoch

        def handle_readback(epoch, sums) -> bool:
            """Per-replica divergence check; True iff NO replica is left.

            A non-finite replica is rolled back to the last fenced-clean
            snapshot and retried at half its LR; past the rollback budget
            it is masked (lr=0, rows pinned at the snapshot). Siblings are
            untouched either way — isolation is structural (row-wise
            dataflow) and asserted bit-exactly by tests/test_stacked.py.
            """
            nonlocal pstack, ostack
            means = stacked_metric_means(sums, R)
            for r in range(R):
                if statuses[r] == "masked":
                    emit_replica(epoch, r, means[r], diverged=False)
                    continue
                loss = means[r].get("total", float("nan"))
                if faults.fire(
                    "stacked.replica_loss", epoch=epoch, replica=r
                ) == "nan":
                    loss = float("nan")
                bad = not np.isfinite(loss)
                emit_replica(epoch, r, means[r], diverged=bad)
                if not bad:
                    if statuses[r] == "recovering":
                        set_status(r, "active", epoch, "finite loss again")
                    continue
                rollbacks[r] += 1
                if last_good is not None:
                    snap_p, snap_o = last_good
                    pstack = {
                        k: v.at[r].set(snap_p[k][r])
                        for k, v in pstack.items()
                    }
                    ostack = ostack._replace(
                        count=ostack.count.at[r].set(snap_o.count[r]),
                        mu={
                            k: v.at[r].set(snap_o.mu[k][r])
                            for k, v in ostack.mu.items()
                        },
                        nu={
                            k: v.at[r].set(snap_o.nu[k][r])
                            for k, v in ostack.nu.items()
                        },
                    )
                if rollbacks[r] > self.max_replica_rollbacks:
                    set_status(
                        r, "masked", epoch,
                        "rollback budget exhausted; frozen at last good "
                        "state",
                    )
                else:
                    schedulers[r].lr *= 0.5
                    set_status(
                        r, "recovering", epoch,
                        f"non-finite loss; rolled back, lr halved to "
                        f"{schedulers[r].lr:.3g}",
                    )
            return all(s == "masked" for s in statuses)

        history_rows: list[dict] = []  # (epoch, sums) readback pipeline
        pending: tuple[int, Any] | None = None
        t_start = None
        total_steps = 0
        all_dead = False

        for epoch in range(start_epoch, self.max_epochs):
            if flight is not None:
                flight.beat(phase="train", epoch=epoch)
            if rec:
                rec.begin(epoch)
            pstack, ostack, sums = epoch_fn(
                pstack, ostack, active_lrs(), epoch_keys(epoch), train_dev
            )
            total_steps += steps_per_epoch
            if rec:
                rec.dispatched(compiles=tracker.poll())

            if pending is not None:
                prev_epoch, prev_sums = pending
                pending = None
                all_dead = handle_readback(prev_epoch, prev_sums)
                if all_dead:
                    break

            is_val = (
                (epoch + 1) % self.check_val_every_n_epoch == 0
                and val_prepared
            )
            if is_val or t_start is None:
                # Fenced path: block on this epoch's sums, validate every
                # replica, and only THEN snapshot — last_good never holds a
                # poisoned stack.
                t_fence = time.perf_counter()
                all_dead = handle_readback(epoch, sums)
                if rec:
                    rec.fenced(time.perf_counter() - t_fence)
                    tel.sample_memory(epoch)
                if t_start is None:
                    t_start = time.perf_counter()
                if all_dead:
                    break
                last_good = snapshot(pstack, ostack)
                if is_val:
                    self._run_val(
                        epoch, pstack, eval_fn, val_prepared, replicas,
                        schedulers, statuses, best_vals, histories, tx,
                        fspec, ostack, spec, dm, tel,
                    )
            else:
                pending = (epoch, sums)

        if pending is not None and not all_dead:
            all_dead = handle_readback(*pending)

        jax.block_until_ready(pstack)
        if rec:
            rec.finish()
        elapsed = time.perf_counter() - (t_start or time.perf_counter())
        post_compile_steps = total_steps - steps_per_epoch
        steps_per_sec = (
            post_compile_steps / elapsed
            if elapsed > 0 and post_compile_steps > 0
            else 0.0
        )

        # Final per-replica checkpoints: masked replicas were rolled back
        # to their last clean state, so 'last' is always safe to restore.
        results = []
        pstack_h = jax.device_get(pstack)
        ostack_h = jax.device_get(ostack)
        for r, rep in enumerate(replicas):
            params_r = replica_params(pstack_h, r)
            opt_r = replica_opt_state(ostack_h, r)
            if self.ckpt_dir:
                self._save_replica(
                    rep, "last", params_r, opt_r, spec, dm,
                    self.max_epochs - 1, best_vals[r], schedulers[r],
                    statuses[r],
                )
            results.append(
                ReplicaResult(
                    name=rep.name,
                    params=params_r,
                    opt_state=opt_r,
                    best_val_loss=best_vals[r],
                    history=histories[r],
                    status=statuses[r],
                    rollbacks=rollbacks[r],
                )
            )

        if tel:
            if flight is not None:
                flight.beat(phase="finished")
            tel.sample_memory(None)
            tel.tracer.end(
                fit_span,
                status="error" if all_dead else "ok",
                epochs=max((len(h) for h in histories), default=0),
            )
            tel.event(
                "run_finished",
                epochs=max((len(h) for h in histories), default=0),
                total_steps=total_steps,
                steps_per_sec=steps_per_sec,
                diverged=all_dead,
                best_val=min(
                    (v for v in best_vals if np.isfinite(v)), default=None
                ),
                epoch_compiles=tracker.total,
                eval_compiles=0,
                stacked_replicas=R,
                replica_status={
                    replicas[r].name: statuses[r] for r in range(R)
                },
            )
            tel.snapshot_metrics()

        del history_rows
        return StackedResult(
            replicas=results,
            steps_per_sec=steps_per_sec,
            epochs=self.max_epochs - start_epoch,
        )

    # ------------------------------------------------------------- helpers

    def _run_val(
        self, epoch, pstack, eval_fn, val_prepared, replicas, schedulers,
        statuses, best_vals, histories, tx, fspec, ostack, spec, dm, tel,
    ):
        """Per-replica validation through ONE compiled eval program.

        Row extraction is a device-side slice; all R calls share the same
        (shape, sharding) signature, so eval compiles once regardless of R.
        """
        for r, rep in enumerate(replicas):
            if statuses[r] == "masked":
                continue
            params_r = unflatten(replica_flat(pstack, r), fspec)
            val_sums = eval_fn(params_r, *val_prepared)
            val_metrics = metric_means(jax.device_get(val_sums))
            val_loss = val_metrics["total"]
            if histories[r] and histories[r][-1]["epoch"] == epoch:
                histories[r][-1].update(
                    {f"loss/{k}/val": v for k, v in val_metrics.items()}
                )
            schedulers[r].step(val_loss)
            if tel:
                tel.event(
                    "replica_eval",
                    epoch=epoch,
                    replica=r,
                    name=rep.name,
                    val_loss=float(val_loss),
                )
            if val_loss < best_vals[r] and self.ckpt_dir:
                best_vals[r] = val_loss
                self._save_replica(
                    rep, "best",
                    jax.device_get(params_r),
                    replica_opt_state(jax.device_get(ostack), r),
                    spec, dm, epoch, best_vals[r], schedulers[r],
                    statuses[r],
                )
            elif val_loss < best_vals[r]:
                best_vals[r] = val_loss

    def _replica_dir(self, rep: ReplicaSpec) -> Path:
        return self.ckpt_dir / rep.name

    def _try_resume(self, replicas, tx, params_list):
        if not (self.resume and self.ckpt_dir):
            return None
        restorable = all(
            ckpt_lib.checkpoint_restorable(self._replica_dir(rep), "last")
            for rep in replicas
        )
        if not restorable:
            return None
        from masters_thesis_tpu.train.checkpoint import (
            restore_checkpoint,
            restore_opt_state,
        )

        new_params, new_opts, metas, epochs = [], [], [], set()
        for rep, template_params in zip(replicas, params_list):
            r_params, r_opt, _, r_meta = restore_checkpoint(
                self._replica_dir(rep), "last"
            )
            params = jax.tree_util.tree_map(jnp.asarray, r_params)
            template = jax.device_get(tx.init(template_params))
            new_params.append(params)
            new_opts.append(restore_opt_state(template, r_opt, params=params))
            metas.append(r_meta)
            epochs.add(int(r_meta.get("epoch", -1)))
        if len(epochs) != 1:
            self._print(
                f"resume skipped: replica checkpoints at mixed epochs "
                f"{sorted(epochs)}; starting fresh"
            )
            return None
        return new_params, new_opts, epochs.pop() + 1, metas

    def _save_replica(
        self, rep, tag, params, opt_state, spec, dm, epoch, best_val,
        scheduler, status,
    ):
        ckpt_lib.save_checkpoint(
            self._replica_dir(rep), tag, params, opt_state, spec,
            meta={
                "epoch": epoch,
                "val_loss": float(best_val),
                "scheduler": scheduler.state_dict(),
                "best_val": (
                    None if not np.isfinite(best_val) else float(best_val)
                ),
                "trainer": "stacked",
                "replica": dataclasses.asdict(rep),
                "replica_status": status,
                "datamodule": {
                    "lookback_window": dm.lookback_window,
                    "target_window": dm.target_window,
                    "stride": dm.stride,
                    "prediction_task": dm.prediction_task,
                    "interaction_only": dm.interaction_only,
                    "batch_size": dm.batch_size,
                },
            },
        )
        if self.telemetry:
            self.telemetry.event(
                "checkpoint_saved",
                tag=tag,
                epoch=epoch,
                replica=rep.name,
                path=str(self._replica_dir(rep) / tag),
            )

    def _print(self, msg: str) -> None:
        if self.enable_progress_bar and jax.process_index() == 0:
            print(msg, flush=True)
