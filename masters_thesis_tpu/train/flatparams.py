"""Flat-parameter buffers: one contiguous view of the whole model.

The r4 bench showed the multi-chip hot loop paying per-*leaf* overhead:
``lax.pmean(grads)`` over the param pytree lowers to one all-reduce per
leaf (8 for even the 1-layer audit model), so every step pays N collective
launches for a few hundred KB of gradient. Production JAX trainers flatten
the pytree into one contiguous buffer and reduce THAT (PAPERS.md: pjit
LM-training at scale; TorchTitan's bucketed flat all-reduce). This module
is that layout:

- :func:`flatten_spec` walks the pytree once and records a static **view
  table**: for every leaf, which per-dtype buffer it lives in, at what
  offset, with what shape. The spec is pure Python (hashable metadata, no
  arrays) — it is closed over at trace time, never traced itself.
- :func:`flatten` / :func:`unflatten` move values between the pytree and
  the per-dtype 1-D buffers. Both are pure layout ops (reshape + concat /
  static slice) — XLA fuses them into the neighbouring computation, and
  ``unflatten(flatten(t)) == t`` bit-for-bit.
- :class:`FlatAdam` is the repo's optimizer chain (global-norm clip ->
  L2 decay -> Adam moments, ``train/optim.py:make_optimizer``) re-stated
  over the flat buffers: moments are stored flat, every update op is one
  elementwise pass over the whole buffer, and the only reduction is the
  clip norm. Formulas replicate optax 0.2.3 term-for-term (including
  ``safe_int32_increment`` and the bias-correction dtype dance) so the
  flat path is **bit-identical** to the pytree path — asserted by
  ``tests/test_flatparams.py`` over multi-epoch runs on the 8-device mesh.

The payoff in ``train/steps.py``: the cross-chip gradient sync becomes
exactly ONE ``lax.pmean`` over the flat buffer per step (trace-audit rule
TA206 pins this in the lowered HLO), and the Adam update is one fused
elementwise kernel instead of a ragged per-leaf sweep.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class LeafView(NamedTuple):
    """Where one pytree leaf lives inside the flat per-dtype buffers."""

    key: str  # dtype buffer key, e.g. "float32"
    offset: int  # element offset into that buffer
    size: int  # element count
    shape: tuple  # original leaf shape


class FlatSpec(NamedTuple):
    """Static view table mapping a pytree onto per-dtype flat buffers.

    ``views`` follow ``jax.tree_util.tree_leaves`` order — the same order
    optax's ``global_norm`` sums leaf norms in, which is what lets the
    flat clip reduction reproduce the pytree clip bit-for-bit.
    """

    treedef: Any
    views: tuple  # tuple[LeafView, ...] in tree_leaves order
    sizes: tuple  # tuple[(key, total elements), ...] per dtype buffer
    dtypes: tuple  # tuple[(key, dtype), ...] per dtype buffer


def flatten_spec(tree) -> FlatSpec:
    """Build the view table for ``tree`` (arrays, tracers, or ShapeDtypeStructs)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    offsets: dict[str, int] = {}
    dtypes: dict[str, Any] = {}
    views = []
    for leaf in leaves:
        dtype = jnp.dtype(leaf.dtype)
        key = dtype.name
        dtypes.setdefault(key, dtype)
        off = offsets.get(key, 0)
        size = 1
        for d in leaf.shape:
            size *= int(d)
        views.append(LeafView(key, off, size, tuple(int(d) for d in leaf.shape)))
        offsets[key] = off + size
    return FlatSpec(
        treedef=treedef,
        views=tuple(views),
        sizes=tuple(sorted(offsets.items())),
        dtypes=tuple(sorted(dtypes.items())),
    )


def flatten(tree, spec: FlatSpec) -> dict:
    """Pack a pytree (matching ``spec``'s treedef) into per-dtype 1-D buffers."""
    leaves = jax.tree_util.tree_leaves(tree)
    segments: dict[str, list] = {key: [] for key, _ in spec.sizes}
    for leaf, view in zip(leaves, spec.views):
        segments[view.key].append(jnp.reshape(leaf, (view.size,)))
    return {
        key: (segs[0] if len(segs) == 1 else jnp.concatenate(segs))
        for key, segs in segments.items()
    }


def unflatten(bufs: dict, spec: FlatSpec):
    """Carve the per-dtype buffers back into the original pytree (views only)."""
    leaves = [
        jnp.reshape(bufs[v.key][v.offset : v.offset + v.size], v.shape)
        for v in spec.views
    ]
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def flat_size_bytes(spec: FlatSpec) -> int:
    """Total bytes of the flat buffers == bytes moved by the one pmean."""
    dtypes = dict(spec.dtypes)
    return sum(n * jnp.dtype(dtypes[key]).itemsize for key, n in spec.sizes)


def num_buffers(spec: FlatSpec) -> int:
    """Distinct dtype buffers == collectives per step on the flat path."""
    return len(spec.sizes)


def _leaf_square_sum(bufs: dict, spec: FlatSpec):
    """``sum(||leaf||^2)`` over views, replicating optax's ``global_norm``.

    Each segment is reshaped back to the leaf's shape before ``jnp.sum`` so
    the per-leaf reduction XLA sees (shape, order) is identical to the one
    the pytree path runs — that, plus Python-ordered accumulation across
    leaves, is what makes the clip trigger bit-identical.
    """
    return sum(
        jnp.sum(
            jnp.square(
                jnp.reshape(bufs[v.key][v.offset : v.offset + v.size], v.shape)
            )
        )
        for v in spec.views
    )


class FlatOptState(NamedTuple):
    """Adam state over flat buffers; a plain pytree (donate/global_put safe)."""

    count: jax.Array  # int32 scalar, safe-incremented like optax
    mu: dict  # per-dtype first-moment buffers
    nu: dict  # per-dtype second-moment buffers


class FlatAdam:
    """``make_optimizer``'s chain, fused over flat buffers.

    Same contract as the optax chain it replaces: ``update_flat`` returns
    the ASCENT direction (the caller applies ``p - lr * u``), clip runs on
    raw (already pmean'd) gradients, L2 decay folds ``wd * p`` into the
    clipped gradient before the moment updates (torch-Adam semantics, not
    AdamW), and the moments/bias-correction match optax's ``scale_by_adam``
    term-for-term.
    """

    def __init__(
        self,
        gradient_clip_val: float | None = None,
        weight_decay: float = 0.0,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-8,
        eps_root: float = 0.0,
    ):
        self.gradient_clip_val = (
            float(gradient_clip_val)
            if gradient_clip_val is not None and gradient_clip_val > 0
            else None
        )
        self.weight_decay = float(weight_decay)
        self.b1 = b1
        self.b2 = b2
        self.eps = eps
        self.eps_root = eps_root

    def init(self, params) -> FlatOptState:
        spec = flatten_spec(params)
        dtypes = dict(spec.dtypes)

        # Distinct arrays per moment: mu and nu sharing one zeros buffer
        # trips XLA's "same buffer donated twice" check under donate_argnums.
        def zeros():
            return {key: jnp.zeros((n,), dtypes[key]) for key, n in spec.sizes}

        return FlatOptState(
            count=jnp.zeros([], jnp.int32), mu=zeros(), nu=zeros()
        )

    def update_flat(
        self, gbufs: dict, state: FlatOptState, pbufs: dict, spec: FlatSpec
    ) -> tuple[dict, FlatOptState]:
        """One fused elementwise pass: (grad bufs, state, param bufs) -> (updates, state)."""
        if self.gradient_clip_val is not None:
            max_norm = self.gradient_clip_val
            g_norm = jnp.sqrt(_leaf_square_sum(gbufs, spec))
            trigger = jnp.squeeze(g_norm < max_norm)
            gbufs = {
                k: jax.lax.select(
                    trigger, g, (g / g_norm.astype(g.dtype)) * max_norm
                )
                for k, g in gbufs.items()
            }
        if self.weight_decay:
            wd = self.weight_decay
            gbufs = {k: g + wd * pbufs[k] for k, g in gbufs.items()}
        b1, b2 = self.b1, self.b2
        mu = {k: (1 - b1) * g + b1 * state.mu[k] for k, g in gbufs.items()}
        nu = {k: (1 - b2) * (g**2) + b2 * state.nu[k] for k, g in gbufs.items()}
        # optax.safe_int32_increment: saturate at int32 max instead of wrapping.
        max_i32 = jnp.iinfo(jnp.int32).max
        one = jnp.array(1, jnp.int32)
        count_inc = jnp.where(state.count < max_i32, state.count + one, max_i32)
        bc1 = 1 - b1**count_inc
        bc2 = 1 - b2**count_inc
        mu_hat = {k: m / bc1.astype(m.dtype) for k, m in mu.items()}
        nu_hat = {k: v / bc2.astype(v.dtype) for k, v in nu.items()}
        updates = {
            k: mu_hat[k] / (jnp.sqrt(nu_hat[k] + self.eps_root) + self.eps)
            for k in mu_hat
        }
        return updates, FlatOptState(count=count_inc, mu=mu, nu=nu)

    def update(self, grads, state: FlatOptState, params):
        """Pytree-facing adapter (the stream-mode step uses this): flatten,
        run the fused pass, unflatten the updates."""
        spec = flatten_spec(params)
        ubufs, state = self.update_flat(
            flatten(grads, spec), state, flatten(params, spec), spec
        )
        return unflatten(ubufs, spec), state


# ------------------------------------------------------- stacked replicas
#
# The stacked training path (train/steps.py:make_stacked_train_epoch) runs
# R independent replicas — grid cells differing only in lr/seed — as a
# leading vmap axis over the SAME flat layout: every per-dtype ``[n]``
# buffer becomes ``[R, n]``, FlatAdam applies elementwise across the stack
# (per-replica clip norms and bias-correction counts fall out of vmap for
# free), and per-replica hyperparameters travel as ``[R]`` vectors. The
# helpers below are the host-side seams: building the stack from R
# single-replica states and carving one replica back out (for per-cell
# checkpoints, which stay layout-independent via to_portable).


def stack_flat(bufs_list: list) -> dict:
    """R single-replica buffer dicts ``{key: [n]}`` -> one ``{key: [R, n]}``."""
    return {k: jnp.stack([b[k] for b in bufs_list]) for k in bufs_list[0]}


def replica_flat(stacked: dict, r: int) -> dict:
    """Carve replica ``r``'s row out of a stacked buffer dict."""
    return {k: v[r] for k, v in stacked.items()}


def set_lane(stacked: dict, r: int, bufs: dict) -> dict:
    """Functionally replace lane ``r``'s row across the stacked buffers.

    The serving-side seam of the stack (serve/stacked.py): a per-lane
    hot-swap writes ONE row of every ``[R, n]`` dtype buffer and leaves
    every sibling row bit-untouched — ``.at[r].set`` is a row scatter, so
    the result is a fresh stacked dict (the caller swaps the reference
    atomically) whose other rows alias the old buffers' values exactly.
    Shapes never change, so the AOT executables compiled against the
    stack keep serving with zero recompiles.
    """
    return {
        k: v.at[r].set(jnp.asarray(bufs[k], v.dtype))
        for k, v in stacked.items()
    }


def stack_opt_states(states: list) -> FlatOptState:
    """R per-replica FlatOptStates -> one stacked state.

    ``count`` becomes an ``[R]`` int32 vector — replicas that diverge and
    get rolled back keep their own bias-correction clock, so a recovered
    replica's Adam trajectory is exactly the one it would have run alone.
    """
    return FlatOptState(
        count=jnp.stack([s.count for s in states]),
        mu=stack_flat([s.mu for s in states]),
        nu=stack_flat([s.nu for s in states]),
    )


def replica_opt_state(state: FlatOptState, r: int) -> FlatOptState:
    """Extract replica ``r``'s single-replica FlatOptState from a stack."""
    return FlatOptState(
        count=state.count[r],
        mu=replica_flat(state.mu, r),
        nu=replica_flat(state.nu, r),
    )


def stacked_size_bytes(spec: FlatSpec, replicas: int) -> int:
    """HBM held by one stacked copy of the flat buffers.

    Total stacked-path growth is ~4x this (params + grads + mu + nu) plus
    activations; docs/perf.md uses it to size R against the memory budget.
    """
    return replicas * flat_size_bytes(spec)


# -------------------------------------------------- checkpoint portability
#
# The on-disk layout must not depend on the flat buffer layout (leaf order
# inside a buffer is an implementation detail that the next refactor may
# change). Checkpoints therefore store the moments UNFLATTENED through the
# view table — the same params-shaped pytree an optax checkpoint holds —
# and the restore side re-flattens against the CURRENT spec.


def to_portable(state: FlatOptState, params) -> dict:
    """FlatOptState -> layout-independent state dict (moments as pytrees)."""
    spec = flatten_spec(params)
    return {
        "count": state.count,
        "mu": unflatten(state.mu, spec),
        "nu": unflatten(state.nu, spec),
    }


def from_portable(raw: dict, params) -> FlatOptState:
    """Inverse of :func:`to_portable`, flattening against params' spec."""
    spec = flatten_spec(params)
    return FlatOptState(
        count=jnp.asarray(raw["count"], jnp.int32),
        mu={k: jnp.asarray(v) for k, v in flatten(raw["mu"], spec).items()},
        nu={k: jnp.asarray(v) for k, v in flatten(raw["nu"], spec).items()},
    )
