"""Orbax-backed checkpointing: best/last, with hparams sidecars.

Replaces Lightning's ModelCheckpoint + ``save_hyperparameters`` reload path
(reference: train.py:151-161 saves best-on-`loss/total/val` and last;
src/model.py:188 + test.py:177-178 reload a module from checkpoint with its
constructor hparams). Layout::

    <ckpt_dir>/
      best/   # orbax pytree: params, opt_state
      last/
      best.json / last.json   # hparams + training metadata sidecar

Orbax handles multi-host coordination and HBM->host streaming natively;
the JSON sidecar carries everything needed to rebuild the ModelSpec and
DataModule without the training config (the ``load_from_checkpoint``
equivalent).
"""

from __future__ import annotations

import dataclasses
import json
import shutil
from pathlib import Path
from typing import Any

import flax.serialization as fser
import jax
import orbax.checkpoint as ocp

from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.utils import atomic_write_text


def save_checkpoint(
    ckpt_dir: Path,
    tag: str,
    params: Any,
    opt_state: Any,
    spec: ModelSpec,
    meta: dict,
) -> None:
    """Atomically write ``<ckpt_dir>/<tag>`` (orbax) + ``<tag>.json`` sidecar."""
    ckpt_dir = Path(ckpt_dir).resolve()
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    path = ckpt_dir / tag
    if path.exists():
        shutil.rmtree(path)
    with ocp.StandardCheckpointer() as ckptr:
        # to_state_dict turns optax namedtuple states into pure dicts, so the
        # restore side can rebuild any optimizer structure via from_state_dict
        # without orbax needing the live pytree as a template.
        ckptr.save(
            path,
            {
                "params": params,
                "opt_state": fser.to_state_dict(jax.device_get(opt_state)),
            },
        )
        ckptr.wait_until_finished()
    sidecar = {"spec": dataclasses.asdict(spec), "meta": meta}
    if jax.process_index() == 0:
        # Atomic publish: a crash mid-write must not leave a torn sidecar
        # (the auto-resume path reads it on restart).
        atomic_write_text(
            ckpt_dir / f"{tag}.json", json.dumps(sidecar, indent=2)
        )


def restore_checkpoint(
    ckpt_dir: Path, tag: str = "best"
) -> tuple[Any, Any, ModelSpec, dict]:
    """Load (params, opt_state, spec, meta) from a checkpoint directory.

    Accepts either the checkpoint root (picks ``<tag>``) or a direct path to
    a tagged checkpoint — mirroring how the reference's test.py takes the
    checkpoint file path on the CLI (reference: test.py:153,177).
    """
    ckpt_dir = Path(ckpt_dir).resolve()
    if (ckpt_dir / tag).exists():
        path = ckpt_dir / tag
        sidecar_path = ckpt_dir / f"{tag}.json"
    else:
        path = ckpt_dir
        sidecar_path = ckpt_dir.parent / f"{ckpt_dir.name}.json"
    sidecar = json.loads(sidecar_path.read_text())
    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(path)
    spec = ModelSpec(**sidecar["spec"])
    return tree["params"], tree["opt_state"], spec, sidecar["meta"]


def restore_opt_state(template: Any, raw: Any) -> Any:
    """Rebuild an optax state pytree from its checkpointed state dict."""
    return fser.from_state_dict(template, raw)


def apply_datamodule_sidecar(cfg, meta: dict) -> None:
    """Overwrite cfg.datamodule's window hparams from a checkpoint's meta.

    Evaluation must window the data exactly the way the checkpoint was
    trained (lookback/target/stride/...); ``data_dir`` and ``engine`` stay
    config-driven — they are environment-, not model-specific. Shared by
    test.py and sweeps/eval_cell.py so the invariant lives in one place.
    """
    for key, value in meta.get("datamodule", {}).items():
        if key in cfg.datamodule:
            cfg.datamodule[key] = value
