"""Orbax-backed checkpointing: best/last, with hparams sidecars.

Replaces Lightning's ModelCheckpoint + ``save_hyperparameters`` reload path
(reference: train.py:151-161 saves best-on-`loss/total/val` and last;
src/model.py:188 + test.py:177-178 reload a module from checkpoint with its
constructor hparams). Layout::

    <ckpt_dir>/
      best/   # orbax pytree: params, opt_state (+ MANIFEST.json checksums)
      last/
      best.json / last.json   # hparams + training metadata sidecar
      last.prev/ + last.prev.json   # previous good save (restore fallback)

Orbax handles multi-host coordination and HBM->host streaming natively;
the JSON sidecar carries everything needed to rebuild the ModelSpec and
DataModule without the training config (the ``load_from_checkpoint``
equivalent).
"""

from __future__ import annotations

import dataclasses
import json
import shutil
import sys
from pathlib import Path
from typing import Any

import flax.serialization as fser
import jax
import numpy as np
import orbax.checkpoint as ocp

from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.train import flatparams

# Manifest machinery lives in the stdlib-only train.manifest module (the
# fleet supervisor verifies checkpoints on hosts where importing jax can
# hang); re-exported here for the historical import path.
from masters_thesis_tpu.train.manifest import (  # noqa: F401
    MANIFEST_NAME,
    verify_checkpoint,
    write_manifest as _write_manifest,
)
from masters_thesis_tpu.utils import atomic_write_text, fsync_path


class CorruptCheckpointError(RuntimeError):
    """No restorable checkpoint: latest (and any previous-good fallback)
    failed content verification."""


def save_checkpoint(
    ckpt_dir: Path,
    tag: str,
    params: Any,
    opt_state: Any,
    spec: ModelSpec,
    meta: dict,
    extra_files: dict[str, str] | None = None,
) -> None:
    """Atomically write ``<ckpt_dir>/<tag>`` (orbax) + ``<tag>.json`` sidecar.

    ``extra_files`` maps filenames to text written INTO the staged tree
    before its manifest — e.g. the trainer's ``quality.json`` model
    fingerprint. They are therefore sha256-covered by ``MANIFEST.json``,
    fsync'd with the tree, rotate to ``<tag>.prev`` with the pair, and a
    torn or doctored copy fails strict verification exactly like a torn
    checkpoint file.
    """
    ckpt_dir = Path(ckpt_dir).resolve()
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    path = ckpt_dir / tag
    # Crash-safe replacement protocol (the old code rmtree'd the live
    # <tag> before the new write was durable — a SIGKILL mid-save then
    # destroyed the only resume point, caught by the CLI kill-test):
    #   1. orbax tree  -> <tag>.new        (complete before anything moves)
    #   2. MANIFEST.json (sha256 per file, fsync'd) inside the staged tree
    #   3. sidecar     -> <tag>.json.new   (meta matching the staged tree)
    #   4. publish, renames only:  <tag> -> <tag>.prev (kept as the
    #      previous-good fallback),  <tag>.new -> <tag>,
    #      <tag>.json.new -> <tag>.json
    # A kill at ANY point leaves either the previous checkpoint intact or
    # a staged pair that _recover_staged finishes on the next restore;
    # the sidecar rides the same swap so tree and meta can never pair up
    # across different saves. Publish steps run on process 0 only
    # (multi-host checkpointing assumes shared storage, as orbax does).
    staging = ckpt_dir / f"{tag}.new"
    staged_sidecar = ckpt_dir / f"{tag}.json.new"
    if jax.process_index() == 0:
        # Sidecar BEFORE tree: a kill in between leaves an orphan tree
        # (safely dropped by recovery), never an orphan sidecar that could
        # later pair with a mismatched tree.
        staged_sidecar.unlink(missing_ok=True)
        if staging.exists():
            shutil.rmtree(staging)
    # Flat optimizer states (train/flatparams.py) are stored UNFLATTENED
    # through the view table: the on-disk layout is the params-shaped moment
    # pytree an optax checkpoint would hold, independent of the flat
    # buffers' internal leaf order — a layout refactor must not invalidate
    # every checkpoint. The restore side re-flattens against the current
    # params (restore_opt_state(params=...)).
    host_state = jax.device_get(opt_state)
    if isinstance(host_state, flatparams.FlatOptState):
        host_state = flatparams.to_portable(
            host_state, jax.device_get(params)
        )
    with ocp.StandardCheckpointer() as ckptr:
        # to_state_dict turns optax namedtuple states into pure dicts, so the
        # restore side can rebuild any optimizer structure via from_state_dict
        # without orbax needing the live pytree as a template.
        ckptr.save(
            staging,
            {
                "params": params,
                "opt_state": fser.to_state_dict(host_state),
            },
        )
        ckptr.wait_until_finished()
    if jax.process_index() == 0:
        # Extra sidecar files (quality fingerprint, ...) land inside the
        # staged tree BEFORE the manifest walk so they get sha256+size
        # coverage and ride every later rename with the data they
        # describe. fsync before hashing: the manifest must describe
        # bytes that are actually durable.
        for name, text in (extra_files or {}).items():
            target = staging / name
            target.write_text(text)
            fsync_path(target)
        # Content checksums INSIDE the staged tree: the manifest travels
        # through the publish renames with the data it describes, so a
        # torn or bit-flipped tree is detectable at restore time and can
        # never silently pair with a clean manifest from another save.
        _write_manifest(staging)
        sidecar = {"spec": dataclasses.asdict(spec), "meta": meta}
        atomic_write_text(
            staged_sidecar, json.dumps(sidecar, indent=2), fsync=True
        )
        faults.fire("checkpoint.pre_publish", tag=tag)
        _publish(ckpt_dir, tag)
        if faults.fire("checkpoint.post_publish", tag=tag) == "corrupt":
            _corrupt_tree(path, seed=faults.corruption_seed())
    # Publish barrier: non-zero ranks must not race ahead (into the next
    # save's staging reset, or a preemption-window exit) while rank 0 is
    # still mid-rotation — a fleet-level kill landing in that window
    # would otherwise see a torn publish that NO rank was responsible
    # for finishing. No-op single-process.
    from masters_thesis_tpu.parallel.mesh import fleet_barrier

    fleet_barrier(f"checkpoint.publish.{tag}")


def _corrupt_tree(path: Path, seed: int) -> None:
    """Deterministically flip one byte in the largest data file of a
    checkpoint tree (fault-injection helper for ``kind: corrupt``)."""
    import random

    files = sorted(
        (p for p in Path(path).rglob("*") if p.is_file() and p.name != MANIFEST_NAME),
        key=lambda p: (-p.stat().st_size, str(p)),
    )
    if not files:
        return
    target = files[0]
    data = bytearray(target.read_bytes())
    if not data:
        return
    idx = random.Random(seed).randrange(len(data))
    data[idx] ^= 0xFF
    target.write_bytes(bytes(data))


def _publish(ckpt_dir: Path, tag: str) -> None:
    """Swap a complete staged pair into place. Renames only (atomic);
    shared by save_checkpoint and crash recovery so the ordering can't
    diverge. The outgoing checkpoint is ROTATED to ``<tag>.prev`` (tree +
    sidecar) instead of deleted: restore falls back to it when the latest
    tree fails content verification. A crash mid-rotation can at worst
    leave an incomplete ``.prev`` pair — never a damaged primary, since
    recovery re-runs the staging swap.

    Callers must run this on rank 0 only (save_checkpoint and
    _run_recovery both gate on ``jax.process_index() == 0``): under
    shared multi-host storage, two processes racing the rotation could
    rename the same tree twice. The directory is fsync'd after the
    rotation and again after the staging swap so the rename ORDER is
    what reaches stable storage — a power cut must never surface the new
    tree as live while the ``.prev`` rotation it depends on is still
    only in the page cache."""
    path = ckpt_dir / tag
    prev = ckpt_dir / f"{tag}.prev"
    prev_sidecar = ckpt_dir / f"{tag}.prev.json"
    if path.exists():
        if prev.exists():
            shutil.rmtree(prev)
        prev_sidecar.unlink(missing_ok=True)
        path.rename(prev)
        sidecar = ckpt_dir / f"{tag}.json"
        if sidecar.exists():
            sidecar.replace(prev_sidecar)
        fsync_path(ckpt_dir)
    # The most exposed instant of the protocol: the rotation has moved
    # the old checkpoint aside but the staged tree is not yet live. A
    # kill here must leave .prev restorable and the staged pair intact
    # for recovery — the torn-mid-publish chaos test fires exactly here.
    faults.fire("checkpoint.mid_publish", tag=tag)
    (ckpt_dir / f"{tag}.new").rename(path)
    (ckpt_dir / f"{tag}.json.new").replace(ckpt_dir / f"{tag}.json")
    fsync_path(ckpt_dir)


def _recover_staged(ckpt_dir: Path, tag: str) -> None:
    """Finish (or discard) an interrupted save_checkpoint publish.

    Covers every kill point of the staged-swap protocol (see
    save_checkpoint): a finalized staging PAIR (tree + sidecar) supersedes
    whatever is in place and is swapped in; a staged tree without its
    sidecar predates publish — the previous checkpoint is still current,
    so the orphan is dropped; a staged sidecar alone means the tree swap
    finished and only the sidecar rename was lost. Orbax only ever exposes
    a finalized tree under the staging name (its own writes go through a
    tmp suffix), so ``staging.exists()`` implies the tree is complete.
    """
    path = ckpt_dir / tag
    old = ckpt_dir / f"{tag}.old"
    staging = ckpt_dir / f"{tag}.new"
    staged_sidecar = ckpt_dir / f"{tag}.json.new"
    if staging.exists():
        if staged_sidecar.exists():
            _publish(ckpt_dir, tag)
        else:
            shutil.rmtree(staging)
    elif staged_sidecar.exists():
        staged_sidecar.replace(ckpt_dir / f"{tag}.json")
    if old.exists() and path.exists():
        shutil.rmtree(old, ignore_errors=True)


def _run_recovery(ckpt_dir: Path, tag: str) -> None:
    """Process-0 performs recovery; other processes WAIT for the staging
    artifacts to disappear (shared checkpoint storage, as orbax assumes).

    The wait triggers whenever artifacts are visible — even if a
    restorable-looking pair already exists — because a (new tree, stale
    sidecar) layout mid-recovery would otherwise let a non-zero process
    read an epoch that disagrees with process 0's, desyncing the
    multi-host resume decision and hanging the collectives.
    """
    staging = ckpt_dir / f"{tag}.new"
    staged_sidecar = ckpt_dir / f"{tag}.json.new"
    if jax.process_index() == 0:
        _recover_staged(ckpt_dir, tag)
    elif staging.exists() or staged_sidecar.exists():
        from masters_thesis_tpu.utils import wait_until

        wait_until(
            lambda: not staging.exists() and not staged_sidecar.exists(),
            60.0,
        )
    # Fence the recovery (DV705): without this barrier a non-zero process
    # whose staging check raced ahead of process 0's rename could read the
    # pre-recovery tree and resume from a different epoch. The polling
    # wait above bounds the stall; the barrier makes the ordering exact.
    from masters_thesis_tpu.parallel.mesh import fleet_barrier

    fleet_barrier(f"checkpoint.recover.{tag}")


def _candidates(ckpt_dir: Path, tag: str) -> list[tuple[Path, Path]]:
    """(tree, sidecar) pairs in restore-preference order: latest, then
    the previous-good rotation."""
    return [
        (ckpt_dir / tag, ckpt_dir / f"{tag}.json"),
        (ckpt_dir / f"{tag}.prev", ckpt_dir / f"{tag}.prev.json"),
    ]


def _pick_restorable(ckpt_dir: Path, tag: str) -> tuple[Path, Path] | None:
    for tree, sidecar in _candidates(ckpt_dir, tag):
        if tree.exists() and sidecar.exists() and verify_checkpoint(tree):
            return tree, sidecar
    return None


def checkpoint_restorable(ckpt_dir: Path, tag: str) -> bool:
    """True if ``<ckpt_dir>/<tag>`` — or its ``.prev`` previous-good
    rotation — verifies and can be restored, after finishing any
    interrupted staging swap."""
    ckpt_dir = Path(ckpt_dir)
    if ckpt_dir.exists():
        _run_recovery(ckpt_dir, tag)
    return _pick_restorable(ckpt_dir, tag) is not None


def restore_checkpoint(
    ckpt_dir: Path, tag: str = "best"
) -> tuple[Any, Any, ModelSpec, dict]:
    """Load (params, opt_state, spec, meta) from a checkpoint directory.

    Accepts either the checkpoint root (picks ``<tag>``) or a direct path to
    a tagged checkpoint — mirroring how the reference's test.py takes the
    checkpoint file path on the CLI (reference: test.py:153,177).
    """
    ckpt_dir = Path(ckpt_dir).resolve()
    # Recovery must look where the staging artifacts actually live: next
    # to <tag> under a checkpoint ROOT, or next to the direct path itself
    # (a direct path may not even exist yet if the kill landed mid-swap).
    if any(
        (ckpt_dir / n).exists() for n in (tag, f"{tag}.new", f"{tag}.prev")
    ):
        _run_recovery(ckpt_dir, tag)
        root, name = ckpt_dir, tag
    else:
        if ckpt_dir.parent.exists():
            _run_recovery(ckpt_dir.parent, ckpt_dir.name)
        root, name = ckpt_dir.parent, ckpt_dir.name
    # Content verification with previous-good fallback: a torn or
    # bit-flipped latest tree (detected via its MANIFEST.json) must not
    # end the run when the ``.prev`` rotation still holds a good save.
    chosen = _pick_restorable(root, name)
    if chosen is None:
        primary, primary_sidecar = _candidates(root, name)[0]
        if primary.exists() and primary_sidecar.exists():
            raise CorruptCheckpointError(
                f"checkpoint {primary} failed content verification and no "
                f"previous-good fallback ({primary}.prev) is restorable"
            )
        # Preserve the legacy missing-checkpoint error shape.
        raise FileNotFoundError(f"no checkpoint at {primary}")
    path, sidecar_path = chosen
    if path.name.endswith(".prev"):
        print(
            f"[checkpoint] latest {root / name} failed verification; "
            f"restoring previous good {path}",
            file=sys.stderr,
            flush=True,
        )
    sidecar = json.loads(sidecar_path.read_text())
    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(path)
    # Detach every leaf from the checkpointer's restore buffers. Orbax can
    # hand back arrays aliasing its own (mmap/tensorstore) storage; feeding
    # such leaves into the donated hot loop lets XLA free memory it does not
    # own — an observed hard SIGSEGV on the CPU backend (resume + warm
    # persistent compilation cache). A plain host copy severs the alias.
    tree = jax.tree_util.tree_map(lambda a: np.array(a), tree)
    spec = ModelSpec(**sidecar["spec"])
    return tree["params"], tree["opt_state"], spec, sidecar["meta"]


def restore_opt_state(template: Any, raw: Any, params: Any = None) -> Any:
    """Rebuild an optimizer state pytree from its checkpointed state dict.

    For a flat optimizer state (``template`` is a
    :class:`~masters_thesis_tpu.train.flatparams.FlatOptState`) the
    checkpoint holds params-shaped moment pytrees; ``params`` provides the
    view table to re-flatten them against (required in that case).
    """
    if isinstance(template, flatparams.FlatOptState):
        if params is None:
            raise ValueError(
                "restoring a flat optimizer state needs params= for the "
                "view table"
            )
        portable_template = flatparams.to_portable(template, params)
        raw = fser.from_state_dict(portable_template, raw)
        return flatparams.from_portable(raw, params)
    return fser.from_state_dict(template, raw)


def apply_datamodule_sidecar(cfg, meta: dict) -> None:
    """Overwrite cfg.datamodule's window hparams from a checkpoint's meta.

    Evaluation must window the data exactly the way the checkpoint was
    trained (lookback/target/stride/...); ``data_dir`` and ``engine`` stay
    config-driven — they are environment-, not model-specific. Shared by
    test.py and sweeps/eval_cell.py so the invariant lives in one place.
    """
    for key, value in meta.get("datamodule", {}).items():
        if key in cfg.datamodule:
            cfg.datamodule[key] = value
