"""Orbax-backed checkpointing: best/last, with hparams sidecars.

Replaces Lightning's ModelCheckpoint + ``save_hyperparameters`` reload path
(reference: train.py:151-161 saves best-on-`loss/total/val` and last;
src/model.py:188 + test.py:177-178 reload a module from checkpoint with its
constructor hparams). Layout::

    <ckpt_dir>/
      best/   # orbax pytree: params, opt_state
      last/
      best.json / last.json   # hparams + training metadata sidecar

Orbax handles multi-host coordination and HBM->host streaming natively;
the JSON sidecar carries everything needed to rebuild the ModelSpec and
DataModule without the training config (the ``load_from_checkpoint``
equivalent).
"""

from __future__ import annotations

import dataclasses
import json
import shutil
from pathlib import Path
from typing import Any

import flax.serialization as fser
import jax
import numpy as np
import orbax.checkpoint as ocp

from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.train import flatparams
from masters_thesis_tpu.utils import atomic_write_text


def save_checkpoint(
    ckpt_dir: Path,
    tag: str,
    params: Any,
    opt_state: Any,
    spec: ModelSpec,
    meta: dict,
) -> None:
    """Atomically write ``<ckpt_dir>/<tag>`` (orbax) + ``<tag>.json`` sidecar."""
    ckpt_dir = Path(ckpt_dir).resolve()
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    path = ckpt_dir / tag
    # Crash-safe replacement protocol (the old code rmtree'd the live
    # <tag> before the new write was durable — a SIGKILL mid-save then
    # destroyed the only resume point, caught by the CLI kill-test):
    #   1. orbax tree  -> <tag>.new        (complete before anything moves)
    #   2. sidecar     -> <tag>.json.new   (meta matching the staged tree)
    #   3. publish, renames only:  <tag> -> <tag>.old,  <tag>.new -> <tag>,
    #      <tag>.json.new -> <tag>.json,  then best-effort rm <tag>.old
    # A kill at ANY point leaves either the previous checkpoint intact or
    # a staged pair that _recover_staged finishes on the next restore;
    # the sidecar rides the same swap so tree and meta can never pair up
    # across different saves. Publish steps run on process 0 only
    # (multi-host checkpointing assumes shared storage, as orbax does).
    staging = ckpt_dir / f"{tag}.new"
    staged_sidecar = ckpt_dir / f"{tag}.json.new"
    if jax.process_index() == 0:
        # Sidecar BEFORE tree: a kill in between leaves an orphan tree
        # (safely dropped by recovery), never an orphan sidecar that could
        # later pair with a mismatched tree.
        staged_sidecar.unlink(missing_ok=True)
        if staging.exists():
            shutil.rmtree(staging)
    # Flat optimizer states (train/flatparams.py) are stored UNFLATTENED
    # through the view table: the on-disk layout is the params-shaped moment
    # pytree an optax checkpoint would hold, independent of the flat
    # buffers' internal leaf order — a layout refactor must not invalidate
    # every checkpoint. The restore side re-flattens against the current
    # params (restore_opt_state(params=...)).
    host_state = jax.device_get(opt_state)
    if isinstance(host_state, flatparams.FlatOptState):
        host_state = flatparams.to_portable(
            host_state, jax.device_get(params)
        )
    with ocp.StandardCheckpointer() as ckptr:
        # to_state_dict turns optax namedtuple states into pure dicts, so the
        # restore side can rebuild any optimizer structure via from_state_dict
        # without orbax needing the live pytree as a template.
        ckptr.save(
            staging,
            {
                "params": params,
                "opt_state": fser.to_state_dict(host_state),
            },
        )
        ckptr.wait_until_finished()
    if jax.process_index() == 0:
        sidecar = {"spec": dataclasses.asdict(spec), "meta": meta}
        atomic_write_text(staged_sidecar, json.dumps(sidecar, indent=2))
        _publish(ckpt_dir, tag)


def _publish(ckpt_dir: Path, tag: str) -> None:
    """Swap a complete staged pair into place. Renames only (atomic); the
    old tree is moved aside first and deleted last, best-effort. Shared by
    save_checkpoint and crash recovery so the ordering can't diverge."""
    path = ckpt_dir / tag
    old = ckpt_dir / f"{tag}.old"
    if old.exists():
        shutil.rmtree(old)
    if path.exists():
        path.rename(old)
    (ckpt_dir / f"{tag}.new").rename(path)
    (ckpt_dir / f"{tag}.json.new").replace(ckpt_dir / f"{tag}.json")
    shutil.rmtree(old, ignore_errors=True)


def _recover_staged(ckpt_dir: Path, tag: str) -> None:
    """Finish (or discard) an interrupted save_checkpoint publish.

    Covers every kill point of the staged-swap protocol (see
    save_checkpoint): a finalized staging PAIR (tree + sidecar) supersedes
    whatever is in place and is swapped in; a staged tree without its
    sidecar predates publish — the previous checkpoint is still current,
    so the orphan is dropped; a staged sidecar alone means the tree swap
    finished and only the sidecar rename was lost. Orbax only ever exposes
    a finalized tree under the staging name (its own writes go through a
    tmp suffix), so ``staging.exists()`` implies the tree is complete.
    """
    path = ckpt_dir / tag
    old = ckpt_dir / f"{tag}.old"
    staging = ckpt_dir / f"{tag}.new"
    staged_sidecar = ckpt_dir / f"{tag}.json.new"
    if staging.exists():
        if staged_sidecar.exists():
            _publish(ckpt_dir, tag)
        else:
            shutil.rmtree(staging)
    elif staged_sidecar.exists():
        staged_sidecar.replace(ckpt_dir / f"{tag}.json")
    if old.exists() and path.exists():
        shutil.rmtree(old, ignore_errors=True)


def _run_recovery(ckpt_dir: Path, tag: str) -> None:
    """Process-0 performs recovery; other processes WAIT for the staging
    artifacts to disappear (shared checkpoint storage, as orbax assumes).

    The wait triggers whenever artifacts are visible — even if a
    restorable-looking pair already exists — because a (new tree, stale
    sidecar) layout mid-recovery would otherwise let a non-zero process
    read an epoch that disagrees with process 0's, desyncing the
    multi-host resume decision and hanging the collectives.
    """
    staging = ckpt_dir / f"{tag}.new"
    staged_sidecar = ckpt_dir / f"{tag}.json.new"
    if jax.process_index() == 0:
        _recover_staged(ckpt_dir, tag)
    elif staging.exists() or staged_sidecar.exists():
        from masters_thesis_tpu.utils import wait_until

        wait_until(
            lambda: not staging.exists() and not staged_sidecar.exists(),
            60.0,
        )


def checkpoint_restorable(ckpt_dir: Path, tag: str) -> bool:
    """True if ``<ckpt_dir>/<tag>`` (tree + sidecar) can be restored,
    after finishing any interrupted staging swap."""
    ckpt_dir = Path(ckpt_dir)
    if ckpt_dir.exists():
        _run_recovery(ckpt_dir, tag)
    return (ckpt_dir / tag).exists() and (ckpt_dir / f"{tag}.json").exists()


def restore_checkpoint(
    ckpt_dir: Path, tag: str = "best"
) -> tuple[Any, Any, ModelSpec, dict]:
    """Load (params, opt_state, spec, meta) from a checkpoint directory.

    Accepts either the checkpoint root (picks ``<tag>``) or a direct path to
    a tagged checkpoint — mirroring how the reference's test.py takes the
    checkpoint file path on the CLI (reference: test.py:153,177).
    """
    ckpt_dir = Path(ckpt_dir).resolve()
    # Recovery must look where the staging artifacts actually live: next
    # to <tag> under a checkpoint ROOT, or next to the direct path itself
    # (a direct path may not even exist yet if the kill landed mid-swap).
    if (ckpt_dir / tag).exists() or (ckpt_dir / f"{tag}.new").exists():
        _run_recovery(ckpt_dir, tag)
    elif ckpt_dir.parent.exists():
        _run_recovery(ckpt_dir.parent, ckpt_dir.name)
    if (ckpt_dir / tag).exists():
        path = ckpt_dir / tag
        sidecar_path = ckpt_dir / f"{tag}.json"
    else:
        path = ckpt_dir
        sidecar_path = ckpt_dir.parent / f"{ckpt_dir.name}.json"
    sidecar = json.loads(sidecar_path.read_text())
    with ocp.StandardCheckpointer() as ckptr:
        tree = ckptr.restore(path)
    # Detach every leaf from the checkpointer's restore buffers. Orbax can
    # hand back arrays aliasing its own (mmap/tensorstore) storage; feeding
    # such leaves into the donated hot loop lets XLA free memory it does not
    # own — an observed hard SIGSEGV on the CPU backend (resume + warm
    # persistent compilation cache). A plain host copy severs the alias.
    tree = jax.tree_util.tree_map(lambda a: np.array(a), tree)
    spec = ModelSpec(**sidecar["spec"])
    return tree["params"], tree["opt_state"], spec, sidecar["meta"]


def restore_opt_state(template: Any, raw: Any, params: Any = None) -> Any:
    """Rebuild an optimizer state pytree from its checkpointed state dict.

    For a flat optimizer state (``template`` is a
    :class:`~masters_thesis_tpu.train.flatparams.FlatOptState`) the
    checkpoint holds params-shaped moment pytrees; ``params`` provides the
    view table to re-flatten them against (required in that case).
    """
    if isinstance(template, flatparams.FlatOptState):
        if params is None:
            raise ValueError(
                "restoring a flat optimizer state needs params= for the "
                "view table"
            )
        portable_template = flatparams.to_portable(template, params)
        raw = fser.from_state_dict(portable_template, raw)
        return flatparams.from_portable(raw, params)
    return fser.from_state_dict(template, raw)


def apply_datamodule_sidecar(cfg, meta: dict) -> None:
    """Overwrite cfg.datamodule's window hparams from a checkpoint's meta.

    Evaluation must window the data exactly the way the checkpoint was
    trained (lookback/target/stride/...); ``data_dir`` and ``engine`` stay
    config-driven — they are environment-, not model-specific. Shared by
    test.py and sweeps/eval_cell.py so the invariant lives in one place.
    """
    for key, value in meta.get("datamodule", {}).items():
        if key in cfg.datamodule:
            cfg.datamodule[key] = value
