"""Native training stack: the framework's replacement for PyTorch-Lightning.

The reference delegates its training loop, device placement, gradient
clipping, checkpointing, LR scheduling, and metric reduction to Lightning
(reference: train.py:169-198, src/model.py:149-172). Here those are owned
in-tree, TPU-first:

- :mod:`steps`: the whole training epoch is ONE jitted ``shard_map`` +
  ``lax.scan`` program — no per-step host round trips at all.
- :mod:`flatparams`: the flat-buffer update path — params/grads/moments as
  one contiguous per-dtype buffer, ONE ``pmean`` per step over it (TA206),
  one fused Adam pass; bit-identical to the optax chain.
- :mod:`optim`: optax chain matching torch ``Adam(weight_decay=...)`` +
  Lightning ``gradient_clip_val`` semantics (kept as the parity reference
  for the flat path), plus a host-side ReduceLROnPlateau equivalent.
- :mod:`checkpoint`: Orbax best/last checkpoints with hparams sidecars.
- :mod:`logging`: TensorBoard scalars/hparams/figures (same taxonomy as the
  reference's TensorBoardLogger).
- :mod:`trainer`: the fit/test orchestration loop.
- :mod:`stacked`: R independent replicas (lr/seed grid cells, ensemble
  members) trained as a leading ``vmap`` axis inside ONE compiled epoch
  program — one compile and one batched all-reduce per dtype buffer per
  step regardless of R (TA207).
"""

from masters_thesis_tpu.train.flatparams import (
    FlatAdam,
    FlatOptState,
    flat_size_bytes,
    flatten,
    flatten_spec,
    num_buffers,
    replica_flat,
    replica_opt_state,
    stack_flat,
    stack_opt_states,
    stacked_size_bytes,
    unflatten,
)
from masters_thesis_tpu.train.optim import PlateauScheduler, make_optimizer
from masters_thesis_tpu.train.stacked import (
    ReplicaResult,
    ReplicaSpec,
    StackedResult,
    StackedTrainer,
)
from masters_thesis_tpu.train.trainer import Trainer, TrainResult

__all__ = [
    "FlatAdam",
    "FlatOptState",
    "PlateauScheduler",
    "ReplicaResult",
    "ReplicaSpec",
    "StackedResult",
    "StackedTrainer",
    "Trainer",
    "TrainResult",
    "flat_size_bytes",
    "flatten",
    "flatten_spec",
    "make_optimizer",
    "num_buffers",
    "replica_flat",
    "replica_opt_state",
    "stack_flat",
    "stack_opt_states",
    "stacked_size_bytes",
    "unflatten",
]
