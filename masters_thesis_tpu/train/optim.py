"""Optimizer chain and LR plateau scheduling.

Matches the reference's optimization setup (reference: src/model.py:149-172):
``torch.optim.Adam(lr, weight_decay=1e-5)`` — torch Adam's ``weight_decay``
is L2 regularization folded into the gradient *before* the Adam moments, not
AdamW-style decoupled decay — plus Lightning's ``gradient_clip_val`` (global
norm, applied to raw grads) and ``ReduceLROnPlateau(factor=0.5, patience=2)``
monitoring the validation loss.

The learning rate is NOT baked into the optax chain: the jitted epoch step
receives it as a traced scalar, so the host-side plateau scheduler can change
it between epochs without triggering an XLA recompile.
"""

from __future__ import annotations

import math

import optax


def make_optimizer(
    gradient_clip_val: float | None, weight_decay: float
) -> optax.GradientTransformation:
    """Grad-clip -> L2 decay -> Adam moments. LR is applied by the caller.

    Order matters and mirrors the reference stack: Lightning clips raw
    gradients first (reference: train.py:172 `gradient_clip_val`), then torch
    Adam adds ``weight_decay * param`` to the (clipped) gradient before the
    moment updates.
    """
    parts = []
    if gradient_clip_val is not None and gradient_clip_val > 0:
        parts.append(optax.clip_by_global_norm(gradient_clip_val))
    if weight_decay:
        parts.append(optax.add_decayed_weights(weight_decay))
    parts.append(optax.scale_by_adam())
    # Ascent direction out; the train step multiplies by -lr.
    return optax.chain(*parts)


class PlateauScheduler:
    """Host-side ReduceLROnPlateau with torch default semantics.

    (reference: src/model.py:156-172 — factor 0.5, patience 2, mode 'min',
    and torch defaults threshold=1e-4 in 'rel' mode, cooldown 0, min_lr 0.)
    Stateful, val-metric-driven control flow lives outside jit by design
    (SURVEY.md §7 hard parts).
    """

    def __init__(
        self,
        init_lr: float,
        factor: float = 0.5,
        patience: int = 2,
        threshold: float = 1e-4,
        min_lr: float = 0.0,
    ):
        self.lr = float(init_lr)
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.min_lr = min_lr
        self.best = math.inf
        self.num_bad_epochs = 0

    def step(self, metric: float) -> float:
        """Record one monitored value; returns the (possibly reduced) LR."""
        metric = float(metric)
        if metric < self.best * (1.0 - self.threshold):
            self.best = metric
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.num_bad_epochs > self.patience:
            self.lr = max(self.lr * self.factor, self.min_lr)
            self.num_bad_epochs = 0
        return self.lr

    def state_dict(self) -> dict:
        return {
            "lr": self.lr,
            "best": self.best,
            "num_bad_epochs": self.num_bad_epochs,
        }

    def load_state_dict(self, state: dict) -> None:
        self.lr = state["lr"]
        self.best = state["best"]
        self.num_bad_epochs = state["num_bad_epochs"]
