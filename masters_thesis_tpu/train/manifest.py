"""Checkpoint content manifests — stdlib-only, importable on wedged hosts.

The manifest (sha256 + size per file, written INSIDE the checkpoint tree
so it rides the same staged-publish renames as the data it describes) is
consumed from two very different places:

- the trainer/serve restore paths (``train/checkpoint.py``, which owns
  the orbax machinery and re-exports these names), and
- the fleet supervisor, which must pick the last *verified* checkpoint to
  relaunch a dead fleet from — on a host where importing jax/orbax can
  hang on the exact wedge that killed the fleet.

Hence this module's contract: no jax, no orbax, no numpy — hashing and
json only, like the telemetry CLIs.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

from masters_thesis_tpu.utils import atomic_write_text

#: Content-checksum manifest written INSIDE the checkpoint tree, so it
#: rides the same staged-swap renames as the data it describes.
MANIFEST_NAME = "MANIFEST.json"


def write_manifest(tree: Path) -> None:
    """Write ``MANIFEST.json`` (sha256 + size per file) into ``tree``,
    fsync'ing so the checksums are durable before the publish rename."""
    files = {}
    for p in sorted(Path(tree).rglob("*")):
        if p.is_file() and p.name != MANIFEST_NAME:
            files[str(p.relative_to(tree))] = {
                "sha256": hashlib.sha256(p.read_bytes()).hexdigest(),
                "size": p.stat().st_size,
            }
    atomic_write_text(
        Path(tree) / MANIFEST_NAME,
        json.dumps({"algo": "sha256", "files": files}, indent=2),
        fsync=True,
    )


def verify_checkpoint(path: Path, require_manifest: bool = False) -> bool:
    """Check a checkpoint tree against its content manifest.

    By default, trees without a manifest (pre-manifest checkpoints)
    verify True — backward compatible, no protection; the training
    restore path keeps this lenient grandfathering. With
    ``require_manifest=True`` a manifest-less tree FAILS: the serve
    hot-swap path uses strict mode so an unverifiable tree (torn write,
    pre-manifest save, or anything an attacker could stage without
    checksums) can never be swapped into traffic. A manifest whose files
    are missing, truncated, or checksum-mismatched fails either way.
    """
    path = Path(path)
    manifest_path = path / MANIFEST_NAME
    if not manifest_path.exists():
        return path.exists() and not require_manifest
    try:
        manifest = json.loads(manifest_path.read_text())
        for rel, want in manifest["files"].items():
            p = path / rel
            if not p.is_file() or p.stat().st_size != want["size"]:
                return False
            if hashlib.sha256(p.read_bytes()).hexdigest() != want["sha256"]:
                return False
    except (OSError, ValueError, KeyError, TypeError):
        return False
    return True


def last_verified_checkpoint(
    ckpt_dir: Path | str | None, tag: str = "last"
) -> str | None:
    """The newest manifest-verified restore point under ``ckpt_dir``:
    ``<tag>`` if its (tree, sidecar) pair is complete and verifies, else
    the ``<tag>.prev`` rotation, else ``None``.

    Filesystem + hashing only — this is what the fleet supervisor reports
    as ``resumed_from`` before relaunching; the child trainer's own
    restore (which can additionally finish an interrupted publish) is
    still the authority on what actually loads.
    """
    if ckpt_dir is None:
        return None
    ckpt_dir = Path(ckpt_dir)
    for name in (tag, f"{tag}.prev"):
        tree = ckpt_dir / name
        sidecar = ckpt_dir / f"{name}.json"
        if tree.is_dir() and sidecar.is_file() and verify_checkpoint(tree):
            return str(tree)
    return None
