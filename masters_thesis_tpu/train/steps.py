"""Jitted, sharded epoch and evaluation programs.

TPU-first replacement for the reference's hot loop (reference: Lightning's
fit loop dispatching ``training_step`` per batch, src/model.py:204/251/308,
with host->GPU copies per step through DataLoader workers). Here:

- The ENTIRE train split lives in HBM, sharded over the mesh's data axis.
- One epoch is ONE XLA program: ``shard_map`` over the mesh, ``lax.scan``
  over steps; each step gathers its (pre-permuted) batch locally, computes
  grads, ``pmean``s them over ICI, and applies the Adam update. Zero host
  round-trips inside an epoch — this is where the steps/sec/chip win over
  the reference's per-step Python dispatch comes from.
- Evaluation is likewise one program: scan over chunks, masked metric sums,
  one ``psum`` at the end (the TPU-native form of torchmetrics'
  ``dist_reduce_fx="sum"``, reference: src/model.py:24-25).

All factories below close over static configuration and return functions
ready for ``jax.jit``; batch shapes are static so each (model, shape) pair
compiles exactly once per process.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from masters_thesis_tpu.data.pipeline import Batch
from masters_thesis_tpu.models.objectives import (
    WindowObjective,
    batched_objective,
    mse_window,
    nll_window,
)
from masters_thesis_tpu.parallel import DATA_AXIS, shard_map
from masters_thesis_tpu.train.flatparams import (
    FlatAdam,
    flatten,
    flatten_spec,
    unflatten,
)


def jit_cache_size(fn) -> int | None:
    """Compile-cache entry count of a jitted callable (None if unknown).

    The jit layer owns this hook so every consumer agrees on what "the
    program compiled once" means: the trace audit (analysis.traceaudit
    TA201) asserts it preflight, and telemetry.CompileTracker counts the
    deltas at runtime to detect signature leaks mid-run.
    """
    size = getattr(fn, "_cache_size", None)
    try:
        return size() if callable(size) else None
    except Exception:  # a jit internals change must degrade, not crash
        return None


def forward_rows(module, params, x, dropout_rng=None):
    """Apply the encoder to a window batch: ``(B, K, T, F) -> (B, K, 1)``
    alpha and ``(B, K, n_factors)`` beta.

    Flattens (batch, stocks) into rows exactly like the reference's
    ``flatten(0, 1)`` step preamble (reference: src/model.py:120-123).
    """
    b, k = x.shape[:2]
    rows = x.reshape(b * k, *x.shape[2:])
    deterministic = dropout_rng is None
    rngs = None if deterministic else {"dropout": dropout_rng}
    # window_rows=k tells the recurrence where the window boundaries are in
    # the flattened row axis, so bs>1 batches schedule windows onto
    # single-program Pallas kernels instead of falling onto the row-tiled
    # grid (the bs>1 throughput cliff, RESULTS.md). The kernel layer packs
    # as many whole windows per program as its VMEM budget admits
    # (ops/lstm_kernel.py:window_pack_width), so small-K batches amortize
    # program launches instead of running K-row programs serially.
    alpha, beta = module.apply(
        {"params": params}, rows, deterministic=deterministic, rngs=rngs,
        window_rows=k,
    )
    return alpha.reshape(b, k, 1), beta.reshape(b, k, -1)


def _accumulate(sums: dict, new: dict) -> dict:
    return {k: (sums[k][0] + new[k][0], sums[k][1] + new[k][1]) for k in sums}


def _zero_sums(keys) -> dict:
    return {k: (jnp.zeros(()), jnp.zeros(())) for k in keys}


def metric_means(sums: dict) -> dict:
    """Host-side: turn psum'd (value_sum, weight) pairs into means."""
    return {k: float(v) / max(float(w), 1e-30) for k, (v, w) in sums.items()}


# ------------------------------------------------------------------- train


def _make_loss_fn(module, window_objective: WindowObjective):
    """(params, dropout rng, batch) -> (mean loss, metric sums incl 'total')."""
    batched = batched_objective(window_objective)

    def loss_fn(params, step_rng, batch: Batch):
        alpha, beta = forward_rows(module, params, batch.x, dropout_rng=step_rng)
        return batched(alpha, beta, batch.y, batch.factor, batch.inv_psi)

    return loss_fn


def _epoch_rngs(rng, shard_axis: str):
    """Per-device (shuffle, dropout) rngs for one epoch.

    ``window`` sharding: each device owns a disjoint window shard, so the
    whole stream is device-folded (independent local shuffles). ``asset``
    sharding: every device sees ALL windows (only the asset rows differ), so
    the shuffle MUST be common across devices — folding it would make
    devices gather different windows into the "same" batch and silently
    train on torn batches. Only the dropout stream is device-folded there.
    """
    if shard_axis == "asset":
        shuffle_rng, dropout_rng = jax.random.split(rng)
        dropout_rng = jax.random.fold_in(
            dropout_rng, lax.axis_index(DATA_AXIS)
        )
        return shuffle_rng, dropout_rng
    rng = jax.random.fold_in(rng, lax.axis_index(DATA_AXIS))
    return jax.random.split(rng)


def _flat_epoch_body(
    loss_fn,
    tx,
    spec,
    metric_keys: tuple,
    batch_size: int,
    shard_axis: str = "window",
) -> Callable:
    """Shard-local one-epoch body over FLAT buffers, shared by the single
    and stacked paths.

    Signature: ``body(pbufs, opt_state, lr, rng, data) -> (pbufs,
    opt_state, local_sums)``. Every numeric op the single-replica flat path
    runs lives here, so the stacked path (which maps this body over a
    leading replica axis) is per-replica the SAME op sequence — vmap of
    elementwise/optimizer ops is per-lane bit-identical, and the batched
    ``lax.pmean`` still lowers to one all-reduce per dtype buffer (TA206,
    and TA207 for the stacked program).

    ``shard_axis='asset'`` (universe-scale workloads): the device shard is a
    block of asset ROWS instead of a block of windows. Locally nothing
    changes — batches still gather along axis 0 — but the epoch shuffle
    stays common across devices (see :func:`_epoch_rngs`) and the objective
    is computed per asset block (exact for MSE/MAE; the NLL couples assets
    within a window, so the sharded objective is its block-diagonal form —
    equal-sized blocks keep the pmean'd gradient the true gradient of that
    sharded objective). Still exactly ONE pmean per dtype buffer per step,
    so TA206/TA207 hold verbatim.
    """

    def body(pbufs, opt_state, lr, rng, data: Batch):
        shuffle_rng, dropout_rng = _epoch_rngs(rng, shard_axis)
        n_local = data.x.shape[0]
        n_steps = n_local // batch_size
        perm = jax.random.permutation(shuffle_rng, n_local)
        idx = perm[: n_steps * batch_size].reshape(n_steps, batch_size)

        def step(carry, inp):
            pbufs, opt_state, sums = carry
            i, batch_idx = inp
            step_rng = jax.random.fold_in(dropout_rng, i)
            batch = Batch(
                *(jnp.take(a, batch_idx, axis=0) for a in data)
            )
            params_t = unflatten(pbufs, spec)
            (_, step_sums), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params_t, step_rng, batch
            )
            # Equal per-device batch sizes => pmean of local-mean grads is
            # the global-batch gradient (the DDP all-reduce, on ICI).
            # ONE collective per step: the whole gradient crosses ICI as
            # a single contiguous buffer per dtype (TA206 pins this in
            # the lowered HLO) instead of one all-reduce per pytree leaf.
            gbufs = lax.pmean(flatten(grads, spec), DATA_AXIS)
            ubufs, opt_state = tx.update_flat(gbufs, opt_state, pbufs, spec)
            pbufs = {
                k: p - lr * ubufs[k].astype(p.dtype)
                for k, p in pbufs.items()
            }
            sums = _accumulate(sums, step_sums)
            return (pbufs, opt_state, sums), None

        zero = _zero_sums(tuple(metric_keys) + ("total",))
        (pbufs, opt_state, sums), _ = lax.scan(
            step, (pbufs, opt_state, zero), (jnp.arange(n_steps), idx)
        )
        return pbufs, opt_state, sums

    return body


def epoch_data_spec(shard_axis: str) -> Batch:
    """Partition specs for the train split under either shard axis.

    ``window``: every leaf sharded on its leading window axis. ``asset``:
    the per-asset leaves (x, y, inv_psi) shard on their asset axis (axis 1)
    and the per-window ``factor`` stats — which have no asset axis — stay
    replicated.
    """
    if shard_axis == "asset":
        return Batch(
            P(None, DATA_AXIS), P(None, DATA_AXIS), P(), P(None, DATA_AXIS)
        )
    return Batch(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS))


def make_train_epoch(
    module,
    window_objective: WindowObjective,
    metric_keys: tuple,
    tx,
    mesh: Mesh,
    batch_size: int = 1,
    shard_axis: str = "window",
) -> Callable:
    """Build the one-epoch program.

    Returned signature (all device values)::

        epoch_fn(params, opt_state, lr, rng, data)
            -> (params, opt_state, metric_sums)

    where ``data`` is the full train split sharded on its window axis
    (``P('data')``). The epoch's shuffle happens ON DEVICE: each device draws
    a permutation of its LOCAL shard from the (axis-index-folded) epoch rng —
    shuffling stays shard-local so the gather never crosses ICI, and no
    per-epoch index upload crosses the host↔device link (that round-trip was
    ~30% of wall time on a remote-relay TPU).

    ``shard_axis='asset'`` shards the ASSET axis over the mesh instead: each
    device trains the full window stream over its block of asset rows, which
    is how a universe-scale cross-section (thousands of rows per window)
    fills the per-device batch — and the MXU — without replicating the whole
    cross-section into every device's HBM (see _flat_epoch_body).
    """

    if shard_axis not in ("window", "asset"):
        raise ValueError(f"unknown shard_axis: {shard_axis!r}")
    loss_fn = _make_loss_fn(module, window_objective)
    flat = isinstance(tx, FlatAdam)

    def local_epoch(params, opt_state, lr, rng, data: Batch):
        if flat:
            # Flat path: the scan carries params as per-dtype flat buffers;
            # the view table is static (trace-time Python), so pack/unpack
            # are pure layout ops XLA folds into the neighbouring
            # computation. The body is shared with the stacked path.
            spec = flatten_spec(params)
            body = _flat_epoch_body(
                loss_fn, tx, spec, metric_keys, batch_size,
                shard_axis=shard_axis,
            )
            pbufs, opt_state, sums = body(
                flatten(params, spec), opt_state, lr, rng, data
            )
            params = unflatten(pbufs, spec)
            sums = lax.psum(sums, DATA_AXIS)
            return params, opt_state, sums

        shuffle_rng, dropout_rng = _epoch_rngs(rng, shard_axis)
        n_local = data.x.shape[0]
        n_steps = n_local // batch_size
        perm = jax.random.permutation(shuffle_rng, n_local)
        idx = perm[: n_steps * batch_size].reshape(n_steps, batch_size)

        def step(carry, inp):
            params, opt_state, sums = carry
            i, batch_idx = inp
            step_rng = jax.random.fold_in(dropout_rng, i)
            batch = Batch(
                *(jnp.take(a, batch_idx, axis=0) for a in data)
            )
            (_, step_sums), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, step_rng, batch
            )
            grads = lax.pmean(grads, DATA_AXIS)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p - lr * u.astype(p.dtype), params, updates
            )
            sums = _accumulate(sums, step_sums)
            return (params, opt_state, sums), None

        zero = _zero_sums(tuple(metric_keys) + ("total",))
        (params, opt_state, sums), _ = lax.scan(
            step, (params, opt_state, zero), (jnp.arange(n_steps), idx)
        )
        sums = lax.psum(sums, DATA_AXIS)
        return params, opt_state, sums

    data_spec = epoch_data_spec(shard_axis)
    sharded = shard_map(
        local_epoch,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), data_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    # Explicit shardings keep the jit signature identical across epochs.
    # Without them, epoch 0 (unspecified shardings) and epoch 1 (donated
    # outputs carrying concrete shardings) trigger TWO multi-second XLA
    # compiles of the same program.
    repl = NamedSharding(mesh, P())
    batch_sh = Batch(*(NamedSharding(mesh, s) for s in data_spec))
    return jax.jit(
        sharded,
        donate_argnums=(0, 1),
        in_shardings=(repl, repl, repl, repl, batch_sh),
        out_shardings=(repl, repl, repl),
    )


def make_stacked_train_epoch(
    module,
    window_objective: WindowObjective,
    metric_keys: tuple,
    tx,
    mesh: Mesh,
    spec,
    batch_size: int = 1,
) -> Callable:
    """Build the STACKED one-epoch program: R replicas, one XLA program.

    Independent training replicas (grid cells over lr/seed, ensemble
    members) run as a leading ``vmap`` axis over the shared flat epoch
    body. Returned signature (all device values)::

        epoch_fn(pstack, opt_state, lrs, rngs, data)
            -> (pstack, opt_state, metric_sums)

    where ``pstack`` is the stacked flat-buffer dict ``{key: [R, n]}``
    (see flatparams.stack_flat), ``opt_state`` a stacked FlatOptState
    (``count [R]``, moments ``[R, n]``), ``lrs`` an ``[R]`` float32
    vector of per-replica learning rates, and ``rngs`` an ``[R]`` typed
    PRNG key array (one independent seed stream per replica). ``data`` is
    the train split sharded on its window axis exactly as in
    :func:`make_train_epoch` — replicas share the data plane, so HBM
    grows only by the stacked params/grads/moments (~4x
    ``flatparams.stacked_size_bytes``), not by R copies of the dataset.

    Why this multiplies cells/hour: every replica reuses ONE compile, ONE
    host dispatch per epoch, and ONE gradient all-reduce per dtype buffer
    per step — ``lax.pmean`` under ``vmap`` batches into a single
    collective over the ``[R, n]`` buffer (trace-audit rule TA207 pins
    this, the stacked extension of TA206). Per-replica numerics: the body
    is the same op sequence per lane, so RNG streams, the clip norm, and
    the whole Adam update are per-replica bit-identical to independent
    runs; only batched matmul kernels may reassociate at the ULP level
    (measured ~1e-9 on XLA:CPU — see tests/test_stacked.py and
    docs/perf.md for the exact parity contract).

    Replica isolation is structural: row r of every buffer is a function
    of row r's inputs only (elementwise optimizer, per-replica reductions,
    per-replica pmean rows), so a diverged replica's NaNs never reach its
    siblings — the trainer can roll back or mask one row while the rest
    keep training (tested bit-exactly in tests/test_stacked.py).
    """

    loss_fn = _make_loss_fn(module, window_objective)
    if not isinstance(tx, FlatAdam):
        raise TypeError("stacked training requires the flat-buffer FlatAdam")
    body = _flat_epoch_body(loss_fn, tx, spec, metric_keys, batch_size)

    def local_epoch(pstack, opt_state, lrs, rngs, data: Batch):
        # Replicas share the local data shard; everything else is mapped.
        pstack, opt_state, sums = jax.vmap(
            body, in_axes=(0, 0, 0, 0, None)
        )(pstack, opt_state, lrs, rngs, data)
        # Per-replica metric sums: leaves become [R]; one psum outside the
        # step scan, exactly like the single path.
        sums = lax.psum(sums, DATA_AXIS)
        return pstack, opt_state, sums

    data_spec = Batch(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS))
    sharded = shard_map(
        local_epoch,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), data_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    repl = NamedSharding(mesh, P())
    batch_sh = Batch(*(NamedSharding(mesh, s) for s in data_spec))
    return jax.jit(
        sharded,
        donate_argnums=(0, 1),
        in_shardings=(repl, repl, repl, repl, batch_sh),
        out_shardings=(repl, repl, repl),
    )


def stacked_metric_means(sums: dict, replicas: int) -> list:
    """Host-side: per-replica means from stacked (value, weight) sums.

    The stacked program's metric leaves are ``[R]`` arrays; one
    ``device_get`` on the dict then R cheap slices — the readback cost does
    not grow with R beyond the tiny metric vectors themselves.
    """
    host = jax.device_get(sums)
    return [
        {
            k: float(v[r]) / max(float(w[r]), 1e-30)
            for k, (v, w) in host.items()
        }
        for r in range(replicas)
    ]


def make_train_step(
    module,
    window_objective: WindowObjective,
    tx,
    mesh: Mesh,
    weighted: bool = False,
) -> Callable:
    """Per-batch jitted update for the ``stream`` epoch mode.

    Unlike :func:`make_train_epoch` this is the pjit path: the batch arrives
    sharded on its window axis (the prefetcher places it), params arrive
    replicated, and XLA's sharding propagation inserts the gradient
    all-reduce — no explicit collectives in user code. With a
    :class:`FlatAdam` optimizer the gradients land in the per-dtype flat
    buffers before the optimizer fold, so the partitioner reduces one
    contiguous buffer per dtype (XLA's all-reduce combiner sees a single
    fusable producer) and the Adam update runs as one elementwise pass.

    With ``weighted=True`` the step takes an extra ``(B,)`` weight vector
    and optimizes the weighted-mean loss. The trainer uses this to run the
    epoch's tail partial batch (padded back to the full batch shape with
    zero-weight windows) through the SAME compiled program — the reference's
    DataLoader trains on the tail too (drop_last defaults to False), so
    dropping it would silently change the optimization trajectory.
    """
    batched = batched_objective(window_objective)

    def loss_fn(params, step_rng, batch: Batch, weights):
        alpha, beta = forward_rows(module, params, batch.x, dropout_rng=step_rng)
        return batched(
            alpha, beta, batch.y, batch.factor, batch.inv_psi, weights=weights
        )

    flat = isinstance(tx, FlatAdam)

    def step_core(params, opt_state, lr, rng, batch: Batch, weights):
        (_, sums), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, rng, batch, weights
        )
        if flat:
            spec = flatten_spec(params)
            pbufs = flatten(params, spec)
            ubufs, opt_state = tx.update_flat(
                flatten(grads, spec), opt_state, pbufs, spec
            )
            pbufs = {
                k: p - lr * ubufs[k].astype(p.dtype)
                for k, p in pbufs.items()
            }
            params = unflatten(pbufs, spec)
        else:
            updates, opt_state = tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p - lr * u.astype(p.dtype), params, updates
            )
        return params, opt_state, sums

    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(DATA_AXIS))
    batch_sh = Batch(shard, shard, shard, shard)
    if weighted:
        return jax.jit(
            step_core,
            donate_argnums=(0, 1),
            in_shardings=(repl, repl, repl, repl, batch_sh, shard),
            out_shardings=(repl, repl, repl),
        )

    def step_fn(params, opt_state, lr, rng, batch: Batch):
        return step_core(params, opt_state, lr, rng, batch, None)

    return jax.jit(
        step_fn,
        donate_argnums=(0, 1),
        in_shardings=(repl, repl, repl, repl, batch_sh),
        out_shardings=(repl, repl, repl),
    )


# -------------------------------------------------------------------- eval


def window_eval_metrics(alpha, beta, y, factor, inv_psi) -> dict:
    """Per-window evaluation metrics: objective components + test-path MAE.

    Mirrors the reference's ``test_step`` (reference: src/model.py:119-141):
    MAE of ``alpha + beta · factors`` against realized returns, plus the
    Gaussian NLL under the Woodbury inverse covariance, plus plain MSE.
    """
    r_target = y[:, :, 0]
    n_f = beta.shape[-1]
    if n_f == 1:
        r_market = y[:, :, 1]
        r_pred = alpha + beta * r_market
    else:
        r_pred = alpha + jnp.einsum(
            "kf,ktf->kt", beta, y[:, :, 1 : 1 + n_f], precision="highest"
        )
    n = jnp.float32(r_target.size)
    mse_loss, _ = mse_window(alpha, beta, y, factor, inv_psi)
    nll_loss, _ = nll_window(alpha, beta, y, factor, inv_psi)
    mae = jnp.mean(jnp.abs(r_pred - r_target))
    return {
        "mse": (mse_loss * n, n),
        "nll": (nll_loss, jnp.float32(1.0)),
        "mae": (mae * n, n),
    }


def make_eval_fn(
    module,
    window_objective: WindowObjective,
    mesh: Mesh,
) -> Callable:
    """Build the one-pass evaluation program.

    Returned signature::

        eval_fn(params, data, mask) -> metric_sums

    ``data`` leaves are shaped ``(steps, n_dev * chunk, ...)`` sharded on
    axis 1; ``mask`` is ``(steps, n_dev * chunk)`` with 0 marking padding
    windows (splits rarely divide evenly — masked sums keep the means
    exact, unlike silently dropping or double-counting remainder windows).
    """

    def window_fn(alpha, beta, y, factor, inv_psi):
        loss, _ = window_objective(alpha, beta, y, factor, inv_psi)
        metrics = window_eval_metrics(alpha, beta, y, factor, inv_psi)
        metrics["total"] = (loss, jnp.float32(1.0))
        return metrics

    def local_eval(params, data: Batch, mask):
        def step(sums, inp):
            batch, m = inp
            alpha, beta = forward_rows(module, params, batch.x)
            metrics = jax.vmap(window_fn)(
                alpha, beta, batch.y, batch.factor, batch.inv_psi
            )
            # where(), not multiply: padded windows have singular factor
            # stats, so their metric values are NaN and NaN*0 == NaN.
            masked = {
                k: (
                    jnp.sum(jnp.where(m > 0, v, 0.0)),
                    jnp.sum(jnp.where(m > 0, w, 0.0)),
                )
                for k, (v, w) in metrics.items()
            }
            sums = _accumulate(sums, masked) if sums else masked
            return sums, None

        zero = _zero_sums(("mse", "nll", "mae", "total"))
        sums, _ = lax.scan(step, zero, (data, mask))
        return lax.psum(sums, DATA_AXIS)

    data_spec = Batch(
        P(None, DATA_AXIS),
        P(None, DATA_AXIS),
        P(None, DATA_AXIS),
        P(None, DATA_AXIS),
    )
    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(P(), data_spec, P(None, DATA_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    repl = NamedSharding(mesh, P())
    batch_sh = Batch(*(NamedSharding(mesh, s) for s in data_spec))
    mask_sh = NamedSharding(mesh, P(None, DATA_AXIS))
    return jax.jit(
        sharded,
        in_shardings=(repl, batch_sh, mask_sh),
        out_shardings=repl,
    )
