"""Test-split result collection: model vs OLS vs ground truth.

Capability parity with the reference's evaluation loop (reference:
test.py:14-88): for every test window, collect the model's (alpha, beta),
the analytical OLS fit on the SAME lookback window, the ground-truth
coefficients, and the reconstruction/coefficient residuals.

TPU-first: the reference iterates the test loader window-by-window in Python
under ``no_grad`` (test.py:205-207). Here the whole collection is a single
jitted, vmapped program evaluated in fixed-size chunks — the model forward
and the batched OLS solve both ride the MXU, and the host only sees the
final stacked arrays.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from masters_thesis_tpu.data.pipeline import Batch, FinancialWindowDataModule
from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.ops import ols
from masters_thesis_tpu.train.steps import forward_rows

CHUNK = 64


def collect_test_results(
    spec: ModelSpec, params: Any, dm: FinancialWindowDataModule
) -> dict:
    """Evaluate the test split; returns numpy arrays shaped (n_windows, K).

    Result schema mirrors the reference's ``init_test_results`` /
    ``transform_test_results`` (reference: test.py:14-37,75-88):
    ``recon_residuals`` are averaged over the target dimension;
    ``alpha``/``beta`` carry model/ols/true estimates per window.
    """
    dm.setup("test")
    arrays = dm.test_arrays()
    module = spec.build_module()

    @jax.jit
    def eval_chunk(x, y):
        # x: (C, K, T, F) lookback features; y: (C, K, T, 4) targets.
        alpha_m, beta_m = forward_rows(module, params, x)  # (C, K, 1)
        alpha_m, beta_m = alpha_m[..., 0], beta_m[..., 0]  # (C, K)
        # OLS on the lookback window: regress each stock's return (channel 0)
        # on the market return (channel 1, identical across stocks)
        # (reference: test.py:52).
        alpha_o, beta_o = ols(x[:, 0, :, 1], x[:, :, :, 0])  # (C, K)

        r_target = y[:, :, :, 0]  # (C, K, T)
        r_market = y[:, :, :, 1]
        alpha_t = y[:, :, 0, 2]  # (C, K)
        beta_t = y[:, :, 0, 3]

        r_pred_m = alpha_m[..., None] + beta_m[..., None] * r_market
        r_pred_o = alpha_o[..., None] + beta_o[..., None] * r_market
        return {
            "recon_residuals": {
                "model": jnp.mean(r_target - r_pred_m, axis=-1),
                "ols": jnp.mean(r_target - r_pred_o, axis=-1),
            },
            "alpha_residuals": {
                "model": alpha_t - alpha_m,
                "ols": alpha_t - alpha_o,
            },
            "beta_residuals": {
                "model": beta_t - beta_m,
                "ols": beta_t - beta_o,
            },
            "alpha": {"model": alpha_m, "ols": alpha_o, "true": alpha_t},
            "beta": {"model": beta_m, "ols": beta_o, "true": beta_t},
        }

    n = arrays.x.shape[0]
    chunks = []
    for start in range(0, n, CHUNK):
        sl = slice(start, min(start + CHUNK, n))
        x = np.asarray(arrays.x[sl])
        y = np.asarray(arrays.y[sl])
        pad = CHUNK - x.shape[0]
        if pad:  # keep one static chunk shape -> exactly one compile
            x = np.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            y = np.pad(y, [(0, pad)] + [(0, 0)] * (y.ndim - 1))
        out = jax.device_get(eval_chunk(x, y))
        if pad:
            out = jax.tree_util.tree_map(lambda a: a[:-pad], out)
        chunks.append(out)

    return jax.tree_util.tree_map(
        lambda *parts: np.concatenate(parts, axis=0), *chunks
    )
