"""Test-split result collection: model vs OLS vs ground truth.

Capability parity with the reference's evaluation loop (reference:
test.py:14-88): for every test window, collect the model's (alpha, beta),
the analytical OLS fit on the SAME lookback window, the ground-truth
coefficients, and the reconstruction/coefficient residuals. Plus the thesis'
headline ΔL quality metrics (reference: tex/diplomski_rad.tex:1077-1084).

TPU-first: the reference iterates the test loader window-by-window in Python
under ``no_grad`` (test.py:205-207). Here the whole collection is a single
jitted, vmapped program evaluated in fixed-size chunks — the model forward
and the batched OLS solve both ride the MXU, and the host only sees the
final stacked arrays.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from masters_thesis_tpu.data.pipeline import Batch, FinancialWindowDataModule
from masters_thesis_tpu.models.objectives import ModelSpec, mse_window, nll_window
from masters_thesis_tpu.ops import ols
from masters_thesis_tpu.train.steps import forward_rows

CHUNK = 64


def _eval_in_chunks(tree: Any, fn: Callable[[Any], Any]) -> Any:
    """Map a jitted function over fixed-size leading-dim chunks of a pytree.

    The tail chunk is zero-padded so ``fn`` sees exactly one static shape
    (one XLA compile); padded rows are stripped from the outputs, which must
    keep the chunk dim leading.
    """
    n = jax.tree_util.tree_leaves(tree)[0].shape[0]
    if n == 0:
        raise ValueError("empty split: nothing to evaluate")
    chunks = []
    for start in range(0, n, CHUNK):
        stop = min(start + CHUNK, n)
        piece = jax.tree_util.tree_map(lambda a: np.asarray(a[start:stop]), tree)
        pad = CHUNK - (stop - start)
        if pad:
            piece = jax.tree_util.tree_map(
                lambda a: np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1)), piece
            )
        out = jax.device_get(fn(piece))
        if pad:
            out = jax.tree_util.tree_map(lambda a: a[:-pad], out)
        chunks.append(out)
    return jax.tree_util.tree_map(
        lambda *parts: np.concatenate(parts, axis=0), *chunks
    )


def collect_test_results(
    spec: ModelSpec, params: Any, dm: FinancialWindowDataModule
) -> dict:
    """Evaluate the test split; returns numpy arrays shaped (n_windows, K).

    Result schema mirrors the reference's ``init_test_results`` /
    ``transform_test_results`` (reference: test.py:14-37,75-88):
    ``recon_residuals`` are averaged over the target dimension;
    ``alpha``/``beta`` carry model/ols/true estimates per window.
    """
    if dm.test_range is None:
        dm.setup("test")
    arrays = dm.test_arrays()
    module = spec.build_module()

    @jax.jit
    def eval_chunk(t):
        x, y = t["x"], t["y"]
        # x: (C, K, T, F) lookback features; y: (C, K, T, 4) targets.
        alpha_m, beta_m = forward_rows(module, params, x)  # (C, K, 1)
        alpha_m, beta_m = alpha_m[..., 0], beta_m[..., 0]  # (C, K)
        # OLS on the lookback window: regress each stock's return (channel 0)
        # on the market return (channel 1, identical across stocks)
        # (reference: test.py:52).
        alpha_o, beta_o = ols(x[:, 0, :, 1], x[:, :, :, 0])  # (C, K)

        r_target = y[:, :, :, 0]  # (C, K, T)
        r_market = y[:, :, :, 1]
        alpha_t = y[:, :, 0, 2]  # (C, K)
        beta_t = y[:, :, 0, 3]

        r_pred_m = alpha_m[..., None] + beta_m[..., None] * r_market
        r_pred_o = alpha_o[..., None] + beta_o[..., None] * r_market
        return {
            "recon_residuals": {
                "model": jnp.mean(r_target - r_pred_m, axis=-1),
                "ols": jnp.mean(r_target - r_pred_o, axis=-1),
            },
            "alpha_residuals": {
                "model": alpha_t - alpha_m,
                "ols": alpha_t - alpha_o,
            },
            "beta_residuals": {
                "model": beta_t - beta_m,
                "ols": beta_t - beta_o,
            },
            "alpha": {"model": alpha_m, "ols": alpha_o, "true": alpha_t},
            "beta": {"model": beta_m, "ols": beta_o, "true": beta_t},
        }

    return _eval_in_chunks({"x": arrays.x, "y": arrays.y}, eval_chunk)


def delta_losses(
    spec: ModelSpec,
    params: Any,
    dm: FinancialWindowDataModule,
    zeta: float = 1e5,
    estimates: dict | None = None,
) -> dict:
    """The thesis' headline quality metrics: losses ABOVE the OLS-on-target
    baseline (reference: tex/diplomski_rad.tex:1077-1084 defines
    ``ΔL(o_x, o_y, Y_P) = L(o_x, Y_P) − L(o_y, Y_P)`` where ``o_y`` uses the
    target-window OLS coefficients; the results table at :1155-1176 reports
    ΔL_MSE, ΔL_NLL and ΔL_MIX = ΔL_NLL + ζ·ΔL_MSE with ζ=1e5 on the test
    split, for both the trained model and the lookback-window OLS estimator).

    ``estimates``: pass the dict from :func:`collect_test_results` to reuse
    its model forward + historical-OLS coefficients instead of recomputing.

    Returns ``{"model": {"delta_mse", "delta_nll", "delta_mix"},
    "ols": {...}, "baseline": {"mse", "nll"}, "zeta": zeta}`` — ``ols`` is
    the reference table's OLS row (historical-window OLS above target-window
    OLS), and ``delta_mse`` is in absolute units (the thesis table prints it
    ×1e⁻⁵).
    """
    if dm.test_range is None:
        dm.setup("test")
    arrays = dm.test_arrays()
    module = spec.build_module()

    tree: dict = {
        "y": arrays.y, "factor": arrays.factor, "inv_psi": arrays.inv_psi,
    }
    if estimates is None:
        tree["x"] = arrays.x
    else:
        tree["est"] = {
            "alpha_m": estimates["alpha"]["model"],
            "beta_m": estimates["beta"]["model"],
            "alpha_h": estimates["alpha"]["ols"],
            "beta_h": estimates["beta"]["ols"],
        }

    def losses_for(alpha, beta, y, factor, inv_psi):
        """Per-window (L_MSE, L_NLL) for estimates shaped (C, K)."""
        a, b = alpha[..., None], beta[..., None]  # (C, K, 1)
        mse_l, _ = jax.vmap(mse_window)(a, b, y, factor, inv_psi)
        nll_l, _ = jax.vmap(nll_window)(a, b, y, factor, inv_psi)
        return mse_l, nll_l  # each (C,)

    @jax.jit
    def eval_chunk(t):
        y = t["y"]
        if estimates is None:
            alpha_m, beta_m = forward_rows(module, params, t["x"])
            alpha_m, beta_m = alpha_m[..., 0], beta_m[..., 0]  # (C, K)
            # Historical-window OLS (the table's OLS row; test.py:52).
            alpha_h, beta_h = ols(t["x"][:, 0, :, 1], t["x"][:, :, :, 0])
        else:
            alpha_m, beta_m = t["est"]["alpha_m"], t["est"]["beta_m"]
            alpha_h, beta_h = t["est"]["alpha_h"], t["est"]["beta_h"]
        # Target-window OLS — the ΔL baseline o_y (recomputed rather than
        # read from the label channels, which hold ground truth on synthetic
        # data; reference: src/data.py:209-211).
        alpha_t, beta_t = ols(y[:, 0, :, 1], y[:, :, :, 0])
        out = {}
        for key, (a, b) in {
            "model": (alpha_m, beta_m),
            "ols": (alpha_h, beta_h),
            "baseline": (alpha_t, beta_t),
        }.items():
            mse_l, nll_l = losses_for(a, b, y, t["factor"], t["inv_psi"])
            out[key] = {"mse": mse_l, "nll": nll_l}
        return out

    per_window = _eval_in_chunks(tree, eval_chunk)

    mean = {
        k: {m: float(np.mean(v)) for m, v in d.items()}
        for k, d in per_window.items()
    }
    result: dict = {"baseline": mean["baseline"], "zeta": zeta}
    for key in ("model", "ols"):
        d_mse = mean[key]["mse"] - mean["baseline"]["mse"]
        d_nll = mean[key]["nll"] - mean["baseline"]["nll"]
        result[key] = {
            "delta_mse": d_mse,
            "delta_nll": d_nll,
            "delta_mix": d_nll + zeta * d_mse,
        }
    return result
