"""masters_thesis_tpu — a TPU-native framework for single-factor return-model estimation.

A brand-new JAX/XLA framework with the full capabilities of the reference
masters-thesis codebase (an LSTM encoder estimating CAPM-style alpha/beta from
lookback windows of returns), re-designed TPU-first:

- ``ops``      — stateless numerical core (pure jnp, static shapes, jit-safe)
- ``data``     — synthetic DGP, Fama-French ingestion, windowed dataset pipeline
- ``models``   — Flax LSTM encoder + loss objectives fused into the train step
- ``parallel`` — device meshes, shardings, collectives (DP/TP over ICI, multi-host)
- ``train``    — native trainer: jitted steps, optax optimization, plateau LR,
                 checkpointing, metric pytrees, TensorBoard event writing
- ``config``   — Hydra-compatible config composition + multirun sweeps
- ``viz``      — evaluation plots (model vs OLS vs ground truth)

Reference capability map: see SURVEY.md section 2 (citations into
/root/reference are given per-module in docstrings).
"""

__version__ = "0.1.0"
