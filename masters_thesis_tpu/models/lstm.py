"""Flax LSTM encoder with torch-compatible semantics, built for the MXU.

Capability parity with the reference encoder (reference: src/model.py:88-109):
a stacked LSTM over the lookback window with inter-layer dropout, whose final
hidden state feeds two scalar heads (alpha, beta).

TPU-first design decisions:

- Per odd (pair-leading) layer, the input projection for ALL timesteps is
  computed as one large ``(B*T, in) @ (in, 4H)`` matmul before the time
  scan — batched and maximal for the MXU. The time recurrence then runs
  through the fused Pallas kernels (ops/lstm_kernel.py) on TPU — recurrent
  weights and state resident in VMEM for the whole loop — or an equivalent
  ``lax.scan`` on other backends (``kernel_impl`` selects; both paths are
  parity-tested). Consecutive layers fuse into a wavefront PAIR kernel
  (layer l step t alongside layer l+1 step t-1), which moves the even
  layer's per-step ``(B, H) @ (H, 4H)`` input projection and the
  inter-layer dropout inside the kernel — trading that projection's
  batching for a ~2x shorter serial matmul chain (measured +14-16%
  steps/s; RESULTS.md).
- Gate layout, gate order (i, f, g, o), double bias (``b_ih + b_hh``), and
  uniform(-1/sqrt(H), 1/sqrt(H)) initialization all match ``torch.nn.LSTM``
  so reference-trained behavior is reproducible (cross-checked numerically in
  tests/test_models_lstm.py).
- ``compute_dtype`` lets the recurrence run in bfloat16 on the MXU while
  parameters and head outputs stay float32 (the reference's
  ``precision: 32-true`` corresponds to the float32 default).
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import Array

from masters_thesis_tpu.ops.lstm_kernel import (
    window_schedulable,
    lstm_pair_recurrence,
    lstm_recurrence,
    lstm_stack_recurrence,
    pair_fusion_enabled,
    stack_fits,
    wavefront_enabled,
)


def _torch_lstm_init(scale: float):
    """uniform(-scale, scale) — torch.nn.LSTM/Linear reset_parameters."""

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)

    return init


class LstmEncoder(nn.Module):
    """Stacked LSTM over ``(batch, time, features)`` with alpha/beta heads."""

    hidden_size: int = 64
    num_layers: int = 2
    dropout: float = 0.2
    # Loadings per row: the beta head emits one coefficient per factor. The
    # default keeps the scalar (alpha, beta) head — parameter shapes, names,
    # and init draws are unchanged at n_factors=1.
    n_factors: int = 1
    compute_dtype: Any = jnp.float32
    kernel_impl: str = "auto"  # pallas | xla | interpret | auto
    # Rematerialize each layer's recurrence in the backward pass: the
    # recurrence VJP's per-step h/c residual stash is recomputed instead of
    # stored — a constant-factor (~2-3x) activation-memory saving per layer
    # (each layer's (T, B, 4H) x_proj input is still saved as the remat
    # residual) at ~1.3x backward FLOPs. Long-lookback story: there is no
    # ring-attention analog here — the LSTM recurrence is inherently
    # sequential, so long sequences cannot shard over devices; they STREAM
    # through VMEM instead. Lookbacks whose planes exceed the VMEM budget
    # automatically take the time-blocked kernel (grid over time chunks,
    # h/c carried in scratch across sequential grid steps;
    # ops/lstm_kernel.py time-blocked section), and remat bounds the
    # HBM-side activation footprint on top.
    remat: bool = False

    @nn.compact
    def __call__(
        self,
        x: Array,
        *,
        deterministic: bool = True,
        window_rows: int | None = None,
    ) -> tuple[Array, Array]:
        """Encode lookback windows into per-row (alpha, beta) estimates.

        Args:
            x: ``(batch, time, features)`` feature-expanded lookback windows.
            deterministic: disables inter-layer dropout (eval mode).
            window_rows: rows per window when ``batch`` is a flattened
                stack of independent windows (the train/eval steps flatten
                ``(B, K)`` into rows); lets the recurrence schedule big
                batches window-per-Pallas-program instead of falling onto
                the row-tiled grid (ops/lstm_kernel.py, window-granular
                section).

        Returns:
            ``(alpha, beta)``: ``(batch, 1)`` and ``(batch, n_factors)``
            float32.
        """
        hidden = self.hidden_size
        scale = 1.0 / math.sqrt(hidden)
        init = _torch_lstm_init(scale)
        batch = x.shape[0]

        # Wavefront fusion: consecutive layers run inside ONE Pallas
        # program (layer l at step t alongside layer l+1 at t-1 ...), which
        # cuts the serial recurrence chain from L*T to ~T+L
        # (ops/lstm_kernel.py). How DEEP a wavefront fits is a VMEM byte
        # question: at the canonical f32 shape the budget caps depth at 2
        # (the pair kernel, +14-16% measured); in bf16 compute every stash
        # plane halves and 4-5 deep wavefronts fit — the deep-model chain
        # shortener. Layers are grouped greedily into the deepest fused
        # block that fits; shapes over budget keep the per-layer path
        # unless window-granular scheduling applies (window_rows).
        # The GROUPING applies on every backend (on non-TPU the fused calls
        # lower to equivalent scan formulations), so the fused branches'
        # dropout mask draws — one explicit bernoulli per seam instead of
        # nn.Dropout's — are the same on all backends. All paths are
        # parity-tested.
        has_mask = self.dropout > 0.0 and not deterministic
        n_t = x.shape[1]
        itemsize = jnp.dtype(self.compute_dtype).itemsize

        def depth_fits(depth: int) -> bool:
            return stack_fits(
                n_t, batch, hidden, depth, has_mask, itemsize
            ) or (
                window_schedulable(batch, window_rows)
                and stack_fits(
                    n_t, window_rows, hidden, depth, has_mask, itemsize
                )
            )

        def fused_depth(start: int) -> int:
            """Deepest wavefront starting at layer ``start`` (1 = unfused)."""
            if (
                not pair_fusion_enabled()
                or self.kernel_impl not in ("auto", "pallas", "interpret")
            ):
                return 1
            limit = self.num_layers - start
            if not wavefront_enabled():
                limit = min(limit, 2)
            depth = 1
            while depth < limit and depth_fits(depth + 1):
                depth += 1
            return depth

        def draw_mask():
            if not has_mask:
                return None
            keep = jax.random.bernoulli(
                self.make_rng("dropout"), 1.0 - self.dropout,
                (n_t, batch, hidden),
            )
            return keep.astype(self.compute_dtype) / (1.0 - self.dropout)

        def layer_params(layer: int, in_dim: int):
            w_ih = self.param(f"w_ih_l{layer}", init, (4 * hidden, in_dim))
            w_hh = self.param(f"w_hh_l{layer}", init, (4 * hidden, hidden))
            b_ih = self.param(f"b_ih_l{layer}", init, (4 * hidden,))
            b_hh = self.param(f"b_hh_l{layer}", init, (4 * hidden,))
            return w_ih, w_hh, b_ih, b_hh

        inputs = x.astype(self.compute_dtype)
        layer = 0
        while layer < self.num_layers:
            in_dim = inputs.shape[-1]
            w_ih, w_hh, b_ih, b_hh = layer_params(layer, in_dim)

            # One big MXU matmul for every timestep's input projection.
            x_proj = (
                inputs @ w_ih.T.astype(self.compute_dtype)
                + (b_ih + b_hh).astype(self.compute_dtype)
            )  # (B, T, 4H)

            w_hh_t = w_hh.T.astype(self.compute_dtype)
            depth = fused_depth(layer)

            if depth >= 3:
                # Deep wavefront: the group's seam projections and dropout
                # move inside the kernel. Mask draws come from the same
                # 'dropout' RNG collection as nn.Dropout but are
                # independent samples, so fused/unfused training runs are
                # statistically (not bitwise) identical under dropout.
                w_hhs, w_ins, biases, masks = [w_hh_t], [], [], []
                for off in range(1, depth):
                    wi_l, whh_l, bi_l, bh_l = layer_params(
                        layer + off, hidden
                    )
                    w_hhs.append(whh_l.T.astype(self.compute_dtype))
                    w_ins.append(wi_l.T.astype(self.compute_dtype))
                    biases.append((bi_l + bh_l).astype(self.compute_dtype))
                    if has_mask:
                        masks.append(draw_mask())
                run = lambda xp, weights, m: lstm_stack_recurrence(
                    xp, weights, m, impl=self.kernel_impl,
                    window_rows=window_rows,
                )
                if self.remat:
                    run = jax.checkpoint(run)
                hs = run(
                    jnp.swapaxes(x_proj, 0, 1),
                    (tuple(w_hhs), tuple(w_ins), tuple(biases)),
                    tuple(masks) if has_mask else None,
                )
                layer += depth
            elif depth == 2:
                w_ih2, w_hh2, b_ih2, b_hh2 = layer_params(layer + 1, hidden)
                # Inter-layer dropout moves inside the kernel as a
                # precomputed, pre-scaled mask (torch semantics: dropout on
                # every layer's output except the last — within a pair the
                # first layer is never the last). Deterministic / dropout=0
                # runs the maskless kernel variant — no (T,B,H) mask plane
                # in VMEM at all.
                mask = draw_mask()
                run = lambda xp, w1, wi2, b2, w2, m: lstm_pair_recurrence(
                    xp, w1, wi2, b2, w2, m, impl=self.kernel_impl,
                    window_rows=window_rows,
                )
                if self.remat:
                    run = jax.checkpoint(run)
                hs = run(
                    jnp.swapaxes(x_proj, 0, 1),
                    w_hh_t,
                    w_ih2.T.astype(self.compute_dtype),
                    (b_ih2 + b_hh2).astype(self.compute_dtype),
                    w_hh2.T.astype(self.compute_dtype),
                    mask,
                )
                layer += 2
            else:
                run = lambda xp, wh: lstm_recurrence(
                    xp, wh, impl=self.kernel_impl, window_rows=window_rows
                )
                if self.remat:
                    run = jax.checkpoint(run)
                hs = run(jnp.swapaxes(x_proj, 0, 1), w_hh_t)
                layer += 1
            outputs = jnp.swapaxes(hs, 0, 1)  # (B, T, H)

            # torch applies inter-layer dropout to every layer except the
            # last (the reference additionally zeroes it for 1-layer nets,
            # src/model.py:92 — same condition).
            if layer < self.num_layers and self.dropout > 0.0:
                outputs = nn.Dropout(rate=self.dropout)(
                    outputs, deterministic=deterministic
                )
            inputs = outputs

        final_hidden = inputs[:, -1, :].astype(jnp.float32)

        head_init = _torch_lstm_init(scale)  # torch Linear: 1/sqrt(in) = 1/sqrt(H)
        alpha = nn.Dense(
            1, kernel_init=head_init, bias_init=head_init, name="alpha_head"
        )(final_hidden)
        beta = nn.Dense(
            self.n_factors,
            kernel_init=head_init,
            bias_init=head_init,
            name="beta_head",
        )(final_hidden)
        return alpha, beta
