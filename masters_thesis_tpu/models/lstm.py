"""Flax LSTM encoder with torch-compatible semantics, built for the MXU.

Capability parity with the reference encoder (reference: src/model.py:88-109):
a stacked LSTM over the lookback window with inter-layer dropout, whose final
hidden state feeds two scalar heads (alpha, beta).

TPU-first design decisions:

- Per odd (pair-leading) layer, the input projection for ALL timesteps is
  computed as one large ``(B*T, in) @ (in, 4H)`` matmul before the time
  scan — batched and maximal for the MXU. The time recurrence then runs
  through the fused Pallas kernels (ops/lstm_kernel.py) on TPU — recurrent
  weights and state resident in VMEM for the whole loop — or an equivalent
  ``lax.scan`` on other backends (``kernel_impl`` selects; both paths are
  parity-tested). Consecutive layers fuse into a wavefront PAIR kernel
  (layer l step t alongside layer l+1 step t-1), which moves the even
  layer's per-step ``(B, H) @ (H, 4H)`` input projection and the
  inter-layer dropout inside the kernel — trading that projection's
  batching for a ~2x shorter serial matmul chain (measured +14-16%
  steps/s; RESULTS.md).
- Gate layout, gate order (i, f, g, o), double bias (``b_ih + b_hh``), and
  uniform(-1/sqrt(H), 1/sqrt(H)) initialization all match ``torch.nn.LSTM``
  so reference-trained behavior is reproducible (cross-checked numerically in
  tests/test_models_lstm.py).
- ``compute_dtype`` lets the recurrence run in bfloat16 on the MXU while
  parameters and head outputs stay float32 (the reference's
  ``precision: 32-true`` corresponds to the float32 default).
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import Array

from masters_thesis_tpu.ops.lstm_kernel import (
    lstm_pair_recurrence,
    lstm_recurrence,
    pair_fits,
    pair_fusion_enabled,
)


def _torch_lstm_init(scale: float):
    """uniform(-scale, scale) — torch.nn.LSTM/Linear reset_parameters."""

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)

    return init


class LstmEncoder(nn.Module):
    """Stacked LSTM over ``(batch, time, features)`` with alpha/beta heads."""

    hidden_size: int = 64
    num_layers: int = 2
    dropout: float = 0.2
    compute_dtype: Any = jnp.float32
    kernel_impl: str = "auto"  # pallas | xla | interpret | auto
    # Rematerialize each layer's recurrence in the backward pass: the
    # recurrence VJP's per-step h/c residual stash is recomputed instead of
    # stored — a constant-factor (~2-3x) activation-memory saving per layer
    # (each layer's (T, B, 4H) x_proj input is still saved as the remat
    # residual) at ~1.3x backward FLOPs. This is the long-lookback knob:
    # there is no ring-attention analog here — the LSTM recurrence is
    # inherently sequential, so long sequences scale by remat + the
    # VMEM-resident time loop, not by sequence sharding.
    remat: bool = False

    @nn.compact
    def __call__(
        self, x: Array, *, deterministic: bool = True
    ) -> tuple[Array, Array]:
        """Encode lookback windows into per-row (alpha, beta) estimates.

        Args:
            x: ``(batch, time, features)`` feature-expanded lookback windows.
            deterministic: disables inter-layer dropout (eval mode).

        Returns:
            ``(alpha, beta)``, each ``(batch, 1)`` float32.
        """
        hidden = self.hidden_size
        scale = 1.0 / math.sqrt(hidden)
        init = _torch_lstm_init(scale)
        batch = x.shape[0]

        # The fused layer-pair kernel halves the serial recurrence chain by
        # running consecutive layers as a wavefront inside ONE Pallas
        # program (ops/lstm_kernel.py). It covers the reference's shape
        # (~100-stock windows at T=60/H=64); bigger batches, lookbacks, or
        # hidden sizes that would blow the pair's VMEM budget keep the
        # per-layer path (byte-based check, not a row-count constant).
        # The pair GROUPING applies on every backend (on non-TPU,
        # lstm_pair_recurrence lowers to an equivalent scan formulation),
        # so the fused branch's dropout mask draw — one explicit bernoulli
        # per pair instead of nn.Dropout's — is the same on all backends.
        # Both paths are parity-tested.
        fuse_pairs = (
            pair_fusion_enabled()
            and pair_fits(
                x.shape[1], batch, hidden,
                has_mask=self.dropout > 0.0 and not deterministic,
            )
            and self.kernel_impl in ("auto", "pallas", "interpret")
        )

        def layer_params(layer: int, in_dim: int):
            w_ih = self.param(f"w_ih_l{layer}", init, (4 * hidden, in_dim))
            w_hh = self.param(f"w_hh_l{layer}", init, (4 * hidden, hidden))
            b_ih = self.param(f"b_ih_l{layer}", init, (4 * hidden,))
            b_hh = self.param(f"b_hh_l{layer}", init, (4 * hidden,))
            return w_ih, w_hh, b_ih, b_hh

        inputs = x.astype(self.compute_dtype)
        layer = 0
        while layer < self.num_layers:
            in_dim = inputs.shape[-1]
            w_ih, w_hh, b_ih, b_hh = layer_params(layer, in_dim)

            # One big MXU matmul for every timestep's input projection.
            x_proj = (
                inputs @ w_ih.T.astype(self.compute_dtype)
                + (b_ih + b_hh).astype(self.compute_dtype)
            )  # (B, T, 4H)

            w_hh_t = w_hh.T.astype(self.compute_dtype)

            if fuse_pairs and layer + 1 < self.num_layers:
                w_ih2, w_hh2, b_ih2, b_hh2 = layer_params(layer + 1, hidden)
                n_t = x.shape[1]
                # Inter-layer dropout moves inside the kernel as a
                # precomputed, pre-scaled mask (torch semantics: dropout on
                # every layer's output except the last — within a pair the
                # first layer is never the last). Mask draws come from the
                # same 'dropout' RNG collection as nn.Dropout but are
                # independent samples, so fused/unfused training runs are
                # statistically (not bitwise) identical under dropout.
                if self.dropout > 0.0 and not deterministic:
                    keep = jax.random.bernoulli(
                        self.make_rng("dropout"),
                        1.0 - self.dropout,
                        (n_t, batch, hidden),
                    )
                    mask = keep.astype(self.compute_dtype) / (
                        1.0 - self.dropout
                    )
                else:
                    # Deterministic / dropout=0: the maskless kernel
                    # variant — no (T,B,H) mask plane in VMEM at all.
                    mask = None

                run = lambda xp, w1, wi2, b2, w2, m: lstm_pair_recurrence(
                    xp, w1, wi2, b2, w2, m, impl=self.kernel_impl
                )
                if self.remat:
                    run = jax.checkpoint(run)
                hs = run(
                    jnp.swapaxes(x_proj, 0, 1),
                    w_hh_t,
                    w_ih2.T.astype(self.compute_dtype),
                    (b_ih2 + b_hh2).astype(self.compute_dtype),
                    w_hh2.T.astype(self.compute_dtype),
                    mask,
                )
                layer += 2
            else:
                run = lambda xp, wh: lstm_recurrence(
                    xp, wh, impl=self.kernel_impl
                )
                if self.remat:
                    run = jax.checkpoint(run)
                hs = run(jnp.swapaxes(x_proj, 0, 1), w_hh_t)
                layer += 1
            outputs = jnp.swapaxes(hs, 0, 1)  # (B, T, H)

            # torch applies inter-layer dropout to every layer except the
            # last (the reference additionally zeroes it for 1-layer nets,
            # src/model.py:92 — same condition).
            if layer < self.num_layers and self.dropout > 0.0:
                outputs = nn.Dropout(rate=self.dropout)(
                    outputs, deterministic=deterministic
                )
            inputs = outputs

        final_hidden = inputs[:, -1, :].astype(jnp.float32)

        head_init = _torch_lstm_init(scale)  # torch Linear: 1/sqrt(in) = 1/sqrt(H)
        alpha = nn.Dense(
            1, kernel_init=head_init, bias_init=head_init, name="alpha_head"
        )(final_hidden)
        beta = nn.Dense(
            1, kernel_init=head_init, bias_init=head_init, name="beta_head"
        )(final_hidden)
        return alpha, beta
