"""Flax LSTM encoder with torch-compatible semantics, built for the MXU.

Capability parity with the reference encoder (reference: src/model.py:88-109):
a stacked LSTM over the lookback window with inter-layer dropout, whose final
hidden state feeds two scalar heads (alpha, beta).

TPU-first design decisions:

- Per layer, the input projection for ALL timesteps is computed as one large
  ``(B*T, in) @ (in, 4H)`` matmul before the time scan — that is the matmul
  the MXU sees, batched and maximal. The time recurrence then runs through
  the fused Pallas kernel (ops/lstm_kernel.py) on TPU — recurrent weight and
  state resident in VMEM for the whole loop — or an equivalent ``lax.scan``
  on other backends (``kernel_impl`` selects; both paths are parity-tested).
- Gate layout, gate order (i, f, g, o), double bias (``b_ih + b_hh``), and
  uniform(-1/sqrt(H), 1/sqrt(H)) initialization all match ``torch.nn.LSTM``
  so reference-trained behavior is reproducible (cross-checked numerically in
  tests/test_models_lstm.py).
- ``compute_dtype`` lets the recurrence run in bfloat16 on the MXU while
  parameters and head outputs stay float32 (the reference's
  ``precision: 32-true`` corresponds to the float32 default).
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import Array

from masters_thesis_tpu.ops.lstm_kernel import lstm_recurrence


def _torch_lstm_init(scale: float):
    """uniform(-scale, scale) — torch.nn.LSTM/Linear reset_parameters."""

    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)

    return init


class LstmEncoder(nn.Module):
    """Stacked LSTM over ``(batch, time, features)`` with alpha/beta heads."""

    hidden_size: int = 64
    num_layers: int = 2
    dropout: float = 0.2
    compute_dtype: Any = jnp.float32
    kernel_impl: str = "auto"  # pallas | xla | interpret | auto
    # Rematerialize each layer's recurrence in the backward pass: the
    # recurrence VJP's per-step h/c residual stash is recomputed instead of
    # stored — a constant-factor (~2-3x) activation-memory saving per layer
    # (each layer's (T, B, 4H) x_proj input is still saved as the remat
    # residual) at ~1.3x backward FLOPs. This is the long-lookback knob:
    # there is no ring-attention analog here — the LSTM recurrence is
    # inherently sequential, so long sequences scale by remat + the
    # VMEM-resident time loop, not by sequence sharding.
    remat: bool = False

    @nn.compact
    def __call__(
        self, x: Array, *, deterministic: bool = True
    ) -> tuple[Array, Array]:
        """Encode lookback windows into per-row (alpha, beta) estimates.

        Args:
            x: ``(batch, time, features)`` feature-expanded lookback windows.
            deterministic: disables inter-layer dropout (eval mode).

        Returns:
            ``(alpha, beta)``, each ``(batch, 1)`` float32.
        """
        hidden = self.hidden_size
        scale = 1.0 / math.sqrt(hidden)
        init = _torch_lstm_init(scale)
        batch = x.shape[0]

        inputs = x.astype(self.compute_dtype)
        for layer in range(self.num_layers):
            in_dim = inputs.shape[-1]
            w_ih = self.param(f"w_ih_l{layer}", init, (4 * hidden, in_dim))
            w_hh = self.param(f"w_hh_l{layer}", init, (4 * hidden, hidden))
            b_ih = self.param(f"b_ih_l{layer}", init, (4 * hidden,))
            b_hh = self.param(f"b_hh_l{layer}", init, (4 * hidden,))

            # One big MXU matmul for every timestep's input projection.
            x_proj = (
                inputs @ w_ih.T.astype(self.compute_dtype)
                + (b_ih + b_hh).astype(self.compute_dtype)
            )  # (B, T, 4H)

            w_hh_t = w_hh.T.astype(self.compute_dtype)

            run = lambda xp, wh: lstm_recurrence(xp, wh, impl=self.kernel_impl)
            if self.remat:
                run = jax.checkpoint(run)
            hs = run(jnp.swapaxes(x_proj, 0, 1), w_hh_t)
            outputs = jnp.swapaxes(hs, 0, 1)  # (B, T, H)

            # torch applies inter-layer dropout to every layer except the
            # last (the reference additionally zeroes it for 1-layer nets,
            # src/model.py:92 — same condition).
            if layer < self.num_layers - 1 and self.dropout > 0.0:
                outputs = nn.Dropout(rate=self.dropout)(
                    outputs, deterministic=deterministic
                )
            inputs = outputs

        final_hidden = inputs[:, -1, :].astype(jnp.float32)

        head_init = _torch_lstm_init(scale)  # torch Linear: 1/sqrt(in) = 1/sqrt(H)
        alpha = nn.Dense(
            1, kernel_init=head_init, bias_init=head_init, name="alpha_head"
        )(final_hidden)
        beta = nn.Dense(
            1, kernel_init=head_init, bias_init=head_init, name="beta_head"
        )(final_hidden)
        return alpha, beta
