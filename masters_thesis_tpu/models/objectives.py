"""Loss objectives as pure per-window functions + the model registry.

Capability parity with the reference's three LightningModule variants
(reference: src/model.py:176-331): MSE, multivariate-Gaussian NLL with the
Woodbury inverse covariance, and the Combined objective
``NLL + mse_weight * MSE``.

Each objective is a pure function of one window's model outputs and labels;
``batched_objective`` vmaps it over the batch of windows and averages. At the
reference's batch_size=1 this is numerically identical to the reference's
per-step losses; for larger batches it generalizes the NLL correctly (each
window keeps its own factor statistics — the reference's flatten(0,1)
handling is only well-defined at batch_size=1). Everything here traces into
the jitted train step, so the objective choice is fused into one XLA program
(the BASELINE.json north star: "configs/loss is traced and fused into the
train step").

Batch window schema (see masters_thesis_tpu.data.pipeline.Batch):
``y``: (K, T, 2F+2) channels [r_stock, f_1..f_F, alpha, beta_1..beta_F]
((K, T, 4) in the scalar F=1 case); ``factor``: (2,) = (market mean, market
var) at F=1, (F+F²,) = [f_mean | f_cov.ravel()] otherwise; ``inv_psi``: (K,).
The factor count is read statically from ``beta.shape[-1]``, and the F=1
branch is the *original* scalar code, so scalar training is bit-identical.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
from jax import Array

from masters_thesis_tpu.ops import (
    kfactor_gaussian_nll,
    mean_squared_error,
    single_factor_gaussian_nll,
)

# (loss, metric sums) for one window; metric sums are psum/accumulation-ready
# (value_sum, weight) pairs mirroring torchmetrics' dist_reduce_fx="sum"
# states (reference: src/model.py:24-25).
WindowObjective = Callable[..., tuple[Array, dict[str, tuple[Array, Array]]]]


def mse_window(
    alpha: Array, beta: Array, y: Array, factor: Array, inv_psi: Array
) -> tuple[Array, dict]:
    """MSE of ``alpha + beta · factors`` vs realized returns over the target
    window (reference: src/model.py:192-202)."""
    r_target = y[:, :, 0]
    n_f = beta.shape[-1]
    if n_f == 1:
        r_market = y[:, :, 1]
        r_pred = alpha + beta * r_market  # (K,1) broadcast over (K,T)
    else:
        factors = y[:, :, 1 : 1 + n_f]  # (K, T, F)
        r_pred = alpha + jnp.einsum(
            "kf,ktf->kt", beta, factors, precision="highest"
        )
    loss = mean_squared_error(r_pred, r_target)
    n = jnp.float32(r_target.size)
    return loss, {"mse": (loss * n, n)}


def nll_window(
    alpha: Array, beta: Array, y: Array, factor: Array, inv_psi: Array
) -> tuple[Array, dict]:
    """Multivariate-Gaussian NLL with single-factor Woodbury inverse
    covariance (reference: src/model.py:234-249), computed via the fused
    O(K·n) form (ops/losses.py single_factor_gaussian_nll) instead of
    materializing the K×K inverse covariance. With F>1 loadings the rank-F
    Woodbury form (ops/losses.py kfactor_gaussian_nll) takes over."""
    r_target = y[:, :, 0]
    n_f = beta.shape[-1]
    if n_f == 1:
        f_mean, f_var = factor[0], factor[1]
        r_mean = alpha + beta * f_mean  # (K, 1)
        loss = single_factor_gaussian_nll(
            r_mean, beta, inv_psi, f_var, r_target
        )
    else:
        f_mean = factor[:n_f]  # (F,)
        f_cov = factor[n_f:].reshape(n_f, n_f)
        r_mean = alpha + jnp.matmul(
            beta, f_mean[:, None], precision="highest"
        )  # (K, 1)
        loss = kfactor_gaussian_nll(r_mean, beta, inv_psi, f_cov, r_target)
    return loss, {"nll": (loss, jnp.float32(1.0))}


def make_combined_window(mse_weight: float) -> WindowObjective:
    """``NLL + mse_weight * MSE`` (reference: src/model.py:308-319; default
    weight 1e2 at src/model.py:275, 100 via configs/loss/combined.yaml)."""

    def combined_window(alpha, beta, y, factor, inv_psi):
        mse_loss, mse_metrics = mse_window(alpha, beta, y, factor, inv_psi)
        nll_loss, nll_metrics = nll_window(alpha, beta, y, factor, inv_psi)
        loss = nll_loss + mse_weight * mse_loss
        return loss, {**mse_metrics, **nll_metrics}

    return combined_window


def batched_objective(window_fn: WindowObjective):
    """Lift a per-window objective over a batch of windows.

    Returns ``fn(alpha (B,K,1), beta (B,K,1), batch) -> (mean loss, metric
    sums)`` where metric sums aggregate across the batch (ready for further
    psum across devices) and always include a ``"total"`` entry for the
    objective itself. This is the single lifting used by the jitted train
    step (masters_thesis_tpu.train.steps).

    ``weights`` (optional, (B,)) turns the mean into a weighted mean; a
    zero-weight window contributes nothing to the loss, its gradient, or the
    metric sums. Used to handle a padded tail batch without recompiling —
    pad windows must hold FINITE data (real windows repeated), because a
    NaN loss value survives ``0 * NaN`` in reverse-mode AD.
    """

    def fn(
        alpha: Array,
        beta: Array,
        y: Array,
        factor: Array,
        inv_psi: Array,
        weights: Array | None = None,
    ):
        losses, metrics = jax.vmap(window_fn)(alpha, beta, y, factor, inv_psi)
        if weights is None:
            loss = jnp.mean(losses)
            summed = {
                k: (jnp.sum(v[0]), jnp.sum(v[1])) for k, v in metrics.items()
            }
            summed["total"] = (jnp.sum(losses), jnp.float32(losses.shape[0]))
        else:
            wsum = jnp.maximum(jnp.sum(weights), 1.0)
            loss = jnp.sum(weights * losses) / wsum
            summed = {
                k: (jnp.sum(weights * v[0]), jnp.sum(weights * v[1]))
                for k, v in metrics.items()
            }
            summed["total"] = (jnp.sum(weights * losses), wsum)
        return loss, summed

    return fn


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Hyperparameter bundle for one configured model + objective.

    Mirrors the reference constructor surface (reference: src/model.py:77-85,
    265-276 and train.py:124-136): same fields, same defaults.
    """

    objective: str  # 'mse' | 'nll' | 'combined'
    input_size: int = 3
    hidden_size: int = 64
    num_layers: int = 2
    dropout: float = 0.2
    n_factors: int = 1  # loadings per row (beta head width)
    learning_rate: float = 1e-4
    weight_decay: float = 1e-5
    mse_weight: float = 1e2
    kernel_impl: str = "auto"  # LSTM recurrence: pallas | xla | interpret
    remat: bool = False  # rematerialize recurrences (long-lookback memory)

    def build_module(self, compute_dtype=jnp.float32):
        from masters_thesis_tpu.models.lstm import LstmEncoder

        return LstmEncoder(
            hidden_size=self.hidden_size,
            num_layers=self.num_layers,
            dropout=self.dropout,
            n_factors=self.n_factors,
            compute_dtype=compute_dtype,
            kernel_impl=self.kernel_impl,
            remat=self.remat,
        )

    @property
    def metric_keys(self) -> tuple:
        """Per-objective logged metric names (reference logs loss/mse, loss/nll,
        loss/total per variant: src/model.py:207-208,254-255,314-318)."""
        return {
            "mse": ("mse",),
            "nll": ("nll",),
            "combined": ("mse", "nll"),
        }[self.objective]

    def window_objective(self) -> WindowObjective:
        if self.objective == "mse":
            return mse_window
        if self.objective == "nll":
            return nll_window
        if self.objective == "combined":
            return make_combined_window(self.mse_weight)
        raise ValueError(f"unknown objective: {self.objective}")


# String registry keeping the reference's CLI class names working
# (reference: train.py:45-67).
MODEL_REGISTRY: dict[str, str] = {
    "FinancialLstmMse": "mse",
    "FinancialLstmNll": "nll",
    "FinancialLstmCombined": "combined",
}


def get_model_spec(module_class_name: str, **hparams) -> ModelSpec:
    """Map a reference-style class name to a configured ModelSpec."""
    if module_class_name not in MODEL_REGISTRY:
        raise ValueError(
            f"Unknown module class: {module_class_name}. "
            f"Available: {list(MODEL_REGISTRY.keys())}"
        )
    return ModelSpec(objective=MODEL_REGISTRY[module_class_name], **hparams)
