"""Model layer: Flax LSTM encoder + loss objectives as pure functions.

TPU-native re-design of the reference's LightningModule hierarchy
(reference: src/model.py:72-331). The reference couples network, loss, and
training loop into one class per objective; here the *network* is a single
Flax module, each *objective* is a pure function fused into the jitted train
step, and the *loop* lives in ``masters_thesis_tpu.train`` — the idiomatic
JAX factoring of the same capability surface.
"""

from masters_thesis_tpu.models.lstm import LstmEncoder
from masters_thesis_tpu.models.objectives import (
    ModelSpec,
    MODEL_REGISTRY,
    get_model_spec,
    mse_window,
    nll_window,
    make_combined_window,
    batched_objective,
)

__all__ = [
    "LstmEncoder",
    "ModelSpec",
    "MODEL_REGISTRY",
    "get_model_spec",
    "mse_window",
    "nll_window",
    "make_combined_window",
    "batched_objective",
]
