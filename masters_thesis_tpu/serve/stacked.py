"""Multi-tenant stacked inference: R checkpoints, ONE program per bucket.

The stacked trainer (train/steps.py:make_stacked_train_epoch) proved the
lane-stacking economics on this hardware: R independent replicas run as
one compiled program at ~R× cells/hour because compile, dispatch, and
collective launches amortize across the stack. This module spends the
same insight on the serving plane. ``StackedPredictEngine`` loads R
manifest-verified checkpoints (ensemble members, grid winners,
per-universe/per-tenant models) into the flat ``[R, n]`` per-dtype
buffers from :mod:`~masters_thesis_tpu.train.flatparams` and AOT-compiles
ONE predict executable per batch bucket — a request fans across all R
lanes in a single dispatch, at roughly one model's dispatch cost.

Layout of the lane axis — a rolled ``lax.scan``, not ``vmap``:

- ``vmap`` over the param axis batches every lane matmul into one
  ``dot_general`` with a leading batch dim; XLA:CPU reassociates those
  reductions differently from the unbatched kernel, and per-lane outputs
  drift from the solo engine at the ULP level (measured ~6e-8 — the same
  effect docs/perf.md records for the stacked TRAINER, where it is
  tolerated). Serving has a harder contract: a tenant's answers must be
  **bit-identical** to the solo engine serving the same checkpoint, or a
  migration onto the stack is observable (and un-debuggable) downstream.
- A rolled ``lax.scan`` over the ``[R, n]`` buffers runs each lane
  through literally the same op sequence as the solo engine — bitwise
  parity, pinned per bucket by tests/test_stacked_serve.py — while still
  compiling to ONE executable per bucket whose HLO does not grow with R
  (the loop stays rolled; preflight rule SV307 pins this on the compiled
  HLO, the serving twin of TA207).

Per-lane hot-swap (serve/swap.py:try_swap_lane) commits through
:meth:`StackedPredictEngine.set_lane`: one row-scatter over the stacked
buffers under the engine lock. Shapes never change, so the swap performs
ZERO recompiles (SV308); sibling lanes' rows — and therefore their
outputs — are bit-untouched.

Program-cache identity: the stacked executable's entry key covers the
ORDERED per-lane content digests (:func:`lane_digest`) on top of the
usual spec/window/bucket/backend identity. A lane swap therefore misses
the cache for the stack on the next boot (the golden record stored with
the entry replays the old lane's outputs — content must be part of the
key for parity to mean anything) while every unchanged SOLO program
still hits: solo keys never see lane digests.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.parallel import (
    DATA_AXIS,
    global_put,
    make_data_mesh,
    replicated_sharding,
)
from masters_thesis_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    BucketOverflowError,
)
from masters_thesis_tpu.train import flatparams
from masters_thesis_tpu.train.steps import forward_rows


def lane_digest(host_bufs: dict) -> str:
    """Content hash of one lane's flat buffers (host-side, order-stable).

    Part of the stacked program-cache identity: unlike the solo engine —
    whose executable is param-CONTENT-independent, so its key only needs
    the leaf signature — the stacked entry's golden record replays every
    lane's stored outputs, so the key must pin which checkpoints occupy
    which lanes.
    """
    h = hashlib.sha256()
    for key in sorted(host_bufs):
        arr = np.ascontiguousarray(np.asarray(host_bufs[key]))
        h.update(key.encode())
        h.update(str(arr.dtype).encode())
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


def ensemble_stats(alpha: np.ndarray, beta: np.ndarray) -> dict:
    """Ensemble mean + uncertainty bands over per-lane outputs.

    ``alpha``/``beta`` are the engine's batch-major per-lane arrays
    ``(n, R, K)``; returns host f64 arrays shaped ``(n, K)``:
    ``{alpha,beta}_mean``, ``_std`` (population std across lanes — the
    band half-width), and ``_lo``/``_hi`` (the lane envelope). f64 on
    purpose: the reduction is host-side statistics over R samples and
    must not add f32 rounding of its own.
    """
    out: dict[str, np.ndarray] = {}
    for name, v in (("alpha", alpha), ("beta", beta)):
        a = np.asarray(v, np.float64)  # mtt: disable=TL104 -- host-only ensemble statistics; never traced
        if a.ndim != 3:
            raise ValueError(
                f"{name} must be (n, R, K) per-lane outputs, got {a.shape}"
            )
        out[f"{name}_mean"] = a.mean(axis=1)
        out[f"{name}_std"] = a.std(axis=1)
        out[f"{name}_lo"] = a.min(axis=1)
        out[f"{name}_hi"] = a.max(axis=1)
    return out


class LaneMismatchError(ValueError):
    """Candidate lane params do not match the stack's shared signature."""


class StackedPredictEngine:
    """Bucketed AOT predict programs over R stacked model lanes.

    ``predict`` maps a host batch ``x (n, K, T, F)`` to BATCH-MAJOR
    per-lane outputs ``(alpha (n, R, K), beta (n, R, K))`` — batch axis
    first so the server/fleet dispatch loops index per-request outputs
    exactly as they do for the solo engine (``alpha[i]`` is request i's
    ``(R, K)`` fan-out). :func:`ensemble_stats` folds the lane axis into
    mean/bands for callers that want one answer with uncertainty.

    API contract shared with :class:`~masters_thesis_tpu.serve.engine
    .PredictEngine` (what server.py/fleet.py/preflight rely on):
    ``window_shape``, ``max_bucket``, ``platform``, ``buckets``,
    ``compile_events``/``_cache_size``, ``cache_hits``, ``cost_profiles``,
    ``warmup()``, ``bucket_for``, ``predict``, ``golden_batch``,
    ``degrade_to_cpu``.
    """

    def __init__(
        self,
        spec: ModelSpec,
        params_list: Sequence[Any],
        *,
        n_stocks: int,
        lookback: int,
        n_features: int = 3,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        mesh: Mesh | None = None,
        program_cache=None,
        lanes: Sequence[str] | None = None,
    ):
        if not params_list:
            raise ValueError("need at least one lane (R >= 1)")
        self.spec = spec
        self.n_stocks = n_stocks
        self.lookback = lookback
        self.n_features = n_features
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"invalid buckets: {buckets!r}")
        self.mesh = mesh if mesh is not None else make_data_mesh(None)
        self._module = spec.build_module()
        self.num_lanes = len(params_list)
        #: Lane names (tenant ids / checkpoint tags); purely descriptive.
        self.lanes = (
            tuple(str(x) for x in lanes)
            if lanes is not None
            else tuple(f"lane{i}" for i in range(self.num_lanes))
        )
        if len(self.lanes) != self.num_lanes:
            raise ValueError(
                f"{len(self.lanes)} lane names for {self.num_lanes} lanes"
            )
        #: Monotonic count of XLA compilations (same contract as the solo
        #: engine: constant after warmup(); SV307/SV308 pin the deltas).
        self.compile_events = 0
        self.cache_hits = 0
        self.program_cache = program_cache
        self._compiled: dict[int, tuple[Any, NamedSharding]] = {}
        self.cost_profiles: dict[int, dict] = {}
        self._lock = threading.RLock()
        # One shared view table for every lane: the stack is only sound if
        # all R trees carve identically.
        host_trees = [jax.device_get(p) for p in params_list]
        self._fspec = flatparams.flatten_spec(host_trees[0])
        sig0 = self._solo_signature(host_trees[0])
        for i, tree in enumerate(host_trees[1:], start=1):
            if self._solo_signature(tree) != sig0:
                raise LaneMismatchError(
                    f"lane {i} ({self.lanes[i]}) param tree does not match "
                    "lane 0 — stacked serving requires identical "
                    "architectures across lanes"
                )
        self._solo_sig = sig0
        host_flat = [
            flatparams.flatten(t, self._fspec) for t in host_trees
        ]
        self._lane_digests = [lane_digest(b) for b in host_flat]
        self._stacked = global_put(
            {
                k: np.stack([np.asarray(b[k]) for b in host_flat])
                for k in host_flat[0]
            },
            replicated_sharding(self.mesh),
        )

    @staticmethod
    def _solo_signature(host_tree: Any) -> tuple:
        leaves, treedef = jax.tree_util.tree_flatten(host_tree)
        return (
            str(treedef),
            tuple(
                (tuple(np.shape(x)), str(np.asarray(x).dtype))
                for x in leaves
            ),
        )

    # jit_cache_size()/CompileTracker compatibility.
    def _cache_size(self) -> int:
        return self.compile_events

    @property
    def window_shape(self) -> tuple[int, int, int]:
        return (self.n_stocks, self.lookback, self.n_features)

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    @property
    def platform(self) -> str:
        devs = list(self.mesh.devices.flat)
        return devs[0].platform if devs else jax.default_backend()

    def _predict_fn(self, stacked, x):
        # Rolled scan over the lane axis: each iteration is the solo
        # engine's exact op sequence (unflatten is views-only; forward is
        # the unbatched kernel), so per-lane outputs are bit-identical to
        # R solo engines while the whole fan-out stays one executable.
        def lane_step(carry, lane_bufs):
            params = flatparams.unflatten(lane_bufs, self._fspec)
            alpha, beta = forward_rows(self._module, params, x)
            return carry, (alpha[..., 0], beta[..., 0])

        _, (alpha, beta) = lax.scan(lane_step, None, stacked)
        # (R, n, K) -> batch-major (n, R, K) so dispatch loops can index
        # request i's outputs as alpha[i] exactly like the solo engine.
        return jnp.moveaxis(alpha, 0, 1), jnp.moveaxis(beta, 0, 1)

    # ------------------------------------------------- program-cache glue

    def _cache_identity(self, b: int) -> tuple[str, dict]:
        """(entry key, backend fingerprint) for bucket ``b``'s program.

        On top of the solo identity (spec / signature / window / bucket /
        backend), the stacked key pins the ORDERED per-lane content
        digests: a lane swap re-keys the stack (its stored golden replay
        embodies the old lane's outputs) while unchanged solo entries —
        whose keys never include lane digests — keep hitting.
        """
        import dataclasses

        from masters_thesis_tpu.serve import program_cache as pc
        from masters_thesis_tpu.utils.backend_probe import backend_fingerprint

        fp = backend_fingerprint(self.mesh)
        ident = {
            "spec": dataclasses.asdict(self.spec),
            "params": pc.param_signature(self._stacked),
            "lanes": list(self._lane_digests),
            "window": list(self.window_shape),
            "bucket": int(b),
            "fingerprint": fp,
        }
        return pc.entry_key(ident), fp

    def _golden_x(self, b: int) -> np.ndarray:
        # Seed offset vs the solo engine so a stacked and a solo entry for
        # the same checkpoint never share golden inputs by accident.
        return self.golden_batch(n=b, seed=2003 * b + 11)

    def _cache_load(self, b: int, x_sh: NamedSharding, repl: NamedSharding):
        """Try to boot bucket ``b`` from the program cache (None = miss)."""
        key, fp = self._cache_identity(b)
        treedef = jax.tree_util.tree_structure(self._stacked)
        in_tree = jax.tree_util.tree_structure(((self._stacked, 0), {}))
        out_tree = jax.tree_util.tree_structure((0, 0))

        def run_golden(compiled, golden):
            n_leaves = sum(1 for k2 in golden if k2.startswith("param_"))
            leaves = [golden[f"param_{i}"] for i in range(n_leaves)]
            stree = jax.tree_util.tree_unflatten(treedef, leaves)
            sd = global_put(stree, repl)
            xd = jax.device_put(np.ascontiguousarray(golden["x"]), x_sh)
            alpha, beta = compiled(sd, xd)
            return (
                np.asarray(jax.device_get(alpha)),
                np.asarray(jax.device_get(beta)),
            )

        return self.program_cache.load(
            key,
            fingerprint=fp,
            in_tree=in_tree,
            out_tree=out_tree,
            run_golden=run_golden,
        )

    def _cache_store(self, b: int, compiled, x_sh: NamedSharding) -> None:
        key, fp = self._cache_identity(b)
        x = self._golden_x(b)
        xd = jax.device_put(np.ascontiguousarray(x), x_sh)
        alpha, beta = compiled(self._stacked, xd)
        host_leaves = jax.tree_util.tree_leaves(
            jax.device_get(self._stacked)
        )
        golden = {
            "x": x,
            "alpha": np.asarray(jax.device_get(alpha)),
            "beta": np.asarray(jax.device_get(beta)),
        }
        for i, leaf in enumerate(host_leaves):
            golden[f"param_{i}"] = np.asarray(leaf)
        self.program_cache.store(key, compiled, fingerprint=fp, golden=golden)

    # ------------------------------------------------------------ compile

    def _compile_bucket(self, b: int) -> None:
        k, t, f = self.window_shape
        repl = replicated_sharding(self.mesh)
        if b % self.mesh.size == 0:
            x_sh = NamedSharding(self.mesh, P(DATA_AXIS))
        else:
            x_sh = repl
        compiled = None
        if self.program_cache is not None:
            compiled = self._cache_load(b, x_sh, repl)
        if compiled is not None:
            self.cache_hits += 1
        else:
            jfn = jax.jit(
                self._predict_fn,
                in_shardings=(repl, x_sh),
                out_shardings=(repl, repl),
            )
            x_struct = jax.ShapeDtypeStruct((b, k, t, f), jnp.float32)
            compiled = jfn.lower(self._stacked, x_struct).compile()
            self.compile_events += 1
            if self.program_cache is not None:
                self._cache_store(b, compiled, x_sh)
        self._compiled[b] = (compiled, x_sh)
        try:
            from masters_thesis_tpu.telemetry.costs import extract_cost

            self.cost_profiles[b] = extract_cost(
                compiled,
                program=f"serve_stacked_bucket_{b}",
                meta={
                    "bucket": b,
                    "lanes": self.num_lanes,
                    "platform": self.platform,
                    "mesh_size": self.mesh.size,
                },
            ).to_payload()
        except Exception:  # cost accounting must never block serving
            self.cost_profiles.pop(b, None)

    def compiled_text(self, b: int) -> str:
        """Compiled HLO for bucket ``b`` (preflight rule SV307 asserts the
        lane loop stayed rolled — the module must not grow with R)."""
        compiled, _ = self._compiled[b]
        return compiled.as_text()

    def warmup(self) -> float:
        """Compile every bucket; return one max-bucket execution's wall
        seconds (seeds the queue's service-time model, same as solo)."""
        for b in self.buckets:
            if b not in self._compiled:
                self._compile_bucket(b)
        k, t, f = self.window_shape
        x = np.zeros((self.max_bucket, k, t, f), np.float32)
        self.predict(x)
        t0 = time.perf_counter()
        self.predict(x)
        return time.perf_counter() - t0

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise BucketOverflowError(
            f"batch of {n} exceeds largest compiled bucket "
            f"{self.max_bucket} (buckets: {self.buckets})"
        )

    # ------------------------------------------------------------ predict

    def predict(
        self, x: np.ndarray, params: Any = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """One padded micro-batch through the bucket's AOT executable.

        Returns batch-major per-lane ``(alpha (n, R, K), beta (n, R, K))``
        host arrays. ``params`` overrides the serving STACK for this call
        only (the per-lane canary path stages a candidate stack without
        exposing it to traffic). Only explicit transfers.
        """
        x = np.asarray(x, np.float32)
        if x.ndim != 4 or x.shape[1:] != self.window_shape:
            raise ValueError(
                f"request shape {x.shape} != (n, {self.n_stocks}, "
                f"{self.lookback}, {self.n_features})"
            )
        n = x.shape[0]
        b = self.bucket_for(n)
        if n < b:
            pad = np.broadcast_to(x[:1], (b - n,) + x.shape[1:])
            x = np.concatenate([x, pad], axis=0)
        compiled, x_sh = self._compiled[b]
        xd = jax.device_put(np.ascontiguousarray(x), x_sh)
        with self._lock:
            s = self._stacked if params is None else params
        alpha, beta = compiled(s, xd)
        return (
            np.asarray(jax.device_get(alpha))[:n],
            np.asarray(jax.device_get(beta))[:n],
        )

    def predict_lane(
        self, x: np.ndarray, lane: int, params: Any = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """One lane's slice of :meth:`predict`: ``(alpha (n, K), beta
        (n, K))`` — the solo-engine view of lane ``lane``."""
        self._check_lane(lane)
        alpha, beta = self.predict(x, params=params)
        return alpha[:, lane, :], beta[:, lane, :]

    def predict_ensemble(self, x: np.ndarray) -> dict:
        """Per-lane outputs plus ensemble mean/bands in one dispatch."""
        alpha, beta = self.predict(x)
        out = ensemble_stats(alpha, beta)
        out["alpha"] = alpha
        out["beta"] = beta
        return out

    def golden_batch(self, n: int = 1, seed: int = 0) -> np.ndarray:
        k, t, f = self.window_shape
        rng = np.random.default_rng(seed)
        return rng.standard_normal((n, k, t, f)).astype(np.float32)

    # -------------------------------------------------------------- lanes

    def _check_lane(self, lane: int) -> None:
        if not 0 <= int(lane) < self.num_lanes:
            raise IndexError(
                f"lane {lane} out of range (stack has {self.num_lanes})"
            )

    def lane_params(self, lane: int) -> Any:
        """Host param tree currently serving on lane ``lane``."""
        self._check_lane(lane)
        host = jax.device_get(self._stacked)
        return flatparams.unflatten(
            flatparams.replica_flat(host, int(lane)), self._fspec
        )

    def lane_digests(self) -> tuple[str, ...]:
        with self._lock:
            return tuple(self._lane_digests)

    def stage_lane(self, lane: int, host_params: Any) -> Any:
        """Candidate stack with lane ``lane`` replaced (does NOT commit).

        The canary path runs this staged stack through the SAME compiled
        executables as live traffic (``predict(..., params=staged)``) —
        sibling rows are bit-identical to the serving stack, so any
        sibling output movement is a lane-isolation bug, not noise.
        """
        self._check_lane(lane)
        if self._solo_signature(jax.device_get(host_params)) != self._solo_sig:
            raise LaneMismatchError(
                "candidate lane params do not match the stack's shared "
                "architecture (per-lane swap cannot change shapes — the "
                "AOT executables are shape-specialized)"
            )
        bufs = flatparams.flatten(
            jax.device_get(host_params), self._fspec
        )
        with self._lock:
            staged = flatparams.set_lane(self._stacked, int(lane), bufs)
        return global_put(
            jax.device_get(staged), replicated_sharding(self.mesh)
        )

    def set_lane(self, lane: int, host_params: Any, staged: Any = None
                 ) -> str:
        """Atomically commit lane ``lane``'s params; returns the lane's
        NEW content digest. ``staged`` (from :meth:`stage_lane`) skips
        rebuilding the stack when the canary already staged it. Zero
        recompiles by construction — shapes never change."""
        self._check_lane(lane)
        host = jax.device_get(host_params)
        if self._solo_signature(host) != self._solo_sig:
            raise LaneMismatchError(
                "candidate lane params do not match the stack's shared "
                "architecture"
            )
        bufs = flatparams.flatten(host, self._fspec)
        digest = lane_digest(jax.device_get(bufs))
        with self._lock:
            if staged is None:
                staged = global_put(
                    jax.device_get(
                        flatparams.set_lane(self._stacked, int(lane), bufs)
                    ),
                    replicated_sharding(self.mesh),
                )
            self._stacked = staged
            self._lane_digests[int(lane)] = digest
        return digest

    # -------------------------------------------------------- degradation

    def degrade_to_cpu(self) -> None:
        """Rebuild mesh + executables on the CPU backend (breaker policy);
        one deliberate compile burst, same contract as the solo engine."""
        from masters_thesis_tpu.utils.backend_probe import pin_cpu_in_process

        host_stacked = jax.device_get(self._stacked)
        pin_cpu_in_process()
        cpu = jax.devices("cpu")
        with self._lock:
            self.mesh = Mesh(np.asarray(cpu[:1]), axis_names=(DATA_AXIS,))
            self._stacked = global_put(
                host_stacked, replicated_sharding(self.mesh)
            )
            self._compiled.clear()
            self.cost_profiles.clear()
            for b in self.buckets:
                self._compile_bucket(b)  # mtt: disable=CL503 -- CPU-degrade failover must swap stack+programs atomically; callers accept the pause

    # -------------------------------------------------------------- boot

    @classmethod
    def from_checkpoints(
        cls,
        ckpt_dirs: Sequence[Any],
        tag: str = "best",
        *,
        n_stocks: int,
        n_features: int = 3,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        mesh: Mesh | None = None,
        program_cache=None,
        lanes: Sequence[str] | None = None,
    ) -> "StackedPredictEngine":
        """Boot a stack from R published checkpoints, STRICT verification
        per lane: every lane's tree must prove itself against its own
        manifest — one unprovable tenant must not board the stack."""
        from pathlib import Path

        from masters_thesis_tpu.train.checkpoint import (
            CorruptCheckpointError,
            restore_checkpoint,
            verify_checkpoint,
        )

        if not ckpt_dirs:
            raise ValueError("need at least one checkpoint directory")
        params_list, spec0, lookback0 = [], None, None
        for i, d in enumerate(ckpt_dirs):
            path = Path(d) / tag
            if not verify_checkpoint(path, require_manifest=True):
                raise CorruptCheckpointError(
                    f"refusing to serve lane {i} from {path}: strict "
                    "manifest verification failed"
                )
            params, _, spec, meta = restore_checkpoint(d, tag)
            lookback = meta.get("datamodule", {}).get("lookback_window")
            if lookback is None:
                raise ValueError(
                    f"checkpoint sidecar for {path} has no "
                    "datamodule.lookback_window; cannot size programs"
                )
            if spec0 is None:
                spec0, lookback0 = spec, int(lookback)
            elif spec != spec0 or int(lookback) != lookback0:
                raise LaneMismatchError(
                    f"lane {i} ({path}) spec/lookback differs from lane 0 "
                    "— stacked serving requires identical architectures"
                )
            params_list.append(params)
        return cls(
            spec0,
            params_list,
            n_stocks=n_stocks,
            lookback=lookback0,
            n_features=n_features,
            buckets=buckets,
            mesh=mesh,
            program_cache=program_cache,
            lanes=lanes,
        )
