"""``python -m masters_thesis_tpu.serve`` — serving gates.

Subcommands:

- ``selfcheck`` — hermetic, JAX-FREE smoke of the request path: the real
  queue + admission control + dispatch loop + deadline enforcement +
  canary verdict + breaker/degradation policy, driven with a fake engine.
  Runs on operator machines where touching the backend can hang on a
  wedged relay lease (docs/OPERATIONS.md). Exit 1 on any failure; the
  tools/check.sh serve gate.
- ``preflight`` — the serve twin of tracelint Pass 2 on a hermetic
  8-device virtual CPU mesh: every bucket compiles exactly once, zero
  compile delta in steady state, hot path clean under
  ``transfer_guard("disallow")`` (SV301–SV304), plus the fleet-era rules:
  warm program-cache boot performs zero compiles (SV305), a single
  injected replica death leaves >= 1 serving replica with every request
  explicitly resolved (SV306), stacked multi-tenant serving compiles one
  program per bucket regardless of lane count (SV307), and a per-lane
  hot-swap is zero-compile with zero late answers (SV308). Exit 1 on
  findings; the other tools/check.sh serve gate.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


class _FakeEngine:
    """Backend-free engine stand-in for the selfcheck: obeys the engine
    protocol (warmup/predict/degrade_to_cpu/window_shape/...) with a
    configurable service time and failure script."""

    def __init__(self, service_s: float = 0.001, buckets=(1, 2, 4)):
        import numpy as np

        self._np = np
        self.service_s = service_s
        self.buckets = tuple(buckets)
        self.window_shape = (2, 3, 1)
        self.max_bucket = self.buckets[-1]
        self.compile_events = len(self.buckets)
        self.cache_hits = 0
        self.platform = "fake"
        self.fail_next = 0  # raise on the next N predict calls
        self.degraded = False

    def warmup(self) -> float:
        return self.service_s

    def predict(self, x, params=None):
        time.sleep(self.service_s)
        if self.fail_next > 0:
            self.fail_next -= 1
            raise RuntimeError("scripted device failure")
        n = x.shape[0]
        k = self.window_shape[0]
        return (
            self._np.zeros((n, k), self._np.float32),
            self._np.zeros((n, k), self._np.float32),
        )

    def degrade_to_cpu(self) -> None:
        self.degraded = True
        self.fail_next = 0


class _FakeStackedEngine(_FakeEngine):
    """Stacked-engine stand-in: per-lane ``(n, R, K)`` outputs, so the
    selfcheck can prove the queue/server plumbing is lane-shape-agnostic
    without importing jax."""

    def __init__(self, lanes: int = 3, **kw):
        super().__init__(**kw)
        self.num_lanes = lanes

    def predict(self, x, params=None):
        a, b = super().predict(x, params)
        a = self._np.repeat(a[:, None, :], self.num_lanes, axis=1)
        return a, a.copy()


class _StubHealth:
    """BackendHealth stand-in: a canned single-attempt probe decision."""

    def __init__(self, ok: bool):
        self._ok = ok
        self.calls = 0

    def ensure_responsive(self, single_attempt: bool = False, log=None):
        from masters_thesis_tpu.utils.backend_probe import HealthDecision

        self.calls += 1
        assert single_attempt, "serve must probe with single_attempt=True"
        return HealthDecision(
            ok=self._ok, degraded=not self._ok, attempts=1,
            detail="" if self._ok else "stubbed wedge",
            known_wedged=False, cached_age_s=None,
        )


def _selfcheck(args) -> int:
    import tempfile

    import numpy as np

    from masters_thesis_tpu.resilience import faults
    from masters_thesis_tpu.serve.queue import (
        STATUS_OK,
        STATUS_SHED,
    )
    from masters_thesis_tpu.serve.server import PredictServer
    from masters_thesis_tpu.serve.swap import canary_checks
    from masters_thesis_tpu.telemetry.run import TelemetryRun

    failures: list[str] = []
    window = np.zeros((2, 3, 1), np.float32)

    # 1. Happy path: generous deadlines, everything completes before them.
    engine = _FakeEngine(service_s=0.001)
    server = PredictServer(engine, max_wait_s=0.002)
    server.start()
    pending = [server.submit(window, deadline_s=5.0) for _ in range(10)]
    results = [p.result(timeout=10.0) for p in pending]
    server.stop()
    if not all(r.status == STATUS_OK for r in results):
        failures.append(
            "happy path: statuses "
            f"{sorted({r.status for r in results})} != ['ok']"
        )
    if any(r.delivered_ts > p.request.deadline_ts
           for p, r in zip(pending, results)):
        failures.append("happy path: a response was delivered past its "
                        "deadline")

    # 2. Overload: slow engine + tight deadlines -> explicit sheds, zero
    #    late ok-deliveries, every request resolved.
    engine = _FakeEngine(service_s=0.02, buckets=(1, 2))
    server = PredictServer(engine, max_wait_s=0.001)
    server.start()
    pending = [server.submit(window, deadline_s=0.05) for _ in range(20)]
    results = [p.result(timeout=10.0) for p in pending]
    stats = server.stop()
    if stats["shed"] + stats["late_converted"] == 0:
        failures.append(
            f"overload: nothing was shed or rejected ({stats})"
        )
    if stats["late_deliveries"] != 0:
        failures.append(
            f"overload: {stats['late_deliveries']} late ok-deliveries"
        )
    for p, r in zip(pending, results):
        if r.status == STATUS_OK and r.delivered_ts > p.request.deadline_ts:
            failures.append("overload: ok response delivered late")
            break

    # 3. Forced shed via the serve.admit fault point (the chaos-suite
    #    mechanism, minus jax).
    plan = faults.FaultPlan.parse(
        '{"faults": [{"point": "serve.admit", "kind": "wedge",'
        ' "attempt": null}]}'
    )
    faults.install_plan(plan)
    try:
        engine = _FakeEngine()
        server = PredictServer(engine)
        server.start()
        r = server.submit(window, deadline_s=5.0).result(timeout=5.0)
        server.stop()
        if r.status != STATUS_SHED or "fault" not in r.detail:
            failures.append(
                f"fault shed: got status={r.status!r} detail={r.detail!r}"
            )
    finally:
        faults.clear_plan()

    # 4. Canary verdict math (numpy-only core of the swap gate).
    ok_pair = (np.zeros((1, 2)), np.zeros((1, 2)))
    nan_pair = (np.full((1, 2), np.nan), np.zeros((1, 2)))
    big_pair = (np.full((1, 2), 1e9), np.zeros((1, 2)))
    if not canary_checks(ok_pair, ok_pair).ok:
        failures.append("canary: identical outputs rejected")
    if canary_checks(ok_pair, nan_pair).ok:
        failures.append("canary: NaN candidate accepted")
    if canary_checks(ok_pair, big_pair).ok:
        failures.append("canary: exploded candidate accepted")
    if canary_checks(ok_pair, (np.ones((1, 2)), np.zeros((1, 2))),
                     max_drift=0.5).ok:
        failures.append("canary: drift budget not enforced")

    # 5. Breaker + degradation policy with a stubbed failing probe: the
    #    scripted failures trip the breaker, ONE probe runs, the engine
    #    degrades, traffic recovers.
    with tempfile.TemporaryDirectory() as tmp:
        tel = TelemetryRun(tmp, run_id="serve-selfcheck")
        engine = _FakeEngine(service_s=0.001)
        health = _StubHealth(ok=False)
        server = PredictServer(
            engine, telemetry=tel, health=health, breaker_threshold=2,
            max_wait_s=0.001,
        )
        server.start()
        engine.fail_next = 2
        # Sequential submit/await: each failure must be its own dispatch,
        # so exactly two consecutive failures reach the breaker.
        for _ in range(2):
            server.submit(window, deadline_s=5.0).result(timeout=10.0)
        # Wait for the breaker->probe->degrade sequence to land.
        deadline = time.monotonic() + 5.0
        while not engine.degraded and time.monotonic() < deadline:
            time.sleep(0.005)
        ok_after = server.submit(window, deadline_s=5.0).result(timeout=10.0)
        stats = server.stop()
        tel.close()
        if health.calls != 1:
            failures.append(f"breaker: {health.calls} probes (wanted 1)")
        if not engine.degraded or stats["degradations"] != 1:
            failures.append(f"breaker: no degradation recorded ({stats})")
        if ok_after.status != STATUS_OK:
            failures.append(
                f"breaker: post-degrade request {ok_after.status!r}"
            )

    # 6. Fleet failover, jax-free: three fake replicas, one killed by an
    #    injected dispatch crash mid-traffic. Survivors absorb the
    #    re-dispatched work, the dead replica restarts a new generation,
    #    and not one answer is delivered late or silently dropped.
    from masters_thesis_tpu.resilience.supervisor import ReplicaRestartPolicy
    from masters_thesis_tpu.serve.fleet import FleetServer

    fleet = FleetServer(
        {f"r{i}": (lambda: _FakeEngine(service_s=0.002)) for i in range(3)},
        max_wait_s=0.002,
        hang_timeout_s=0.5,
        restart_policy=ReplicaRestartPolicy(backoff_s=0.01),
    )
    fleet.start()
    plan = faults.FaultPlan.parse(
        '{"faults": [{"point": "serve.replica_dispatch", "kind": "raise",'
        ' "attempt": null, "match": {"replica": "r1"}}]}'
    )
    faults.install_plan(plan)
    try:
        pending = [fleet.submit(window, deadline_s=2.0) for _ in range(30)]
        deadline = time.monotonic() + 5.0
        while (
            fleet.replicas["r1"].state != "dead"
            and time.monotonic() < deadline
        ):
            time.sleep(0.005)
        results = [p.result(timeout=10.0) for p in pending]
    finally:
        faults.clear_plan()
    stats = fleet.stop()
    if stats["deaths"] < 1:
        failures.append(f"fleet: injected crash never killed r1 ({stats})")
    if stats["n_live"] < 1 and stats["replicas"]["r1"]["generation"] < 2:
        failures.append(f"fleet: no survivor and no restart ({stats})")
    if stats["late_deliveries"] != 0:
        failures.append(
            f"fleet: {stats['late_deliveries']} late ok-deliveries"
        )
    bad = [r.status for r in results
           if r.status not in ("ok", "shed", "rejected_late")]
    if bad:
        failures.append(f"fleet: non-explicit outcomes {sorted(set(bad))}")

    # 7. Multi-tenant stacked serving, jax-free: a deadline-classed tenant
    #    submits WITHOUT a per-request deadline, a second tenant rides
    #    along, per-tenant accounting splits cleanly, and stacked
    #    (R, K)-per-window responses resolve through the unchanged
    #    dispatch loop.
    engine = _FakeStackedEngine(lanes=3, service_s=0.001)
    server = PredictServer(engine, max_wait_s=0.002)
    server.register_tenant("quant-a", deadline_s=5.0)
    server.start()
    pending = [server.submit(window, tenant="quant-a") for _ in range(6)]
    pending += [
        server.submit(window, deadline_s=5.0, tenant="quant-b")
        for _ in range(4)
    ]
    results = [p.result(timeout=10.0) for p in pending]
    try:
        server.submit(window, tenant="no-class")
        failures.append("tenancy: deadline-less submit for an unclassed "
                        "tenant was admitted")
    except ValueError:
        pass
    stats = server.stop()
    if not all(r.status == STATUS_OK for r in results):
        failures.append(
            "tenancy: statuses "
            f"{sorted({r.status for r in results})} != ['ok']"
        )
    lane_shapes = {r.outputs[0].shape for r in results if r.outputs}
    if lane_shapes != {(3, 2)}:
        failures.append(
            f"tenancy: stacked per-request outputs {lane_shapes} != "
            "{(3, 2)} (lanes, stocks)"
        )
    tstats = stats.get("tenants", {})
    if (
        tstats.get("quant-a", {}).get("admitted") != 6
        or tstats.get("quant-b", {}).get("admitted") != 4
    ):
        failures.append(f"tenancy: per-tenant admission split {tstats}")
    if stats.get("lanes") != 3:
        failures.append(f"tenancy: stats lanes {stats.get('lanes')} != 3")

    if failures:
        print("serve: selfcheck FAILED: " + "; ".join(failures))
        return 1
    print("serve: selfcheck ok")
    return 0


def _force_cpu_mesh(n_devices: int) -> None:
    """Virtual 8-device CPU mesh regardless of ambient plugins (same
    incantation as analysis/__main__.py — the audited invariants are
    properties of the compiled programs, not the backend)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")


def _preflight(args) -> int:
    _force_cpu_mesh(args.devices)
    from masters_thesis_tpu.analysis.findings import format_report
    from masters_thesis_tpu.serve.preflight import (
        run_fleet_preflight,
        run_program_cache_preflight,
        run_serve_preflight,
        run_stacked_preflight,
    )

    findings = run_serve_preflight(requests=args.requests)
    findings += run_program_cache_preflight()
    findings += run_fleet_preflight()
    findings += run_stacked_preflight(requests=args.requests)
    print(format_report(findings, as_json=args.json))
    if not findings and not args.json:
        print(
            "serve: preflight ok (zero recompiles, transfer-clean, "
            "warm-cache boot compile-free, fleet survives replica death, "
            "stacked lanes share one program per bucket, lane swap is "
            "zero-compile with zero late answers)"
        )
    return 1 if findings else 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m masters_thesis_tpu.serve",
        description="serving-engine gates: jax-free selfcheck + preflight",
    )
    sub = parser.add_subparsers(dest="command", required=True)
    p_check = sub.add_parser(
        "selfcheck",
        help="jax-free smoke of queue/admission/deadline/breaker/canary",
    )
    p_check.set_defaults(fn=_selfcheck)
    p_pre = sub.add_parser(
        "preflight",
        help="AOT predict-path audit on a virtual CPU mesh (SV301-SV303)",
    )
    p_pre.add_argument(
        "--devices", type=int, default=8, metavar="N",
        help="virtual CPU devices for the preflight mesh",
    )
    p_pre.add_argument(
        "--requests", type=int, default=12, metavar="N",
        help="steady-state requests driven through the hot path",
    )
    p_pre.add_argument(
        "--json", action="store_true", help="machine-readable findings"
    )
    p_pre.set_defaults(fn=_preflight)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
