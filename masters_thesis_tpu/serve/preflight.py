"""Serve preflight: prove the predict hot path cannot trace or transfer.

Tracelint's Pass-2 (analysis/traceaudit.py) audits the TRAINING epoch
program; this is its serving twin. It builds a hermetic engine on the
current mesh, warms every bucket, then drives a steady-state request
window of varied batch sizes and asserts the serving contract:

- **SV301** — compile accounting: warmup compiles exactly one executable
  per bucket, and the compile-event delta over the steady-state window is
  ZERO. AOT ``Compiled`` programs cannot retrace by construction; this
  catches the regression where predict falls back to a plain ``jax.jit``
  call (or a bucket is compiled lazily on the request path).
- **SV302** — the whole steady-state window runs under
  ``jax.transfer_guard("disallow")``: request I/O must be explicit
  ``device_put``/``device_get`` only; any implicit host touch raises.
- **SV303** — the preflight itself failed to run (infrastructure — a red
  check, never a silent green).
- **SV304** — memory admission: every bucket executable's
  ``memory_analysis()`` peak bytes must fit the backend's reported device
  memory, so an OOM-bound bucket config is refused here instead of at the
  first live request. Skipped (not failed) when the backend reports no
  budget (the virtual CPU mesh).

Sized to run in seconds on the 8-device virtual CPU mesh; the invariants
are properties of the compiled programs, not of the backend.
"""

from __future__ import annotations

import numpy as np

from masters_thesis_tpu.analysis.findings import Finding

PREFLIGHT_STOCKS = 4
PREFLIGHT_LOOKBACK = 8
PREFLIGHT_FEATURES = 3
PREFLIGHT_BUCKETS = (1, 2, 4, 8)
PREFLIGHT_REQUESTS = 12


class ServePreflightError(RuntimeError):
    """Raised by :func:`assert_serve_clean` when the preflight finds
    violations of the serving contract."""

    def __init__(self, findings: list[Finding]):
        self.findings = findings
        super().__init__(
            "serve preflight failed:\n"
            + "\n".join(f.format() for f in findings)
        )


def run_serve_preflight(
    spec=None,
    mesh=None,
    buckets=PREFLIGHT_BUCKETS,
    requests: int = PREFLIGHT_REQUESTS,
) -> list[Finding]:
    """Build a hermetic engine and audit its hot path; [] when clean."""
    try:
        return _run(spec, mesh, buckets, requests)
    except Exception as exc:  # noqa: BLE001 — SV303 carries the cause
        return [
            Finding(
                rule="SV303",
                message=f"preflight could not run: "
                f"{type(exc).__name__}: {exc}",
            )
        ]


def _run(spec, mesh, buckets, requests) -> list[Finding]:
    import jax
    import jax.numpy as jnp

    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.serve.engine import PredictEngine

    findings: list[Finding] = []
    if spec is None:
        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
            kernel_impl="xla",
        )
    module = spec.build_module()
    dummy = jnp.zeros((1, PREFLIGHT_LOOKBACK, PREFLIGHT_FEATURES),
                      jnp.float32)
    params = module.init(jax.random.key(0), dummy)["params"]
    engine = PredictEngine(
        spec, params,
        n_stocks=PREFLIGHT_STOCKS,
        lookback=PREFLIGHT_LOOKBACK,
        n_features=PREFLIGHT_FEATURES,
        buckets=buckets,
        mesh=mesh,
    )

    engine.warmup()
    if engine.compile_events != len(engine.buckets):
        findings.append(
            Finding(
                rule="SV301",
                message=f"warmup compiled {engine.compile_events} "
                f"executables for {len(engine.buckets)} buckets "
                f"{engine.buckets} (expected exactly one per bucket)",
            )
        )

    # SV304 — memory admission: hold every bucket's compiler-reported peak
    # bytes against the device memory budget. No budget reported (virtual
    # CPU mesh) = no check; a missing profile is CP401's department, not a
    # serve failure.
    from masters_thesis_tpu.telemetry.costs import device_memory_budget

    budget = device_memory_budget(engine.mesh)
    if budget:
        for b in engine.buckets:
            payload = engine.cost_profiles.get(b) or {}
            peak = payload.get("peak_bytes")
            if peak is not None and peak > budget:
                findings.append(
                    Finding(
                        rule="SV304",
                        message=f"bucket {b} peak memory {peak} bytes "
                        f"exceeds the device budget {budget} bytes — this "
                        "bucket would OOM at first request; shrink the "
                        "bucket or the model before serving",
                    )
                )

    # Steady-state window: request sizes sweep every bucket boundary
    # (exact fits and pad-to-bucket), inputs pre-generated on the host.
    rng = np.random.default_rng(0)
    sizes = [1 + (i % engine.max_bucket) for i in range(requests)]
    k, t, f = engine.window_shape
    inputs = [
        rng.standard_normal((n, k, t, f)).astype(np.float32) for n in sizes
    ]
    baseline = engine.compile_events
    alpha = beta = np.zeros((1,), np.float32)
    try:
        with jax.transfer_guard("disallow"):
            for x in inputs:
                alpha, beta = engine.predict(x)
    except Exception as exc:  # noqa: BLE001 — the guard raises plain errors
        findings.append(
            Finding(
                rule="SV302",
                message=f"implicit host transfer in the serve hot path: "
                f"{exc}",
            )
        )
    delta = engine.compile_events - baseline
    if delta:
        findings.append(
            Finding(
                rule="SV301",
                message=f"steady-state serving compiled {delta} new "
                f"executable(s) over {requests} varied-size requests "
                "(expected 0 — serving must never trace)",
            )
        )
    if not np.isfinite(alpha).all() or not np.isfinite(beta).all():
        findings.append(
            Finding(
                rule="SV303",
                message="preflight predictions are non-finite on random "
                "inputs (engine wiring is broken)",
            )
        )
    return findings


def assert_serve_clean(**kwargs) -> None:
    """Gate form: raise :class:`ServePreflightError` on any finding."""
    findings = run_serve_preflight(**kwargs)
    if findings:
        raise ServePreflightError(findings)
