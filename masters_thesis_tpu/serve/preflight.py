"""Serve preflight: prove the predict hot path cannot trace or transfer.

Tracelint's Pass-2 (analysis/traceaudit.py) audits the TRAINING epoch
program; this is its serving twin. It builds a hermetic engine on the
current mesh, warms every bucket, then drives a steady-state request
window of varied batch sizes and asserts the serving contract:

- **SV301** — compile accounting: warmup compiles exactly one executable
  per bucket, and the compile-event delta over the steady-state window is
  ZERO. AOT ``Compiled`` programs cannot retrace by construction; this
  catches the regression where predict falls back to a plain ``jax.jit``
  call (or a bucket is compiled lazily on the request path).
- **SV302** — the whole steady-state window runs under
  ``jax.transfer_guard("disallow")``: request I/O must be explicit
  ``device_put``/``device_get`` only; any implicit host touch raises.
- **SV303** — the preflight itself failed to run (infrastructure — a red
  check, never a silent green).
- **SV304** — memory admission: every bucket executable's
  ``memory_analysis()`` peak bytes must fit the backend's reported device
  memory, so an OOM-bound bucket config is refused here instead of at the
  first live request. Skipped (not failed) when the backend reports no
  budget (the virtual CPU mesh).
- **SV305** — warm-cache boot: an engine booting against a program cache
  another engine just populated performs ZERO compiles (measured through
  the same ``CompileTracker`` accounting the telemetry uses), hits the
  cache once per bucket, and produces bitwise-identical predictions to
  the engine that stored the entries. A silent fallback to compiling —
  or a deserialized program that computes differently — fails here, not
  in production.
- **SV306** — single-death survival: a small fleet with one replica
  killed by an injected dispatch crash must keep >= 1 serving replica,
  resolve every in-flight request explicitly (ok / shed /
  rejected_late — zero silent drops), and deliver zero late answers.
- **SV307** — stacked serving compiles ONE program per bucket regardless
  of the lane count R, and the compiled HLO is structurally R-invariant
  (the lane axis is scanned, never unrolled into R copies of the model).
- **SV308** — a per-lane hot-swap under steady-state load causes zero
  new compiles and zero late answers, and sibling lanes keep answering
  bitwise-identically through the identical executable.

Sized to run in seconds on the 8-device virtual CPU mesh; the invariants
are properties of the compiled programs, not of the backend.
"""

from __future__ import annotations

import numpy as np

from masters_thesis_tpu.analysis.findings import Finding

PREFLIGHT_STOCKS = 4
PREFLIGHT_LOOKBACK = 8
PREFLIGHT_FEATURES = 3
PREFLIGHT_BUCKETS = (1, 2, 4, 8)
PREFLIGHT_REQUESTS = 12


class ServePreflightError(RuntimeError):
    """Raised by :func:`assert_serve_clean` when the preflight finds
    violations of the serving contract."""

    def __init__(self, findings: list[Finding]):
        self.findings = findings
        super().__init__(
            "serve preflight failed:\n"
            + "\n".join(f.format() for f in findings)
        )


def run_serve_preflight(
    spec=None,
    mesh=None,
    buckets=PREFLIGHT_BUCKETS,
    requests: int = PREFLIGHT_REQUESTS,
) -> list[Finding]:
    """Build a hermetic engine and audit its hot path; [] when clean."""
    try:
        return _run(spec, mesh, buckets, requests)
    except Exception as exc:  # noqa: BLE001 — SV303 carries the cause
        return [
            Finding(
                rule="SV303",
                message=f"preflight could not run: "
                f"{type(exc).__name__}: {exc}",
            )
        ]


def _run(spec, mesh, buckets, requests) -> list[Finding]:
    import jax
    import jax.numpy as jnp

    from masters_thesis_tpu.models.objectives import ModelSpec
    from masters_thesis_tpu.serve.engine import PredictEngine

    findings: list[Finding] = []
    if spec is None:
        spec = ModelSpec(
            objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
            kernel_impl="xla",
        )
    module = spec.build_module()
    dummy = jnp.zeros((1, PREFLIGHT_LOOKBACK, PREFLIGHT_FEATURES),
                      jnp.float32)
    params = module.init(jax.random.key(0), dummy)["params"]
    engine = PredictEngine(
        spec, params,
        n_stocks=PREFLIGHT_STOCKS,
        lookback=PREFLIGHT_LOOKBACK,
        n_features=PREFLIGHT_FEATURES,
        buckets=buckets,
        mesh=mesh,
    )

    engine.warmup()
    if engine.compile_events != len(engine.buckets):
        findings.append(
            Finding(
                rule="SV301",
                message=f"warmup compiled {engine.compile_events} "
                f"executables for {len(engine.buckets)} buckets "
                f"{engine.buckets} (expected exactly one per bucket)",
            )
        )

    # SV304 — memory admission: hold every bucket's compiler-reported peak
    # bytes against the device memory budget. No budget reported (virtual
    # CPU mesh) = no check; a missing profile is CP401's department, not a
    # serve failure.
    from masters_thesis_tpu.telemetry.costs import device_memory_budget

    budget = device_memory_budget(engine.mesh)
    if budget:
        for b in engine.buckets:
            payload = engine.cost_profiles.get(b) or {}
            peak = payload.get("peak_bytes")
            if peak is not None and peak > budget:
                findings.append(
                    Finding(
                        rule="SV304",
                        message=f"bucket {b} peak memory {peak} bytes "
                        f"exceeds the device budget {budget} bytes — this "
                        "bucket would OOM at first request; shrink the "
                        "bucket or the model before serving",
                    )
                )

    # Steady-state window: request sizes sweep every bucket boundary
    # (exact fits and pad-to-bucket), inputs pre-generated on the host.
    rng = np.random.default_rng(0)
    sizes = [1 + (i % engine.max_bucket) for i in range(requests)]
    k, t, f = engine.window_shape
    inputs = [
        rng.standard_normal((n, k, t, f)).astype(np.float32) for n in sizes
    ]
    baseline = engine.compile_events
    alpha = beta = np.zeros((1,), np.float32)
    try:
        with jax.transfer_guard("disallow"):
            for x in inputs:
                alpha, beta = engine.predict(x)
    except Exception as exc:  # noqa: BLE001 — the guard raises plain errors
        findings.append(
            Finding(
                rule="SV302",
                message=f"implicit host transfer in the serve hot path: "
                f"{exc}",
            )
        )
    delta = engine.compile_events - baseline
    if delta:
        findings.append(
            Finding(
                rule="SV301",
                message=f"steady-state serving compiled {delta} new "
                f"executable(s) over {requests} varied-size requests "
                "(expected 0 — serving must never trace)",
            )
        )
    if not np.isfinite(alpha).all() or not np.isfinite(beta).all():
        findings.append(
            Finding(
                rule="SV303",
                message="preflight predictions are non-finite on random "
                "inputs (engine wiring is broken)",
            )
        )
    return findings


def _preflight_spec():
    from masters_thesis_tpu.models.objectives import ModelSpec

    return ModelSpec(
        objective="mse", hidden_size=8, num_layers=1, dropout=0.0,
        kernel_impl="xla",
    )


def _preflight_params(spec):
    import jax
    import jax.numpy as jnp

    module = spec.build_module()
    dummy = jnp.zeros(
        (1, PREFLIGHT_LOOKBACK, PREFLIGHT_FEATURES), jnp.float32
    )
    return module.init(jax.random.key(0), dummy)["params"]


def run_program_cache_preflight(
    spec=None, mesh=None, buckets=(1, 2), cache_dir=None
) -> list[Finding]:
    """SV305 — warm program-cache boot performs zero compiles."""
    try:
        return _run_program_cache(spec, mesh, buckets, cache_dir)
    except Exception as exc:  # noqa: BLE001 — SV303 carries the cause
        return [
            Finding(
                rule="SV303",
                message=f"program-cache preflight could not run: "
                f"{type(exc).__name__}: {exc}",
            )
        ]


def _run_program_cache(spec, mesh, buckets, cache_dir) -> list[Finding]:
    import tempfile

    from masters_thesis_tpu.serve.engine import PredictEngine
    from masters_thesis_tpu.serve.program_cache import ProgramCache
    from masters_thesis_tpu.telemetry.run import CompileTracker

    findings: list[Finding] = []
    spec = spec or _preflight_spec()
    params = _preflight_params(spec)

    def build(cache):
        return PredictEngine(
            spec, params,
            n_stocks=PREFLIGHT_STOCKS,
            lookback=PREFLIGHT_LOOKBACK,
            n_features=PREFLIGHT_FEATURES,
            buckets=buckets,
            mesh=mesh,
            program_cache=cache,
        )

    with tempfile.TemporaryDirectory() as tmp:
        root = cache_dir or tmp
        cold = build(ProgramCache(root))
        cold.warmup()
        warm_cache = ProgramCache(root)
        warm = build(warm_cache)
        tracker = CompileTracker(warm)
        warm.warmup()
        delta = tracker.poll()
        if delta != 0:
            rejections = [
                e for e in warm_cache.events
                if e["kind"] == "cache_rejected"
            ]
            findings.append(
                Finding(
                    rule="SV305",
                    message=f"warm-cache boot compiled {delta} "
                    f"executable(s) for buckets {warm.buckets} (expected "
                    f"0 — every program must load from the cache); "
                    f"rejections: {rejections or 'none'}",
                )
            )
        if warm.cache_hits != len(warm.buckets):
            findings.append(
                Finding(
                    rule="SV305",
                    message=f"warm-cache boot hit the cache "
                    f"{warm.cache_hits} time(s) for {len(warm.buckets)} "
                    f"buckets (expected one hit per bucket)",
                )
            )
        x = cold.golden_batch(min(2, max(buckets)), seed=11)
        a_cold, b_cold = cold.predict(x)
        a_warm, b_warm = warm.predict(x)
        if not (
            np.array_equal(a_cold, a_warm)
            and np.array_equal(b_cold, b_warm)
        ):
            findings.append(
                Finding(
                    rule="SV305",
                    message="cache-loaded executables do not reproduce "
                    "the storing engine's predictions bitwise — the "
                    "deserialized program is not the program that was "
                    "serialized",
                )
            )
    return findings


def run_fleet_preflight(
    spec=None, n_replicas: int = 2, buckets=(1, 2), requests: int = 24
) -> list[Finding]:
    """SV306 — the fleet survives any single injected replica death."""
    try:
        return _run_fleet(spec, n_replicas, buckets, requests)
    except Exception as exc:  # noqa: BLE001 — SV303 carries the cause
        return [
            Finding(
                rule="SV303",
                message=f"fleet preflight could not run: "
                f"{type(exc).__name__}: {exc}",
            )
        ]


def _run_fleet(spec, n_replicas, buckets, requests) -> list[Finding]:
    import time

    from masters_thesis_tpu.resilience import faults
    from masters_thesis_tpu.resilience.supervisor import ReplicaRestartPolicy
    from masters_thesis_tpu.serve.engine import PredictEngine
    from masters_thesis_tpu.serve.fleet import FleetServer, partition_meshes

    findings: list[Finding] = []
    spec = spec or _preflight_spec()
    params = _preflight_params(spec)
    meshes = partition_meshes(n_replicas)

    def factory_for(m):
        return lambda: PredictEngine(
            spec, params,
            n_stocks=PREFLIGHT_STOCKS,
            lookback=PREFLIGHT_LOOKBACK,
            n_features=PREFLIGHT_FEATURES,
            buckets=buckets,
            mesh=m,
        )

    fleet = FleetServer(
        {f"r{i}": factory_for(m) for i, m in enumerate(meshes)},
        max_wait_s=0.003,
        hang_timeout_s=2.0,
        restart_policy=ReplicaRestartPolicy(backoff_s=0.01),
    )
    victim = "r0"
    plan = faults.FaultPlan(
        faults=[
            faults.FaultSpec(
                point="serve.replica_dispatch", kind="raise",
                attempt=None, match={"replica": victim},
            )
        ]
    )
    rng = np.random.default_rng(0)
    k, t, f = PREFLIGHT_STOCKS, PREFLIGHT_LOOKBACK, PREFLIGHT_FEATURES
    try:
        fleet.start()
        faults.install_plan(plan)
        pendings = [
            fleet.submit(
                rng.standard_normal((k, t, f)).astype(np.float32),
                deadline_s=2.0,
            )
            for _ in range(requests)
        ]
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if fleet.replicas[victim].state == "dead":
                break
            time.sleep(0.01)
        faults.clear_plan()
        unresolved = 0
        for p in pendings:
            try:
                p.result(timeout=10.0)
            except TimeoutError:
                unresolved += 1
        # Capture liveness BEFORE stop(): draining is the shutdown state,
        # not a failover outcome.
        survivors = [r.name for r in fleet._serving()]
        stats = fleet.stop()
    finally:
        faults.clear_plan()
    if stats["deaths"] < 1:
        findings.append(
            Finding(
                rule="SV306",
                message="the injected dispatch crash never killed the "
                f"victim replica ({victim}) — the preflight did not "
                "exercise failover",
            )
        )
    if not survivors:
        findings.append(
            Finding(
                rule="SV306",
                message=f"no serving replica survived a single injected "
                f"replica death (states: "
                f"{ {n: r['state'] for n, r in stats['replicas'].items()} })",
            )
        )
    if unresolved:
        findings.append(
            Finding(
                rule="SV306",
                message=f"{unresolved} request(s) were silently dropped "
                "after the replica death (every request must resolve "
                "explicitly: ok, shed, or rejected_late)",
            )
        )
    if stats["late_deliveries"]:
        findings.append(
            Finding(
                rule="SV306",
                message=f"{stats['late_deliveries']} ok response(s) "
                "delivered past their deadline during failover (the "
                "no-late-answers invariant must hold fleet-wide)",
            )
        )
    return findings


def _hlo_fingerprint(text: str) -> dict:
    """Structural fingerprint of a compiled program's HLO text.

    The stacked predict program scans over the lane axis, so its compiled
    shape must be R-invariant up to the lane-dim literals embedded in
    shape annotations: same line count, same dot/while/fusion op counts.
    Per-lane unrolling (a vmap-style batching regression, or a Python
    loop over lanes leaking into the trace) scales these with R and
    fails the comparison loudly.
    """
    lines = text.splitlines()
    return {
        "lines": len(lines),
        "dots": sum(l.count(" dot(") + l.count("= dot(") for l in lines),
        "whiles": sum("while(" in l or " while " in l for l in lines),
        "fusions": sum("fusion(" in l for l in lines),
    }


def run_stacked_preflight(
    spec=None,
    mesh=None,
    buckets=(1, 2),
    lane_counts=(2, 4),
    requests: int = 12,
) -> list[Finding]:
    """SV307/SV308 — multi-tenant stacked serving contract.

    - **SV307** — one program per bucket regardless of R: a stacked
      engine's warmup compiles exactly ``len(buckets)`` executables at
      EVERY lane count, and the compiled HLO is structurally R-invariant
      (no per-lane unrolling — lane count is a data dimension, never a
      program dimension).
    - **SV308** — lane hot-swap under steady-state load: swapping one
      lane's params mid-window causes ZERO new compiles and ZERO late
      answers; sibling lanes keep answering bitwise-identically.
    """
    try:
        return _run_stacked(spec, mesh, buckets, lane_counts, requests)
    except Exception as exc:  # noqa: BLE001 — SV303 carries the cause
        return [
            Finding(
                rule="SV303",
                message=f"stacked preflight could not run: "
                f"{type(exc).__name__}: {exc}",
            )
        ]


def _run_stacked(spec, mesh, buckets, lane_counts, requests) -> list[Finding]:
    import jax

    from masters_thesis_tpu.serve.server import PredictServer
    from masters_thesis_tpu.serve.stacked import StackedPredictEngine

    findings: list[Finding] = []
    spec = spec or _preflight_spec()
    lane_counts = tuple(sorted(set(int(r) for r in lane_counts)))
    max_r = max(lane_counts)

    import jax.numpy as jnp

    module = spec.build_module()
    dummy = jnp.zeros(
        (1, PREFLIGHT_LOOKBACK, PREFLIGHT_FEATURES), jnp.float32
    )
    params = [
        module.init(jax.random.key(seed), dummy)["params"]
        for seed in range(max_r + 1)
    ]

    def build(r):
        return StackedPredictEngine(
            spec, params[:r],
            n_stocks=PREFLIGHT_STOCKS,
            lookback=PREFLIGHT_LOOKBACK,
            n_features=PREFLIGHT_FEATURES,
            buckets=buckets,
            mesh=mesh,
        )

    # SV307 — compile accounting + HLO shape across lane counts.
    fingerprints: dict[int, dict[int, dict]] = {}
    engines: dict[int, StackedPredictEngine] = {}
    for r in lane_counts:
        eng = build(r)
        eng.warmup()
        engines[r] = eng
        if eng.compile_events != len(eng.buckets):
            findings.append(
                Finding(
                    rule="SV307",
                    message=f"R={r}: warmup compiled {eng.compile_events} "
                    f"executables for {len(eng.buckets)} buckets "
                    f"{eng.buckets} (expected exactly one per bucket — "
                    "lane count must not multiply programs)",
                )
            )
        fingerprints[r] = {
            b: _hlo_fingerprint(eng.compiled_text(b)) for b in eng.buckets
        }
    base_r = lane_counts[0]
    for r in lane_counts[1:]:
        for b in fingerprints[base_r]:
            if fingerprints[r].get(b) != fingerprints[base_r][b]:
                findings.append(
                    Finding(
                        rule="SV307",
                        message=f"bucket {b}: compiled HLO shape changed "
                        f"with lane count (R={base_r}: "
                        f"{fingerprints[base_r][b]} vs R={r}: "
                        f"{fingerprints[r][b]}) — the stacked program is "
                        "unrolling per lane instead of scanning the lane "
                        "axis",
                    )
                )

    # SV308 — lane swap under a live serving window.
    eng = engines[max_r]
    server = PredictServer(eng, max_wait_s=0.003)
    rng = np.random.default_rng(0)
    k, t, f = eng.window_shape
    swap_lane = max_r - 1
    gx = eng.golden_batch(min(2, eng.max_bucket), seed=5)
    pre_a, pre_b = eng.predict(gx)
    try:
        server.start()
        baseline = eng.compile_events
        pendings = []
        for i in range(requests):
            if i == requests // 2:
                eng.set_lane(swap_lane, params[max_r])
            pendings.append(
                server.submit(
                    rng.standard_normal((k, t, f)).astype(np.float32),
                    deadline_s=2.0,
                )
            )
        for p in pendings:
            p.result(timeout=10.0)
        stats = server.stop()
    except Exception:
        server.stop()
        raise
    delta = eng.compile_events - baseline
    if delta:
        findings.append(
            Finding(
                rule="SV308",
                message=f"lane hot-swap compiled {delta} new "
                "executable(s) — a lane swap is a row write into the "
                "stacked buffers and must never retrace",
            )
        )
    if stats["late_deliveries"]:
        findings.append(
            Finding(
                rule="SV308",
                message=f"{stats['late_deliveries']} ok response(s) "
                "delivered past their deadline across the lane swap "
                "(the no-late-answers invariant must hold through "
                "per-lane swaps)",
            )
        )
    post_a, post_b = eng.predict(gx)
    sibling_moved = [
        r for r in range(max_r)
        if r != swap_lane
        and not (
            np.array_equal(pre_a[:, r, :], post_a[:, r, :])
            and np.array_equal(pre_b[:, r, :], post_b[:, r, :])
        )
    ]
    if sibling_moved:
        findings.append(
            Finding(
                rule="SV308",
                message=f"lane swap on lane {swap_lane} moved sibling "
                f"lane(s) {sibling_moved} — per-lane isolation must be "
                "bitwise through the identical executable",
            )
        )
    return findings


def assert_serve_clean(**kwargs) -> None:
    """Gate form: raise :class:`ServePreflightError` on any finding."""
    findings = run_serve_preflight(**kwargs)
    if findings:
        raise ServePreflightError(findings)
