"""Per-request span bookkeeping shared by the server and the fleet.

One contract, one implementation: a request span is opened at submit and
closed exactly once with components that TILE its wall clock —

    admit_s + queue_s + batch_form_s + device_s + deliver_s == dur_s

(the trace CLI's critical-path breakdown sums to measured latency by
construction, and ``telemetry trace`` asserts it). The boundaries are:

    t0 (submit) -> t_admitted -> t_pickup -> t_predict0 -> t_predict_end
    -> t_resolve

Missing boundaries (a shed never reaches the engine; a rejected-late
request never reaches the device) collapse to zero-width components, and
boundaries are forced monotone so a stamp race between the submit and
dispatch threads can never produce a negative component.

Extracted from server.py so the fleet server (fleet.py) reuses the exact
same tiling instead of approximating it: a request re-dispatched across
replicas keeps ONE span whose components still tile, with the hop
recorded as a ``redispatched_from`` attribute via :meth:`annotate`.

Jax-free by contract (the selfcheck CLI drives the serving loop with a
fake engine on hosts where touching the backend can hang).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable


class RequestSpans:
    """rid -> open span + boundary stamps; thread-safe.

    ``tracer_fn`` is called at every operation (not once) because the
    server may run without telemetry — every method is a cheap no-op when
    it returns ``None``.
    """

    #: Interior boundaries in tiling order (t0 and t_resolve bracket them).
    BOUNDARIES = ("t_admitted", "t_pickup", "t_predict0", "t_predict_end")

    def __init__(self, tracer_fn: Callable[[], object | None]):
        self._tracer_fn = tracer_fn
        self._lock = threading.Lock()
        self._entries: dict[int, dict] = {}
        # ok-request component sums: the server's queue_wait_share /
        # compute_share stats (and the bench's saturation diagnosis).
        self._sum_queue_s = 0.0
        self._sum_device_s = 0.0
        self._sum_req_wall_s = 0.0

    def _tracer(self):
        return self._tracer_fn()

    @property
    def active(self) -> bool:
        return self._tracer() is not None

    def open(self, rid: int, name: str, *, parent=None, **attrs) -> None:
        """Start the request span. Must run BEFORE queue submit: a shed
        resolves synchronously inside submit and closes the span."""
        tracer = self._tracer()
        if tracer is None:
            return
        entry = {
            "span": tracer.start(name, parent=parent, rid=rid, **attrs),
            "t0": time.perf_counter(),
        }
        with self._lock:
            self._entries[rid] = entry

    def stamp(self, rid: int, key: str, t: float | None = None) -> None:
        if self._tracer() is None:
            return
        t = time.perf_counter() if t is None else t
        with self._lock:
            entry = self._entries.get(rid)
            if entry is not None:
                entry[key] = t

    def stamp_many(self, rids: Iterable[int], key: str, t: float) -> None:
        if self._tracer() is None:
            return
        with self._lock:
            for rid in rids:
                entry = self._entries.get(rid)
                if entry is not None:
                    entry[key] = t

    def annotate(self, rid: int, **attrs) -> None:
        """Attach attributes emitted when the span closes (the fleet marks
        re-dispatched requests with ``redispatched_from=<replica>``)."""
        with self._lock:
            entry = self._entries.get(rid)
            if entry is not None:
                entry.setdefault("attrs", {}).update(attrs)

    def close(self, rid: int, status: str, t_resolve: float, **attrs) -> None:
        """End the span with tiling components (see module docstring)."""
        tracer = self._tracer()
        if tracer is None:
            return
        with self._lock:
            entry = self._entries.pop(rid, None)
        if entry is None:
            return
        b = [entry["t0"]]
        for key in self.BOUNDARIES:
            t = entry.get(key)
            b.append(b[-1] if t is None else max(b[-1], t))
        b.append(max(b[-1], t_resolve))
        admit_s, queue_s, batch_form_s, device_s, deliver_s = (
            b[i + 1] - b[i] for i in range(5)
        )
        wall = b[-1] - b[0]
        if status == "ok":
            with self._lock:
                self._sum_queue_s += queue_s
                self._sum_device_s += device_s
                self._sum_req_wall_s += wall
        merged = {**entry.get("attrs", {}), **attrs}
        tracer.end(
            entry["span"],
            status=status,
            dur_s=wall,
            admit_s=admit_s,
            queue_s=queue_s,
            batch_form_s=batch_form_s,
            device_s=device_s,
            deliver_s=deliver_s,
            **merged,
        )

    def close_shed(self, rid: int, category: str) -> None:
        """End a span shed at admission: the whole wall is admit_s."""
        tracer = self._tracer()
        if tracer is None:
            return
        with self._lock:
            entry = self._entries.pop(rid, None)
        if entry is None:
            return
        tracer.end(
            entry["span"],
            status="shed",
            reason_category=category,
            admit_s=time.perf_counter() - entry["t0"],
            **entry.get("attrs", {}),
        )

    def shares(self) -> tuple[float | None, float | None]:
        """(queue_wait_share, compute_share) over ok requests, or Nones."""
        with self._lock:
            wall = self._sum_req_wall_s
            if wall <= 0:
                return None, None
            return self._sum_queue_s / wall, self._sum_device_s / wall
