"""Self-healing serving fleet: N engine replicas behind one deadline queue.

One engine on one mesh in one process (server.py) is the availability
ceiling ROADMAP item 2 calls out: any single replica loss is an outage.
This module grows that into a fleet —

- **Replicas own device subsets.** Each :class:`Replica` wraps one
  ``PredictEngine`` built by an injected factory (typically on a disjoint
  slice of the local mesh — :func:`partition_meshes`), with its OWN EWMA
  :class:`~masters_thesis_tpu.serve.queue.ServiceTimeModel`, its own
  circuit breaker, and its own worker thread.
- **Least-loaded dispatch.** The shared
  :class:`~masters_thesis_tpu.serve.queue.MicroBatchQueue` feeds a
  scheduler that assigns each micro-batch to the serving replica with the
  smallest estimated completion (its EWMA x its backlog) — a
  degraded-to-CPU replica keeps serving, it just stops winning batches.
- **Per-replica admission.** The queue's ``feasibility`` hook sheds a
  request at admit only when ALL serving replicas are infeasible for its
  deadline; one slow replica cannot poison admission for healthy ones
  (the satellite fix to the single global-model estimate).
- **Evidence-based failure handling.** Dispatch errors feed the replica's
  breaker (threshold trips buy ONE backend probe, then CPU degradation —
  the PR 5 policy, per replica). A crash (``FaultInjected`` or any
  unexpected exception), a hang (watchdog: ``busy_since`` stale), or a
  boot failure declares the replica DEAD; the
  :class:`~masters_thesis_tpu.resilience.supervisor.ReplicaRestartPolicy`
  classifies the death (transient -> restart with backoff; identical
  consecutive fingerprint or exhausted budget -> halt) and a restart
  boots a fresh engine generation — warm from the shared
  :class:`~masters_thesis_tpu.serve.program_cache.ProgramCache`, so a
  replica resurrection costs milliseconds, not a compile burst.
- **No late answers, fleet-wide.** A dead replica's in-flight and queued
  batches are re-dispatched to survivors when their deadlines still
  permit (span attribute ``redispatched_from`` marks the hop; the
  request keeps ONE span whose components still tile). Anything
  infeasible is explicitly shed/rejected — never silently dropped,
  never delivered late.

Jax-free at import (engines arrive via factories), so the selfcheck CLI
can drive the whole failover state machine with fake engines on a host
whose accelerator runtime is wedged.

Fault points: ``serve.replica_dispatch`` (wedge -> device error feeding
the breaker; corrupt/nan -> poisoned outputs; raise -> fatal crash; hang
-> watchdog kill; match ``{"replica": name}`` to target one replica) and
``serve.replica_boot`` (wedge/raise -> boot failure; the restart policy
classifies the repeat).
"""

from __future__ import annotations

import hashlib
import queue as stdqueue
import re
import threading
import time

import numpy as np

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.resilience.supervisor import ReplicaRestartPolicy
from masters_thesis_tpu.serve.queue import (
    DEFAULT_TENANT,
    STATUS_ERROR,
    STATUS_OK,
    STATUS_REJECTED_LATE,
    MicroBatchQueue,
    PendingRequest,
    ServeRequest,
    ServeResponse,
    ServiceTimeModel,
)
from masters_thesis_tpu.serve.server import InjectedDeviceError, shed_category
from masters_thesis_tpu.serve.spans import RequestSpans
from masters_thesis_tpu.utils.backend_probe import CircuitBreaker

#: Replica health states (evidence-driven, see module docstring).
STATE_LIVE = "live"
STATE_DEGRADED = "degraded"
STATE_DRAINING = "draining"
STATE_DEAD = "dead"
#: States that accept new batches.
SERVING_STATES = (STATE_LIVE, STATE_DEGRADED)


class ReplicaBootError(RuntimeError):
    """A replica engine failed to boot (wedged lease, injected fault)."""


def partition_meshes(n_replicas: int, devices=None) -> list:
    """Split the local devices into ``n_replicas`` disjoint data meshes.

    Lazy jax import — the only jax-touching helper in this module."""
    import jax
    from jax.sharding import Mesh

    from masters_thesis_tpu.parallel import DATA_AXIS

    devices = list(jax.devices()) if devices is None else list(devices)
    if n_replicas < 1 or n_replicas > len(devices):
        raise ValueError(
            f"cannot build {n_replicas} replicas from "
            f"{len(devices)} devices"
        )
    per = len(devices) // n_replicas
    return [
        Mesh(
            np.asarray(devices[i * per : (i + 1) * per]),
            axis_names=(DATA_AXIS,),
        )
        for i in range(n_replicas)
    ]


class Replica:
    """One engine slot: state + worker thread + its own load model."""

    def __init__(self, name: str, engine_factory, breaker_threshold: int = 3):
        self.name = name
        self.engine_factory = engine_factory
        self.engine = None
        self.service_model = ServiceTimeModel()
        self.breaker = CircuitBreaker(breaker_threshold)
        self._breaker_threshold = breaker_threshold
        self.state = STATE_DEAD  # not serving until booted
        self.halted = False
        self.generation = 0
        self.inbox: stdqueue.Queue = stdqueue.Queue()
        self.stop_event = threading.Event()
        self.thread: threading.Thread | None = None
        self.span = None
        #: Set while a batch is on the device — the hang watchdog's clock.
        self.busy_since: float | None = None
        self.current_batch: list[PendingRequest] | None = None
        self.completed = 0
        self.errors = 0
        self.busy_s = 0.0
        self.boot_s: float | None = None

    def backlog_estimate_s(self) -> float:
        """Seconds until a batch assigned NOW would complete here."""
        waiting = self.inbox.qsize() + (1 if self.busy_since else 0)  # mtt: disable=CL502 -- advisory estimate; a stale busy_since only skews replica choice
        return (waiting + 1) * self.service_model.batch_s


class FleetServer:
    """Owns the queue, the scheduler, N replicas, and the failover policy.

    ``engine_factories`` maps replica name -> zero-arg callable returning
    a warmed-up-able engine; each (re)boot calls the factory fresh, so a
    restart is a REAL re-instantiation (and, with a shared program cache,
    a zero-compile one).
    """

    def __init__(
        self,
        engine_factories: dict,
        *,
        max_batch: int = 8,
        max_wait_s: float = 0.005,
        max_depth: int = 256,
        telemetry=None,
        health=None,
        breaker_threshold: int = 3,
        restart_policy: ReplicaRestartPolicy | None = None,
        hang_timeout_s: float = 2.0,
        metrics_port: int | None = None,
        slo_rules=None,
        quality_monitor=None,
    ):
        if not engine_factories:
            raise ValueError("fleet needs at least one engine factory")
        self.telemetry = telemetry
        # Model-quality plane (telemetry/quality.py): one fleet-wide
        # 1-in-K sampler over *delivered* responses (its own lock makes
        # the per-replica dispatcher threads safe), fed strictly after
        # _resolve — never on the device path.
        self.quality = quality_monitor
        self.health = health
        # Live telemetry plane (telemetry/exposition.py): /metrics +
        # /slo over the fleet's registry. None disables; 0 binds an
        # ephemeral port. Reader-side only — never on the dispatch path.
        self.metrics_port = metrics_port
        self._slo_rules = slo_rules
        self._exposition = None
        self._slo_engine = None
        self.restart_policy = restart_policy or ReplicaRestartPolicy()
        self.hang_timeout_s = hang_timeout_s
        self.replicas: dict[str, Replica] = {
            name: Replica(name, factory, breaker_threshold)
            for name, factory in engine_factories.items()
        }
        self.queue = MicroBatchQueue(
            max_batch=max_batch,
            max_wait_s=max_wait_s,
            max_depth=max_depth,
            on_shed=self._on_shed,
            feasibility=self._feasibility,
        )
        self.spans = RequestSpans(self._tracer)
        self._lock = threading.RLock()
        self._fleet_span = None
        self._scheduler: threading.Thread | None = None
        self._monitor: threading.Thread | None = None
        self._boot_threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._rid = 0
        self._dispatch_seq = 0
        self._started_ts: float | None = None
        self._window_shape: tuple | None = None
        self.completed = 0
        self.errors = 0
        self.late_converted = 0
        #: ok responses delivered past deadline — 0 by construction.
        self.late_deliveries = 0
        self.degradations = 0
        self.deaths = 0
        self.redispatched = 0
        self.shed_by_reason: dict[str, int] = {}

    # ------------------------------------------------------------ telemetry

    def _tracer(self):
        return self.telemetry.tracer if self.telemetry is not None else None

    def _event(self, kind: str, **payload) -> None:
        if self.telemetry is not None:
            self.telemetry.event(kind, **payload)

    def _count(self, name: str, n: int = 1) -> None:
        if self.telemetry is not None:
            self.telemetry.counter(f"serve/{name}").inc(n)

    def _observe_latency(self, latency_s: float) -> None:
        if self.telemetry is not None:
            self.telemetry.histogram("serve/latency_s").observe(latency_s)

    # ------------------------------------------------------------ admission

    def _serving(self) -> list[Replica]:
        with self._lock:
            return [
                r for r in self.replicas.values()
                if r.state in SERVING_STATES
            ]

    def _feasibility(self, request: ServeRequest, depth: int) -> str | None:
        """Queue admission hook: shed only when EVERY serving replica's
        own estimate misses the deadline (satellite fix: per-replica
        models, not one global EWMA)."""
        serving = self._serving()
        if not serving:
            return "no live replicas (fleet dead or halted)"
        # Waiting queue depth spreads over the fleet; charge each replica
        # its backlog plus an even share of the unassigned queue.
        share = depth // max(1, len(serving) * self.queue.max_batch)
        best = min(
            r.backlog_estimate_s() + share * r.service_model.batch_s
            for r in serving
        )
        now = time.monotonic()
        if now + best > request.deadline_ts:
            budget_ms = (request.deadline_ts - now) * 1e3
            return (
                f"deadline infeasible on ALL {len(serving)} serving "
                f"replicas: best est {best * 1e3:.1f}ms > budget "
                f"{budget_ms:.1f}ms at depth {depth}"
            )
        return None

    def _on_shed(self, request: ServeRequest, reason: str) -> None:
        self._count("shed")
        category = shed_category(reason)
        with self._lock:
            self.shed_by_reason[category] = (
                self.shed_by_reason.get(category, 0) + 1
            )
        self._event("request_shed", rid=request.rid, reason=reason)
        self.spans.close_shed(request.rid, category)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._scheduler is not None:
            raise RuntimeError("fleet already started")
        tracer = self._tracer()
        if tracer is not None:
            self._fleet_span = tracer.start(
                "serve.fleet", replicas=sorted(self.replicas)
            )
        for replica in self.replicas.values():
            self._boot_replica(replica, initial=True)
        serving = self._serving()
        if not serving:
            raise RuntimeError(
                "fleet start failed: no replica survived boot"
            )
        # The fleet micro-batch can never exceed the smallest replica's
        # largest bucket — any replica must be able to take any batch.
        cap = min(r.engine.max_bucket for r in serving)
        self.queue.max_batch = min(self.queue.max_batch, cap)
        self._window_shape = tuple(serving[0].engine.window_shape)
        self._started_ts = time.monotonic()
        self._event(
            "fleet_started",
            replicas=sorted(self.replicas),
            serving=[r.name for r in serving],
            max_batch=self.queue.max_batch,
        )
        self._scheduler = threading.Thread(
            target=self._scheduler_loop, name="fleet-scheduler", daemon=True
        )
        self._scheduler.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True
        )
        self._monitor.start()
        if self.metrics_port is not None and self.telemetry is not None:
            from masters_thesis_tpu.telemetry.exposition import (
                start_telemetry_plane,
            )

            self._exposition, self._slo_engine = start_telemetry_plane(
                self.telemetry, self.metrics_port, rules=self._slo_rules
            )

    def stop(self) -> dict:
        if self._exposition is not None or self._slo_engine is not None:
            from masters_thesis_tpu.telemetry.exposition import (
                stop_telemetry_plane,
            )

            stop_telemetry_plane(self._exposition, self._slo_engine)
            self._exposition = self._slo_engine = None
        self.queue.close()
        with self._lock:
            for r in self.replicas.values():
                if r.state in SERVING_STATES:
                    r.state = STATE_DRAINING
        if self._scheduler is not None:
            self._scheduler.join(timeout=30.0)
            self._scheduler = None
        # An in-flight restart may be mid-compile; wait for it BEFORE the
        # worker sentinels so its fresh worker receives one too (and so
        # the interpreter never exits under a live XLA compile thread).
        for t in list(self._boot_threads):
            t.join(timeout=30.0)
        for r in self.replicas.values():
            if r.thread is not None:
                r.inbox.put(None)  # drain sentinel
                r.thread.join(timeout=30.0)
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        stats = self.stats()
        tracer = self._tracer()
        if tracer is not None:
            for r in self.replicas.values():
                if r.span is not None:
                    tracer.end(
                        r.span, status="ok",
                        completed=r.completed, busy_s=r.busy_s,  # mtt: disable=CL502 -- workers joined above; no concurrent writer remains
                    )
                    r.span = None
            if self._fleet_span is not None:
                tracer.end(
                    self._fleet_span, status="ok",
                    requests=stats["requests"],
                    completed=stats["completed"],
                    shed=stats["shed"],
                )
                self._fleet_span = None
        self._event("fleet_finished", **stats)
        return stats

    def stats(self) -> dict:
        span = (
            time.monotonic() - self._started_ts
            if self._started_ts is not None
            else 0.0
        )
        p50 = p99 = None
        if self.telemetry is not None:
            hist = self.telemetry.histogram("serve/latency_s")
            p50, p99 = hist.quantile(0.5), hist.quantile(0.99)
        queue_wait_share, compute_share = self.spans.shares()
        with self._lock:
            shed_by_reason = dict(self.shed_by_reason)
            per_replica = {
                r.name: {
                    "state": r.state,
                    "generation": r.generation,
                    "restarts": self.restart_policy.restarts(r.name),
                    "completed": r.completed,
                    "errors": r.errors,
                    "busy_s": r.busy_s,
                    "utilization": r.busy_s / span if span > 0 else 0.0,
                    "batch_ms": r.service_model.batch_s * 1e3,
                    "boot_s": r.boot_s,
                }
                for r in self.replicas.values()
            }
            # Fleet counters are mutated under this same lock; snapshot
            # them here so the returned dict is internally consistent.
            counters = {
                "completed": self.completed,
                "errors": self.errors,
                "late_converted": self.late_converted,
                "late_deliveries": self.late_deliveries,
                "degradations": self.degradations,
                "deaths": self.deaths,
                "redispatched": self.redispatched,
            }
        lanes = max(
            (
                getattr(r.engine, "num_lanes", 1)
                for r in self.replicas.values()
                if r.engine is not None
            ),
            default=1,
        )
        return {
            "replicas": per_replica,
            "n_live": sum(
                1 for v in per_replica.values()
                if v["state"] in SERVING_STATES
            ),
            "queue_wait_share": queue_wait_share,
            "compute_share": compute_share,
            "shed_by_reason": shed_by_reason,
            "tenants": self.queue.tenant_stats(),
            "lanes": lanes,
            "requests": self.queue.submitted,
            "shed": self.queue.shed,
            **counters,
            "p50_ms": None if p50 is None else p50 * 1e3,
            "p99_ms": None if p99 is None else p99 * 1e3,
            "qps": counters["completed"] / span if span > 0 else 0.0,
            "wall_s": span,
        }

    # -------------------------------------------------------------- request

    def register_tenant(
        self, name: str, deadline_s: float | None = None
    ) -> None:
        """Onboard (or re-class) a tenant fleet-wide; emits
        ``tenant_admitted`` the first time this fleet sees it."""
        _, created = self.queue.tenant(name, deadline_s)
        if created:
            self._event(
                "tenant_admitted",
                tenant=name,
                deadline_ms=(
                    None if deadline_s is None else deadline_s * 1e3
                ),
            )

    def submit(
        self,
        x,
        deadline_s: float | None = None,
        tenant: str = DEFAULT_TENANT,
    ) -> PendingRequest:
        x = np.asarray(x, np.float32)
        if self._window_shape is None:
            raise RuntimeError("fleet not started")
        if x.shape != self._window_shape:
            raise ValueError(
                f"request window shape {x.shape} != engine window shape "
                f"{self._window_shape}"
            )
        if deadline_s is None:
            deadline_s = self.queue.tenant_deadline_s(tenant)
            if deadline_s is None:
                raise ValueError(
                    f"request carries no deadline and tenant {tenant!r} "
                    "has no deadline class (register_tenant first)"
                )
        self.register_tenant(tenant)
        with self._lock:
            self._rid += 1
            rid = self._rid
        self._count("requests")
        # The span must exist BEFORE queue.submit: a shed resolves
        # synchronously inside it, and _on_shed closes the span.
        self.spans.open(
            rid, "serve.request",
            parent=self._fleet_span, deadline_ms=deadline_s * 1e3,
        )
        pending = self.queue.submit(
            ServeRequest(
                rid=rid, x=x, deadline_ts=time.monotonic() + deadline_s,
                tenant=tenant,
            )
        )
        if not pending.done:
            self.spans.stamp(rid, "t_admitted")
        return pending

    # ------------------------------------------------------------ scheduler

    def _scheduler_loop(self) -> None:
        while True:
            batch = self.queue.next_batch(timeout_s=0.05)
            if not batch:
                if self.queue.closed and len(self.queue) == 0:
                    return
                continue
            self.spans.stamp_many(
                [p.request.rid for p in batch], "t_pickup",
                time.perf_counter(),
            )
            self._assign(batch)

    def _pick_replica(self) -> Replica | None:
        """Least-loaded serving replica by ITS OWN completion estimate."""
        serving = self._serving()
        if not serving:
            return None
        return min(serving, key=lambda r: r.backlog_estimate_s())

    def _assign(self, batch: list[PendingRequest]) -> None:
        target = self._pick_replica()
        if target is None:
            for p in batch:
                if not p.done:
                    self.queue._shed(
                        p, "no live replicas (fleet dead or halted)"
                    )
            return
        target.inbox.put(batch)

    # --------------------------------------------------------------- worker

    def _worker_loop(self, replica: Replica, generation: int) -> None:
        while not replica.stop_event.is_set():
            try:
                batch = replica.inbox.get(timeout=0.05)
            except stdqueue.Empty:
                with self._lock:
                    drained = (
                        replica.state == STATE_DRAINING
                        and replica.inbox.empty()
                    )
                if drained:
                    return
                continue
            if batch is None:  # drain sentinel from stop()
                return
            with self._lock:
                if replica.generation != generation:
                    # A newer generation owns this replica; hand the work
                    # back to the scheduler rather than racing it.
                    self._assign([p for p in batch if not p.done])
                    return
                replica.busy_since = time.monotonic()
                replica.current_batch = batch
            try:
                self._dispatch_on(replica, batch)
            except BaseException as exc:  # noqa: BLE001 — fatal death
                self._on_replica_crash(replica, exc)
                return
            finally:
                with self._lock:
                    replica.current_batch = None
                    replica.busy_since = None

    def _resolve(self, replica: Replica | None, pending: PendingRequest,
                 status: str, detail: str = "",
                 outputs: tuple | None = None) -> None:
        now = time.monotonic()
        t_resolve = time.perf_counter()
        pending.resolve(
            ServeResponse(
                rid=pending.request.rid,
                status=status,
                outputs=outputs,
                detail=detail,
                delivered_ts=now,
                latency_s=now - pending.request.submitted_ts,
            )
        )
        self.spans.close(
            pending.request.rid, status, t_resolve,
            **({"replica": replica.name} if replica is not None else {}),
        )

    def _late_convert(self, replica: Replica | None,
                      pending: PendingRequest, detail: str) -> None:
        with self._lock:
            self.late_converted += 1
        self._count("late_converted")
        self._resolve(replica, pending, STATUS_REJECTED_LATE, detail)

    def _dispatch_on(self, replica: Replica,
                     batch: list[PendingRequest]) -> None:
        # Pre-dispatch feasibility recheck against THIS replica's model.
        est = replica.service_model.batch_s
        now = time.monotonic()
        live = []
        for p in batch:
            if p.done:  # resolved elsewhere (shed/redispatch race)
                continue
            if now + est > p.request.deadline_ts:
                self._late_convert(
                    replica, p,
                    "deadline infeasible at dispatch (queue wait consumed "
                    "the budget); rejected rather than served late",
                )
            else:
                live.append(p)
        if not live:
            return
        with self._lock:
            seq = self._dispatch_seq
            self._dispatch_seq += 1
        # Process kinds (raise -> fatal crash, hang -> watchdog) execute
        # inside fire(); data kinds come back for us to apply.
        kind = faults.fire(
            "serve.replica_dispatch", replica=replica.name, seq=seq,
            n=len(live),
        )
        tracer = self._tracer()
        live_rids = [p.request.rid for p in live]
        t0_wall = time.time()
        t0 = time.perf_counter()
        self.spans.stamp_many(live_rids, "t_predict0", t0)
        try:
            if kind == "wedge":
                raise InjectedDeviceError(
                    f"injected device error on {replica.name} seq={seq}"
                )
            xs = np.stack([p.request.x for p in live])
            alpha, beta = replica.engine.predict(xs)
            if kind in ("nan", "corrupt"):
                alpha = np.full_like(alpha, np.nan)
        except faults.FaultInjected:
            raise  # fatal: the worker loop declares this replica dead
        except Exception as exc:  # noqa: BLE001 — device/runtime error
            self.spans.stamp_many(
                live_rids, "t_predict_end", time.perf_counter()
            )
            with self._lock:
                self.errors += len(live)
                replica.errors += len(live)
            self._count("errors", len(live))
            for p in live:
                self._resolve(
                    replica, p, STATUS_ERROR,
                    f"{type(exc).__name__}: {exc}",
                )
            if replica.breaker.record_failure():
                self._degrade_replica(replica, exc)
            return
        device_s = time.perf_counter() - t0
        self.spans.stamp_many(live_rids, "t_predict_end", t0 + device_s)
        if tracer is not None:
            tracer.emit_span(
                "serve.device",
                start_ts=t0_wall,
                dur_s=device_s,
                parent=replica.span or self._fleet_span,
                seq=seq,
                n=len(live),
                replica=replica.name,
            )
        with self._lock:
            replica.busy_s += device_s
        replica.service_model.update(device_s)
        # Per-tenant EWMA: each tenant in this batch saw this service time.
        self.queue.note_service(
            {p.request.tenant for p in live}, device_s
        )
        replica.breaker.record_success()
        self.restart_policy.note_healthy(replica.name)
        finite = bool(
            np.isfinite(alpha).all() and np.isfinite(beta).all()
        )
        now = time.monotonic()
        delivered: list[int] = []
        for i, p in enumerate(live):
            if not finite:
                with self._lock:
                    self.errors += 1
                    replica.errors += 1
                self._count("errors")
                self._resolve(
                    replica, p, STATUS_ERROR,
                    "non-finite predictions; response withheld",
                )
            elif now > p.request.deadline_ts:
                self._late_convert(
                    replica, p,
                    "batch completed past the deadline; rejected rather "
                    "than delivered late",
                )
            else:
                with self._lock:
                    self.completed += 1
                    replica.completed += 1
                self._count("completed")
                latency = now - p.request.submitted_ts
                self._observe_latency(latency)
                self._resolve(
                    replica, p, STATUS_OK, outputs=(alpha[i], beta[i])
                )
                delivered.append(i)
                if time.monotonic() > p.request.deadline_ts:
                    with self._lock:
                        self.late_deliveries += 1
                    self._count("late_deliveries")
        if self.quality is not None:
            # Strictly post-delivery, host-side numpy only (TL105/TA202
            # and the serve preflight stay green by construction). Stacked
            # engines deliver per-lane (R, K) outputs per window; the
            # quality plane monitors the served ensemble mean.
            for i in delivered:
                a_i, b_i = alpha[i], beta[i]
                if a_i.ndim == 2:
                    a_i, b_i = a_i.mean(axis=0), b_i.mean(axis=0)
                self.quality.sample(live[i].request.x, a_i, b_i)

    # -------------------------------------------------------------- degrade

    def _degrade_replica(self, replica: Replica, cause: Exception) -> None:
        """Breaker tripped on ONE replica: one probe, then CPU rebuild of
        that replica only — the rest of the fleet never notices."""
        attempts = None
        if self.health is not None:
            decision = self.health.ensure_responsive(single_attempt=True)
            attempts = decision.attempts
            if decision.ok:
                self._event(
                    "breaker_probe_ok",
                    replica=replica.name,
                    trips=replica.breaker.trips,
                    attempts=attempts,
                    cause=repr(cause),
                )
                return
        with self._lock:
            self.degradations += 1
        self._count("degradations")
        replica.engine.degrade_to_cpu()
        replica.service_model.seed(replica.engine.warmup())
        with self._lock:
            replica.state = STATE_DEGRADED
        self._event(
            "degradation",
            scope="serve.replica",
            replica=replica.name,
            reason=f"circuit breaker tripped: {cause!r}",
            probe_attempts=attempts,
            platform=replica.engine.platform,
        )

    # ------------------------------------------------------------- failover

    def _fingerprint(self, exc: BaseException) -> str:
        # Digits are normalized out: a crash message embedding a sequence
        # number / address must still fingerprint as the SAME failure, or
        # the deterministic-by-evidence halt can never trigger.
        norm = re.sub(r"\d+", "#", f"{type(exc).__name__}|{exc}")
        return hashlib.sha1(norm.encode()).hexdigest()[:12]

    def _on_replica_crash(self, replica: Replica, exc: BaseException) -> None:
        self._declare_dead(
            replica,
            fingerprint=self._fingerprint(exc),
            detail=f"{type(exc).__name__}: {exc}",
            cause="crash",
        )

    def _declare_dead(self, replica: Replica, *, fingerprint: str,
                      detail: str, cause: str) -> None:
        with self._lock:
            if replica.state == STATE_DEAD:
                return
            replica.state = STATE_DEAD
            replica.stop_event.set()
            generation = replica.generation
            orphans: list[PendingRequest] = []
            if replica.current_batch is not None:
                orphans.extend(replica.current_batch)
                replica.current_batch = None
                replica.busy_since = None
            while True:
                try:
                    batch = replica.inbox.get_nowait()
                except stdqueue.Empty:
                    break
                if batch:
                    orphans.extend(batch)
            self.deaths += 1
        self._count("replica_deaths")
        self._event(
            "replica_dead",
            replica=replica.name,
            # replica_gen, not "generation": that name is the fleet
            # supervisor's envelope key (telemetry.events.RESERVED_KEYS)
            # and would both fail the emit-time clash check and be
            # misread by the aggregator's generation stitching.
            replica_gen=generation,
            cause=cause,
            fingerprint=fingerprint,
            detail=detail,
            orphaned=len(orphans),
        )
        tracer = self._tracer()
        if tracer is not None and replica.span is not None:
            tracer.end(
                replica.span, status="dead", cause=cause,
                completed=replica.completed, busy_s=replica.busy_s,  # mtt: disable=CL502 -- the dead replica's worker has exited; totals are final
            )
            replica.span = None
        self._redispatch(replica, orphans)
        verdict = self.restart_policy.classify(
            replica.name, fingerprint, detail
        )
        if verdict.action == "restart":
            self._event(
                "replica_restart_scheduled",
                replica=replica.name,
                backoff_s=verdict.backoff_s,
                restarts=self.restart_policy.restarts(replica.name),
            )
            timer = threading.Thread(
                target=self._delayed_boot,
                args=(replica, verdict.backoff_s),
                name=f"fleet-boot-{replica.name}",
                daemon=True,
            )
            self._boot_threads.append(timer)
            timer.start()
        else:
            with self._lock:
                replica.halted = True
            self._event(
                "replica_halted",
                replica=replica.name,
                verdict=verdict.kind,
                detail=verdict.detail,
            )

    def _redispatch(self, dead: Replica,
                    orphans: list[PendingRequest]) -> None:
        """Re-route a dead replica's unresolved work to survivors when
        deadlines still permit; explicitly reject the rest. The request
        keeps its ONE span — ``redispatched_from`` marks the hop."""
        for p in orphans:
            if p.done:
                continue
            target = None
            now = time.monotonic()
            serving = self._serving()
            feasible = [
                r for r in serving
                if now + r.backlog_estimate_s() <= p.request.deadline_ts
            ]
            if feasible:
                target = min(feasible, key=lambda r: r.backlog_estimate_s())
            if target is None:
                reason = (
                    f"replica {dead.name} died; "
                    + ("no live replica remains"
                       if not serving else
                       "no survivor can meet the deadline")
                )
                self._late_convert(None, p, reason)
                continue
            with self._lock:
                self.redispatched += 1
            self._count("redispatched")
            self.spans.annotate(
                p.request.rid, redispatched_from=dead.name
            )
            self._event(
                "redispatch",
                rid=p.request.rid,
                from_replica=dead.name,
                to_replica=target.name,
            )
            target.inbox.put([p])

    def _delayed_boot(self, replica: Replica, backoff_s: float) -> None:
        if backoff_s > 0:
            time.sleep(backoff_s)
        if self.queue.closed:
            return
        self._boot_replica(replica)

    def _boot_replica(self, replica: Replica, initial: bool = False) -> None:
        """(Re)build the replica's engine — a fresh generation. With a
        shared program cache this is a zero-compile warm boot."""
        with self._lock:
            if replica.halted or (not initial and self.queue.closed):
                return
            replica.generation += 1
            generation = replica.generation
        try:
            kind = faults.fire(
                "serve.replica_boot",
                replica=replica.name,
                generation=generation,
            )
            if kind == "wedge":
                raise ReplicaBootError(
                    f"injected boot failure on {replica.name} "
                    f"(wedged lease)"
                )
            t0 = time.perf_counter()
            engine = replica.engine_factory()
            warm_s = engine.warmup()
            boot_s = time.perf_counter() - t0
        except BaseException as exc:  # noqa: BLE001 — boot is fallible
            fingerprint = f"boot:{self._fingerprint(exc)}"
            detail = f"boot failed: {type(exc).__name__}: {exc}"
            self._event(
                "replica_boot_failed",
                replica=replica.name,
                replica_gen=generation,
                detail=detail,
            )
            verdict = self.restart_policy.classify(
                replica.name, fingerprint, detail
            )
            if verdict.action == "restart" and not initial:
                timer = threading.Thread(
                    target=self._delayed_boot,
                    args=(replica, verdict.backoff_s),
                    name=f"fleet-boot-{replica.name}",
                    daemon=True,
                )
                self._boot_threads.append(timer)
                timer.start()
            elif verdict.action == "restart" and initial:
                # start() decides fleet viability from serving count;
                # a failed initial boot retries once, inline.
                time.sleep(verdict.backoff_s)
                self._boot_replica(replica)
            else:
                with self._lock:
                    replica.halted = True
                    replica.state = STATE_DEAD
                self._event(
                    "replica_halted",
                    replica=replica.name,
                    verdict=verdict.kind,
                    detail=verdict.detail,
                )
            return
        with self._lock:
            replica.engine = engine
            replica.service_model.seed(warm_s)
            replica.breaker = CircuitBreaker(replica._breaker_threshold)
            replica.stop_event = threading.Event()
            replica.current_batch = None
            replica.busy_since = None
            replica.boot_s = boot_s
            replica.state = STATE_LIVE
            replica.thread = threading.Thread(
                target=self._worker_loop,
                args=(replica, generation),
                name=f"fleet-{replica.name}-g{generation}",
                daemon=True,
            )
        tracer = self._tracer()
        if tracer is not None:
            replica.span = tracer.start(
                "serve.replica",
                parent=self._fleet_span,
                replica=replica.name,
                generation=generation,
                platform=engine.platform,
            )
        self._event(
            "replica_started",
            replica=replica.name,
            replica_gen=generation,
            restart=not initial,
            boot_s=boot_s,
            warmup_batch_ms=warm_s * 1e3,
            compile_events=engine.compile_events,
            cache_hits=getattr(engine, "cache_hits", 0),
            platform=engine.platform,
        )
        replica.thread.start()

    # -------------------------------------------------------------- monitor

    def _monitor_loop(self) -> None:
        """Hang watchdog: a replica stuck on one batch past
        ``hang_timeout_s`` is dead by evidence (the same staleness rule as
        the supervisor's heartbeat watchdog)."""
        period = max(0.01, min(0.05, self.hang_timeout_s / 4.0))
        while not self._stop.wait(period):
            now = time.monotonic()
            for replica in list(self.replicas.values()):
                with self._lock:
                    busy_since = replica.busy_since
                    serving = replica.state in SERVING_STATES
                if (
                    serving
                    and busy_since is not None
                    and now - busy_since > self.hang_timeout_s
                ):
                    self._declare_dead(
                        replica,
                        fingerprint="hang",
                        detail=(
                            f"batch in flight for "
                            f"{now - busy_since:.2f}s > hang timeout "
                            f"{self.hang_timeout_s:.2f}s"
                        ),
                        cause="hang",
                    )
