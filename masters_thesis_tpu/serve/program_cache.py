"""On-disk cache of serialized serving executables: restart without compile.

Rounds 3-6 established that cold compiles plus a wedged device lease are
this environment's dominant serving tail risk (BENCH_r03-r05): an engine
restart that recompiles every bucket is a multi-second availability hole,
and a canaried hot-swap that needs a fresh engine pays it again. This
module closes the hole: every bucketed ``Compiled`` predict program is
serialized (``jax.experimental.serialize_executable`` — the PjRt
executable payload, not just StableHLO) into a content-addressed on-disk
entry, so the NEXT engine boot with the same program identity loads the
executable instead of compiling it. Zero jit compiles, zero traces —
preflight rule SV305 pins the delta through the existing
``CompileTracker`` accounting.

Trust model — a cache entry is evidence, never an oracle:

- **Keyed on identity, not hope.** The entry key is a sha256 over the
  model spec, the param-tree leaf signature (treedef + per-leaf
  shape/dtype), the window shape, the bucket, the mesh (including the
  EXACT device ids — a serialized executable is bound to its device
  assignment, and loading r0's program onto r1's devices would silently
  serve from the wrong replica's chips), and the backend fingerprint
  (jax/jaxlib versions, platform, device kind, forced-host-device flag).
  Anything that could change the compiled program changes the key.
- **Torn entries are refused.** Every file is listed in a sha256
  ``MANIFEST.json`` (same discipline as checkpoint manifests); a missing
  file, mismatched hash, or unparseable manifest rejects the entry with a
  ``cache_rejected`` event and the engine compiles fresh — a partial
  write from a killed process must cost one compile, never a wrong
  program.
- **Stale entries are refused.** The manifest records the fingerprint the
  entry was built under; if the current environment disagrees (jax
  upgrade, different device kind), the entry is rejected as stale even
  though its bytes are intact.
- **Deserialization is verified, not trusted.** The entry stores a
  deterministic golden input, the golden params it was serialized with,
  and the outputs the ORIGINAL executable produced on them. At load, the
  deserialized executable re-runs the golden batch and must reproduce
  those outputs bitwise — the observed hazard where a deserialized
  multi-device CPU executable computes ~0.7% differently from the program
  that was serialized (see utils/compilation_cache.py) becomes a detected
  refusal instead of a silently wrong answer.

Fault point ``cache.load`` (kind ``corrupt``) flips a byte in the entry
payload on disk before verification, so the chaos suite drives the real
refusal machinery end to end.

Import surface: numpy/stdlib only at module scope — jax loads lazily
inside the (de)serialization paths, keeping ``serve``'s jax-free import
contract intact.
"""

from __future__ import annotations

import hashlib
import json
import time
from pathlib import Path
from typing import Any, Callable

import numpy as np

from masters_thesis_tpu.resilience import faults

MANIFEST_NAME = "MANIFEST.json"
#: Bump when the entry layout or key recipe changes: old entries become
#: unreachable (different keys) instead of misread.
CACHE_SCHEMA = 1


def param_signature(tree: Any) -> dict:
    """Stable identity of a param tree: treedef repr + per-leaf
    (shape, dtype) in flatten order. Host- and device-tree agnostic."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return {
        "treedef": str(treedef),
        "leaves": [
            [
                list(np.shape(leaf)),
                str(getattr(leaf, "dtype", None) or np.asarray(leaf).dtype),
            ]
            for leaf in leaves
        ],
    }


def entry_key(ident: dict) -> str:
    """Content-addressed entry key: sha256 over the canonical JSON of the
    full program identity (spec, signature, bucket, mesh devices,
    backend fingerprint, schema)."""
    canon = json.dumps(
        {"schema": CACHE_SCHEMA, **ident}, sort_keys=True, default=str
    )
    return hashlib.sha256(canon.encode()).hexdigest()[:24]


class ProgramCache:
    """One cache root holding many entries; shared across engine replicas.

    Layout::

        <root>/MANIFEST.json           # {entries: {key: {files, fingerprint, ...}}}
        <root>/<key>.bin               # serialized executable payload
        <root>/<key>.golden.npz        # golden params/input/outputs for parity

    Counters (``hits``/``misses``/``stores``/``rejections``) and the
    ``events`` list make boot behavior auditable without telemetry; when
    ``telemetry`` is attached every decision also lands in the event
    stream (``cache_hit``/``cache_miss``/``cache_store``/
    ``cache_rejected``).
    """

    def __init__(self, root: str | Path, telemetry=None):
        self.root = Path(root)
        self.telemetry = telemetry
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.rejections = 0
        self.events: list[dict] = []

    # ------------------------------------------------------------- plumbing

    def _event(self, kind: str, **payload) -> None:
        record = {"kind": kind, **payload}
        self.events.append(record)
        if self.telemetry is not None:
            try:
                self.telemetry.event(kind, **payload)
            except Exception:  # cache accounting must never cost serving
                pass

    def _manifest_path(self) -> Path:
        return self.root / MANIFEST_NAME

    def _read_manifest(self) -> dict:
        try:
            raw = json.loads(self._manifest_path().read_text())
        except (OSError, json.JSONDecodeError):
            return {"schema": CACHE_SCHEMA, "entries": {}}
        if not isinstance(raw, dict) or not isinstance(
            raw.get("entries"), dict
        ):
            return {"schema": CACHE_SCHEMA, "entries": {}}
        return raw

    def _write_manifest(self, manifest: dict) -> None:
        from masters_thesis_tpu.utils.io import atomic_write_text

        self.root.mkdir(parents=True, exist_ok=True)
        atomic_write_text(
            self._manifest_path(), json.dumps(manifest, indent=2), fsync=True
        )

    def _remove_entry(self, key: str) -> None:
        """Drop a refused entry so the rebuild can re-store cleanly."""
        manifest = self._read_manifest()
        manifest["entries"].pop(key, None)
        try:
            self._write_manifest(manifest)
        except OSError:
            pass
        for suffix in (".bin", ".golden.npz"):
            try:
                (self.root / f"{key}{suffix}").unlink(missing_ok=True)
            except OSError:
                pass

    def _reject(self, key: str, reason: str, detail: str = "") -> None:
        self.rejections += 1
        self._event("cache_rejected", key=key, reason=reason, detail=detail)
        self._remove_entry(key)

    # ----------------------------------------------------------------- load

    def load(
        self,
        key: str,
        *,
        fingerprint: dict,
        in_tree,
        out_tree,
        run_golden: Callable[[Any, dict], tuple] | None = None,
    ):
        """Return a loaded ``Compiled`` for ``key``, or ``None``.

        ``None`` means miss OR refusal (torn/stale/corrupt/parity) — the
        caller compiles fresh either way; refusals additionally emit
        ``cache_rejected`` and delete the entry. ``run_golden(compiled,
        golden)`` must execute the deserialized program on the entry's
        stored golden params/input and return host (alpha, beta) arrays
        for the bitwise parity check.
        """
        manifest = self._read_manifest()
        entry = manifest["entries"].get(key)
        if entry is None:
            self.misses += 1
            self._event("cache_miss", key=key)
            return None
        # Fault point: corrupt the payload ON DISK before verification so
        # the refusal machinery below runs against a real torn entry.
        if faults.fire("cache.load", key=key) == "corrupt":
            self._corrupt_entry(key, seed=faults.corruption_seed())
        if entry.get("fingerprint") != fingerprint:
            self._reject(
                key, "stale",
                "entry fingerprint does not match the current backend "
                f"(entry: {entry.get('fingerprint')!r})",
            )
            return None
        files = entry.get("files")
        if not isinstance(files, dict) or not files:
            self._reject(key, "torn", "manifest entry lists no files")
            return None
        for name, want in files.items():
            path = self.root / name
            try:
                blob = path.read_bytes()
            except OSError:
                self._reject(key, "torn", f"missing file {name}")
                return None
            if len(blob) != want.get("size") or (
                hashlib.sha256(blob).hexdigest() != want.get("sha256")
            ):
                self._reject(key, "torn", f"sha256 mismatch on {name}")
                return None
        try:
            compiled = self._deserialize(key, in_tree, out_tree)
        except Exception as exc:  # noqa: BLE001 — any load failure refuses
            self._reject(
                key, "deserialize_failed", f"{type(exc).__name__}: {exc}"
            )
            return None
        if run_golden is not None:
            try:
                golden = self._read_golden(key)
                got_alpha, got_beta = run_golden(compiled, golden)
                ok = np.array_equal(
                    np.asarray(got_alpha), golden["alpha"]
                ) and np.array_equal(np.asarray(got_beta), golden["beta"])
            except Exception as exc:  # noqa: BLE001
                self._reject(
                    key, "golden_failed", f"{type(exc).__name__}: {exc}"
                )
                return None
            if not ok:
                self._reject(
                    key, "golden_mismatch",
                    "deserialized executable does not reproduce the stored "
                    "golden outputs bitwise — the reload is not the program "
                    "that was serialized (see utils/compilation_cache.py "
                    "for the observed CPU-divergence hazard)",
                )
                return None
        self.hits += 1
        self._event("cache_hit", key=key)
        return compiled

    def _deserialize(self, key: str, in_tree, out_tree):
        from jax.experimental import serialize_executable as se

        payload = (self.root / f"{key}.bin").read_bytes()
        return se.deserialize_and_load(payload, in_tree, out_tree)

    def _read_golden(self, key: str) -> dict:
        with np.load(self.root / f"{key}.golden.npz") as z:
            return {name: z[name] for name in z.files}

    def _corrupt_entry(self, key: str, seed: int) -> None:
        """Flip one byte of the payload (the chaos drill's torn write)."""
        path = self.root / f"{key}.bin"
        try:
            blob = bytearray(path.read_bytes())
        except OSError:
            return
        if not blob:
            return
        idx = seed % len(blob)
        blob[idx] ^= 0xFF
        path.write_bytes(bytes(blob))

    # ---------------------------------------------------------------- store

    def store(
        self,
        key: str,
        compiled,
        *,
        fingerprint: dict,
        golden: dict,
    ) -> bool:
        """Serialize ``compiled`` under ``key`` with its golden-parity data.

        ``golden`` carries the flat golden param leaves (``param_<i>``),
        the golden input (``x``), and the outputs the live executable
        produced on them (``alpha``, ``beta``). Best-effort: a failed
        store costs nothing but the warm start it would have bought.
        """
        try:
            from jax.experimental import serialize_executable as se

            payload, _, _ = se.serialize(compiled)
            self.root.mkdir(parents=True, exist_ok=True)
            bin_path = self.root / f"{key}.bin"
            golden_path = self.root / f"{key}.golden.npz"
            bin_path.write_bytes(payload)
            with golden_path.open("wb") as fh:
                np.savez(fh, **golden)
            files = {}
            for path in (bin_path, golden_path):
                blob = path.read_bytes()
                files[path.name] = {
                    "sha256": hashlib.sha256(blob).hexdigest(),
                    "size": len(blob),
                }
            manifest = self._read_manifest()
            manifest["entries"][key] = {
                "files": files,
                "fingerprint": fingerprint,
                "created": time.time(),
            }
            self._write_manifest(manifest)
        except Exception as exc:  # noqa: BLE001 — cache is an optimization
            self._event(
                "cache_store_failed",
                key=key,
                detail=f"{type(exc).__name__}: {exc}",
            )
            return False
        self.stores += 1
        self._event("cache_store", key=key)
        return True

    # ------------------------------------------------------------- summary

    def stats(self) -> dict:
        return {
            "root": str(self.root),
            "hits": self.hits,
            "misses": self.misses,
            "stores": self.stores,
            "rejections": self.rejections,
        }
