"""Canaried checkpoint hot-swap: verify, canary, atomically swap or roll back.

A serving replica must pick up newly trained params without a restart —
but a torn, bit-flipped, or NaN checkpoint must NEVER reach traffic. The
swap protocol is a one-way gate with three independent checks, each of
which rejects without touching the serving params:

1. **Strict manifest verification** — ``verify_checkpoint(...,
   require_manifest=True)``: the candidate tree's content must prove
   itself against its sha256 manifest; a manifest-LESS tree (pre-manifest
   save, or a tree staged without checksums) is refused outright, unlike
   the lenient training-restore path. No ``.prev`` fallback here: swapping
   in the previous checkpoint would silently serve stale params while
   reporting success.
2. **Shape compatibility** — the candidate param tree must match the
   serving tree leaf-for-leaf; the AOT executables are shape-specialized,
   so an architecture change requires a restart, not a swap.
3. **Golden-batch canary** — the candidate runs on a fixed input through
   the SAME compiled program as live traffic; outputs must be finite,
   bounded, and (optionally) within a drift budget of the current params'
   outputs. The verdict math is plain numpy (:func:`canary_checks`) so
   the jax-free selfcheck can exercise it.

Only after all three pass does :meth:`CheckpointSwapper.try_swap` flip the
engine's param pointer — one atomic reference swap under the engine lock;
in-flight batches finish on the old tree, the next batch sees the new one.

A fourth, *model-quality* gate rides on top when the candidate ships a
``quality.json`` fingerprint (telemetry/quality.py): the candidate's
golden-batch outputs are scored against its own shipped sketches and
shadow-OLS budget (catching a fine-tune that silently diverged between
fingerprinting and deploy), and — when a live :class:`QualityMonitor` is
attached — against the live serving sketch. Rejections carry a named
``quality_*`` reason plus the numeric scores. A checkpoint without a
fingerprint and no live sketch passes untouched: the quality gate is
additive, never a new way for a healthy legacy checkpoint to fail.

Fault point ``serve.pre_swap`` (kind ``corrupt``) corrupts the candidate
tree before verification — the chaos suite's torn-checkpoint drill.

Stacked engines get the same protocol per tenant lane:
:meth:`CheckpointSwapper.try_swap_lane` runs verify → signature → canary →
quality against ONE lane of a :class:`StackedPredictEngine`'s ``[R, n]``
param stack, adds a bitwise sibling-isolation check (staging a candidate
must not move any OTHER lane's outputs through the identical executable),
and commits as an atomic row write — zero recompiles, zero sibling churn.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import numpy as np

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.telemetry import quality as quality_lib

#: Default canary bound on |output|: the estimator's alpha/beta are
#: standardized-return-scale quantities; anything this large is a blown-up
#: tree even before NaN shows up.
DEFAULT_MAX_ABS = 1e3


@dataclass
class SwapVerdict:
    ok: bool
    reason: str  # "committed" | "verify_failed" | "restore_failed" |
    #              "shape_mismatch" | "canary_<check>" | "quality_<check>"
    detail: str = ""
    checks: dict = field(default_factory=dict)


def canary_checks(
    current: tuple,
    candidate: tuple,
    max_abs: float = DEFAULT_MAX_ABS,
    max_drift: float | None = None,
) -> SwapVerdict:
    """Pure-numpy canary verdict over (alpha, beta) output pairs.

    ``max_drift`` bounds the max elementwise |candidate - current|; None
    disables the drift check (first deploy after an intentional retrain
    can move outputs arbitrarily — the operator opts in per rollout).
    """
    # Host-side verdict math in f64 on purpose: the drift/abs thresholds
    # must not be blurred by the comparison's own f32 rounding. Never
    # traced — these arrays exist only on the host.
    cur = [np.asarray(a, np.float64) for a in current]  # mtt: disable=TL104 -- host-only f64 canary comparison; param deltas must not blur in f32
    cand = [np.asarray(a, np.float64) for a in candidate]  # mtt: disable=TL104 -- host-only f64 canary comparison; param deltas must not blur in f32
    checks: dict[str, float | bool] = {}
    finite = all(bool(np.isfinite(a).all()) for a in cand)
    checks["finite"] = finite
    if not finite:
        return SwapVerdict(
            False, "canary_nonfinite",
            "candidate produced NaN/inf on the golden batch", checks,
        )
    peak = max(float(np.abs(a).max()) for a in cand)
    checks["max_abs"] = peak
    if peak > max_abs:
        return SwapVerdict(
            False, "canary_abs",
            f"candidate |output| {peak:.3g} exceeds bound {max_abs:.3g}",
            checks,
        )
    drift = max(
        float(np.abs(a - b).max()) for a, b in zip(cand, cur)
    )
    checks["drift"] = drift
    if max_drift is not None and drift > max_drift:
        return SwapVerdict(
            False, "canary_drift",
            f"candidate drifted {drift:.3g} from serving outputs "
            f"(budget {max_drift:.3g})",
            checks,
        )
    return SwapVerdict(True, "committed", checks=checks)


def _tree_signature(tree: Any) -> tuple[str, list]:
    """(treedef repr, per-leaf (shape, dtype)) — host trees only."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return str(treedef), [
        (tuple(np.shape(leaf)), str(np.asarray(leaf).dtype))
        for leaf in leaves
    ]


class CheckpointSwapper:
    """Drives the swap protocol against one engine + checkpoint directory."""

    def __init__(
        self,
        engine,
        *,
        golden_x: np.ndarray | None = None,
        telemetry=None,
        max_abs: float = DEFAULT_MAX_ABS,
        max_drift: float | None = None,
        quality_monitor=None,
    ):
        self.engine = engine
        self.golden_x = (
            golden_x if golden_x is not None else engine.golden_batch()
        )
        self.telemetry = telemetry
        self.max_abs = max_abs
        self.max_drift = max_drift
        #: Optional live QualityMonitor (telemetry/quality.py). When set,
        #: the quality gate can score candidates against the live serving
        #: sketch, and a committed swap re-baselines the monitor's
        #: reference to the new checkpoint's shipped fingerprint.
        self.quality = quality_monitor
        self.committed = 0
        self.rejected = 0
        self.lane_committed = 0
        self.lane_rejected = 0

    def _event(self, kind: str, **payload) -> None:
        if self.telemetry is not None:
            self.telemetry.event(kind, **payload)

    def _reject(self, tag: str, verdict: SwapVerdict) -> SwapVerdict:
        self.rejected += 1
        self._event(
            "swap_rejected",
            tag=tag,
            reason=verdict.reason,
            detail=verdict.detail,
            checks=verdict.checks,
        )
        return verdict

    def try_swap(self, ckpt_dir, tag: str = "best") -> SwapVerdict:
        """Verify + canary ``<ckpt_dir>/<tag>``; swap on pass, keep the
        current params (and say why) on any failure. Never raises for a
        bad candidate — a broken checkpoint must not take the replica
        down, only be refused."""
        # train.checkpoint imports jax/orbax at module scope; keep serve's
        # jax-free import surface intact by deferring.
        from masters_thesis_tpu.train import checkpoint as ckpt

        ckpt_dir = Path(ckpt_dir)
        path = ckpt_dir / tag
        if faults.fire("serve.pre_swap", tag=tag) == "corrupt":
            ckpt._corrupt_tree(path, seed=faults.corruption_seed())
        if not ckpt.verify_checkpoint(path, require_manifest=True):
            return self._reject(
                tag,
                SwapVerdict(
                    False, "verify_failed",
                    f"strict manifest verification failed for {path} "
                    "(torn/corrupt tree, or no MANIFEST.json)",
                ),
            )
        try:
            params, _, spec, meta = ckpt.restore_checkpoint(ckpt_dir, tag)
        except Exception as exc:  # noqa: BLE001 — any restore failure rejects
            return self._reject(
                tag,
                SwapVerdict(
                    False, "restore_failed",
                    f"{type(exc).__name__}: {exc}",
                ),
            )
        if _tree_signature(params) != _tree_signature(
            self._host_serving_params()
        ):
            return self._reject(
                tag,
                SwapVerdict(
                    False, "shape_mismatch",
                    "candidate param tree does not match the serving tree "
                    "(architecture change requires a replica restart — the "
                    "AOT predict programs are shape-specialized)",
                ),
            )
        candidate = self.engine.put_params(params)
        current_out = self.engine.predict(self.golden_x)
        candidate_out = self.engine.predict(self.golden_x, params=candidate)
        verdict = canary_checks(
            current_out, candidate_out,
            max_abs=self.max_abs, max_drift=self.max_drift,
        )
        if not verdict.ok:
            return self._reject(tag, verdict)
        # Model-quality gate: score the candidate against its own shipped
        # fingerprint (regenerating the seeded golden windows it was
        # fingerprinted on) and/or the live serving sketch. Skipped
        # gracefully when neither exists — legacy checkpoints still swap.
        fp = quality_lib.read_fingerprint(path)
        try:
            gold = (fp or {}).get("golden")
            live = (
                self.quality.live_summaries()
                if self.quality is not None
                else None
            )
            q_x = q_out = None
            if gold is not None and tuple(gold["shape"][1:]) == tuple(
                self.engine.window_shape
            ):
                q_x = quality_lib.golden_windows(
                    *gold["shape"], seed=gold.get("seed", 0)
                )
                q_out = self._predict_chunked(q_x, candidate)
            elif live:
                # No usable fingerprint: fall back to the swapper's own
                # golden batch so the live-sketch check still has outputs
                # to score.
                q_x, q_out = self.golden_x, candidate_out
            if q_out is not None:
                ok, reason, detail, qchecks = quality_lib.quality_gate(
                    fp, q_x, q_out[0], q_out[1], live=live
                )
                verdict.checks.update(qchecks)
                if not ok:
                    return self._reject(
                        tag,
                        SwapVerdict(False, reason, detail, verdict.checks),
                    )
        except Exception as exc:  # noqa: BLE001 — a malformed fingerprint
            # must reject the candidate, never take the replica down.
            return self._reject(
                tag,
                SwapVerdict(
                    False, "quality_error",
                    f"quality gate could not score the candidate: "
                    f"{type(exc).__name__}: {exc}",
                    verdict.checks,
                ),
            )
        self.engine.set_params(candidate)
        if self.quality is not None and fp is not None:
            # The new checkpoint's fingerprint is now the drift baseline:
            # an intentional retrain must not alarm against the OLD model's
            # prediction sketch.
            self.quality.set_reference(fp)
        self.committed += 1
        self._event(
            "swap_committed",
            tag=tag,
            epoch=meta.get("epoch"),
            checks=verdict.checks,
        )
        return verdict

    def try_swap_lane(
        self, lane: int, ckpt_dir, tag: str = "best"
    ) -> SwapVerdict:
        """Per-lane swap against a :class:`StackedPredictEngine`: replace
        ONE tenant's lane in the stacked param buffers while sibling lanes
        keep serving bit-identical answers through the same compiled
        programs.

        Same one-way gate as :meth:`try_swap` (strict manifest verify,
        restore, shape signature, golden-batch canary, quality gate), plus
        a **sibling-isolation check**: the staged stack's outputs on every
        OTHER lane must be bitwise equal to the serving stack's — a row
        scatter that perturbs a sibling is a correctness bug, never noise,
        because both runs go through the identical executable. The commit
        is an atomic row write (:meth:`StackedPredictEngine.set_lane`):
        shapes never change, so zero recompiles by construction.

        The quality gate scores the candidate against its OWN shipped
        fingerprint only; the live sketch tracks the served ensemble mean
        and would false-alarm against any single lane.
        """
        from masters_thesis_tpu.train import checkpoint as ckpt

        if not hasattr(self.engine, "stage_lane"):
            raise TypeError(
                "try_swap_lane requires a StackedPredictEngine; "
                f"{type(self.engine).__name__} has no lanes"
            )
        lane = int(lane)
        ckpt_dir = Path(ckpt_dir)
        path = ckpt_dir / tag
        if faults.fire("serve.pre_swap", tag=tag) == "corrupt":
            ckpt._corrupt_tree(path, seed=faults.corruption_seed())
        if not ckpt.verify_checkpoint(path, require_manifest=True):
            return self._reject_lane(
                tag, lane,
                SwapVerdict(
                    False, "verify_failed",
                    f"strict manifest verification failed for {path} "
                    "(torn/corrupt tree, or no MANIFEST.json)",
                ),
            )
        try:
            params, _, spec, meta = ckpt.restore_checkpoint(ckpt_dir, tag)
        except Exception as exc:  # noqa: BLE001 — any restore failure rejects
            return self._reject_lane(
                tag, lane,
                SwapVerdict(
                    False, "restore_failed",
                    f"{type(exc).__name__}: {exc}",
                ),
            )
        if _tree_signature(params) != _tree_signature(
            self.engine.lane_params(lane)
        ):
            return self._reject_lane(
                tag, lane,
                SwapVerdict(
                    False, "shape_mismatch",
                    "candidate param tree does not match the lane's serving "
                    "tree (per-lane swap cannot change architecture — the "
                    "stacked AOT programs are shape-specialized)",
                ),
            )
        try:
            staged = self.engine.stage_lane(lane, params)
        except Exception as exc:  # noqa: BLE001 — staging failure rejects
            return self._reject_lane(
                tag, lane,
                SwapVerdict(
                    False, "stage_failed",
                    f"{type(exc).__name__}: {exc}",
                ),
            )
        cur_a, cur_b = self.engine.predict(self.golden_x)
        stg_a, stg_b = self.engine.predict(self.golden_x, params=staged)
        verdict = canary_checks(
            (cur_a[:, lane, :], cur_b[:, lane, :]),
            (stg_a[:, lane, :], stg_b[:, lane, :]),
            max_abs=self.max_abs, max_drift=self.max_drift,
        )
        if not verdict.ok:
            return self._reject_lane(tag, lane, verdict)
        siblings_clean = all(
            np.array_equal(cur_a[:, r, :], stg_a[:, r, :])
            and np.array_equal(cur_b[:, r, :], stg_b[:, r, :])
            for r in range(self.engine.num_lanes)
            if r != lane
        )
        verdict.checks["siblings_bitwise"] = siblings_clean
        if not siblings_clean:
            return self._reject_lane(
                tag, lane,
                SwapVerdict(
                    False, "sibling_perturbed",
                    "staging the candidate moved a SIBLING lane's outputs "
                    "through the identical executable — lane isolation is "
                    "broken; refusing to commit",
                    verdict.checks,
                ),
            )
        fp = quality_lib.read_fingerprint(path)
        try:
            gold = (fp or {}).get("golden")
            if gold is not None and tuple(gold["shape"][1:]) == tuple(
                self.engine.window_shape
            ):
                q_x = quality_lib.golden_windows(
                    *gold["shape"], seed=gold.get("seed", 0)
                )
                q_out = self._predict_lane_chunked(q_x, lane, staged)
                ok, reason, detail, qchecks = quality_lib.quality_gate(
                    fp, q_x, q_out[0], q_out[1], live=None
                )
                verdict.checks.update(qchecks)
                if not ok:
                    return self._reject_lane(
                        tag, lane,
                        SwapVerdict(False, reason, detail, verdict.checks),
                    )
        except Exception as exc:  # noqa: BLE001 — a malformed fingerprint
            # must reject the candidate, never take the replica down.
            return self._reject_lane(
                tag, lane,
                SwapVerdict(
                    False, "quality_error",
                    f"quality gate could not score the candidate: "
                    f"{type(exc).__name__}: {exc}",
                    verdict.checks,
                ),
            )
        digest = self.engine.set_lane(lane, params, staged=staged)
        self.lane_committed += 1
        self._event(
            "lane_swap_committed",
            tag=tag,
            lane=lane,
            digest=digest,
            epoch=meta.get("epoch"),
            checks=verdict.checks,
        )
        return verdict

    def _reject_lane(
        self, tag: str, lane: int, verdict: SwapVerdict
    ) -> SwapVerdict:
        self.lane_rejected += 1
        self._event(
            "lane_swap_rejected",
            tag=tag,
            lane=lane,
            reason=verdict.reason,
            detail=verdict.detail,
            checks=verdict.checks,
        )
        return verdict

    def _predict_lane_chunked(
        self, x: np.ndarray, lane: int, staged: Any
    ) -> tuple:
        """Lane-sliced :meth:`_predict_chunked` over a staged stack."""
        cap = getattr(self.engine, "max_bucket", None)
        if not cap or len(x) <= cap:
            return self.engine.predict_lane(x, lane, params=staged)
        outs = [
            self.engine.predict_lane(x[i : i + cap], lane, params=staged)
            for i in range(0, len(x), cap)
        ]
        return (
            np.concatenate([np.asarray(o[0]) for o in outs]),
            np.concatenate([np.asarray(o[1]) for o in outs]),
        )

    def _predict_chunked(self, x: np.ndarray, params: Any) -> tuple:
        """Predict a golden batch that may exceed the engine's largest
        compiled bucket — fingerprints ship 32-window goldens while a
        replica may only compile small buckets. Chunks of ``max_bucket``
        windows each, concatenated host-side."""
        cap = getattr(self.engine, "max_bucket", None)
        if not cap or len(x) <= cap:
            return self.engine.predict(x, params=params)
        outs = [
            self.engine.predict(x[i : i + cap], params=params)
            for i in range(0, len(x), cap)
        ]
        return (
            np.concatenate([np.asarray(o[0]) for o in outs]),
            np.concatenate([np.asarray(o[1]) for o in outs]),
        )

    def _host_serving_params(self) -> Any:
        import jax

        return jax.device_get(self.engine._params)
