"""AOT-compiled predict engine over bucketed batch shapes.

Steady-state serving must NEVER trace (tracing is a multi-second,
GIL-holding stall — fatal under a latency SLO). So every predict program
is lowered and compiled ahead-of-time at startup, one per bucketed batch
size, and requests are padded up to the nearest bucket:

- ``jax.jit(predict).lower(...).compile()`` yields a ``Compiled``
  executable that can only EXECUTE — a shape it was not built for raises
  instead of silently retracing, which turns the "no recompiles in
  serving" policy from a hope into a structural guarantee.
- The hot path does only EXPLICIT transfers (``jax.device_put`` for the
  padded request batch, ``jax.device_get`` for the outputs), so it runs
  clean under ``jax.transfer_guard("disallow")`` — enforced by the serve
  preflight (serve/preflight.py, rules SV301/SV302).
- Params live device-resident and replicated; :meth:`set_params` swaps
  the serving tree atomically under a lock (the hot-swap path,
  serve/swap.py), and the same compiled executables keep serving — a
  param swap never recompiles anything.

Degradation: :meth:`degrade_to_cpu` rebuilds the mesh + executables on
the CPU backend (one compile burst, outside the steady-state guarantee)
after the server's circuit breaker trips and the single backend probe
fails — mirroring the supervisor's CPU-failover policy.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from masters_thesis_tpu.models.objectives import ModelSpec
from masters_thesis_tpu.parallel import (
    DATA_AXIS,
    global_put,
    make_data_mesh,
    replicated_sharding,
)
from masters_thesis_tpu.train.steps import forward_rows

DEFAULT_BUCKETS = (1, 2, 4, 8)


def resolve_buckets(value: Any = None) -> tuple[int, ...]:
    """Normalize a bucket ladder from config/CLI into the engine's form.

    Accepts ``None`` (the code default), a sequence of ints (the
    ``serve.buckets`` config list — configs/serve/*.yaml), or a
    comma-separated string (CLI overrides like ``--buckets 1,8,64``).
    The default ladder tops out at 8 windows — sized for interactive
    traffic; universe-scale batches (thousands of windows per request,
    configs/serve/universe.yaml) need their own profile, which is why
    the ladder is config, not code.
    """
    if value is None:
        return DEFAULT_BUCKETS
    if isinstance(value, str):
        value = [v for v in value.replace(",", " ").split() if v]
    buckets = tuple(sorted(set(int(b) for b in value)))
    if not buckets or buckets[0] < 1:
        raise ValueError(f"invalid serve bucket ladder: {value!r}")
    return buckets


class BucketOverflowError(ValueError):
    """Request batch larger than the largest compiled bucket."""


class PredictEngine:
    """Bucketed AOT predict programs for one (spec, window-shape) pair.

    ``predict`` maps a host batch ``x (n, K, T, F)`` to per-stock
    ``(alpha (n, K), beta (n, K))`` numpy arrays, deterministically
    (dropout off), padding ``n`` up to the nearest compiled bucket.
    """

    def __init__(
        self,
        spec: ModelSpec,
        params: Any,
        *,
        n_stocks: int,
        lookback: int,
        n_features: int = 3,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        mesh: Mesh | None = None,
        program_cache=None,
    ):
        self.spec = spec
        self.n_stocks = n_stocks
        self.lookback = lookback
        self.n_features = n_features
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"invalid buckets: {buckets!r}")
        self.mesh = mesh if mesh is not None else make_data_mesh(None)
        self._module = spec.build_module()
        #: Monotonic count of XLA compilations this engine performed.
        #: Steady-state contract: constant after warmup() — the preflight
        #: asserts the delta is zero over a varied-shape request window.
        self.compile_events = 0
        #: Buckets booted from the on-disk program cache instead of a
        #: compile. A fully warm boot has cache_hits == len(buckets) and
        #: compile_events == 0 (preflight rule SV305).
        self.cache_hits = 0
        #: Optional :class:`~masters_thesis_tpu.serve.program_cache
        #: .ProgramCache`: serialized executables keyed on the full
        #: program identity; torn/stale entries are refused and rebuilt.
        self.program_cache = program_cache
        self._compiled: dict[int, tuple[Any, NamedSharding]] = {}
        #: Static cost model per bucket (telemetry/costs.py payload dict),
        #: extracted from the very Compiled executables that serve traffic
        #: — zero extra compiles. SV304 holds peak_bytes against the
        #: device memory budget at preflight.
        self.cost_profiles: dict[int, dict] = {}
        self._lock = threading.RLock()
        self._params = global_put(
            jax.device_get(params), replicated_sharding(self.mesh)
        )

    # jit_cache_size()/CompileTracker compatibility: the engine is its own
    # "jitted callable" for compile accounting purposes.
    def _cache_size(self) -> int:
        return self.compile_events

    @property
    def window_shape(self) -> tuple[int, int, int]:
        return (self.n_stocks, self.lookback, self.n_features)

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    @property
    def platform(self) -> str:
        devs = list(self.mesh.devices.flat)
        return devs[0].platform if devs else jax.default_backend()

    def _predict_fn(self, params, x):
        alpha, beta = forward_rows(self._module, params, x)
        return alpha[..., 0], beta[..., 0]

    # ------------------------------------------------- program-cache glue

    def _cache_identity(self, b: int) -> tuple[str, dict]:
        """(entry key, backend fingerprint) for bucket ``b``'s program.

        The key covers everything that changes the compiled executable:
        model spec, param leaf signature, window shape, bucket, and the
        backend fingerprint (which includes the EXACT device ids — fleet
        replicas own disjoint device subsets and must never load each
        other's executables).
        """
        import dataclasses

        from masters_thesis_tpu.serve import program_cache as pc
        from masters_thesis_tpu.utils.backend_probe import backend_fingerprint

        fp = backend_fingerprint(self.mesh)
        ident = {
            "spec": dataclasses.asdict(self.spec),
            "params": pc.param_signature(self._params),
            "window": list(self.window_shape),
            "bucket": int(b),
            "fingerprint": fp,
        }
        return pc.entry_key(ident), fp

    def _golden_x(self, b: int) -> np.ndarray:
        """Deterministic per-bucket parity input (seed varies by bucket so
        each entry's golden data exercises its own executable shape)."""
        return self.golden_batch(n=b, seed=1009 * b + 7)

    def _cache_load(self, b: int, x_sh: NamedSharding, repl: NamedSharding):
        """Try to boot bucket ``b`` from the program cache (None = miss)."""
        key, fp = self._cache_identity(b)
        treedef = jax.tree_util.tree_structure(self._params)
        # Compiled.call trees for predict(params, x) -> (alpha, beta);
        # 0 stands in for any array leaf.
        in_tree = jax.tree_util.tree_structure(((self._params, 0), {}))
        out_tree = jax.tree_util.tree_structure((0, 0))

        def run_golden(compiled, golden):
            n_leaves = sum(1 for k2 in golden if k2.startswith("param_"))
            leaves = [golden[f"param_{i}"] for i in range(n_leaves)]
            ptree = jax.tree_util.tree_unflatten(treedef, leaves)
            pd = global_put(ptree, repl)
            xd = jax.device_put(np.ascontiguousarray(golden["x"]), x_sh)
            alpha, beta = compiled(pd, xd)
            return (
                np.asarray(jax.device_get(alpha)),
                np.asarray(jax.device_get(beta)),
            )

        return self.program_cache.load(
            key,
            fingerprint=fp,
            in_tree=in_tree,
            out_tree=out_tree,
            run_golden=run_golden,
        )

    def _cache_store(self, b: int, compiled, x_sh: NamedSharding) -> None:
        """Serialize a freshly compiled bucket with its golden-parity data
        (stored golden params = the CURRENT serving tree: future loads
        verify against these stored values, not whatever tree is serving
        then, so hot-swapped params don't invalidate parity)."""
        key, fp = self._cache_identity(b)
        x = self._golden_x(b)
        xd = jax.device_put(np.ascontiguousarray(x), x_sh)
        alpha, beta = compiled(self._params, xd)
        host_leaves = jax.tree_util.tree_leaves(jax.device_get(self._params))
        golden = {
            "x": x,
            "alpha": np.asarray(jax.device_get(alpha)),
            "beta": np.asarray(jax.device_get(beta)),
        }
        for i, leaf in enumerate(host_leaves):
            golden[f"param_{i}"] = np.asarray(leaf)
        self.program_cache.store(key, compiled, fingerprint=fp, golden=golden)

    def _compile_bucket(self, b: int) -> None:
        k, t, f = self.window_shape
        repl = replicated_sharding(self.mesh)
        # Shard the padded batch over the data axis when it divides evenly;
        # tiny buckets below the mesh size run replicated (a 1-window
        # request cannot be split 8 ways).
        if b % self.mesh.size == 0:
            x_sh = NamedSharding(self.mesh, P(DATA_AXIS))
        else:
            x_sh = repl
        compiled = None
        if self.program_cache is not None:
            compiled = self._cache_load(b, x_sh, repl)
        if compiled is not None:
            self.cache_hits += 1
        else:
            jfn = jax.jit(
                self._predict_fn,
                in_shardings=(repl, x_sh),
                out_shardings=(repl, repl),
            )
            x_struct = jax.ShapeDtypeStruct((b, k, t, f), jnp.float32)
            compiled = jfn.lower(self._params, x_struct).compile()
            self.compile_events += 1
            if self.program_cache is not None:
                self._cache_store(b, compiled, x_sh)
        self._compiled[b] = (compiled, x_sh)
        try:
            from masters_thesis_tpu.telemetry.costs import extract_cost

            self.cost_profiles[b] = extract_cost(
                compiled,
                program=f"serve_bucket_{b}",
                meta={
                    "bucket": b,
                    "platform": self.platform,
                    "mesh_size": self.mesh.size,
                },
            ).to_payload()
        except Exception:  # cost accounting must never block serving
            self.cost_profiles.pop(b, None)

    def warmup(self) -> float:
        """Compile every bucket and return the measured wall seconds of one
        max-bucket execution (seeds the queue's service-time model)."""
        for b in self.buckets:
            if b not in self._compiled:
                self._compile_bucket(b)
        k, t, f = self.window_shape
        x = np.zeros((self.max_bucket, k, t, f), np.float32)
        self.predict(x)  # execute once so the timing below is steady-state
        t0 = time.perf_counter()
        self.predict(x)
        return time.perf_counter() - t0

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise BucketOverflowError(
            f"batch of {n} exceeds largest compiled bucket "
            f"{self.max_bucket} (buckets: {self.buckets})"
        )

    def put_params(self, host_params: Any) -> Any:
        """Place a candidate host param tree device-resident with the
        serving sharding (canary staging; does NOT swap)."""
        return global_put(host_params, replicated_sharding(self.mesh))

    def set_params(self, device_params: Any) -> Any:
        """Atomically swap the serving params; returns the old tree (the
        swapper keeps it for rollback bookkeeping)."""
        with self._lock:
            old, self._params = self._params, device_params
            return old

    def predict(
        self, x: np.ndarray, params: Any = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Run one padded micro-batch through the bucket's AOT executable.

        ``params`` overrides the serving tree for this call only (the
        canary path evaluates a candidate without exposing it to traffic).
        Only explicit transfers: device_put in, device_get out.
        """
        x = np.asarray(x, np.float32)
        if x.ndim != 4 or x.shape[1:] != self.window_shape:
            raise ValueError(
                f"request shape {x.shape} != (n, {self.n_stocks}, "
                f"{self.lookback}, {self.n_features})"
            )
        n = x.shape[0]
        b = self.bucket_for(n)
        if n < b:
            # Pad by repeating the first window: finite data (padding with
            # garbage could manufacture inf/nan that trips output checks),
            # sliced off before returning.
            pad = np.broadcast_to(x[:1], (b - n,) + x.shape[1:])
            x = np.concatenate([x, pad], axis=0)
        compiled, x_sh = self._compiled[b]
        xd = jax.device_put(np.ascontiguousarray(x), x_sh)
        with self._lock:
            p = self._params if params is None else params
        alpha, beta = compiled(p, xd)
        return (
            np.asarray(jax.device_get(alpha))[:n],
            np.asarray(jax.device_get(beta))[:n],
        )

    def golden_batch(self, n: int = 1, seed: int = 0) -> np.ndarray:
        """Deterministic canary input matched to this engine's window shape."""
        k, t, f = self.window_shape
        rng = np.random.default_rng(seed)
        return rng.standard_normal((n, k, t, f)).astype(np.float32)

    def degrade_to_cpu(self) -> None:
        """Rebuild mesh + executables on the CPU backend (breaker policy).

        One deliberate compile burst — compile_events grows — after which
        the steady-state no-trace contract holds again on the new mesh.
        """
        from masters_thesis_tpu.utils.backend_probe import pin_cpu_in_process

        host_params = jax.device_get(self._params)
        pin_cpu_in_process()
        cpu = jax.devices("cpu")
        with self._lock:
            self.mesh = Mesh(np.asarray(cpu[:1]), axis_names=(DATA_AXIS,))
            self._params = global_put(
                host_params, replicated_sharding(self.mesh)
            )
            self._compiled.clear()
            self.cost_profiles.clear()
            for b in self.buckets:
                self._compile_bucket(b)  # mtt: disable=CL503 -- CPU-degrade failover must swap params+programs atomically; callers accept the pause

    @classmethod
    def from_checkpoint(
        cls,
        ckpt_dir,
        tag: str = "best",
        *,
        n_stocks: int,
        n_features: int = 3,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        mesh: Mesh | None = None,
        program_cache=None,
    ) -> "PredictEngine":
        """Boot an engine from a published checkpoint, STRICT verification:
        serving never starts from a tree whose content cannot be proven."""
        from pathlib import Path

        from masters_thesis_tpu.train.checkpoint import (
            CorruptCheckpointError,
            restore_checkpoint,
            verify_checkpoint,
        )

        path = Path(ckpt_dir) / tag
        if not verify_checkpoint(path, require_manifest=True):
            raise CorruptCheckpointError(
                f"refusing to serve from {path}: strict manifest "
                "verification failed (missing or mismatched MANIFEST.json)"
            )
        params, _, spec, meta = restore_checkpoint(ckpt_dir, tag)
        lookback = meta.get("datamodule", {}).get("lookback_window")
        if lookback is None:
            raise ValueError(
                f"checkpoint sidecar for {path} has no "
                "datamodule.lookback_window; cannot size predict programs"
            )
        return cls(
            spec,
            params,
            n_stocks=n_stocks,
            lookback=int(lookback),
            n_features=n_features,
            buckets=buckets,
            mesh=mesh,
            program_cache=program_cache,
        )
