"""Deadline-aware micro-batching queue with admission control (jax-free).

The request path's robustness rules live here, deliberately independent of
any backend:

- every request carries an absolute deadline (monotonic clock);
- the queue fires a micro-batch when ``max_batch`` requests are waiting or
  the oldest waiting request has aged ``max_wait_s`` — whichever first;
- admission control sheds load EARLY: a request whose deadline the current
  backlog already makes infeasible (estimated via an EWMA of measured
  batch service time) is rejected at submit time with an explicit ``shed``
  response instead of being served late — a late answer is worthless to
  the caller and steals capacity from every request behind it;
- the server converts any response that would still be delivered past its
  deadline into an explicit rejection (server.py): the engine never
  returns a late answer as if it were good;
- admission is TENANT-aware: every request bills to a tenant
  (:class:`TenantClass`), each tenant carries its own deadline class (the
  default budget for its requests), its own shed accounting, and its own
  EWMA service model — once a tenant has been served at least one batch,
  its admission forecasts use its own measured rate instead of the
  queue-wide aggregate, so one tenant's pathological traffic cannot
  silently distort another's admission decisions.

Fault point ``serve.admit`` (kind ``wedge``) forces a shed at submit time,
so the chaos suite can drive deterministic overload decisions without
having to race the real clock; kind ``shift`` applies a seeded
scale/offset regime shift to the admitted window's features instead — the
deterministic trigger for the model-quality drift detectors.

Jax-free by contract: ``python -m masters_thesis_tpu.serve selfcheck``
drives this module (and the server loop) with a fake engine on operator
machines where touching the backend can hang (docs/OPERATIONS.md).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from masters_thesis_tpu.resilience import faults

#: Response statuses. ``shed`` and ``rejected_late`` are both explicit
#: rejections — the difference is WHEN the server gave up: at admission
#: (predicted infeasible) vs. after compute (finished past the deadline).
STATUS_OK = "ok"
STATUS_SHED = "shed"
STATUS_REJECTED_LATE = "rejected_late"
STATUS_ERROR = "error"


#: Tenant assigned to requests that don't declare one. Single-tenant
#: deployments never see tenancy — the default tenant is auto-registered
#: and all accounting folds into it.
DEFAULT_TENANT = "default"


@dataclass
class ServeRequest:
    """One predict request: a single window ``x`` of shape (K, T, F) plus
    an absolute deadline on the monotonic clock."""

    rid: int
    x: Any  # np.ndarray (K, T, F); typed Any to keep this module jax/np-light
    deadline_ts: float
    submitted_ts: float = field(default_factory=time.monotonic)
    #: Logical tenant this request bills to (stacked serving: typically
    #: the lane owner). Pure accounting/admission metadata — dispatch
    #: fans every request across all lanes regardless.
    tenant: str = DEFAULT_TENANT


@dataclass
class ServeResponse:
    rid: int
    status: str  # STATUS_* above
    outputs: tuple | None = None  # (alpha (K,), beta (K,)) when ok
    detail: str = ""
    delivered_ts: float = 0.0
    latency_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK


class PendingRequest:
    """Future for a submitted request; resolved exactly once."""

    def __init__(self, request: ServeRequest):
        self.request = request
        self._done = threading.Event()
        self._response: ServeResponse | None = None

    def resolve(self, response: ServeResponse) -> None:
        if self._done.is_set():  # first resolution wins (shed vs late race)
            return
        self._response = response
        self._done.set()

    def result(self, timeout: float | None = None) -> ServeResponse:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request.rid} unresolved after {timeout}s"
            )
        assert self._response is not None
        return self._response

    @property
    def done(self) -> bool:
        return self._done.is_set()


class ServiceTimeModel:
    """EWMA of measured per-batch service seconds.

    Admission control needs a forecast, not an average over history: the
    EWMA tracks the CURRENT service rate (which shifts when the server
    degrades to CPU) while smoothing over per-batch jitter. Thread-safe;
    written by the dispatch thread, read by every submitter.
    """

    def __init__(self, alpha: float = 0.3, initial_s: float = 0.05):
        self.alpha = alpha
        self._batch_s = initial_s
        self._lock = threading.Lock()

    @property
    def batch_s(self) -> float:
        with self._lock:
            return self._batch_s

    def seed(self, batch_s: float) -> None:
        """Reset to a measured value (the engine's warmup timing)."""
        with self._lock:
            self._batch_s = max(1e-6, batch_s)

    def update(self, batch_s: float) -> None:
        with self._lock:
            self._batch_s = (
                self.alpha * max(1e-6, batch_s)
                + (1.0 - self.alpha) * self._batch_s
            )

    def estimate_completion_s(self, queue_depth: int, max_batch: int) -> float:
        """Seconds until a request admitted NOW would complete: the batches
        already ahead of it, plus its own batch."""
        batches_ahead = queue_depth // max(1, max_batch)
        return (batches_ahead + 1) * self.batch_s


@dataclass
class TenantClass:
    """Admission policy + accounting for one tenant (jax-free).

    ``deadline_s`` is the tenant's deadline CLASS: the default budget
    stamped on its requests when the caller doesn't carry an explicit
    one (an interactive tenant rides a tight class, a batch tenant a
    loose one). The per-tenant :class:`ServiceTimeModel` tracks the
    service rate THIS tenant's batches actually see — seeded from the
    queue-wide model at registration, updated only by this tenant's
    dispatches — so per-tenant admission forecasts stay honest even when
    tenants' deadline classes differ by orders of magnitude.
    """

    name: str
    deadline_s: float | None = None
    model: ServiceTimeModel = field(default_factory=ServiceTimeModel)
    admitted: int = 0
    shed: int = 0
    #: Batches this tenant has actually been served in. Until the first
    #: one, admission falls back to the queue-wide model — a freshly
    #: onboarded tenant must not forecast from an unseeded EWMA.
    observed: int = 0

    def stats(self) -> dict:
        return {
            "admitted": self.admitted,
            "shed": self.shed,
            "deadline_ms": (
                None if self.deadline_s is None else self.deadline_s * 1e3
            ),
            "batch_ms": self.model.batch_s * 1e3,
        }


class MicroBatchQueue:
    """Bounded FIFO with deadline admission and max-wait/max-batch firing."""

    def __init__(
        self,
        max_batch: int = 8,
        max_wait_s: float = 0.005,
        max_depth: int = 256,
        service_model: ServiceTimeModel | None = None,
        on_shed: Callable[[ServeRequest, str], None] | None = None,
        feasibility: Callable[[ServeRequest, int], str | None] | None = None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1: {max_batch}")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.max_depth = max_depth
        self.service_model = service_model or ServiceTimeModel()
        self.on_shed = on_shed
        #: Optional admission override ``(request, depth) -> reason | None``.
        #: The fleet installs one that consults each DISPATCHING replica's
        #: own service-time model (shed only when ALL serving replicas are
        #: infeasible) — a degraded-to-CPU replica's slow EWMA must not
        #: poison admission for healthy replicas, and a single global model
        #: cannot express that. ``None`` keeps the single-engine behavior:
        #: the queue-wide ``service_model`` estimate.
        self.feasibility = feasibility
        self._items: list[PendingRequest] = []
        self._cond = threading.Condition()
        self._closed = False
        self.submitted = 0
        self.shed = 0
        #: Per-tenant admission state, keyed by tenant name. The default
        #: tenant always exists so single-tenant callers never special-case.
        self._tenants: dict[str, TenantClass] = {}
        self.tenant(DEFAULT_TENANT)

    def __len__(self) -> int:
        with self._cond:
            return len(self._items)

    # ------------------------------------------------------------- tenancy

    def tenant(
        self, name: str, deadline_s: float | None = None
    ) -> tuple[TenantClass, bool]:
        """Look up (auto-registering) a tenant; returns ``(class, created)``.

        A new tenant's EWMA seeds from the queue-wide model's CURRENT
        estimate so its first forecast reflects the engine warmup timing
        rather than the class default. ``deadline_s`` (re)pins the
        tenant's deadline class when given.
        """
        with self._cond:
            t = self._tenants.get(name)
            if t is None:
                t = TenantClass(
                    name=name,
                    deadline_s=deadline_s,
                    model=ServiceTimeModel(
                        initial_s=self.service_model.batch_s
                    ),
                )
                self._tenants[name] = t
                return t, True
            if deadline_s is not None:
                t.deadline_s = deadline_s
            return t, False

    def tenant_deadline_s(self, name: str) -> float | None:
        """The tenant's deadline class (None when it never declared one)."""
        with self._cond:
            t = self._tenants.get(name)
            return t.deadline_s if t is not None else None

    def note_service(self, tenants, batch_s: float) -> None:
        """Fold one measured batch service time into each named tenant's
        EWMA (called by the dispatch loop after compute)."""
        with self._cond:
            ts = [
                self._tenants[n] for n in set(tenants) if n in self._tenants
            ]
            for t in ts:
                t.observed += 1
        for t in ts:  # EWMA has its own lock; keep it out of _cond
            t.model.update(batch_s)

    def tenant_stats(self) -> dict:
        """``{tenant: {admitted, shed, deadline_ms, batch_ms}}`` snapshot."""
        with self._cond:
            return {
                name: t.stats()
                for name, t in sorted(self._tenants.items())
            }

    def _shed(self, pending: PendingRequest, reason: str) -> PendingRequest:
        # Only the counter bump takes the lock: resolving the pending and
        # the on_shed callback must run unlocked (the fleet's on_shed
        # takes FleetServer._lock — holding _cond across it would create
        # a lock-order inversion against the dispatch path).
        with self._cond:
            self.shed += 1
            t = self._tenants.get(pending.request.tenant)
            if t is not None:
                t.shed += 1
        now = time.monotonic()
        pending.resolve(
            ServeResponse(
                rid=pending.request.rid,
                status=STATUS_SHED,
                detail=reason,
                delivered_ts=now,
                latency_s=now - pending.request.submitted_ts,
            )
        )
        if self.on_shed is not None:
            self.on_shed(pending.request, reason)
        return pending

    def submit(self, request: ServeRequest) -> PendingRequest:
        """Admit or shed; always returns a PendingRequest (a shed one is
        already resolved). Never blocks on capacity — backpressure is an
        explicit rejection, not a stalled caller."""
        pending = PendingRequest(request)
        tenant, _ = self.tenant(request.tenant)
        with self._cond:
            self.submitted += 1
            depth = len(self._items)
            closed = self._closed
        if closed:
            return self._shed(pending, "server shutting down")
        fired = faults.fire("serve.admit", rid=request.rid, depth=depth)
        if fired == "wedge":
            return self._shed(pending, "injected admission shed (fault)")
        if fired == "shift":
            # Seeded scale/offset regime shift on the window features —
            # the request is still served, but its data now comes from a
            # shifted regime (the quality plane's deterministic trigger).
            scale, offset = faults.shift_params()
            request.x = (request.x * scale + offset).astype(
                request.x.dtype, copy=False
            )
        if depth >= self.max_depth:
            return self._shed(pending, f"queue full (depth {depth})")
        if self.feasibility is not None:
            reason = self.feasibility(request, depth)
            if reason is not None:
                return self._shed(pending, reason)
        else:
            # Forecast with the tenant's OWN service model once it has
            # seen a batch (its requests may systematically differ from
            # the aggregate); a fresh tenant uses the queue-wide EWMA.
            model = (
                tenant.model if tenant.observed > 0 else self.service_model
            )
            est = model.estimate_completion_s(depth, self.max_batch)
            now = time.monotonic()
            if now + est > request.deadline_ts:
                budget_ms = (request.deadline_ts - now) * 1e3
                return self._shed(
                    pending,
                    f"deadline infeasible: est {est * 1e3:.1f}ms > "
                    f"budget {budget_ms:.1f}ms at depth {depth}",
                )
        with self._cond:
            if self._closed:  # re-check under the lock (close() raced us)
                pass
            else:
                self._items.append(pending)
                tenant.admitted += 1
                self._cond.notify_all()
                return pending
        return self._shed(pending, "server shutting down")

    def next_batch(self, timeout_s: float = 0.1) -> list[PendingRequest]:
        """Block until a micro-batch is ready; [] on timeout or close.

        Fires when ``max_batch`` requests are waiting, or the oldest
        waiting request has aged ``max_wait_s`` — latency is bounded by
        max-wait even at low QPS, throughput by max-batch at high QPS.
        """
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while True:
                now = time.monotonic()
                if self._items:
                    oldest = self._items[0].request.submitted_ts
                    fire_at = oldest + self.max_wait_s
                    if (
                        len(self._items) >= self.max_batch
                        or now >= fire_at
                        or self._closed
                    ):
                        batch = self._items[: self.max_batch]
                        del self._items[: len(batch)]
                        return batch
                    wake = min(fire_at, deadline)
                else:
                    if self._closed or now >= deadline:
                        return []
                    wake = deadline
                if now >= wake:
                    # Timed out while a batch is still aging toward its
                    # max-wait; hand control back so the caller can re-poll
                    # (and observe a stop request) instead of spinning.
                    return []
                self._cond.wait(wake - now)

    def close(self) -> None:
        """Stop admitting; wake consumers so they can drain the remainder."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed
