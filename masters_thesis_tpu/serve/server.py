"""The serving loop: queue -> engine dispatch, deadline enforcement,
circuit-breaker CPU degradation. Jax-free at import — the engine (real or
fake) is injected, so the selfcheck CLI can drive this exact loop without
a backend.

Invariants this module owns:

- **No late answers.** A response is delivered as ``ok`` only if it is
  handed back BEFORE the request's deadline; a batch that finishes late
  resolves those requests as explicit ``rejected_late`` rejections. The
  ``late_deliveries`` counter (an ok delivered past its deadline) must
  therefore stay 0 by construction — bench.py --serve exits nonzero if it
  ever isn't.
- **Degrade, don't flail.** Dispatch errors feed a
  :class:`~masters_thesis_tpu.utils.backend_probe.CircuitBreaker`;
  ``breaker_threshold`` consecutive failures buy exactly ONE backend
  probe (``BackendHealth.ensure_responsive(single_attempt=True)``). If
  the probe fails, the engine rebuilds on the CPU mesh and a
  ``degradation`` event is recorded — same policy, same event kind, as
  the training supervisor.
- **Non-finite outputs never leave.** A batch whose outputs contain
  NaN/inf resolves as ``error`` — the canary gate (swap.py) keeps bad
  params out, this is the last-line check for runtime corruption.

Fault points: ``serve.dispatch`` kind ``wedge`` simulates a device error
at dispatch (feeding the breaker); kind ``nan`` poisons a batch's outputs
(exercising the finite check). ``serve.admit`` is handled in queue.py.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.serve.queue import (
    DEFAULT_TENANT,
    STATUS_ERROR,
    STATUS_OK,
    STATUS_REJECTED_LATE,
    MicroBatchQueue,
    PendingRequest,
    ServeRequest,
    ServeResponse,
    ServiceTimeModel,
)
from masters_thesis_tpu.serve.spans import RequestSpans
from masters_thesis_tpu.utils.backend_probe import CircuitBreaker


class InjectedDeviceError(RuntimeError):
    """Stand-in for a device/runtime failure (serve.dispatch wedge)."""


def shed_category(reason: str) -> str:
    """Collapse the queue's free-text shed reasons into stable categories
    (the trace CLI and bench report break sheds down by these)."""
    if reason.startswith("server shutting down"):
        return "shutdown"
    if reason.startswith("injected admission shed"):
        return "fault"
    if reason.startswith("queue full"):
        return "queue_full"
    if reason.startswith("deadline infeasible"):
        return "deadline_infeasible"
    if reason.startswith("no live replicas"):
        return "no_live_replicas"
    if reason.startswith("replica"):
        return "replica_death"
    return "other"


class PredictServer:
    """Owns the queue, the dispatch thread, and the degradation policy."""

    def __init__(
        self,
        engine,
        *,
        max_batch: int | None = None,
        max_wait_s: float = 0.005,
        max_depth: int = 256,
        telemetry=None,
        health=None,
        breaker_threshold: int = 3,
        metrics_port: int | None = None,
        slo_rules=None,
        quality_monitor=None,
    ):
        self.engine = engine
        self.telemetry = telemetry
        # Model-quality plane (telemetry/quality.py): 1-in-K sampler over
        # *delivered* responses, fed strictly after _resolve with the
        # host-side arrays already in hand — never on the device path.
        self.quality = quality_monitor
        self.health = health
        # Live telemetry plane (telemetry/exposition.py): /metrics +
        # /slo over this server's registry. None disables; 0 binds an
        # ephemeral port. Reader-side only — started in start(), never
        # touched by the dispatch hot path.
        self.metrics_port = metrics_port
        self._slo_rules = slo_rules
        self._exposition = None
        self._slo_engine = None
        self.breaker = CircuitBreaker(breaker_threshold)
        self.service_model = ServiceTimeModel()
        # The queue's micro-batch can never exceed the largest compiled
        # bucket — a bigger batch would have to trace a new program.
        cap = engine.max_bucket
        self.max_batch = min(max_batch, cap) if max_batch else cap
        self.queue = MicroBatchQueue(
            max_batch=self.max_batch,
            max_wait_s=max_wait_s,
            max_depth=max_depth,
            service_model=self.service_model,
            on_shed=self._on_shed,
        )
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._rid = 0
        self._rid_lock = threading.Lock()
        self._started_ts: float | None = None
        self._dispatch_seq = 0
        # Stats counters are written from the dispatch thread and read by
        # stats() from whatever thread asks; _stats_lock keeps the
        # increments atomic and the snapshot consistent.
        self._stats_lock = threading.Lock()
        self.completed = 0
        self.errors = 0
        self.late_converted = 0
        #: ok responses delivered past their deadline — 0 by construction;
        #: anything else is a bug and fails the serve bench.
        self.late_deliveries = 0
        self.degradations = 0
        self.shed_by_reason: dict[str, int] = {}
        # Per-request trace state (serve/spans.py): each request span's
        # boundaries tile its wall exactly, so the trace CLI's
        # critical-path components sum to measured latency by construction.
        self._serve_span = None
        self.spans = RequestSpans(self._tracer)
        self._trace_lock = threading.Lock()

    # ------------------------------------------------------------ telemetry

    def _event(self, kind: str, **payload) -> None:
        if self.telemetry is not None:
            self.telemetry.event(kind, **payload)

    def _count(self, name: str, n: int = 1) -> None:
        if self.telemetry is not None:
            self.telemetry.counter(f"serve/{name}").inc(n)

    def _bump(self, name: str, n: int = 1) -> None:
        """Locked increment of a stats counter, mirrored to telemetry."""
        with self._stats_lock:
            setattr(self, name, getattr(self, name) + n)
        self._count(name, n)

    def _observe_latency(self, latency_s: float) -> None:
        if self.telemetry is not None:
            self.telemetry.histogram("serve/latency_s").observe(latency_s)

    def _tracer(self):
        return self.telemetry.tracer if self.telemetry is not None else None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("server already started")
        warm_s = self.engine.warmup()
        self.service_model.seed(warm_s)
        self._started_ts = time.monotonic()
        tracer = self._tracer()
        if tracer is not None:
            self._serve_span = tracer.start(
                "serve.server",
                platform=self.engine.platform,
                max_batch=self.max_batch,
            )
        self._event(
            "serve_started",
            platform=self.engine.platform,
            buckets=list(self.engine.buckets),
            max_batch=self.max_batch,
            max_wait_ms=self.queue.max_wait_s * 1e3,
            warmup_batch_ms=warm_s * 1e3,
            compile_events=self.engine.compile_events,
        )
        # One cost_profile event per bucket executable (extracted by the
        # engine at compile time, so this is pure event I/O): FLOPs, bytes
        # accessed, peak memory — the summarize CLI's utilization section
        # and preflight SV304 both read these numbers.
        warned = False
        # getattr: injected fake engines (selfcheck CLI) carry no profiles.
        profiles = getattr(self.engine, "cost_profiles", {})
        for b in self.engine.buckets:
            payload = profiles.get(b)
            if payload:
                self._event("cost_profile", **payload)
            elif not warned:  # warn-once; summarize renders "n/a"
                warned = True
                self._event("cost_unavailable",
                            program=f"serve_bucket_{b}")
        self._thread = threading.Thread(
            target=self._worker, name="serve-dispatch", daemon=True
        )
        self._thread.start()
        if self.metrics_port is not None and self.telemetry is not None:
            from masters_thesis_tpu.telemetry.exposition import (
                start_telemetry_plane,
            )

            self._exposition, self._slo_engine = start_telemetry_plane(
                self.telemetry, self.metrics_port, rules=self._slo_rules
            )

    def stop(self) -> dict:
        """Drain, stop the dispatch thread, emit ``serve_finished``;
        returns the summary stats dict the event carries."""
        if self._exposition is not None or self._slo_engine is not None:
            from masters_thesis_tpu.telemetry.exposition import (
                stop_telemetry_plane,
            )

            stop_telemetry_plane(self._exposition, self._slo_engine)
            self._exposition = self._slo_engine = None
        self.queue.close()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        self._stop.set()
        stats = self.stats()
        tracer = self._tracer()
        if tracer is not None and self._serve_span is not None:
            tracer.end(
                self._serve_span,
                status="ok",
                requests=stats["requests"],
                completed=stats["completed"],
                shed=stats["shed"],
            )
            self._serve_span = None
        self._event("serve_finished", **stats)
        return stats

    def stats(self) -> dict:
        span = (
            time.monotonic() - self._started_ts
            if self._started_ts is not None
            else 0.0
        )
        p50 = p99 = None
        if self.telemetry is not None:
            hist = self.telemetry.histogram("serve/latency_s")
            p50, p99 = hist.quantile(0.5), hist.quantile(0.99)
        queue_wait_share, compute_share = self.spans.shares()
        with self._trace_lock:
            shed_by_reason = dict(self.shed_by_reason)
        return {
            "queue_wait_share": queue_wait_share,
            "compute_share": compute_share,
            "shed_by_reason": shed_by_reason,
            "tenants": self.queue.tenant_stats(),
            "lanes": getattr(self.engine, "num_lanes", 1),
            "requests": self.queue.submitted,
            "completed": self.completed,
            "shed": self.queue.shed,
            "errors": self.errors,
            "late_converted": self.late_converted,
            "late_deliveries": self.late_deliveries,
            "degradations": self.degradations,
            "p50_ms": None if p50 is None else p50 * 1e3,
            "p99_ms": None if p99 is None else p99 * 1e3,
            "qps": self.completed / span if span > 0 else 0.0,
            "wall_s": span,
        }

    # -------------------------------------------------------------- request

    def register_tenant(
        self, name: str, deadline_s: float | None = None
    ) -> None:
        """Onboard (or re-class) a tenant: pins its deadline class on the
        queue and emits ``tenant_admitted`` the first time the serving
        plane sees it — the operator-visible onboarding record."""
        _, created = self.queue.tenant(name, deadline_s)
        if created:
            self._event(
                "tenant_admitted",
                tenant=name,
                deadline_ms=(
                    None if deadline_s is None else deadline_s * 1e3
                ),
            )

    def submit(
        self,
        x,
        deadline_s: float | None = None,
        tenant: str = DEFAULT_TENANT,
    ) -> PendingRequest:
        """Admit one window with a relative deadline budget in seconds.

        ``deadline_s=None`` falls back to ``tenant``'s deadline class
        (register_tenant); a request with neither is a caller bug.
        An unregistered tenant is onboarded on first submit (with the
        ``tenant_admitted`` event) so accounting never drops requests.
        """
        x = np.asarray(x, np.float32)
        if x.shape != tuple(self.engine.window_shape):
            raise ValueError(
                f"request window shape {x.shape} != engine window shape "
                f"{tuple(self.engine.window_shape)}"
            )
        if deadline_s is None:
            deadline_s = self.queue.tenant_deadline_s(tenant)
            if deadline_s is None:
                raise ValueError(
                    f"request carries no deadline and tenant {tenant!r} "
                    "has no deadline class (register_tenant first)"
                )
        self.register_tenant(tenant)
        with self._rid_lock:
            self._rid += 1
            rid = self._rid
        self._count("requests")
        # The span must exist BEFORE queue.submit: a shed resolves
        # synchronously inside it, and _on_shed closes the span.
        self.spans.open(
            rid, "serve.request",
            parent=self._serve_span, deadline_ms=deadline_s * 1e3,
        )
        pending = self.queue.submit(
            ServeRequest(
                rid=rid, x=x, deadline_ts=time.monotonic() + deadline_s,
                tenant=tenant,
            )
        )
        if not pending.done:
            self.spans.stamp(rid, "t_admitted")
        return pending

    def _on_shed(self, request: ServeRequest, reason: str) -> None:
        self._count("shed")
        category = shed_category(reason)
        with self._trace_lock:
            self.shed_by_reason[category] = (
                self.shed_by_reason.get(category, 0) + 1
            )
        self._event("request_shed", rid=request.rid, reason=reason)
        self.spans.close_shed(request.rid, category)

    # ------------------------------------------------------------- dispatch

    def _worker(self) -> None:
        while True:
            batch = self.queue.next_batch(timeout_s=0.05)
            if not batch:
                if self.queue.closed and len(self.queue) == 0:
                    return
                continue
            self.spans.stamp_many(
                [p.request.rid for p in batch], "t_pickup",
                time.perf_counter(),
            )
            self._dispatch(batch)

    def _resolve(self, pending: PendingRequest, status: str, detail: str = "",
                 outputs: tuple | None = None) -> None:
        now = time.monotonic()
        t_resolve = time.perf_counter()
        pending.resolve(
            ServeResponse(
                rid=pending.request.rid,
                status=status,
                outputs=outputs,
                detail=detail,
                delivered_ts=now,
                latency_s=now - pending.request.submitted_ts,
            )
        )
        self.spans.close(pending.request.rid, status, t_resolve)

    def _dispatch(self, batch: list[PendingRequest]) -> None:
        # Pre-dispatch feasibility re-check: queue wait may have eaten a
        # request's whole budget; spending device time on it would only
        # produce a late answer — reject now, serve the rest.
        est = self.service_model.batch_s
        now = time.monotonic()
        live: list[PendingRequest] = []
        for p in batch:
            if now + est > p.request.deadline_ts:
                self._bump("late_converted")
                self._resolve(
                    p, STATUS_REJECTED_LATE,
                    "deadline infeasible at dispatch (queue wait consumed "
                    "the budget); rejected rather than served late",
                )
            else:
                live.append(p)
        if not live:
            return
        with self._stats_lock:
            seq = self._dispatch_seq
            self._dispatch_seq += 1
        kind = faults.fire("serve.dispatch", seq=seq, n=len(live))
        tracer = self._tracer()
        t0_wall = time.time()
        t0 = time.perf_counter()
        live_rids = [p.request.rid for p in live]

        def stamp(key: str, t: float) -> None:
            self.spans.stamp_many(live_rids, key, t)

        stamp("t_predict0", t0)
        try:
            if kind == "wedge":
                raise InjectedDeviceError(
                    f"injected device error at dispatch seq={seq}"
                )
            xs = np.stack([p.request.x for p in live])
            alpha, beta = self.engine.predict(xs)
            if kind == "nan":
                alpha = np.full_like(alpha, np.nan)
        except Exception as exc:  # noqa: BLE001 — any dispatch failure
            stamp("t_predict_end", time.perf_counter())
            self._bump("errors", len(live))
            for p in live:
                self._resolve(
                    p, STATUS_ERROR, f"{type(exc).__name__}: {exc}"
                )
            if self.breaker.record_failure():
                self._degrade(exc)
            return
        device_s = time.perf_counter() - t0
        stamp("t_predict_end", t0 + device_s)
        if tracer is not None:
            tracer.emit_span(
                "serve.device",
                start_ts=t0_wall,
                dur_s=device_s,
                parent=self._serve_span,
                seq=seq,
                n=len(live),
            )
        self.service_model.update(device_s)
        # Per-tenant EWMA: each tenant in this batch saw this service time.
        self.queue.note_service(
            {p.request.tenant for p in live}, device_s
        )
        self.breaker.record_success()
        finite = bool(
            np.isfinite(alpha).all() and np.isfinite(beta).all()
        )
        now = time.monotonic()
        delivered: list[int] = []
        for i, p in enumerate(live):
            if not finite:
                self._bump("errors")
                self._resolve(
                    p, STATUS_ERROR,
                    "non-finite predictions; response withheld",
                )
            elif now > p.request.deadline_ts:
                self._bump("late_converted")
                self._resolve(
                    p, STATUS_REJECTED_LATE,
                    "batch completed past the deadline; rejected rather "
                    "than delivered late",
                )
            else:
                self._bump("completed")
                latency = now - p.request.submitted_ts
                self._observe_latency(latency)
                self._resolve(
                    p, STATUS_OK, outputs=(alpha[i], beta[i])
                )
                delivered.append(i)
                if time.monotonic() > p.request.deadline_ts:
                    # The delivery itself slid past the deadline — this
                    # must never happen (the check above runs against the
                    # same clock); count it so the bench can fail loudly.
                    self._bump("late_deliveries")
        if self.quality is not None:
            # Strictly post-delivery: every sampled response has already
            # been resolved to its caller, and alpha/beta/x are host
            # numpy — zero new fences or transfers on the hot path.
            # Stacked engines deliver per-lane (R, K) outputs per window;
            # the quality plane monitors THE served answer, which for an
            # ensemble is its mean across lanes.
            for i in delivered:
                a_i, b_i = alpha[i], beta[i]
                if a_i.ndim == 2:
                    a_i, b_i = a_i.mean(axis=0), b_i.mean(axis=0)
                self.quality.sample(live[i].request.x, a_i, b_i)

    # ----------------------------------------------------------- degrade

    def _degrade(self, cause: Exception) -> None:
        """Breaker tripped: ONE probe via the shared BackendHealth, then
        either keep the backend (transient errors) or rebuild on CPU."""
        attempts = None
        if self.health is not None:
            decision = self.health.ensure_responsive(single_attempt=True)
            attempts = decision.attempts
            if decision.ok:
                self._event(
                    "breaker_probe_ok",
                    trips=self.breaker.trips,
                    attempts=attempts,
                    cause=repr(cause),
                )
                return
        self._bump("degradations")
        self.engine.degrade_to_cpu()
        self.service_model.seed(self.engine.warmup())
        self._event(
            "degradation",
            scope="serve",
            reason=f"circuit breaker tripped: {cause!r}",
            probe_attempts=attempts,
            platform=self.engine.platform,
        )
