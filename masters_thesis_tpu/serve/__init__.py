"""Low-latency predict serving with graceful degradation.

The serving stack, bottom to top:

- :mod:`~masters_thesis_tpu.serve.queue` — deadline-aware micro-batching
  with admission control (jax-free).
- :mod:`~masters_thesis_tpu.serve.engine` — AOT-compiled predict programs
  per bucketed batch shape; steady-state serving never traces.
- :mod:`~masters_thesis_tpu.serve.swap` — canaried checkpoint hot-swap:
  strict manifest verification, golden-batch canary, atomic swap/rollback.
- :mod:`~masters_thesis_tpu.serve.server` — the dispatch loop: deadline
  enforcement (no late answers, ever) and the circuit-breaker CPU
  degradation policy.
- :mod:`~masters_thesis_tpu.serve.fleet` — N engine replicas on disjoint
  device subsets behind one queue: least-loaded dispatch, per-replica
  health states, dead-replica re-dispatch, supervised restart (jax-free).
- :mod:`~masters_thesis_tpu.serve.program_cache` — content-addressed
  on-disk cache of serialized predict executables: restarts and hot-swaps
  boot with zero compiles; torn/stale entries refused, never trusted.
- :mod:`~masters_thesis_tpu.serve.preflight` — tracelint-style audit of
  the hot path (SV301–SV306): zero recompiles, no implicit transfers,
  warm-cache zero-compile boot, single-replica-death survival.

Importing this package (and queue/server) stays jax-free so
``python -m masters_thesis_tpu.serve selfcheck`` runs on machines where
backend init can hang; the engine/swap/preflight symbols below import
lazily on first access.
"""

from masters_thesis_tpu.serve.queue import (
    MicroBatchQueue,
    PendingRequest,
    ServeRequest,
    ServeResponse,
    ServiceTimeModel,
    TenantClass,
)
from masters_thesis_tpu.serve.fleet import (
    FleetServer,
    Replica,
    ReplicaBootError,
)
from masters_thesis_tpu.serve.server import InjectedDeviceError, PredictServer
from masters_thesis_tpu.serve.spans import RequestSpans

_LAZY = {
    "ProgramCache": (
        "masters_thesis_tpu.serve.program_cache", "ProgramCache",
    ),
    "entry_key": ("masters_thesis_tpu.serve.program_cache", "entry_key"),
    "param_signature": (
        "masters_thesis_tpu.serve.program_cache", "param_signature",
    ),
    "PredictEngine": ("masters_thesis_tpu.serve.engine", "PredictEngine"),
    "BucketOverflowError": (
        "masters_thesis_tpu.serve.engine", "BucketOverflowError",
    ),
    "resolve_buckets": ("masters_thesis_tpu.serve.engine", "resolve_buckets"),
    "StackedPredictEngine": (
        "masters_thesis_tpu.serve.stacked", "StackedPredictEngine",
    ),
    "LaneMismatchError": (
        "masters_thesis_tpu.serve.stacked", "LaneMismatchError",
    ),
    "ensemble_stats": ("masters_thesis_tpu.serve.stacked", "ensemble_stats"),
    "lane_digest": ("masters_thesis_tpu.serve.stacked", "lane_digest"),
    "CheckpointSwapper": ("masters_thesis_tpu.serve.swap", "CheckpointSwapper"),
    "SwapVerdict": ("masters_thesis_tpu.serve.swap", "SwapVerdict"),
    "canary_checks": ("masters_thesis_tpu.serve.swap", "canary_checks"),
    "run_serve_preflight": (
        "masters_thesis_tpu.serve.preflight", "run_serve_preflight",
    ),
    "run_stacked_preflight": (
        "masters_thesis_tpu.serve.preflight", "run_stacked_preflight",
    ),
    "assert_serve_clean": (
        "masters_thesis_tpu.serve.preflight", "assert_serve_clean",
    ),
    "ServePreflightError": (
        "masters_thesis_tpu.serve.preflight", "ServePreflightError",
    ),
}


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)


__all__ = [
    "FleetServer",
    "InjectedDeviceError",
    "MicroBatchQueue",
    "PendingRequest",
    "PredictServer",
    "Replica",
    "ReplicaBootError",
    "RequestSpans",
    "ServeRequest",
    "ServeResponse",
    "ServiceTimeModel",
    "TenantClass",
    *sorted(_LAZY),
]
