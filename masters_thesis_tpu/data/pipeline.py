"""Windowed-dataset pipeline: preparation, caching, splits, and batch iteration.

Capability parity with the reference DataModule (reference: src/data.py:133-250)
without Lightning: the prepared dataset is cached under
``<data_dir>/datasets/`` keyed by a SHA-256 of the window hyperparameters
(same scheme as src/data.py:166-190), split chronologically 70/20/10, and
served as either

- a stream of per-window batches (train shuffled per epoch with an explicit
  seed; val/test sequential) for host-driven loops, or
- whole-split device-resident arrays for the ``lax.scan``-over-batches fast
  path, which keeps the entire epoch in HBM and is the TPU-idiomatic way to
  train a dataset this size (no per-step host round-trips at all).

The bootstrap helpers replace the reference's import-time side effects
(reference: train.py:15-36) with explicit, testable functions.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Iterator, NamedTuple

import numpy as np

from masters_thesis_tpu.data.fama_french import FamaFrench25Portfolios
from masters_thesis_tpu.data.synthetic import (
    SyntheticKFactorReturns,
    SyntheticLogReturns,
)
from masters_thesis_tpu.utils import (
    atomic_publish,
    atomic_write_text,
    multihost_rank,
    wait_until,
)
from masters_thesis_tpu.ops import (
    add_quadratic_features,
    lookback_target_split,
    ols_features,
)


class Batch(NamedTuple):
    """One training batch. Leading dims: ``(batch, n_stocks, ...)``.

    Schema matches the reference's TensorDataset columns
    (reference: src/data.py:216): ``x`` carries the feature-expanded lookback
    window, ``y`` the target window with channels
    ``[r_stock, f_1..f_F, alpha, beta_1..beta_F]`` (``[r_stock, r_market,
    alpha, beta]`` in the scalar F=1 case), plus per-window factor stats and
    inverse idiosyncratic variances.
    """

    x: np.ndarray  # (B, K, lookback, n_features)
    y: np.ndarray  # (B, K, target, 2F+2)
    factor: np.ndarray  # (B, 2) = (mean, var) at F=1; (B, F+F²) = [mean|cov]
    inv_psi: np.ndarray  # (B, K)


def bootstrap_synthetic(
    data_dir: Path,
    n_stocks: int = 100,
    n_samples: int = 1_000_000,
    seed: int = 0,
    variant: str = "no_outliers",
    marker_grace_s: float = 60.0,
    n_factors: int = 1,
) -> None:
    """Generate and save the synthetic market history if not already present.

    Mirrors the reference's first-run bootstrap (reference: train.py:30-36)
    with an explicit seed instead of torch global RNG state. A ``dgp.json``
    sidecar records the generation parameters and acts as the COMPLETION
    marker (written last, atomically); re-bootstrapping the same
    ``data_dir`` with different parameters — or over arrays missing the
    sidecar (torn/unknown provenance) — is an error, not a silent reuse or
    overwrite. Multi-host: process 0 generates, the rest wait for the
    marker (host-local dirs fall back to generating after the wait).
    """
    data_dir = Path(data_dir)
    requested = {
        "n_stocks": n_stocks, "n_samples": n_samples, "seed": seed,
        "variant": variant,
    }
    if n_factors != 1:
        # Only recorded off the scalar default so existing K=1 datasets (and
        # their byte-identical dgp.json markers) keep validating unchanged.
        requested["n_factors"] = n_factors
    meta_file = data_dir / "dgp.json"

    def check_existing() -> bool:
        if not (meta_file.exists() and (data_dir / "stocks.npy").exists()):
            return False
        existing = json.loads(meta_file.read_text())
        if existing != requested:
            raise ValueError(
                f"{data_dir} holds a synthetic dataset generated with "
                f"{existing}, but {requested} was requested — use a "
                "different data_dir or delete the old dataset"
            )
        return True

    if check_existing():
        return
    if (data_dir / "stocks.npy").exists():
        # A concurrent writer publishes arrays before the marker: give it a
        # grace window before declaring the directory torn (parallel sweep
        # workers sharing a fresh data_dir hit this routinely).
        if wait_until(check_existing, marker_grace_s):
            return
        raise ValueError(
            f"{data_dir} contains arrays without a dgp.json sidecar (torn "
            "bootstrap or pre-sidecar dataset of unknown provenance) — "
            "delete the directory to regenerate"
        )

    # multihost_rank (not jax.process_count) keeps single-host bootstrap off
    # the device backend entirely — a parent process bootstrapping data must
    # not take the one-per-process TPU relay lease as a side effect.
    rank, world = multihost_rank()
    if world > 1 and rank != 0:
        # Shared dir: wait for process 0's marker; host-local: generate.
        if wait_until(check_existing, 600.0):
            return

    data_dir.mkdir(parents=True, exist_ok=True)
    if n_factors == 1:
        r_stocks, r_market, alphas, betas = SyntheticLogReturns.generate(
            n_stocks, n_samples, seed, variant=variant
        )
        arrays = {
            "stocks.npy": r_stocks, "market.npy": r_market,
            "alphas.npy": alphas, "betas.npy": betas,
        }
    else:
        r_assets, factors, alphas, betas = SyntheticKFactorReturns.generate(
            n_stocks, n_samples, n_factors, seed, variant=variant
        )
        arrays = {
            "stocks.npy": r_assets, "factors.npy": factors,
            "alphas.npy": alphas, "betas.npy": betas,
        }
    for name, arr in arrays.items():
        # Atomic per-file publish: concurrent same-params writers (parallel
        # sweep jobs sharing a data_dir) never expose a torn .npy.
        with atomic_publish(data_dir / name) as tmp:
            with open(tmp, "wb") as f:
                np.save(f, np.asarray(arr))
    atomic_write_text(meta_file, json.dumps(requested, indent=2))


def bootstrap_real(raw_dir: Path, data_dir: Path) -> bool:
    """Convert raw Fama-French CSVs to arrays; returns False if CSVs absent.

    (Reference: train.py:24-28; downloading the CSVs is a manual step there
    too, train.py:19-22.)
    """
    data_dir = Path(data_dir)
    if (data_dir / "stocks.npy").exists():
        return True
    raw_dir = Path(raw_dir)
    if not (raw_dir / FamaFrench25Portfolios.ff3_filename).exists() or not (
        raw_dir / FamaFrench25Portfolios.p25_filename
    ).exists():
        return False
    data_dir.mkdir(parents=True, exist_ok=True)
    p25, mkt = FamaFrench25Portfolios.load(raw_dir)
    np.save(data_dir / "stocks.npy", p25)
    np.save(data_dir / "market.npy", mkt)
    return True


class FinancialWindowDataModule:
    """Prepares, caches, splits, and serves the windowed factor-model dataset."""

    def __init__(
        self,
        data_dir: Path,
        lookback_window: int = 60,
        target_window: int = 20,
        stride: int = 80,
        prediction_task: bool = True,
        interaction_only: bool = True,
        batch_size: int = 1,
        engine: str = "auto",
        store_shards: int | None = None,
    ):
        if engine not in ("auto", "native", "python"):
            raise ValueError(f"unknown engine: {engine!r}")
        if store_shards is not None and store_shards < 1:
            raise ValueError(f"store_shards must be >= 1, got {store_shards}")
        self.data_dir = Path(data_dir)
        self.lookback_window = lookback_window
        self.target_window = target_window
        self.stride = stride
        self.prediction_task = prediction_task
        self.interaction_only = interaction_only
        self.batch_size = batch_size
        self.engine = engine
        self.store_shards = store_shards

        self.train_range: range | None = None
        self.val_range: range | None = None
        self.test_range: range | None = None
        self._arrays: Batch | None = None
        self._store = None  # WindowStore when store_shards is set

        if not prediction_task and target_window > lookback_window:
            raise ValueError(
                "target window must be <= lookback window for reconstruction task"
            )

    # ------------------------------------------------------------------ prep

    @property
    def n_factors(self) -> int:
        """Factor count of the source series: rows of ``factors.npy`` when
        the K-factor DGP wrote one, else 1 (scalar market series)."""
        path = self.data_dir / "factors.npy"
        if not path.exists():
            return 1
        return int(np.load(path, mmap_mode="r").shape[0])

    @property
    def n_features(self) -> int:
        k = self.n_factors
        return 2 * k + 1 if self.interaction_only else 3 * k + 2

    @property
    def n_stocks(self) -> int | None:
        """Stocks per window (the LSTM kernel's row count), once ``setup``
        has loaded the arrays; None before that."""
        if getattr(self, "_store", None) is not None:
            return int(self._store.field_shape("x")[1])
        arrays = getattr(self, "_arrays", None)
        return None if arrays is None else int(arrays.x.shape[1])

    def _hparams_hash(self) -> str:
        """SHA-256 over the window hyperparameters AND a source fingerprint.

        (Reference: src/data.py:166-175 hashes only the window hparams —
        which goes stale silently if the source arrays are regenerated, e.g.
        with a different DGP variant. Including each source file's size +
        mtime and the dgp.json sidecar makes the windowed cache rebuild
        whenever its inputs change.)
        """
        hparams = {
            "lookback_window": self.lookback_window,
            "target_window": self.target_window,
            "stride": self.stride,
            "prediction_task": self.prediction_task,
            "interaction_only": self.interaction_only,
            "source": self._source_fingerprint(),
        }
        return hashlib.sha256(
            json.dumps(hparams, sort_keys=True).encode()
        ).hexdigest()

    def _source_fingerprint(self) -> list:
        """Content-based source identity: size + head-of-file digest.

        Deliberately NOT mtime-based — mtimes differ across hosts writing a
        shared dir, which would break the multi-host cache rendezvous. The
        first 64 KiB covers the npy header (shape/dtype) plus a content
        sample, so regenerating with a different DGP changes the key while
        byte-identical regeneration doesn't.
        """
        fingerprint: list = []
        for name in ("stocks.npy", "market.npy", "factors.npy", "dgp.json"):
            path = self.data_dir / name
            if path.exists():
                with open(path, "rb") as f:
                    head = f.read(65536)
                digest = hashlib.sha256(head).hexdigest()[:16]
                fingerprint.append([name, path.stat().st_size, digest])
        return fingerprint

    @property
    def _datasets_dir(self) -> Path:
        return self.data_dir / "datasets"

    def _load_if_exists(self, filename: str) -> np.ndarray | None:
        path = self.data_dir / filename
        return np.load(path) if path.exists() else None

    def prepare_data(
        self, verbose: bool = True, cache_timeout_s: float = 600.0
    ) -> None:
        """Build the windowed dataset and cache it, keyed by the hparams hash.

        Multi-host safe (SURVEY.md §7 hard parts: one writer or per-host
        caches): on a shared ``data_dir`` only process 0 builds and the
        others poll for the published cache; if nothing appears within
        ``cache_timeout_s`` the directory is host-local, and the process
        builds its own cache (atomic pid-suffixed publishing makes a
        concurrent duplicate build harmless). The hash file is written AFTER
        the dataset, so readers never observe a torn cache.
        """
        if self.store_shards is not None:
            self._prepare_store(verbose=verbose, cache_timeout_s=cache_timeout_s)
            return

        hparams_hash = self._hparams_hash()
        self._datasets_dir.mkdir(parents=True, exist_ok=True)
        hash_file = self._datasets_dir / "hparams_hash.txt"
        dataset_file = self._datasets_dir / "dataset.npz"

        def cache_ready() -> bool:
            return (
                hash_file.exists()
                and dataset_file.exists()
                and hash_file.read_text().strip() == hparams_hash
            )

        if cache_ready():
            if verbose:
                print("Dataset parameters unchanged, skipping data preparation")
            return
        rank, world = multihost_rank()  # backend-free: see bootstrap_synthetic
        if world > 1 and rank != 0:
            if wait_until(cache_ready, cache_timeout_s):
                return
            if verbose:
                print(
                    "no shared cache appeared; building a host-local one"
                )

        r_stocks, r_market, alphas, betas = self._load_source()

        x, y, t_alphas, t_betas, t_factor, t_inv_psi = self._build_windows(
            r_stocks, r_market, verbose=verbose
        )

        # Real data has no ground-truth coefficients; supervise with the
        # target-window OLS fit instead (reference: src/data.py:209-211).
        from masters_thesis_tpu.data.window_store import append_label_channels

        y = append_label_channels(np.asarray(y), t_alphas, t_betas, alphas, betas)

        # Atomic publish (dataset first, then hash): concurrent readers only
        # accept the cache once both files are complete and consistent.
        with atomic_publish(dataset_file) as tmp_dataset:
            with open(tmp_dataset, "wb") as f:  # handle: savez keeps the name
                np.savez(
                    f,
                    x=np.asarray(x),
                    y=y,
                    factor=np.asarray(t_factor),
                    inv_psi=np.asarray(t_inv_psi),
                )
        atomic_write_text(hash_file, hparams_hash)

    def _load_source(self):
        """Raw series + ground-truth labels: K-factor block when the DGP
        wrote ``factors.npy``, else the scalar market series."""
        r_stocks = np.load(self.data_dir / "stocks.npy")
        factors = self._load_if_exists("factors.npy")
        if factors is None:
            factors = np.load(self.data_dir / "market.npy")
        alphas = self._load_if_exists("alphas.npy")
        betas = self._load_if_exists("betas.npy")
        return r_stocks, factors, alphas, betas

    @property
    def _store_dir(self) -> Path:
        return self._datasets_dir / "window_store"

    def _prepare_store(self, verbose: bool, cache_timeout_s: float) -> None:
        """Build (or accept) the on-disk sharded window store.

        Same multi-host discipline as the npz cache: the manifest is the
        completion marker, a matching ``source_hash`` (the hparams hash) plus
        shard count means the store is current, and non-zero ranks poll
        before falling back to a host-local build.
        """
        from masters_thesis_tpu.data.window_store import (
            WindowStore,
            WindowStoreError,
        )

        hparams_hash = self._hparams_hash()
        n_shards = self.store_shards
        assert n_shards is not None

        def cache_ready() -> bool:
            try:
                store = WindowStore.open(self._store_dir)
            except WindowStoreError:
                return False
            return (
                store.source_hash == hparams_hash
                and store.n_shards == min(n_shards, store.n_windows)
            )

        if cache_ready():
            if verbose:
                print("Window store unchanged, skipping data preparation")
            return
        rank, world = multihost_rank()
        if world > 1 and rank != 0:
            if wait_until(cache_ready, cache_timeout_s):
                return
            if verbose:
                print("no shared window store appeared; building host-local")

        r_stocks, factors, alphas, betas = self._load_source()
        if self.engine == "native" and verbose:
            print("window store builds use the jnp path (native engine N/A)")
        WindowStore.build_from_series(
            self._store_dir,
            r_stocks,
            factors,
            alphas,
            betas,
            lookback_window=self.lookback_window,
            target_window=self.target_window,
            stride=self.stride,
            prediction=self.prediction_task,
            interaction_only=self.interaction_only,
            n_shards=n_shards,
            source_hash=hparams_hash,
        )

    def _build_windows(self, r_stocks, r_market, verbose: bool):
        """Window + feature-expand + OLS-label pass, native engine preferred.

        ``engine='auto'`` uses the C++ builder when a compiler/cached build is
        available and falls back to the jnp pipeline otherwise; both paths are
        parity-tested (tests/test_native_engine.py).
        """
        if np.ndim(r_market) > 1 and self.engine == "native":
            raise ValueError(
                "engine='native' only supports the scalar market series; the "
                "K-factor pipeline uses the jnp path (engine='python'/'auto')"
            )
        if self.engine in ("auto", "native") and np.ndim(r_market) == 1:
            from masters_thesis_tpu import native

            try:
                if self.engine == "native" or native.available():
                    out = native.build_dataset(
                        np.asarray(r_stocks),
                        np.asarray(r_market),
                        lookback_window=self.lookback_window,
                        target_window=self.target_window,
                        stride=self.stride,
                        prediction=self.prediction_task,
                        interaction_only=self.interaction_only,
                    )
                    return (
                        out["x"], out["y"], out["alphas"], out["betas"],
                        out["factor"], out["inv_psi"],
                    )
            except (native.NativeBuildError, OSError) as exc:
                # OSError covers an unloadable cached .so (wrong arch/corrupt).
                if self.engine == "native":
                    raise
                if verbose:
                    print(f"native engine unavailable ({exc}); using jnp path")

        x, y = lookback_target_split(
            r_stocks,
            r_market,
            lookback_window=self.lookback_window,
            target_window=self.target_window,
            stride=self.stride,
            prediction=self.prediction_task,
        )
        x = add_quadratic_features(x, interaction_only=self.interaction_only)
        t_alphas, t_betas, t_factor, t_inv_psi = ols_features(y)
        return x, y, t_alphas, t_betas, t_factor, t_inv_psi

    # ----------------------------------------------------------------- setup

    def setup(self, stage: str | None = None) -> None:
        """Load the cached dataset and compute the chronological 70/20/10 split."""
        if self.store_shards is not None:
            from masters_thesis_tpu.data.window_store import WindowStore

            self._store = WindowStore.open(self._store_dir)
            n = self._store.n_windows
        else:
            with np.load(self._datasets_dir / "dataset.npz") as data:
                self._arrays = Batch(
                    x=data["x"], y=data["y"], factor=data["factor"],
                    inv_psi=data["inv_psi"],
                )
            n = self._arrays.x.shape[0]
        train_end = int(0.7 * n)
        val_end = int(0.9 * n)
        if stage in ("fit", None):
            self.train_range = range(0, train_end)
            self.val_range = range(train_end, val_end)
        if stage in ("test", None):
            self.test_range = range(val_end, n)

    def _slice(self, idx) -> Batch:
        if self._store is not None:
            if isinstance(idx, slice):
                idx = np.arange(self._store.n_windows)[idx]
            return Batch(*self._store.take(idx))
        assert self._arrays is not None, "call setup() first"
        a = self._arrays
        return Batch(a.x[idx], a.y[idx], a.factor[idx], a.inv_psi[idx])

    # --------------------------------------------------------------- serving

    def _iterate(
        self, window_range: range, batch_size: int, shuffle_seed
    ) -> Iterator[Batch]:
        order = np.asarray(window_range)
        if shuffle_seed is not None:
            order = np.random.default_rng(shuffle_seed).permutation(order)
        for start in range(0, len(order), batch_size):
            yield self._slice(order[start : start + batch_size])

    def train_batches(
        self, epoch: int = 0, seed: int = 0, shuffle: bool = True
    ) -> Iterator[Batch]:
        """Shuffled train batches; shuffle order is (seed, epoch)-deterministic.

        ``shuffle=False`` iterates windows in order — through the window
        store that keeps every same-shard batch a contiguous zero-copy
        memmap slice (the streaming-health measurement path; training
        itself always shuffles).
        """
        assert self.train_range is not None, "call setup('fit') first"
        # Sequence seed, not hash((seed, epoch)): tuple hashing is a CPython
        # implementation detail and would break cross-version reproducibility.
        return self._iterate(
            self.train_range,
            self.batch_size,
            shuffle_seed=(seed, epoch) if shuffle else None,
        )

    def val_batches(self) -> Iterator[Batch]:
        assert self.val_range is not None, "call setup('fit') first"
        return self._iterate(self.val_range, 1, shuffle_seed=None)

    def test_batches(self) -> Iterator[Batch]:
        assert self.test_range is not None, "call setup('test') first"
        return self._iterate(self.test_range, 1, shuffle_seed=None)

    def train_arrays(self) -> Batch:
        """Whole train split as arrays — for the device-resident epoch path."""
        assert self.train_range is not None, "call setup('fit') first"
        return self._slice(slice(self.train_range.start, self.train_range.stop))

    def val_arrays(self) -> Batch:
        assert self.val_range is not None, "call setup('fit') first"
        return self._slice(slice(self.val_range.start, self.val_range.stop))

    def test_arrays(self) -> Batch:
        assert self.test_range is not None, "call setup('test') first"
        return self._slice(slice(self.test_range.start, self.test_range.stop))

    def teardown(self, stage: str | None = None) -> None:
        """Delete the cached dataset (reference: src/data.py:246-250)."""
        if stage == "cleanup":
            (self._datasets_dir / "dataset.npz").unlink(missing_ok=True)
            (self._datasets_dir / "hparams_hash.txt").unlink(missing_ok=True)
            if self._store_dir.exists():
                self._store = None
                for shard_file in self._store_dir.iterdir():
                    shard_file.unlink()
                self._store_dir.rmdir()
            if self._datasets_dir.exists():
                self._datasets_dir.rmdir()
