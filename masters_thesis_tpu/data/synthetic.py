"""Synthetic single-factor log-return data-generating process.

Capability parity with the reference DGP (reference: src/data.py:17-59):
daily log returns (in percent) for ``n_stocks`` driven by one market factor,

    r_stock[i, t] = alpha[i] + beta[i] * r_market[t] + eps[i, t]

with Student-t market and idiosyncratic shocks and Normal alpha/beta, using
the same distribution parameters (estimated from the 25-Portfolios dataset,
"no outliers" variant).

Design note (TPU-first means host-first here): dataset generation is one-off
host data preparation, so it samples with numpy under an explicit seed — the
chip session is reserved for training, and bootstrap never depends on TPU
availability or compile latency. The reference instead samples through torch's
implicit global RNG on whatever device torch picks.
"""

from __future__ import annotations

import numpy as np


class SyntheticLogReturns:
    """Single-factor DGP with heavy-tailed shocks.

    Returned arrays (all float32):
        ``r_stocks``: ``(n_stocks, n_samples)``
        ``r_market``: ``(n_samples,)``
        ``alphas``:   ``(n_stocks,)``
        ``betas``:    ``(n_stocks,)``
    """

    # Parameters estimated from the 25_Portfolios dataset (no-outliers variant),
    # matching the reference constants (src/data.py:36-39).
    mkt_params = {"loc": 0.0678, "scale": 0.5099, "df": 5.0}  # Student-t
    idio_params = {"loc": 0.0000, "scale": 0.3140, "df": 5.0}  # Student-t
    alpha_params = {"loc": 0.0098, "scale": 0.1271}  # Normal
    beta_params = {"loc": 0.9444, "scale": 0.3521}  # Normal

    # Alternative estimate including outlier days (the reference keeps these
    # in a comment, src/data.py:41-47; here they are a selectable variant).
    mkt_params_outliers = {"loc": 0.0538, "scale": 0.6616, "df": 5.0}
    idio_params_outliers = {"loc": 0.0000, "scale": 0.3539, "df": 5.0}
    alpha_params_outliers = {"loc": 0.0056, "scale": 0.1501}
    beta_params_outliers = {"loc": 1.0046, "scale": 0.3785}

    @staticmethod
    def generate(
        n_stocks: int,
        n_samples: int,
        seed: int = 0,
        variant: str = "no_outliers",
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Sample one synthetic market history under an explicit seed.

        ``variant``: ``"no_outliers"`` (reference default) or ``"outliers"``
        (parameters estimated including outlier days).
        """
        rng = np.random.default_rng(seed)
        p = SyntheticLogReturns
        if variant == "no_outliers":
            mkt, idio = p.mkt_params, p.idio_params
            alpha_p, beta_p = p.alpha_params, p.beta_params
        elif variant == "outliers":
            mkt, idio = p.mkt_params_outliers, p.idio_params_outliers
            alpha_p, beta_p = p.alpha_params_outliers, p.beta_params_outliers
        else:
            raise ValueError(f"unknown DGP variant: {variant!r}")

        def student_t(params, shape):
            return (
                params["loc"] + params["scale"] * rng.standard_t(params["df"], shape)
            ).astype(np.float32)

        r_market = student_t(mkt, (n_samples,))
        r_idio = student_t(idio, (n_stocks, n_samples))
        alphas = (
            alpha_p["loc"] + alpha_p["scale"] * rng.standard_normal(n_stocks)
        ).astype(np.float32)
        betas = (
            beta_p["loc"] + beta_p["scale"] * rng.standard_normal(n_stocks)
        ).astype(np.float32)

        r_systematic = alphas[:, None] + betas[:, None] * r_market[None, :]
        r_stocks = (r_systematic + r_idio).astype(np.float32)
        return r_stocks, r_market, alphas, betas


class SyntheticKFactorReturns:
    """K-factor DGP with heavy-tailed factor shocks.

    The universe-scale generalization of :class:`SyntheticLogReturns`:

        r_asset[i, t] = alpha[i] + Σ_k beta[i, k] * f[k, t] + eps[i, t]

    Factor 0 keeps the market's Student-t parameters; the remaining factors
    are zero-mean style factors with the same scale/tails. Loadings on the
    market keep the reference Normal cross-section; style loadings are
    zero-centered with the same dispersion. Idiosyncratic shocks and alphas
    are unchanged from the scalar DGP.

    Returned arrays (all float32):
        ``r_assets``: ``(n_assets, n_samples)``
        ``factors``:  ``(n_factors, n_samples)``
        ``alphas``:   ``(n_assets,)``
        ``betas``:    ``(n_assets, n_factors)``
    """

    @staticmethod
    def generate(
        n_assets: int,
        n_samples: int,
        n_factors: int = 1,
        seed: int = 0,
        variant: str = "no_outliers",
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Sample one synthetic K-factor history under an explicit seed."""
        if n_factors < 1:
            raise ValueError(f"n_factors must be >= 1, got {n_factors}")
        rng = np.random.default_rng(seed)
        p = SyntheticLogReturns
        if variant == "no_outliers":
            mkt, idio = p.mkt_params, p.idio_params
            alpha_p, beta_p = p.alpha_params, p.beta_params
        elif variant == "outliers":
            mkt, idio = p.mkt_params_outliers, p.idio_params_outliers
            alpha_p, beta_p = p.alpha_params_outliers, p.beta_params_outliers
        else:
            raise ValueError(f"unknown DGP variant: {variant!r}")

        def student_t(params, shape):
            return (
                params["loc"] + params["scale"] * rng.standard_t(params["df"], shape)
            ).astype(np.float32)

        factors = student_t(mkt, (n_factors, n_samples))
        if n_factors > 1:
            # Style factors: market tails and scale, but zero drift.
            factors[1:] -= np.float32(mkt["loc"])
        r_idio = student_t(idio, (n_assets, n_samples))
        alphas = (
            alpha_p["loc"] + alpha_p["scale"] * rng.standard_normal(n_assets)
        ).astype(np.float32)
        betas = (
            beta_p["scale"] * rng.standard_normal((n_assets, n_factors))
        ).astype(np.float32)
        betas[:, 0] += np.float32(beta_p["loc"])

        r_systematic = alphas[:, None] + betas @ factors
        r_assets = (r_systematic + r_idio).astype(np.float32)
        return r_assets, factors, alphas, betas
