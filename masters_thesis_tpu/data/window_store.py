"""On-disk sharded window store: memory-mapped, content-hashed, mesh-aligned.

``data/pipeline.py`` materializes every window in host RAM before training —
fine for 25 portfolios, a wall at universe scale (thousands of assets ×
``2K+2`` target channels). The store keeps the windowed dataset on disk as
``n_shards`` independent shard files per field, built shard-by-shard from
bounded time slices of the raw series, and serves them back as ``np.memmap``
views so the OS page cache — not the Python heap — owns residency.

Layout decisions mirror the rest of the repo:

- shard boundaries come from :func:`masters_thesis_tpu.parallel.mesh.shard_bounds`
  (balanced contiguous, remainder to the first ranks) so a shard per mesh
  rank lines up exactly with the device sharding the trainer will request;
- every shard file is atomically published and recorded in ``manifest.json``
  with its byte size and full SHA-256, the same torn/consistency discipline
  as the dataset cache (``manifest.json`` is written last and is the
  completion marker);
- builds go through the *same* jnp window ops as
  ``FinancialWindowDataModule._build_windows`` — each window's features and
  OLS labels depend only on that window's own time slice, so a shard built
  from its slice is bitwise identical to the corresponding rows of an
  all-in-memory build through the python engine (parity-tested on the
  8-way mesh layout in tests/test_window_store.py; the NATIVE engine's
  scalar-path windows differ from the jnp path at the last ulp, which is
  why store builds always use the jnp path).
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

import numpy as np

from masters_thesis_tpu.ops import (
    add_quadratic_features,
    lookback_target_split,
    ols_features,
)
from masters_thesis_tpu.parallel.mesh import shard_bounds
from masters_thesis_tpu.utils import atomic_publish, atomic_write_text

MANIFEST_NAME = "manifest.json"
STORE_VERSION = 1

# Per-window fields, in the order the pipeline's Batch expects them.
FIELDS = ("x", "y", "factor", "inv_psi")


class WindowStoreError(RuntimeError):
    """Raised when a store is absent, torn, or fails content verification."""


def _shard_filename(shard: int, field: str) -> str:
    return f"shard{shard:05d}.{field}.npy"


def _sha256_file(path: Path) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            digest.update(chunk)
    return digest.hexdigest()


class WindowStore:
    """Reader over a built store directory; shards are served as memmaps."""

    def __init__(self, store_dir: Path, manifest: dict):
        self.store_dir = Path(store_dir)
        self.manifest = manifest
        self._shard_cache: dict[int, dict[str, np.ndarray]] = {}

    # ---------------------------------------------------------------- opening

    @classmethod
    def open(cls, store_dir: Path, verify: bool = False) -> "WindowStore":
        """Open a store, refusing anything torn or (with ``verify``) altered.

        Structural checks always run: the manifest must exist (it is written
        last, so its absence means an unfinished or absent build) and every
        recorded shard file must exist with exactly its recorded byte size.
        ``verify=True`` additionally re-hashes every shard file against the
        manifest SHA-256 — the slow path for provenance disputes and the
        corrupt-shard runbook (docs/OPERATIONS.md).
        """
        store_dir = Path(store_dir)
        manifest_file = store_dir / MANIFEST_NAME
        if not manifest_file.exists():
            raise WindowStoreError(
                f"{store_dir} has no {MANIFEST_NAME} — the store is absent or "
                "a build was torn before completion; rebuild it"
            )
        manifest = json.loads(manifest_file.read_text())
        if manifest.get("version") != STORE_VERSION:
            raise WindowStoreError(
                f"{store_dir} manifest version {manifest.get('version')!r} != "
                f"{STORE_VERSION} — rebuild the store"
            )
        for entry in manifest["shards"]:
            for field, rec in entry["files"].items():
                path = store_dir / _shard_filename(entry["shard"], field)
                if not path.exists():
                    raise WindowStoreError(
                        f"{path.name} is missing from {store_dir} (torn "
                        "store) — rebuild the store"
                    )
                size = path.stat().st_size
                if size != rec["bytes"]:
                    raise WindowStoreError(
                        f"{path.name} is {size} bytes, manifest records "
                        f"{rec['bytes']} (torn or truncated shard) — rebuild "
                        "the store"
                    )
                if verify and _sha256_file(path) != rec["sha256"]:
                    raise WindowStoreError(
                        f"{path.name} content hash does not match the "
                        "manifest — the shard was altered or corrupted after "
                        "publish; rebuild the store"
                    )
        return cls(store_dir, manifest)

    # ------------------------------------------------------------- properties

    @property
    def n_windows(self) -> int:
        return int(self.manifest["n_windows"])

    @property
    def n_shards(self) -> int:
        return int(self.manifest["n_shards"])

    @property
    def source_hash(self) -> str:
        return self.manifest.get("source_hash", "")

    @property
    def nbytes(self) -> int:
        return sum(
            rec["bytes"]
            for entry in self.manifest["shards"]
            for rec in entry["files"].values()
        )

    def field_shape(self, field: str) -> tuple[int, ...]:
        """Global (all-windows) shape of one field."""
        return (self.n_windows, *self.manifest["fields"][field]["shape"])

    def bounds(self, shard: int) -> tuple[int, int]:
        entry = self.manifest["shards"][shard]
        return int(entry["lo"]), int(entry["hi"])

    # ---------------------------------------------------------------- reading

    def load_shard(self, shard: int) -> dict[str, np.ndarray]:
        """Memory-mapped views of one shard's fields (windows ``[lo, hi)``)."""
        cached = self._shard_cache.get(shard)
        if cached is not None:
            return cached
        arrays = {
            field: np.load(
                self.store_dir / _shard_filename(shard, field), mmap_mode="r"
            )
            for field in FIELDS
        }
        self._shard_cache[shard] = arrays
        return arrays

    def _shard_of(self, indices: np.ndarray) -> np.ndarray:
        los = np.asarray([e["lo"] for e in self.manifest["shards"]])
        return np.searchsorted(los, indices, side="right") - 1

    def take(self, indices) -> tuple[np.ndarray, ...]:
        """Rows ``indices`` of every field, in FIELDS order.

        A contiguous ascending run inside one shard comes back as zero-copy
        memmap views (the hot path: sequential batches through the prefetcher);
        anything else is gathered shard-by-shard into fresh arrays.
        """
        idx = np.asarray(indices, dtype=np.int64)
        if idx.ndim != 1:
            raise ValueError(f"take() wants a 1-D index array, got {idx.shape}")
        if idx.size == 0:
            return tuple(
                np.empty((0, *self.manifest["fields"][f]["shape"]),
                         dtype=self.manifest["fields"][f]["dtype"])
                for f in FIELDS
            )
        shards = self._shard_of(idx)
        same_shard = bool((shards == shards[0]).all())
        contiguous = idx.size == 1 or bool((np.diff(idx) == 1).all())
        if same_shard and contiguous:
            lo, _ = self.bounds(int(shards[0]))
            arrays = self.load_shard(int(shards[0]))
            a, b = int(idx[0] - lo), int(idx[-1] - lo + 1)
            return tuple(arrays[f][a:b] for f in FIELDS)
        out = tuple(
            np.empty((idx.size, *self.manifest["fields"][f]["shape"]),
                     dtype=self.manifest["fields"][f]["dtype"])
            for f in FIELDS
        )
        for shard in np.unique(shards):
            mask = shards == shard
            s_lo, _ = self.bounds(int(shard))
            arrays = self.load_shard(int(shard))
            rel = idx[mask] - s_lo
            for field, dst in zip(FIELDS, out):
                dst[mask] = arrays[field][rel]
        return out

    def load_all(self) -> tuple[np.ndarray, ...]:
        """Every window of every field, concatenated (copies — test-sized use)."""
        return self.take(np.arange(self.n_windows))

    def iter_shards(self):
        """Yield ``(lo, hi, {field: memmap})`` per shard, in layout order."""
        for shard in range(self.n_shards):
            lo, hi = self.bounds(shard)
            yield lo, hi, self.load_shard(shard)

    # --------------------------------------------------------------- building

    @classmethod
    def build_from_series(
        cls,
        store_dir: Path,
        r_stocks: np.ndarray,
        r_factors: np.ndarray,
        alphas: np.ndarray | None = None,
        betas: np.ndarray | None = None,
        *,
        lookback_window: int,
        target_window: int,
        stride: int,
        prediction: bool = True,
        interaction_only: bool = True,
        n_shards: int,
        source_hash: str = "",
        telemetry=None,
    ) -> "WindowStore":
        """Build a store shard-by-shard from the raw return series.

        Each shard is computed from the minimal time slice covering its
        windows and runs the exact jnp window/feature/OLS-label ops the
        in-memory pipeline uses, so rows are bitwise identical to a full
        ``_build_windows`` pass. Ground-truth ``alphas``/``betas`` (synthetic
        data) become the labels; without them the per-window OLS fit is the
        label, matching ``prepare_data``.
        """
        store_dir = Path(store_dir)
        store_dir.mkdir(parents=True, exist_ok=True)
        total_window = (
            lookback_window + target_window if prediction else lookback_window
        )
        n_samples = r_stocks.shape[1]
        n_windows = (n_samples - total_window) // stride + 1
        if n_windows < n_shards:
            n_shards = max(1, n_windows)

        shard_entries = []
        fields_meta: dict[str, dict] = {}
        for shard in range(n_shards):
            lo, hi = shard_bounds(n_windows, n_shards, shard)
            t0 = lo * stride
            t1 = (hi - 1) * stride + total_window
            factors_slice = (
                r_factors[t0:t1]
                if r_factors.ndim == 1
                else r_factors[:, t0:t1]
            )
            x, y = lookback_target_split(
                r_stocks[:, t0:t1],
                factors_slice,
                lookback_window=lookback_window,
                target_window=target_window,
                stride=stride,
                prediction=prediction,
            )
            x = add_quadratic_features(x, interaction_only=interaction_only)
            t_alphas, t_betas, t_factor, t_inv_psi = ols_features(y)
            y = append_label_channels(
                np.asarray(y), t_alphas, t_betas, alphas, betas
            )
            arrays = {
                "x": np.asarray(x),
                "y": y,
                "factor": np.asarray(t_factor),
                "inv_psi": np.asarray(t_inv_psi),
            }
            files = {}
            for field, arr in arrays.items():
                path = store_dir / _shard_filename(shard, field)
                with atomic_publish(path) as tmp:
                    with open(tmp, "wb") as f:
                        np.save(f, arr)
                    files[field] = {
                        "sha256": _sha256_file(Path(tmp)),
                        "bytes": Path(tmp).stat().st_size,
                    }
                if field not in fields_meta:
                    fields_meta[field] = {
                        "shape": list(arr.shape[1:]),
                        "dtype": str(arr.dtype),
                    }
            shard_entries.append(
                {"shard": shard, "lo": lo, "hi": hi, "files": files}
            )

        manifest = {
            "version": STORE_VERSION,
            "n_windows": n_windows,
            "n_shards": n_shards,
            "source_hash": source_hash,
            "fields": fields_meta,
            "shards": shard_entries,
        }
        # Manifest last: it is the completion marker, so readers never see a
        # half-built store as valid.
        atomic_write_text(
            store_dir / MANIFEST_NAME, json.dumps(manifest, indent=2)
        )
        if telemetry is not None:
            telemetry.event(
                "window_store",
                action="build",
                shards=n_shards,
                windows=n_windows,
                bytes=sum(
                    rec["bytes"]
                    for entry in shard_entries
                    for rec in entry["files"].values()
                ),
            )
        return cls(store_dir, manifest)


def append_label_channels(
    y: np.ndarray,
    t_alphas,
    t_betas,
    alphas: np.ndarray | None,
    betas: np.ndarray | None,
) -> np.ndarray:
    """Append ``[alpha, beta_1..beta_F]`` label channels to the target window.

    Same semantics as ``FinancialWindowDataModule.prepare_data``: ground-truth
    coefficients when the DGP recorded them, otherwise the target-window OLS
    fit. ``betas`` may be ``(n_stocks,)`` (scalar path) or ``(n_stocks, F)``.
    """
    n_windows = y.shape[0]
    if alphas is None or betas is None:
        alpha_label = np.asarray(t_alphas)
        beta_label = np.asarray(t_betas)
    else:
        alpha_label = np.broadcast_to(alphas[None, :], (n_windows, len(alphas)))
        beta_label = np.broadcast_to(betas[None], (n_windows,) + betas.shape)
    if beta_label.ndim == 2:
        beta_label = beta_label[..., None]  # scalar loading -> one channel
    return np.concatenate(
        [
            y,
            np.broadcast_to(alpha_label[:, :, None, None], y.shape[:3] + (1,)),
            np.broadcast_to(
                beta_label[:, :, None, :],
                y.shape[:3] + (beta_label.shape[-1],),
            ),
        ],
        axis=-1,
    )
