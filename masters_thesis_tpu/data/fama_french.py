"""Fama-French 25-Portfolios daily dataset ingestion.

Capability parity with the reference loader (reference: src/data.py:62-123):
reads the Ken French data-library CSVs ("F-F_Research_Data_Factors_daily" and
"25_Portfolios_5x5_Daily"), skips the documented header preambles plus the
first ``skip_old_data`` rows, subtracts the risk-free rate, drops rows carrying
the -99.99/-999 missing-data sentinels, and converts percent arithmetic
returns to percent log returns ``100 * (log(R + 100) - log 100)``.

Host-side by design: CSV parsing is pandas/numpy work; arrays are handed to
the window pipeline as float32 numpy and only enter HBM once windowed.
"""

from __future__ import annotations

from collections import defaultdict
from pathlib import Path

import numpy as np
import pandas as pd


class FamaFrench25Portfolios:
    """Loader for 25 Portfolios Formed on Size and Book-to-Market [Daily]."""

    n_samples = 26129
    skip_old_data = 3125

    ff3_filename = "F-F_Research_Data_Factors_daily.csv"
    ff3_skip = 4
    ff3_cols = ["DATE", "Mkt-RF", "SMB", "HML", "RF"]

    p25_filename = "25_Portfolios_5x5_Daily.csv"
    p25_skip = 18
    p25_cols = ["DATE", "SMALL LoBM", "ME1 BM2", "ME1 BM3", "ME1 BM4", "SMALL HiBM",
                        "ME2 BM1", "ME2 BM2", "ME2 BM3", "ME2 BM4", "ME2 BM5",
                        "ME3 BM1", "ME3 BM2", "ME3 BM3", "ME3 BM4", "ME3 BM5",
                        "ME4 BM1", "ME4 BM2", "ME4 BM3", "ME4 BM4", "ME4 BM5",
                        "BIG LoBM", "ME5 BM2", "ME5 BM3", "ME5 BM4", "BIG HiBM"]

    @staticmethod
    def load(data_dir: Path) -> tuple[np.ndarray, np.ndarray]:
        """Load (portfolio log returns ``(25, T)``, market log returns ``(T,)``)."""
        cls = FamaFrench25Portfolios
        ff3_types = defaultdict(lambda: np.float32, DATE=np.int32)
        ff3_df = pd.read_csv(
            Path(data_dir) / cls.ff3_filename,
            header=0,
            index_col=0,
            names=cls.ff3_cols,
            usecols=["DATE", "Mkt-RF", "RF"],
            dtype=ff3_types,
            skiprows=cls.ff3_skip + cls.skip_old_data,
            nrows=cls.n_samples - cls.skip_old_data,
        )

        p25_types = defaultdict(lambda: np.float32, DATE=np.int32)
        p25_df = pd.read_csv(
            Path(data_dir) / cls.p25_filename,
            header=0,
            index_col=0,
            names=cls.p25_cols,
            dtype=p25_types,
            skiprows=cls.p25_skip + cls.skip_old_data,
            nrows=cls.n_samples - cls.skip_old_data,
        )

        mkt_excess = ff3_df["Mkt-RF"].to_numpy(dtype=np.float32)
        risk_free = ff3_df["RF"].to_numpy(dtype=np.float32)
        p25_raw = p25_df.to_numpy(dtype=np.float32).T

        # Drop days where any portfolio carries a missing-data sentinel.
        # Conscious fix over the reference (src/data.py:112-115), which
        # matches the sentinel only AFTER subtracting RF — on a day with
        # nonzero RF the sentinel escapes and log(-99.99 - RF + 100) injects
        # NaN. Matching on the raw values guards the log transform reliably.
        missing = ((p25_raw == -99.99) | (p25_raw == -999)).any(axis=0)
        p25_excess = (p25_raw - risk_free)[:, ~missing]
        mkt_excess = mkt_excess[~missing]

        # Percent arithmetic returns -> percent log returns.
        mkt = 100.0 * (np.log(mkt_excess + 100.0) - np.log(100.0))
        p25 = 100.0 * (np.log(p25_excess + 100.0) - np.log(100.0))
        return p25.astype(np.float32), mkt.astype(np.float32)
