"""Host→device prefetching for batch iterators.

TPU-native replacement for the reference's DataLoader worker processes +
pinned-memory copies (reference: src/data.py:236-244): batches are pushed to
device asynchronously ``size`` steps ahead of consumption, so the host→HBM
transfer of batch *k+1* overlaps the device compute of batch *k* (JAX
dispatch is async; ``device_put`` returns immediately).

Instrumentation: pass a :class:`PrefetchStats` to make input-pipeline
starvation observable rather than inferred. Because this generator is
synchronous, the host time spent inside ``next(source)`` + dispatch is
exactly the time that could NOT overlap device compute — the stream-mode
trainer reads per-epoch deltas off the stats object and telemetry reports
it as the run's data-wait / starvation figure.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Iterable, Iterator, Any

import jax
import numpy as np

from masters_thesis_tpu.parallel import global_put


@dataclasses.dataclass
class PrefetchStats:
    """Counters a prefetch iterator updates in place (host-side only)."""

    gets: int = 0            # items pulled from the source iterator
    yields: int = 0          # items handed to the consumer
    get_wait_s: float = 0.0  # host time producing + dispatching items
    depth_sum: int = 0       # queue depth observed at each yield
    min_depth: int | None = None
    exhausted: bool = False  # source ran dry (the tail of every epoch)
    # Memory-mapped sources (data/window_store.py): a store iterator returns
    # memmap VIEWS in microseconds and the real I/O happens as page faults
    # when the bytes are first touched. Without forcing residency here,
    # those faults land inside the device transfer and the get-wait split
    # under-reports starvation as "fast producer" + mysteriously slow
    # dispatch. fault_wait_s is the page-in time (a sub-component of
    # get_wait_s); mmap_bytes the volume paged through the store.
    fault_wait_s: float = 0.0
    mmap_bytes: int = 0

    def observe_depth(self, depth: int) -> None:
        self.yields += 1
        self.depth_sum += depth
        self.min_depth = (
            depth if self.min_depth is None else min(self.min_depth, depth)
        )

    @property
    def mean_depth(self) -> float:
        return self.depth_sum / self.yields if self.yields else 0.0

    def snapshot(self) -> dict:
        return {
            "gets": self.gets,
            "yields": self.yields,
            "get_wait_s": self.get_wait_s,
            "mean_depth": self.mean_depth,
            "min_depth": self.min_depth,
            "exhausted": self.exhausted,
            "fault_wait_s": self.fault_wait_s,
            "mmap_bytes": self.mmap_bytes,
        }


def _materialize_mmap(item, stats: PrefetchStats | None):
    """Force memmap leaves resident (timed), leaving other leaves untouched.

    ``np.ascontiguousarray`` on a memmap touches every page — the fault wait
    happens HERE, on the producer side of the double buffer where it can
    overlap device compute, and is accounted in ``stats.fault_wait_s``
    instead of hiding inside the device transfer.
    """
    leaves, treedef = jax.tree_util.tree_flatten(item)
    out = []
    for leaf in leaves:
        if isinstance(leaf, np.memmap):
            t0 = time.perf_counter()
            forced = np.ascontiguousarray(leaf)
            if stats is not None:
                stats.fault_wait_s += time.perf_counter() - t0
                stats.mmap_bytes += int(leaf.nbytes)
            out.append(forced)
        else:
            out.append(leaf)
    return jax.tree_util.tree_unflatten(treedef, out)


def prefetch_to_device(
    iterator: Iterable[Any],
    size: int = 2,
    sharding=None,
    stats: PrefetchStats | None = None,
) -> Iterator[Any]:
    """Yield items from ``iterator`` with ``size`` items already on device.

    Args:
        iterator: yields pytrees of host arrays.
        size: prefetch depth (2 = classic double buffering).
        sharding: optional ``jax.sharding.Sharding`` to place each leaf with
            (used by the data-parallel trainer to shard the batch axis);
            default places on the default device.
        stats: optional :class:`PrefetchStats` updated in place — get-wait
            seconds, queue depth per yield, and exhaustion, so telemetry
            can report starvation instead of guessing at it.
    """
    if size < 0:
        raise ValueError(f"prefetch size must be >= 0, got {size}")

    queue: collections.deque = collections.deque()

    def put(item):
        if sharding is not None:
            # global_put == device_put on a single-process mesh; on a
            # multi-process mesh it materializes each process's shards from
            # the (host-identical) full batch, which plain device_put would
            # reject — this is what makes stream mode multi-host capable.
            return global_put(item, sharding)
        return jax.device_put(item)

    it = iter(iterator)

    def pull() -> bool:
        """Produce + dispatch one item; False once the source is dry."""
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            if stats is not None:
                stats.get_wait_s += time.perf_counter() - t0
                stats.exhausted = True
            return False
        queue.append(put(_materialize_mmap(item, stats)))
        if stats is not None:
            stats.get_wait_s += time.perf_counter() - t0
            stats.gets += 1
        return True

    if size == 0:  # no lookahead: plain put-then-yield
        while pull():
            if stats is not None:
                stats.observe_depth(len(queue))
            yield queue.popleft()
        return

    for _ in range(size):
        if not pull():
            break

    while queue:
        if stats is not None:
            stats.observe_depth(len(queue))
        yield queue.popleft()
        pull()
