"""Host→device prefetching for batch iterators.

TPU-native replacement for the reference's DataLoader worker processes +
pinned-memory copies (reference: src/data.py:236-244): batches are pushed to
device asynchronously ``size`` steps ahead of consumption, so the host→HBM
transfer of batch *k+1* overlaps the device compute of batch *k* (JAX
dispatch is async; ``device_put`` returns immediately).
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Any

import jax

from masters_thesis_tpu.parallel import global_put


def prefetch_to_device(
    iterator: Iterable[Any], size: int = 2, sharding=None
) -> Iterator[Any]:
    """Yield items from ``iterator`` with ``size`` items already on device.

    Args:
        iterator: yields pytrees of host arrays.
        size: prefetch depth (2 = classic double buffering).
        sharding: optional ``jax.sharding.Sharding`` to place each leaf with
            (used by the data-parallel trainer to shard the batch axis);
            default places on the default device.
    """
    if size < 0:
        raise ValueError(f"prefetch size must be >= 0, got {size}")

    queue: collections.deque = collections.deque()

    def put(item):
        if sharding is not None:
            # global_put == device_put on a single-process mesh; on a
            # multi-process mesh it materializes each process's shards from
            # the (host-identical) full batch, which plain device_put would
            # reject — this is what makes stream mode multi-host capable.
            return global_put(item, sharding)
        return jax.device_put(item)

    it = iter(iterator)
    if size == 0:  # no lookahead: plain put-then-yield
        for item in it:
            yield put(item)
        return
    try:
        for _ in range(size):
            queue.append(put(next(it)))
    except StopIteration:
        pass

    while queue:
        yield queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
