"""Data layer: synthetic DGP, Fama-French ingestion, windowed dataset pipeline.

TPU-native replacement for the reference's data stack (reference: src/data.py):
explicit-PRNG synthetic generation, host-side CSV ingestion, a hash-cached
window-preparation pipeline, chronological splits, and host→HBM prefetched
batch iteration (the reference delegates the last to torch DataLoader worker
processes + pinned memory).
"""

from masters_thesis_tpu.data.synthetic import (
    SyntheticKFactorReturns,
    SyntheticLogReturns,
)
from masters_thesis_tpu.data.fama_french import FamaFrench25Portfolios
from masters_thesis_tpu.data.pipeline import (
    Batch,
    FinancialWindowDataModule,
    bootstrap_synthetic,
    bootstrap_real,
)
from masters_thesis_tpu.data.prefetch import prefetch_to_device
from masters_thesis_tpu.data.window_store import WindowStore, WindowStoreError

__all__ = [
    "SyntheticLogReturns",
    "SyntheticKFactorReturns",
    "FamaFrench25Portfolios",
    "Batch",
    "FinancialWindowDataModule",
    "bootstrap_synthetic",
    "bootstrap_real",
    "prefetch_to_device",
    "WindowStore",
    "WindowStoreError",
]
