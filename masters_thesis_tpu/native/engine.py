"""ctypes bindings for the native C++ window engine.

``build_dataset`` is the one-call native equivalent of the Python pipeline's
window construction (reference: src/common.py:81-148 composed by
src/data.py:196-214): it returns the feature-expanded lookback windows, raw
target channels, and per-window OLS supervision labels as freshly-allocated
numpy arrays, computed by the multithreaded C++ engine. ``available()``
reports whether the engine can be (or already is) built on this machine.
"""

from __future__ import annotations

import ctypes
import functools

import numpy as np

from masters_thesis_tpu.native.build import (
    NativeBuildError,
    compiler,
    ensure_built,
    library_path,
)

_i64 = ctypes.c_longlong
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")


@functools.lru_cache(maxsize=1)
def _load() -> ctypes.CDLL:
    try:
        lib = ctypes.CDLL(str(ensure_built()))
    except OSError:
        # Self-heal a corrupt/incompatible cached build: rebuild once.
        library_path().unlink(missing_ok=True)
        lib = ctypes.CDLL(str(ensure_built()))
    lib.mt_num_windows.restype = _i64
    lib.mt_num_windows.argtypes = [_i64, _i64, _i64]
    lib.mt_build_dataset.restype = ctypes.c_int
    lib.mt_build_dataset.argtypes = [
        _f32p, _f32p,  # stocks, market
        _i64, _i64, _i64, _i64, _i64,  # K, T, L, Tt, stride
        ctypes.c_int, ctypes.c_int, ctypes.c_int,  # prediction, interaction_only, n_threads
        _f32p, _f32p, _f32p, _f32p, _f32p, _f32p,  # x, y, alphas, betas, factor, inv_psi
    ]
    return lib


def available() -> bool:
    """True iff the engine is already built or a compiler is on PATH."""
    return library_path().exists() or compiler() is not None


def num_windows(n_samples: int, total_window: int, stride: int) -> int:
    return int(_load().mt_num_windows(n_samples, total_window, stride))


def build_dataset(
    stocks: np.ndarray,
    market: np.ndarray,
    lookback_window: int,
    target_window: int,
    stride: int,
    prediction: bool = True,
    interaction_only: bool = True,
    n_threads: int = 0,
) -> dict[str, np.ndarray]:
    """Run the fused native window/feature/OLS pass.

    Args mirror the Python pipeline (see ops/windows.py). Returns a dict with
    ``x (n_win, K, L, F)``, ``y (n_win, K, Tt, 2)``, ``alphas``/``betas``/
    ``inv_psi (n_win, K)``, and ``factor (n_win, 2)``, all float32.
    """
    stocks = np.ascontiguousarray(stocks, np.float32)
    market = np.ascontiguousarray(market, np.float32)
    if stocks.ndim != 2 or market.ndim != 1 or stocks.shape[1] != market.shape[0]:
        raise ValueError(
            f"expected stocks (K, T) and market (T,); got {stocks.shape} "
            f"and {market.shape}"
        )
    k, t = stocks.shape
    total = lookback_window + target_window if prediction else lookback_window
    lib = _load()
    n_win = int(lib.mt_num_windows(t, total, stride))
    if n_win < 1:
        raise ValueError(
            f"series of length {t} is shorter than one window ({total} steps)"
        )
    n_features = 3 if interaction_only else 5

    x = np.empty((n_win, k, lookback_window, n_features), np.float32)
    y = np.empty((n_win, k, target_window, 2), np.float32)
    alphas = np.empty((n_win, k), np.float32)
    betas = np.empty((n_win, k), np.float32)
    factor = np.empty((n_win, 2), np.float32)
    inv_psi = np.empty((n_win, k), np.float32)

    rc = lib.mt_build_dataset(
        stocks, market, k, t, lookback_window, target_window, stride,
        int(prediction), int(interaction_only), int(n_threads),
        x, y, alphas, betas, factor, inv_psi,
    )
    if rc != 0:
        raise NativeBuildError(f"mt_build_dataset failed with code {rc}")
    return {
        "x": x, "y": y, "alphas": alphas, "betas": betas,
        "factor": factor, "inv_psi": inv_psi,
    }
