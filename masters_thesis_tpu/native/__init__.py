"""Native (C++) runtime components of the framework.

Currently: the multithreaded window/feature/OLS dataset builder — the
framework's native host-side data path (the reference delegates this role to
torch's strided-view kernels and DataLoader worker processes,
src/data.py:236-244). Loaded lazily; everything degrades to the pure-JAX
pipeline when no C++ compiler is available.
"""

from masters_thesis_tpu.native.build import NativeBuildError, ensure_built
from masters_thesis_tpu.native.engine import available, build_dataset, num_windows

__all__ = [
    "NativeBuildError",
    "available",
    "build_dataset",
    "ensure_built",
    "num_windows",
]
