"""Lazy, cached build of the native window engine shared library.

The library is compiled on first use with the system ``g++`` into
``<package>/native/_build/window_engine_<srchash>.so`` — hashing the source
into the filename makes rebuilds automatic when the C++ changes and makes the
cache safe to keep across versions. No pybind11/setuptools machinery: the
engine exposes a plain C ABI consumed via ctypes (see engine.py), so the only
build dependency is a C++ compiler; when none is present the framework
transparently falls back to the pure-JAX pipeline path.

The engine replaces the host-side throughput the reference buys with
DataLoader worker processes and pinned memory (reference:
src/data.py:237-244) — see native/window_engine.cpp for the threaded
window/feature pipeline itself.
"""

from __future__ import annotations

import hashlib
import os
import platform
import shutil
import subprocess
from pathlib import Path

_NATIVE_DIR = Path(__file__).resolve().parent
_SOURCE = _NATIVE_DIR / "window_engine.cpp"
_BUILD_DIR = _NATIVE_DIR / "_build"

_CXX_FLAGS = [
    "-O3",
    "-std=c++17",
    "-shared",
    "-fPIC",
    "-pthread",
    "-fvisibility=hidden",
]


class NativeBuildError(RuntimeError):
    pass


def _source_hash() -> str:
    return hashlib.sha256(_SOURCE.read_bytes()).hexdigest()[:16]


def library_path() -> Path:
    # Arch in the cache key: on a shared filesystem, hosts of different
    # architectures each build and load their own binary.
    return _BUILD_DIR / (
        f"window_engine_{platform.machine()}_{_source_hash()}.so"
    )


def compiler() -> str | None:
    return shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")


def ensure_built(verbose: bool = False) -> Path:
    """Compile the engine if its cached build is missing; returns the .so path."""
    lib = library_path()
    if lib.exists():
        return lib
    cxx = compiler()
    if cxx is None:
        raise NativeBuildError("no C++ compiler found (g++/c++/clang++)")
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    # Per-process tmp name: concurrent first-use builders (pytest-xdist,
    # multi-host on shared FS) each write their own file; the final rename is
    # atomic, so whoever publishes last wins with an intact library.
    tmp = lib.with_suffix(f".so.tmp{os.getpid()}")
    cmd = [cxx, *_CXX_FLAGS, str(_SOURCE), "-o", str(tmp)]
    if verbose:
        print("building native window engine:", " ".join(cmd))
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native engine build failed (exit {proc.returncode}):\n{proc.stderr}"
        )
    tmp.replace(lib)  # atomic: concurrent builders race benignly
    return lib
