// Native host-side window engine: the data-preparation hot path in C++.
//
// Capability parity: one fused pass over the raw return series producing the
// windowed dataset the Python pipeline assembles from four separate steps
// (reference: src/common.py:81-148 lookback_target_split +
// add_quadratic_features + ols_features; driven from src/data.py:177-219).
// The reference leans on torch's native strided `unfold` kernels and
// DataLoader worker processes for its host-side data path; this engine is the
// TPU framework's native equivalent — a multithreaded C++ builder that
// materializes windows, polynomial features, and per-window OLS supervision
// labels in a single cache-friendly sweep, handing zero-copy numpy buffers
// straight to `jax.device_put`.
//
// Numerics: all reductions (OLS sums, means, variances) accumulate in double
// and round once to float32 on store, so results match the float64-accurate
// closed forms within float32 rounding of the XLA path.
//
// C ABI only (loaded via ctypes; no pybind11 on this image).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

#define MT_EXPORT __attribute__((visibility("default")))

extern "C" {

// Number of complete (lookback+target) windows a series of n_samples admits.
// Returns -1 for invalid parameters.
MT_EXPORT long long mt_num_windows(long long n_samples, long long total_window,
                         long long stride) {
  if (total_window <= 0 || stride <= 0 || n_samples < total_window) return -1;
  return (n_samples - total_window) / stride + 1;
}

// Build the full windowed dataset in one pass.
//
// Inputs (row-major, float32):
//   stocks: (K, T)   per-stock return series
//   market: (T)      market return series
// Parameters:
//   L  = lookback_window, Tt = target_window, stride, prediction (1: target
//   follows the lookback; 0: target is the trailing Tt steps of the
//   lookback), interaction_only (1: 3 features, 0: 5), n_threads (<=0: auto).
// Outputs (caller-allocated, row-major float32):
//   x:       (n_win, K, L, F)   features [r_s, r_m, r_s*r_m (, r_s^2, r_m^2)]
//   y:       (n_win, K, Tt, 2)  raw [r_stock, r_market] target channels
//   alphas:  (n_win, K)         per-window target OLS intercepts
//   betas:   (n_win, K)         per-window target OLS slopes
//   factor:  (n_win, 2)         (mean, var ddof=1) of the target market
//   inv_psi: (n_win, K)         1 / var(ddof=1) of the OLS residuals
// Returns 0 on success, nonzero on invalid parameters.
MT_EXPORT int mt_build_dataset(const float* stocks, const float* market, long long K,
                     long long T, long long L, long long Tt, long long stride,
                     int prediction, int interaction_only, int n_threads,
                     float* x, float* y, float* alphas, float* betas,
                     float* factor, float* inv_psi) {
  const long long total = prediction ? (L + Tt) : L;
  const long long n_win = mt_num_windows(T, total, stride);
  if (n_win < 1 || K < 1 || Tt < 2) return 1;
  if (!prediction && Tt > L) return 2;
  const long long F = interaction_only ? 3 : 5;
  const long long t_off = prediction ? L : (L - Tt);

  long long hw = static_cast<long long>(std::thread::hardware_concurrency());
  if (hw < 1) hw = 1;
  long long workers = n_threads > 0 ? n_threads : hw;
  if (workers > n_win) workers = n_win;

  auto worker = [&](long long w_begin, long long w_end) {
    for (long long w = w_begin; w < w_end; ++w) {
      const long long s = w * stride;
      // ---- lookback features: one contiguous write per (stock, step).
      for (long long k = 0; k < K; ++k) {
        const float* sk = stocks + k * T + s;
        const float* mk = market + s;
        float* xw = x + ((w * K + k) * L) * F;
        for (long long t = 0; t < L; ++t) {
          const float rs = sk[t];
          const float rm = mk[t];
          float* row = xw + t * F;
          row[0] = rs;
          row[1] = rm;
          row[2] = rs * rm;
          if (!interaction_only) {
            row[3] = rs * rs;
            row[4] = rm * rm;
          }
        }
      }
      // ---- market moments over the target window (double accumulation).
      const float* mt = market + s + t_off;
      double sx = 0.0, sxx = 0.0;
      for (long long t = 0; t < Tt; ++t) {
        const double v = mt[t];
        sx += v;
        sxx += v * v;
      }
      const double n = static_cast<double>(Tt);
      const double mean_m = sx / n;
      // Unbiased variance (matches torch.var default, ddof=1).
      const double var_m = (sxx - n * mean_m * mean_m) / (n - 1.0);
      factor[w * 2 + 0] = static_cast<float>(mean_m);
      factor[w * 2 + 1] = static_cast<float>(var_m);

      const double denom = n * sxx - sx * sx;  // n^2 * population var
      // ---- per-stock target channels + OLS fit + residual variance.
      for (long long k = 0; k < K; ++k) {
        const float* st = stocks + k * T + s + t_off;
        float* yw = y + ((w * K + k) * Tt) * 2;
        double sy = 0.0, sxy = 0.0;
        for (long long t = 0; t < Tt; ++t) {
          const double ys = st[t];
          yw[t * 2 + 0] = st[t];
          yw[t * 2 + 1] = mt[t];
          sy += ys;
          sxy += ys * static_cast<double>(mt[t]);
        }
        double beta, alpha;
        if (denom != 0.0) {
          beta = (n * sxy - sx * sy) / denom;
          alpha = (sy - beta * sx) / n;
        } else {
          // Degenerate (constant c) regressor: the gram matrix is singular
          // and the Python path's pinv returns the MIN-NORM least-squares
          // solution alpha = ybar/(1+c^2), beta = c*ybar/(1+c^2) — match it.
          const double c = mean_m;
          const double ybar = sy / n;
          alpha = ybar / (1.0 + c * c);
          beta = c * alpha;
        }
        double rss = 0.0, rsum = 0.0;
        for (long long t = 0; t < Tt; ++t) {
          const double r =
              static_cast<double>(st[t]) - (alpha + beta * mt[t]);
          rsum += r;
          rss += r * r;
        }
        // var(residuals, ddof=1) about the residual mean (alpha absorbs it
        // up to rounding, but match the Python path exactly).
        const double rmean = rsum / n;
        const double psi = (rss - n * rmean * rmean) / (n - 1.0);
        alphas[w * K + k] = static_cast<float>(alpha);
        betas[w * K + k] = static_cast<float>(beta);
        inv_psi[w * K + k] = static_cast<float>(1.0 / psi);
      }
    }
  };

  if (workers <= 1) {
    worker(0, n_win);
    return 0;
  }
  std::vector<std::thread> threads;
  threads.reserve(workers);
  const long long chunk = (n_win + workers - 1) / workers;
  for (long long i = 0; i < workers; ++i) {
    const long long b = i * chunk;
    const long long e = std::min(n_win, b + chunk);
    if (b >= e) break;
    threads.emplace_back(worker, b, e);
  }
  for (auto& th : threads) th.join();
  return 0;
}

}  // extern "C"
