"""Evaluation figures: model vs OLS vs ground truth.

Behavioral parity with the reference plot library (reference:
src/plots.py:10-110): same four figure kinds, same statistical annotations
(Pearson correlation in titles, mean/std vlines on histograms, identity
reference lines), operating on plain numpy arrays instead of torch tensors.
"""

from __future__ import annotations

import matplotlib

matplotlib.use("Agg")  # headless: figures only ever go to TensorBoard
import matplotlib.pyplot as plt
import numpy as np

FIGSIZE = (16, 9)


def _corr(a: np.ndarray, b: np.ndarray) -> float:
    return float(np.corrcoef(a.ravel(), b.ravel())[0, 1])


def scatter_plot(model: np.ndarray, ols: np.ndarray, title: str):
    """Model-vs-OLS scatter with identity line; correlation in the title
    (reference: src/plots.py:10-28)."""
    model = np.asarray(model).ravel()
    ols = np.asarray(ols).ravel()
    fig, ax = plt.subplots(figsize=FIGSIZE)
    ax.scatter(model, ols, marker=".")
    identity = (model.min(), model.max())
    ax.plot(identity, identity, "r--")
    ax.set_xlabel("Model")
    ax.set_ylabel("OLS")
    ax.set_title(f"{title}, corr={_corr(model, ols):.4f}")
    ax.grid(alpha=0.5)
    return fig


def hist_plot(model: np.ndarray, ols: np.ndarray, title: str):
    """Overlaid density histograms with mean/std vlines; bin count scales as
    1% of the sample count (reference: src/plots.py:30-54)."""
    model = np.asarray(model).ravel()
    ols = np.asarray(ols).ravel()
    bins = int(len(model) * 0.01) + 1
    fig, ax = plt.subplots(figsize=FIGSIZE)
    ax.hist(model, bins=bins, density=True, alpha=0.6, label="Model", color="blue")
    ax.hist(ols, bins=bins, density=True, alpha=0.6, label="OLS", color="orange")
    for data, color, label in ((model, "blue", "Model"), (ols, "orange", "OLS")):
        ax.axvline(
            data.mean(),
            color=color,
            linestyle="--",
            label=f"{label} Residual Avg: {data.mean():.4f} (std={data.std():.4f})",
        )
    ax.set_title(title)
    ax.grid(alpha=0.5)
    ax.legend()
    return fig


def estimation_plots(tb, model_ests, ols_ests, trues, est_kind: str = "alpha"):
    """Per-stock estimate time-series, one TensorBoard figure per stock for
    the first <=9 stocks (reference: src/plots.py:56-76 logs under
    ``estimation/examples_<kind>`` keyed by global_step=stock index)."""
    model_ests = np.asarray(model_ests)
    ols_ests = np.asarray(ols_ests)
    trues = np.asarray(trues)
    for stock_idx in range(min(model_ests.shape[1], 9)):
        fig, ax = plt.subplots(figsize=FIGSIZE)
        sample = np.arange(model_ests.shape[0])
        ax.plot(
            sample,
            trues[:, stock_idx],
            color="magenta",
            linestyle="--",
            alpha=0.5,
            label=f"True {est_kind}",
        )
        ax.scatter(sample, model_ests[:, stock_idx], marker=".", color="blue",
                   label="Model")
        ax.scatter(sample, ols_ests[:, stock_idx], marker=".", color="orange",
                   label="OLS")
        ax.set_title(f"Model vs OLS {est_kind} estimation (Stock {stock_idx})")
        ax.legend()
        ax.grid(alpha=0.5)
        tb.log_figure(f"estimation/examples_{est_kind}", fig, step=stock_idx)
        plt.close(fig)


def estimation_scatter(model_ests, ols_ests, trues, est_kind: str = "alpha"):
    """Two-panel truth-vs-estimate scatter (model top, OLS bottom), shared
    axes, identity lines, per-panel correlation (reference:
    src/plots.py:78-110)."""
    model_ests = np.asarray(model_ests).ravel()
    ols_ests = np.asarray(ols_ests).ravel()
    trues = np.asarray(trues).ravel()
    fig, (ax1, ax2) = plt.subplots(2, 1, figsize=FIGSIZE, sharex=True, sharey=True)
    fig.suptitle(f"Ground Truth {est_kind} vs Estimated {est_kind}")
    identity = (trues.min(), trues.max())
    for ax, ests, color, label in (
        (ax1, model_ests, "blue", "Model"),
        (ax2, ols_ests, "orange", "OLS"),
    ):
        ax.set_title(f"{label} corr={_corr(ests, trues):.4f}")
        ax.set_ylabel(f"{label} {est_kind}")
        ax.plot(identity, identity, color="magenta", linestyle="--")
        ax.scatter(trues, ests, marker=".", alpha=0.15, color=color)
        ax.grid()
    ax2.set_xlabel(f"Ground Truth {est_kind}")
    return fig
