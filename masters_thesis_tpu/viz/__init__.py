"""Matplotlib plot library for model-vs-OLS-vs-truth evaluation figures."""

from masters_thesis_tpu.viz.plots import (
    estimation_plots,
    estimation_scatter,
    hist_plot,
    scatter_plot,
)

__all__ = ["scatter_plot", "hist_plot", "estimation_plots", "estimation_scatter"]
