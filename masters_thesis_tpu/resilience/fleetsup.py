"""Fleet supervisor: all-rank relaunch + elastic resize for N-process runs.

The one hard failure mode a data-parallel fleet has that the single-run
:class:`~masters_thesis_tpu.resilience.supervisor.RunSupervisor` cannot
see: a host dies and every SURVIVOR wedges forever inside the next
collective — alive, heartbeating its import/setup phases, making no
progress. The fleet invariant this module enforces:

    any rank dead, or hung past ``hang_timeout_s``
        => terminate ALL ranks (SIGTERM, grace, SIGKILL),
           classify the failure with the shared evidence rules,
           relaunch the WHOLE fleet from the last manifest-verified
           checkpoint (resume makes the retry bit-identical to a
           fault-free run — the trainer's own restore contract).

Each whole-fleet (re)launch is a **generation**: generation 0 is the
first launch; every relaunch increments it, exports ``MTT_GENERATION``
(the telemetry envelope's generation tag) and ``MTT_ATTEMPT`` =
generation + 1 (so fault plans stay attempt-scoped and the aggregate
CLI's attempt linking works unchanged), and gets a FRESH coordinator
address (the old coordinator died with the old rank 0).

Elastic degradation: when the evidence says a host is deterministically
gone — the same crash fingerprint on two consecutive fleet failures — or
the full-size relaunch budget is spent, the fleet relaunches at world
size N-1 instead of halting, emitting ``fleet_resized``. Data-parallel
shards re-balance purely from the new world size
(:func:`masters_thesis_tpu.parallel.mesh.shard_bounds` is a pure
function of ``(n, world, rank)``), and ONE trace id threads through
every generation so ``aggregate``/``postmortem`` stitch the attempt
chain into a single incident.

Jax-free by contract, single-threaded by design: the monitor is one
poll loop (child returncodes + per-rank heartbeat staleness through the
flight-recorder channel), so there is no lock ordering, no signal
handler, and nothing for the concurrency lint to find. Relaunch backoff
uses the shared decorrelated jitter — N ranks re-binding to a fresh
coordinator must not thundering-herd it.

CLI: ``python -m masters_thesis_tpu.resilience fleet`` (see __main__).
"""

from __future__ import annotations

import os
import shlex
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Sequence

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.resilience.backoff import DecorrelatedBackoff
from masters_thesis_tpu.resilience.faults import ATTEMPT_ENV
from masters_thesis_tpu.resilience.supervisor import (
    Classification,
    _read_json,
    _tail,
    classify_exit,
)
from masters_thesis_tpu.telemetry.events import GENERATION_ENV
from masters_thesis_tpu.telemetry.schedule import (
    audit_schedules,
    read_rank_schedules,
)
from masters_thesis_tpu.telemetry.trace import (
    PARENT_SPAN_ENV,
    TRACE_ENV,
    new_trace_id,
)

#: Coordinator address env exported per generation (mirrors
#: parallel.mesh.COORDINATOR_ENV — that module imports jax, this one
#: must not).
COORDINATOR_ENV = "MTT_COORDINATOR"

#: Template placeholders a fleet command may use; substituted per rank
#: and per generation.
TEMPLATE_KEYS = ("rank", "world", "coordinator", "gen", "out", "root")


@dataclass
class FleetConfig:
    nprocs: int = 2
    #: Floor for elastic resize; at this size a deterministic failure
    #: halts instead (min_nprocs == nprocs disables resizing entirely).
    min_nprocs: int = 1
    #: Full-size relaunch budget: transient fleet failures retried at
    #: the CURRENT world size before degrading to N-1.
    max_relaunches_per_size: int = 2
    #: Hard cap on generations across all sizes (runaway backstop).
    max_generations: int = 8
    backoff_s: float = 1.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 60.0
    #: Heartbeat staleness -> the rank is hung and the fleet restarts.
    #: Must comfortably exceed worker boot (jax import + compile).
    hang_timeout_s: float | None = None
    term_grace_s: float = 5.0
    poll_interval_s: float = 0.2
    #: Per-rank launch stagger (uniform jitter) so N processes don't
    #: slam the coordinator in the same instant.
    launch_stagger_s: float = 0.0
    #: With a {coordinator} template: rank 0 must open the coordinator
    #: service within this budget or the generation is a boot failure.
    boot_timeout_s: float | None = None


@dataclass
class _Rank:
    rank: int
    proc: subprocess.Popen
    dir: Path
    out_path: Path
    err_path: Path
    files: tuple


@dataclass
class GenerationOutcome:
    gen: int
    nprocs: int
    ok: bool
    wall_s: float
    pids: list[int] = field(default_factory=list)
    failed_rank: int | None = None
    rc: int | None = None
    hang_killed: bool = False
    classification: Classification | None = None


@dataclass
class FleetResult:
    ok: bool
    verdict: str  # completed | deterministic | retries_exhausted |
    #               budget_exhausted
    generations: list[GenerationOutcome] = field(default_factory=list)
    final_nprocs: int = 0
    resized: bool = False
    trace_id: str | None = None

    @property
    def n_generations(self) -> int:
        return len(self.generations)


class FleetSupervisor:
    """Launch and heal an N-process fleet per the module contract.

    ``cmd_template`` is the per-rank command with ``{rank}``/``{world}``/
    ``{coordinator}``/``{gen}``/``{out}``/``{root}`` placeholders; each
    rank's telemetry lands in ``<run_dir>/g<gen>/p<rank>/`` (the ``{out}``
    substitution) so every generation's forensic evidence survives the
    relaunch that supersedes it. ``ckpt_dir`` (optional) is the shared
    checkpoint root the fleet resumes from; the supervisor reports the
    last manifest-verified restore point per relaunch, jax-free.
    """

    def __init__(
        self,
        cmd_template: Sequence[str],
        run_dir: Path | str,
        cfg: FleetConfig | None = None,
        env: dict | None = None,
        ckpt_dir: Path | str | None = None,
        coordinator_host: str = "127.0.0.1",
        metrics_port: int | None = None,
        slo_rules=None,
    ) -> None:
        self.cmd_template = [str(a) for a in cmd_template]
        self.run_dir = Path(run_dir)
        self.cfg = cfg or FleetConfig()
        if self.cfg.min_nprocs > self.cfg.nprocs:
            raise ValueError("min_nprocs exceeds nprocs")
        self.base_env = dict(os.environ if env is None else env)
        self.ckpt_dir = Path(ckpt_dir) if ckpt_dir else None
        self.coordinator_host = coordinator_host
        self._uses_coordinator = any(
            "{coordinator}" in a for a in self.cmd_template
        )
        # One trace id for every generation (adopted from the caller's
        # env when present), exported forward to every rank.
        self.trace_id = self.base_env.get(TRACE_ENV) or new_trace_id()
        self.base_env[TRACE_ENV] = self.trace_id
        self._tel = None
        self._trace = None
        self._run_span = None
        self._ranks: list[_Rank] = []
        # Live telemetry plane (telemetry/exposition.py): /metrics + /slo
        # for the whole fleet. The SLO engine tails the run dir tree —
        # every rank's stream plus the supervisor's own — so heartbeat
        # staleness on ANY rank fires mid-generation. None disables; 0
        # binds an ephemeral port.
        self.metrics_port = metrics_port
        self._slo_rules = slo_rules
        self._exposition = None
        self._slo_engine = None

    # ------------------------------------------------------------ telemetry

    def _telemetry(self):
        if self._tel is None:
            from masters_thesis_tpu.telemetry import TelemetryRun

            self._tel = TelemetryRun(
                self.run_dir / "supervisor",
                run_id=f"fleet-{self.run_dir.name}",
            )
        return self._tel

    def _event(self, kind: str, **payload) -> None:
        try:
            self._telemetry().event(kind, **payload)
        except Exception:
            # The supervisor's telemetry must never kill supervision.
            pass

    def _audit_schedule(self, gen: int) -> dict:
        """Cross-check the generation's per-rank collective schedules.

        Runs on EVERY generation verdict, pass or fail: a generation the
        exit codes call healthy can still have issued divergent
        schedules (a rank that skipped a barrier and happened not to
        wedge yet), and a condemned one gets its diagnosis attached to
        the relaunch decision. Best-effort by contract — forensics must
        never kill supervision.
        """
        try:
            snaps = read_rank_schedules(self.run_dir / f"g{gen}")
            audit = audit_schedules(snaps)
        except Exception:
            return {"ok": True, "verdict": "unavailable"}
        self._event(
            "schedule_audit",
            gen=gen,
            ok=audit["ok"],
            verdict=audit["verdict"],
            divergent_rank=audit.get("divergent_rank"),
            step=audit.get("step"),
            detail=audit.get("detail"),
        )
        if not audit["ok"]:
            print(
                f"[fleetsup] g{gen} collective schedule DIVERGED: "
                f"{audit.get('detail')}",
                file=sys.stderr,
                flush=True,
            )
        return audit

    def _tracer(self):
        if self._trace is None:
            try:
                from masters_thesis_tpu.telemetry.trace import Tracer

                tel = self._telemetry()
                self._trace = Tracer(tel.sink, trace_id=self.trace_id)
                tel._tracer = self._trace
            except Exception:
                return None
        return self._trace

    # ------------------------------------------------------------- evidence

    def _rank_heartbeat_ts(self, rank_dir: Path) -> float | None:
        """Freshest ``last_beat_ts`` under one rank's telemetry dir —
        the PROGRESS marker (the heartbeat file's own mtime keeps
        advancing while the main thread hangs in a dead collective)."""
        from masters_thesis_tpu.telemetry.flightrec import HEARTBEAT_FILENAME

        best = None
        for hb in rank_dir.rglob(HEARTBEAT_FILENAME):
            obj = _read_json(hb)
            ts = obj.get("last_beat_ts") if obj else None
            if ts is None:
                try:
                    ts = hb.stat().st_mtime
                except OSError:
                    continue
            best = ts if best is None else max(best, ts)
        return best

    def _rank_crash_context(
        self, rank_dir: Path, since_ts: float
    ) -> tuple[str | None, int | None]:
        from masters_thesis_tpu.telemetry.flightrec import CRASHDUMP_FILENAME

        phase = epoch = None
        for p in sorted(rank_dir.rglob(CRASHDUMP_FILENAME)):
            dump = _read_json(p)
            if dump and (dump.get("ts") or 0.0) >= since_ts:
                phase, epoch = dump.get("phase"), dump.get("epoch")
        return phase, epoch

    def _verified_checkpoint(self) -> str | None:
        from masters_thesis_tpu.train.manifest import last_verified_checkpoint

        return last_verified_checkpoint(self.ckpt_dir)

    # ------------------------------------------------------------ lifecycle

    def _launch_generation(
        self, gen: int, world: int, coordinator: str | None
    ) -> None:
        import random

        gen_dir = self.run_dir / f"g{gen}"
        env_base = dict(self.base_env)
        env_base[ATTEMPT_ENV] = str(gen + 1)
        env_base[GENERATION_ENV] = str(gen)
        if coordinator:
            env_base[COORDINATOR_ENV] = coordinator
        else:
            env_base.pop(COORDINATOR_ENV, None)
        tracer = self._tracer()
        if tracer is not None:
            self._gen_span = tracer.start(
                "fleet.generation", parent=self._run_span, gen=gen,
                nprocs=world,
            )
            # Every rank's root span hangs off this generation span:
            # one trace covers the supervisor and all N * generations
            # processes it launched.
            env_base[PARENT_SPAN_ENV] = self._gen_span.span_id
        rng = random.Random()
        self._ranks = []
        for rank in range(world):
            rank_dir = gen_dir / f"p{rank}"
            rank_dir.mkdir(parents=True, exist_ok=True)
            subst = {
                "rank": rank,
                "world": world,
                "coordinator": coordinator or "",
                "gen": gen,
                "out": rank_dir,
                "root": self.run_dir,
            }
            cmd = [_fill(a, subst) for a in self.cmd_template]
            env = dict(env_base)
            env["JAX_PROCESS_INDEX"] = str(rank)
            env["JAX_PROCESS_COUNT"] = str(world)
            if self.cfg.launch_stagger_s and rank:
                time.sleep(rng.uniform(0.0, self.cfg.launch_stagger_s))
            out_path = gen_dir / f"p{rank}.out"
            err_path = gen_dir / f"p{rank}.err"
            out_f = open(out_path, "wb")
            err_f = open(err_path, "wb")
            proc = subprocess.Popen(
                cmd,
                stdout=out_f,
                stderr=err_f,
                env=env,
                start_new_session=True,  # killpg hits the rank's tree only
            )
            self._ranks.append(
                _Rank(rank, proc, rank_dir, out_path, err_path,
                      (out_f, err_f))
            )

    def _terminate_all(self, why: str) -> None:
        """SIGTERM every live rank, ONE shared grace window, SIGKILL the
        rest; reap everything. Phased so the grace is fleet-wide (N *
        grace_s would let a 16-rank teardown take minutes)."""
        live = [r for r in self._ranks if r.proc.poll() is None]
        if live:
            print(
                f"[fleetsup] terminating {len(live)} rank(s): {why} "
                f"(SIGTERM, {self.cfg.term_grace_s:.0f}s grace, SIGKILL)",
                file=sys.stderr,
                flush=True,
            )
        for r in live:
            try:
                os.killpg(r.proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
        deadline = time.monotonic() + self.cfg.term_grace_s
        for r in live:
            timeout = max(0.0, deadline - time.monotonic())
            try:
                r.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(r.proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        for r in self._ranks:
            if r.proc.poll() is None:
                try:
                    r.proc.wait(timeout=10.0)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
            for f in r.files:
                try:
                    f.close()
                except OSError:
                    pass

    # -------------------------------------------------------- one generation

    def _run_generation(
        self, gen: int, world: int, resumed_from: str | None
    ) -> GenerationOutcome:
        cfg = self.cfg
        coordinator = None
        if self._uses_coordinator:
            from masters_thesis_tpu.utils.backend_probe import (
                free_coordinator_address,
            )

            coordinator = free_coordinator_address(self.coordinator_host)
        self._event(
            "fleet_generation_started",
            gen=gen,
            nprocs=world,
            coordinator=coordinator,
            resumed_from=resumed_from,
            cmd=shlex.join(self.cmd_template),
        )
        start_ts = time.time()
        t0 = time.monotonic()
        self._gen_span = None
        self._launch_generation(gen, world, coordinator)
        coord_up = coordinator is None
        failed: _Rank | None = None
        hang_killed = False
        why = ""
        try:
            while True:
                time.sleep(cfg.poll_interval_s)
                rcs = {r.rank: r.proc.poll() for r in self._ranks}
                bad = next(
                    (r for r in self._ranks
                     if rcs[r.rank] not in (None, 0)),
                    None,
                )
                if bad is not None:
                    failed = bad
                    why = f"rank {bad.rank} exited rc={rcs[bad.rank]}"
                    break
                if all(rc == 0 for rc in rcs.values()):
                    break  # whole fleet finished clean
                now = time.monotonic()
                if not coord_up and coordinator:
                    from masters_thesis_tpu.utils.backend_probe import (
                        coordinator_reachable,
                    )

                    coord_up = coordinator_reachable(
                        coordinator, timeout_s=0.2
                    )
                    if (
                        not coord_up
                        and cfg.boot_timeout_s is not None
                        and now - t0 > cfg.boot_timeout_s
                    ):
                        failed = self._ranks[0]
                        why = (
                            "coordinator never came up within "
                            f"{cfg.boot_timeout_s:.0f}s"
                        )
                        break
                if cfg.hang_timeout_s and now - t0 > cfg.hang_timeout_s:
                    stale = self._find_hung_rank(rcs, gen)
                    if stale is not None:
                        failed = stale
                        hang_killed = True
                        why = f"rank {stale.rank} heartbeat stale"
                        break
        finally:
            # Any exit from the loop — success, failure, or an exception
            # in the supervisor itself — tears the whole generation down.
            # On success every rank already exited 0 and this only reaps.
            self._terminate_all(why or "generation over")
        wall_s = time.monotonic() - t0
        pids = [r.proc.pid for r in self._ranks]

        if failed is None:
            if self._trace is not None and self._gen_span is not None:
                self._trace.end(self._gen_span, status="ok", nprocs=world)
            return GenerationOutcome(
                gen=gen, nprocs=world, ok=True, wall_s=wall_s, pids=pids
            )
        rc = failed.proc.poll()
        if hang_killed or rc is None:
            rc = None
        phase, epoch = self._rank_crash_context(failed.dir, start_ts)
        cls = classify_exit(
            rc if not hang_killed else None,
            _tail(failed.err_path),
            hang_killed=hang_killed,
            crash_phase=phase,
            crash_epoch=epoch,
        )
        if self._trace is not None and self._gen_span is not None:
            self._trace.end(
                self._gen_span, status="error", failed_rank=failed.rank,
                classification=cls.kind,
            )
        self._event(
            "fleet_failure",
            gen=gen,
            rank=failed.rank,
            rc=rc,
            hang=hang_killed,
            classification=cls.kind,
            reason=cls.reason[:500],
            fingerprint=cls.fingerprint,
        )
        print(
            f"[fleetsup] generation {gen} failed: {why} "
            f"({cls.kind}: {cls.reason})",
            file=sys.stderr,
            flush=True,
        )
        return GenerationOutcome(
            gen=gen, nprocs=world, ok=False, wall_s=wall_s, pids=pids,
            failed_rank=failed.rank, rc=rc, hang_killed=hang_killed,
            classification=cls,
        )

    def _find_hung_rank(self, rcs: dict, gen: int) -> _Rank | None:
        """The first still-running rank whose heartbeat is stale past
        ``hang_timeout_s`` (or that a chaos plan wedged)."""
        cfg = self.cfg
        now = time.time()
        for r in self._ranks:
            if rcs[r.rank] is not None:
                continue  # exited-0 ranks are done, not hung
            if faults.fire(
                "fleet.rank_heartbeat", rank=r.rank, gen=gen
            ) == "wedge":
                return r
            ts = self._rank_heartbeat_ts(r.dir)
            # No heartbeat at all counts from generation start (the
            # elapsed > hang_timeout_s gate in the caller): a rank that
            # never got far enough to beat is as gone as one that
            # stopped.
            if ts is None or now - ts > cfg.hang_timeout_s:
                return r
        return None

    # ------------------------------------------------------------- the loop

    def run(self) -> FleetResult:
        cfg = self.cfg
        result = FleetResult(
            ok=False, verdict="retries_exhausted",
            final_nprocs=cfg.nprocs, trace_id=self.trace_id,
        )
        tracer = self._tracer()
        if tracer is not None:
            self._run_span = tracer.start("fleet.run")
        self._event(
            "fleet_started",
            nprocs=cfg.nprocs,
            min_nprocs=cfg.min_nprocs,
            max_relaunches_per_size=cfg.max_relaunches_per_size,
            max_generations=cfg.max_generations,
            hang_timeout_s=cfg.hang_timeout_s,
            cmd=shlex.join(self.cmd_template),
            trace_id=self.trace_id,
        )
        if self.metrics_port is not None:
            try:
                from masters_thesis_tpu.telemetry.exposition import (
                    start_telemetry_plane,
                )
                from masters_thesis_tpu.telemetry.slo import (
                    default_train_rules,
                )

                self._exposition, self._slo_engine = start_telemetry_plane(
                    self._telemetry(),
                    self.metrics_port,
                    rules=self._slo_rules or default_train_rules(),
                    root=self.run_dir,
                )
            except Exception:
                # Monitoring must never kill supervision.
                self._exposition = self._slo_engine = None
        world = cfg.nprocs
        gen = 0
        relaunches_at_size = 0
        last_fp: str | None = None
        backoff = DecorrelatedBackoff(
            cfg.backoff_s, cfg.max_backoff_s, cfg.backoff_factor
        )
        try:
            while True:
                resumed_from = self._verified_checkpoint()
                outcome = self._run_generation(gen, world, resumed_from)
                result.generations.append(outcome)
                self._audit_schedule(gen)
                result.final_nprocs = world
                if outcome.ok:
                    result.ok = True
                    result.verdict = "completed"
                    break
                cls = outcome.classification
                deterministic = (
                    cls is not None
                    and cls.fingerprint is not None
                    and cls.fingerprint == last_fp
                )
                last_fp = cls.fingerprint if cls is not None else None
                if gen + 1 >= cfg.max_generations:
                    result.verdict = "budget_exhausted"
                    break
                if (
                    deterministic
                    or relaunches_at_size >= cfg.max_relaunches_per_size
                ):
                    if world - 1 < cfg.min_nprocs:
                        result.verdict = (
                            "deterministic" if deterministic
                            else "retries_exhausted"
                        )
                        break
                    reason = (
                        "deterministic host loss (fingerprint "
                        f"{last_fp} reproduced)" if deterministic
                        else "full-size relaunch budget spent "
                        f"({cfg.max_relaunches_per_size})"
                    )
                    self._event(
                        "fleet_resized",
                        gen=gen + 1,
                        from_nprocs=world,
                        to_nprocs=world - 1,
                        reason=reason,
                        fingerprint=last_fp,
                    )
                    print(
                        f"[fleetsup] resizing fleet {world} -> {world - 1}: "
                        f"{reason}",
                        file=sys.stderr,
                        flush=True,
                    )
                    world -= 1
                    result.resized = True
                    relaunches_at_size = 0
                    # Fresh fingerprint chain at the new size: the retired
                    # rank's failure must not instantly condemn N-1.
                    last_fp = None
                else:
                    relaunches_at_size += 1
                delay = backoff.next()
                self._event(
                    "fleet_relaunch",
                    gen=gen + 1,
                    nprocs=world,
                    backoff_s=delay,
                    # Re-resolved NOW, not reused from the loop top: the
                    # dead generation may have published checkpoints the
                    # pre-launch probe never saw (first relaunch would
                    # otherwise always report null).
                    resumed_from=self._verified_checkpoint(),
                    reason=(cls.reason[:500] if cls is not None else None),
                )
                time.sleep(delay)
                gen += 1
        finally:
            # Belt and braces: no verdict may leave orphan ranks behind,
            # even if the supervisor itself blew up mid-generation.
            self._terminate_all("fleet verdict")
        if tracer is not None and self._run_span is not None:
            tracer.end(
                self._run_span,
                status="ok" if result.ok else "error",
                verdict=result.verdict,
                generations=result.n_generations,
                final_nprocs=result.final_nprocs,
            )
            self._run_span = None
        self._event(
            "fleet_verdict",
            ok=result.ok,
            verdict=result.verdict,
            generations=result.n_generations,
            final_nprocs=result.final_nprocs,
            resized=result.resized,
            trace_id=self.trace_id,
        )
        if self._exposition is not None or self._slo_engine is not None:
            try:
                from masters_thesis_tpu.telemetry.exposition import (
                    stop_telemetry_plane,
                )

                stop_telemetry_plane(self._exposition, self._slo_engine)
            except Exception:
                pass
            self._exposition = self._slo_engine = None
        if self._tel is not None:
            try:
                self._tel.close()
            except Exception:
                pass
        return result


def _fill(arg: str, subst: dict) -> str:
    """Substitute ``{key}`` placeholders without str.format (a worker
    arg containing unrelated braces must pass through untouched)."""
    for key in TEMPLATE_KEYS:
        arg = arg.replace("{" + key + "}", str(subst[key]))
    return arg
