"""Decorrelated-jitter backoff, shared by every restart loop.

A fixed exponential schedule synchronizes restarts: when one failure
takes down N processes (a dead host kills the whole data-parallel fleet),
every survivor computes the same delay and they all reconnect to the
coordinator in the same instant — the classic thundering herd. The fix is
the AWS "decorrelated jitter" schedule::

    delay_0 = base
    delay_k = min(cap, uniform(base, delay_{k-1} * factor))

which keeps the exponential *envelope* (the upper bound still grows by
``factor`` per retry, capped) while spreading actual delays uniformly
below it, so independent restart loops decorrelate after one step.

``factor <= 1.0`` degrades to a constant ``base`` delay — exactly the
deterministic schedule the fast selfcheck/test configs rely on
(``uniform(base, base) == base``), so determinism is a configuration,
not a special case.

Stdlib-only; used by :class:`~masters_thesis_tpu.resilience.supervisor.
RunSupervisor` (single-process retries) and
:class:`~masters_thesis_tpu.resilience.fleetsup.FleetSupervisor` (whole-
fleet relaunches, where the herd is real).
"""

from __future__ import annotations

import random


class DecorrelatedBackoff:
    """Stateful delay generator: ``next()`` yields the next sleep."""

    def __init__(
        self,
        base_s: float,
        cap_s: float,
        factor: float = 2.0,
        rng: random.Random | None = None,
    ) -> None:
        if base_s < 0 or cap_s < 0:
            raise ValueError("backoff delays must be non-negative")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.factor = float(factor)
        self._rng = rng if rng is not None else random.Random()
        self._prev: float | None = None

    def next(self) -> float:
        """The next delay; the first call always returns ``base_s``
        (capped) so a single transient blip retries promptly."""
        if self._prev is None:
            delay = min(self.base_s, self.cap_s)
        else:
            hi = max(self.base_s, self._prev * self.factor)
            delay = min(self.cap_s, self._rng.uniform(self.base_s, hi))
        self._prev = delay
        return delay

    def reset(self) -> None:
        """Forget the chain (a success ends the incident; the next
        failure is a fresh one and starts from ``base_s`` again)."""
        self._prev = None
