"""Resilient training: deterministic fault injection + run supervision.

Two halves, one contract:

- :mod:`masters_thesis_tpu.resilience.faults` — a seeded, explicitly
  activated fault-injection harness (preempt/kill/hang/wedge/corrupt/nan)
  wired into host-side points of the trainer, checkpoint, probe, and data
  code. Off by default; never reachable from traced code.
- :mod:`masters_thesis_tpu.resilience.supervisor` — a self-healing run
  supervisor (``python -m masters_thesis_tpu.resilience run -- <cmd>``)
  that wraps training as a child process: resume-from-last-good, retry
  with exponential backoff under a budget, evidence-based failure
  classification (transient vs deterministic), divergence rollback with
  optional LR halving, and graceful CPU degradation on a wedged backend.
- :mod:`masters_thesis_tpu.resilience.fleetsup` — the N-process analogue
  (``python -m masters_thesis_tpu.resilience fleet``): any rank dead or
  hung restarts the WHOLE fleet from the last manifest-verified
  checkpoint; deterministic host loss elastically degrades to N-1 with
  shards re-balanced, one trace id threading every generation.

This package (like the telemetry CLIs) is jax-free by contract: the
supervisor must work exactly when the accelerator runtime is wedged.
"""

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.resilience.faults import FaultInjected, FaultPlan, FaultSpec

__all__ = [
    "faults",
    "DecorrelatedBackoff",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "FleetConfig",
    "FleetResult",
    "FleetSupervisor",
    "ReplicaRestartPolicy",
    "ReplicaVerdict",
    "RunSupervisor",
    "SupervisorConfig",
]


def __getattr__(name: str):
    # Lazy: keep `import masters_thesis_tpu.resilience` cheap for the
    # fault-point fast path inside the trainer hot loop.
    if name in (
        "ReplicaRestartPolicy",
        "ReplicaVerdict",
        "RunSupervisor",
        "SupervisorConfig",
        "SupervisorResult",
    ):
        from masters_thesis_tpu.resilience import supervisor

        return getattr(supervisor, name)
    if name in ("FleetConfig", "FleetResult", "FleetSupervisor"):
        from masters_thesis_tpu.resilience import fleetsup

        return getattr(fleetsup, name)
    if name == "DecorrelatedBackoff":
        from masters_thesis_tpu.resilience.backoff import DecorrelatedBackoff

        return DecorrelatedBackoff
    raise AttributeError(name)
