"""Resilient training: deterministic fault injection + run supervision.

Two halves, one contract:

- :mod:`masters_thesis_tpu.resilience.faults` — a seeded, explicitly
  activated fault-injection harness (preempt/kill/hang/wedge/corrupt/nan)
  wired into host-side points of the trainer, checkpoint, probe, and data
  code. Off by default; never reachable from traced code.
- :mod:`masters_thesis_tpu.resilience.supervisor` — a self-healing run
  supervisor (``python -m masters_thesis_tpu.resilience run -- <cmd>``)
  that wraps training as a child process: resume-from-last-good, retry
  with exponential backoff under a budget, evidence-based failure
  classification (transient vs deterministic), divergence rollback with
  optional LR halving, and graceful CPU degradation on a wedged backend.

This package (like the telemetry CLIs) is jax-free by contract: the
supervisor must work exactly when the accelerator runtime is wedged.
"""

from masters_thesis_tpu.resilience import faults
from masters_thesis_tpu.resilience.faults import FaultInjected, FaultPlan, FaultSpec

__all__ = [
    "faults",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "ReplicaRestartPolicy",
    "ReplicaVerdict",
    "RunSupervisor",
    "SupervisorConfig",
]


def __getattr__(name: str):
    # Lazy: keep `import masters_thesis_tpu.resilience` cheap for the
    # fault-point fast path inside the trainer hot loop.
    if name in (
        "ReplicaRestartPolicy",
        "ReplicaVerdict",
        "RunSupervisor",
        "SupervisorConfig",
        "SupervisorResult",
    ):
        from masters_thesis_tpu.resilience import supervisor

        return getattr(supervisor, name)
    raise AttributeError(name)
