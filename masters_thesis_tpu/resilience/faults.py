"""Deterministic fault injection for chaos-testing the training stack.

A :class:`FaultPlan` is a seeded list of :class:`FaultSpec` entries, each
naming a *fault point* (a host-side call site instrumented with
:func:`fire`), a fault *kind*, and match conditions. Plans are activated
only explicitly — via the ``MTT_FAULT_PLAN`` environment variable (JSON,
or ``@/path/to/plan.json``) or :func:`install_plan` in-process — so the
default-off cost is a single dict lookup per fault point and nothing else.

Every fault point lives strictly in host code (epoch-loop boundaries,
checkpoint publish, the probe subprocess driver, metric readback): no
point is reachable from traced/jitted code, so an active plan cannot
change the compiled step HLO and tracelint/TA201–TA206 stay green by
construction.

Kinds:

- ``preempt`` — SIGTERM self (the flight recorder's handler dumps a
  crashdump on the way down, exactly like a real preemption notice).
- ``kill``    — SIGKILL self: no handler runs, heartbeat goes stale.
- ``hang``    — stop making progress (sleep forever); exercises hang
  watchdogs and supervisor heartbeat-staleness detection.
- ``raise``   — raise :class:`FaultInjected` (a crashing bug stand-in).
- ``wedge``   — returned to the caller: the backend probe treats the
  attempt as a simulated ``jax.devices()`` timeout (wedged lease).
- ``corrupt`` — returned to the caller: checkpoint code flips bytes in
  the just-published tree (seeded, deterministic).
- ``nan``     — returned to the caller: the trainer poisons the host-side
  loss readback with NaN, triggering the divergence halt.
- ``shift``   — returned to the caller: a seeded scale/offset regime
  shift applied to window features (``x*scale + offset`` with both drawn
  from :func:`shift_params`) at ``serve.admit`` / ``trainer.epoch_start``
  — the deterministic trigger for the model-quality drift detectors.

Match semantics: a spec fires when its ``point`` matches, the current
supervisor attempt (``MTT_ATTEMPT``, default 1) equals ``attempt``
(``null`` = any attempt), and every ``match`` key equals the
corresponding ``fire(**ctx)`` value. Attempt scoping is what keeps chaos
runs convergent: a kill-at-epoch-3 fault fires on attempt 1 and stays
quiet after the supervisor resumes the run as attempt 2.
"""

from __future__ import annotations

import json
import os
import random
import signal
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Mapping

FAULT_PLAN_ENV = "MTT_FAULT_PLAN"
ATTEMPT_ENV = "MTT_ATTEMPT"

KINDS = frozenset(
    {"preempt", "kill", "hang", "raise", "wedge", "corrupt", "nan", "shift"}
)
#: Kinds fire() executes itself (the process never returns normally).
PROCESS_KINDS = frozenset({"preempt", "kill", "hang", "raise"})
#: Kinds returned to the call site, which applies the corruption itself.
DATA_KINDS = KINDS - PROCESS_KINDS

#: Known fault points (documentation + parse-time typo guard). Each is a
#: host-side call site; see docs/resilience.md for where they sit.
POINTS = frozenset(
    {
        "trainer.epoch_start",  # top of the epoch loop, before dispatch
        # (kind: shift -> regime shift on this epoch's window features)
        "trainer.epoch_dispatched",  # after dispatch, before readback/save
        "trainer.loss",  # host-side metric readback (kind: nan)
        "stacked.replica_loss",  # per-replica readback in the stacked
        # trainer (kind: nan; match on {"replica": r} to poison one replica)
        "data.epoch",  # host data plane, once per epoch stream
        "checkpoint.pre_publish",  # staged pair complete, not yet live
        "checkpoint.mid_publish",  # rotation done, staged tree not yet
        # live (kind: kill tears the publish at its most exposed point —
        # proving the .prev rotation still verifies and recovery finishes
        # the swap)
        "checkpoint.post_publish",  # after publish (kind: corrupt)
        "dist.barrier",  # cross-process sync points (mesh.fleet_barrier):
        # hang wedges one rank inside the barrier, exactly the survivor
        # pathology a dead host induces in a real collective
        "fleet.rank_heartbeat",  # fleet supervisor's per-rank staleness
        # check (kind: wedge -> the supervisor treats the rank's heartbeat
        # as stale without needing a real hang; match on {"rank": r})
        "probe.attempt",  # backend probe attempt (kind: wedge)
        "worker.epoch",  # jax-free selfcheck worker epochs
        "serve.admit",  # request admission (kind: wedge -> forced shed;
        # shift -> seeded scale/offset regime shift on the window x)
        "serve.dispatch",  # micro-batch dispatch (wedge -> device error)
        "serve.pre_swap",  # hot-swap candidate staged (kind: corrupt)
        "serve.replica_dispatch",  # fleet replica dispatch: wedge -> device
        # error (breaker evidence), raise -> fatal replica death, hang ->
        # replica worker hangs (watchdog territory), corrupt -> poisoned
        # outputs. Match on {"replica": "r0"} to target one replica.
        "serve.replica_boot",  # fleet replica (re)boot (wedge -> boot
        # failure; the restart policy classifies the repeat)
        "cache.load",  # program-cache entry load (corrupt -> byte flipped
        # on disk, exercising the torn-entry refusal)
        "slo.evaluate",  # SLO engine tick (kind: wedge -> the evaluator
        # stops folding new events and its published /slo state goes
        # stale, WITHOUT touching the serving path it observes)
    }
)


class FaultInjected(RuntimeError):
    """Raised by ``kind: raise`` faults — a deterministic crashing bug."""


@dataclass(frozen=True)
class FaultSpec:
    point: str
    kind: str
    #: Supervisor attempt this spec is scoped to (None = every attempt).
    attempt: int | None = 1
    #: Context equality constraints, e.g. ``{"epoch": 3}``.
    match: Mapping[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        # Name the valid values: a typo'd chaos plan should tell the
        # operator what the harness DOES support, not just what it saw.
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind: {self.kind!r} "
                f"(valid kinds: {', '.join(sorted(KINDS))})"
            )
        if self.point not in POINTS:
            raise ValueError(
                f"unknown fault point: {self.point!r} "
                f"(valid points: {', '.join(sorted(POINTS))})"
            )

    def matches(self, point: str, attempt: int, ctx: Mapping[str, Any]) -> bool:
        if point != self.point:
            return False
        if self.attempt is not None and attempt != self.attempt:
            return False
        return all(ctx.get(k) == v for k, v in self.match.items())


@dataclass
class FaultPlan:
    faults: list[FaultSpec]
    seed: int = 0

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        """Parse a plan from JSON text or ``@/path`` file reference."""
        text = text.strip()
        if text.startswith("@"):
            text = Path(text[1:]).read_text()
        raw = json.loads(text)
        if isinstance(raw, list):
            raw = {"faults": raw}
        faults = [FaultSpec(**{**f}) for f in raw.get("faults", [])]
        return cls(faults=faults, seed=int(raw.get("seed", 0)))

    def to_json(self) -> str:
        return json.dumps(
            {
                "seed": self.seed,
                "faults": [
                    {
                        "point": f.point,
                        "kind": f.kind,
                        "attempt": f.attempt,
                        "match": dict(f.match),
                    }
                    for f in self.faults
                ],
            }
        )

    def lookup(
        self, point: str, attempt: int, ctx: Mapping[str, Any]
    ) -> FaultSpec | None:
        for spec in self.faults:
            if spec.matches(point, attempt, ctx):
                return spec
        return None


# In-process override installed by tests; _UNSET means "use the env".
_UNSET = object()
_override: Any = _UNSET
# Env-parse cache keyed by the raw env text, so repeated fire() calls
# don't re-parse and a changed env (new subprocess plan) is picked up.
_env_cache: tuple[str, FaultPlan] | None = None


def install_plan(plan: FaultPlan | None) -> None:
    """Install a plan in-process, taking precedence over
    ``MTT_FAULT_PLAN`` (``None`` forces injection off even if the env is
    set). :func:`clear_plan` falls back to the environment again."""
    global _override
    _override = plan


def clear_plan() -> None:
    global _override
    _override = _UNSET


def active_plan() -> FaultPlan | None:
    global _env_cache
    if _override is not _UNSET:
        return _override
    text = os.environ.get(FAULT_PLAN_ENV)
    if not text:
        return None
    if _env_cache is None or _env_cache[0] != text:
        _env_cache = (text, FaultPlan.parse(text))
    return _env_cache[1]


def current_attempt() -> int:
    try:
        return int(os.environ.get(ATTEMPT_ENV, "1") or 1)
    except ValueError:
        return 1


def fire(point: str, **ctx: Any) -> str | None:
    """Fire any fault armed at ``point`` for the current attempt/context.

    Process kinds (preempt/kill/hang/raise) never return. Data kinds
    (nan/wedge/corrupt) return the kind string for the call site to
    apply; returns ``None`` (the overwhelmingly common case) when no
    plan is active or nothing matches.
    """
    if _override is _UNSET and FAULT_PLAN_ENV not in os.environ:
        return None  # fast path: injection disabled
    plan = active_plan()
    if plan is None:
        return None
    spec = plan.lookup(point, current_attempt(), ctx)
    if spec is None:
        return None
    print(
        f"[faults] firing kind={spec.kind} at point={point} "
        f"attempt={current_attempt()} ctx={ctx}",
        file=sys.stderr,
        flush=True,
    )
    if spec.kind == "preempt":
        os.kill(os.getpid(), signal.SIGTERM)
        # Give a SIGTERM handler (flight recorder dump + re-delivery) time
        # to run; if none is installed the default action already killed us.
        deadline = time.monotonic() + 10.0  # mtt: disable=DV704 -- chaos preempt path: the process is being killed, nothing it computes past here reaches a checkpoint
        while time.monotonic() < deadline:  # mtt: disable=DV704 -- same dying-process grace loop; timing here cannot affect resume determinism
            time.sleep(0.1)
        os._exit(143)
    if spec.kind == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)  # unreachable; SIGKILL is not deliverable-later
    if spec.kind == "hang":
        while True:
            time.sleep(1.0)
    if spec.kind == "raise":
        raise FaultInjected(f"injected crash at {point} (ctx={ctx})")
    return spec.kind


def corruption_seed(extra: int = 0) -> int:
    """Deterministic seed for data-kind corruption at a call site."""
    plan = active_plan()
    return (plan.seed if plan is not None else 0) * 1_000_003 + extra


def shift_params(extra: int = 0) -> tuple[float, float]:
    """Seeded ``(scale, offset)`` for the ``shift`` data-fault kind.

    Deterministic in the plan seed (plus a call-site ``extra``), large
    enough that a drift detector with industry-standard thresholds must
    notice: scale in [1.25, 1.75), offset in [0.25, 0.75).
    """
    rng = random.Random(corruption_seed(extra))
    return 1.0 + rng.uniform(0.25, 0.75), rng.uniform(0.25, 0.75)
