"""Operator CLI for the resilience package (jax-free, like telemetry's).

Subcommands:

- ``run``       — supervise a training command to completion:
                  ``python -m masters_thesis_tpu.resilience run \\
                      --run-dir results/supervisor --watch-dir results/telemetry \\
                      --ckpt-dir results/ckpt -- python train.py trainer.resume=auto``
                  Exit code: 0 completed, 2 deterministic-failure verdict,
                  1 anything else (retries/budget/rollback exhausted).
- ``classify``  — one-shot failure classification from evidence on disk
                  (return code + stderr tail + crashdump/event streams);
                  prints JSON. Used by ``tools/check.sh`` as a jax-free unit.
- ``selfcheck`` — end-to-end smoke of the supervisor against jax-free
                  worker children: preempt -> resume, deterministic crash ->
                  halt, NaN divergence -> rollback with LR scaling. Exits
                  non-zero on any failed scenario. Mirrors
                  ``telemetry postmortem --selfcheck``.
- ``worker``    — internal: the simulated trainee the selfcheck supervises.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path


def _add_policy_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--max-retries", type=int, default=3)
    p.add_argument("--backoff-s", type=float, default=5.0)
    p.add_argument("--backoff-factor", type=float, default=2.0)
    p.add_argument("--max-backoff-s", type=float, default=300.0)
    p.add_argument("--retry-budget-s", type=float, default=None)
    p.add_argument("--attempt-timeout-s", type=float, default=None)
    p.add_argument("--rollback-attempts", type=int, default=2)
    p.add_argument("--lr-factor", type=float, default=0.5)
    p.add_argument("--hang-timeout-s", type=float, default=None)
    p.add_argument(
        "--probe",
        action="store_true",
        help="health-check the backend before each attempt; a failed "
        "probe pins the child to CPU (one probe shot, no retry burn)",
    )
    p.add_argument("--probe-timeout-s", type=float, default=120.0)
    p.add_argument("--probe-cache", type=Path, default=None)
    p.add_argument(
        "--no-cpu-fallback",
        action="store_true",
        help="record a failed probe as a degradation but do not pin CPU",
    )


def _cfg_from_args(args):
    from masters_thesis_tpu.resilience.supervisor import SupervisorConfig

    return SupervisorConfig(
        max_retries=args.max_retries,
        backoff_s=args.backoff_s,
        backoff_factor=args.backoff_factor,
        max_backoff_s=args.max_backoff_s,
        retry_budget_s=args.retry_budget_s,
        attempt_timeout_s=args.attempt_timeout_s,
        rollback_attempts=args.rollback_attempts,
        lr_factor=args.lr_factor,
        hang_timeout_s=args.hang_timeout_s,
        probe=args.probe,
        probe_timeout_s=args.probe_timeout_s,
        probe_cache=args.probe_cache,
        cpu_fallback=not args.no_cpu_fallback,
    )


# ---------------------------------------------------------------------- run


def _cmd_run(args) -> int:
    from masters_thesis_tpu.resilience.supervisor import RunSupervisor

    if not args.cmd:
        print("run: no command given (use `-- cmd ...`)", file=sys.stderr)
        return 2
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    sup = RunSupervisor(
        cmd,
        run_dir=args.run_dir,
        cfg=_cfg_from_args(args),
        watch_dir=args.watch_dir,
        ckpt_dir=args.ckpt_dir,
        passthrough=not args.quiet,
    )
    result = sup.run()
    print(
        f"[supervisor] verdict={result.verdict} attempts={result.n_attempts}"
        f" lost_work_s={result.lost_work_s:.1f}"
        + (" degraded=cpu" if result.degraded else ""),
        file=sys.stderr,
    )
    if result.ok:
        return 0
    return 2 if result.verdict == "deterministic" else 1


# ----------------------------------------------------------------- classify


def _cmd_classify(args) -> int:
    from masters_thesis_tpu.resilience.supervisor import RunSupervisor

    stderr_tail = ""
    if args.stderr_file:
        stderr_tail = Path(args.stderr_file).read_text(errors="replace")
    sup = RunSupervisor(
        ["true"],
        run_dir=args.watch_dir or ".",
        watch_dir=args.watch_dir,
    )
    cls = sup._classify(
        args.rc,
        args.since,
        stderr_tail,
        hang_killed=args.hang_killed,
        timed_out=False,
    )
    print(
        json.dumps(
            {
                "kind": cls.kind,
                "reason": cls.reason,
                "fingerprint": cls.fingerprint,
                "diverged_epoch": cls.diverged_epoch,
            },
            indent=2,
        )
    )
    return 0


# ------------------------------------------------------------------- worker


def _cmd_worker(args) -> int:
    """Simulated trainee: per-epoch progress file + telemetry + fault
    hooks. Resumes from its own progress file exactly like the real
    trainer resumes from a checkpoint — the selfcheck's proof that a
    supervised restart continues instead of starting over."""
    import os

    from masters_thesis_tpu.resilience import faults
    from masters_thesis_tpu.telemetry import TelemetryRun

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    progress = out / "progress.json"
    start = 0
    if progress.exists():
        try:
            start = json.loads(progress.read_text())["epoch"] + 1
        except (ValueError, KeyError):
            start = 0
    tel = TelemetryRun(out / "telemetry", run_id="selfcheck-worker")
    rec = tel.attach_flight_recorder(heartbeat_interval_s=0.1)
    lr_scale = float(os.environ.get("MTT_LR_SCALE", "1") or 1.0)
    tel.event(
        "run_started",
        resumed_from=str(progress) if start else None,
        lr_scale=lr_scale,
    )
    diverged = False
    for epoch in range(start, args.epochs):
        faults.fire("worker.epoch", epoch=epoch)
        rec.beat(phase="epoch", epoch=epoch)
        if args.mode == "hang" and epoch == args.at:
            while True:  # a wedged collective, as seen from the host
                time.sleep(3600)
        if args.mode == "nan" and epoch == args.at and lr_scale == 1.0:
            # Divergence heals at a lower LR: the rollback's relaunch
            # (MTT_LR_SCALE < 1) sails past this epoch.
            diverged = True
            break
        with open(out / "work.log", "a") as f:
            f.write(f"{faults.current_attempt()} {epoch}\n")
        progress.write_text(json.dumps({"epoch": epoch}))
        if args.sleep_s:
            time.sleep(args.sleep_s)
    tel.event("run_finished", diverged=diverged, epochs=args.epochs)
    tel.close()
    if args.mode == "crash":
        print("RuntimeError: injected deterministic failure", file=sys.stderr)
        return 3
    return 0


# ---------------------------------------------------------------- selfcheck


def _selfcheck(args) -> int:
    from masters_thesis_tpu.resilience.supervisor import (
        RunSupervisor,
        SupervisorConfig,
    )

    tmp = Path(tempfile.mkdtemp(prefix="resilience-selfcheck-"))
    failures: list[str] = []

    def worker_cmd(out: Path, mode: str, epochs: int = 4, at: int = 1):
        return [
            sys.executable,
            "-m",
            "masters_thesis_tpu.resilience",
            "worker",
            "--out",
            str(out),
            "--mode",
            mode,
            "--epochs",
            str(epochs),
            "--at",
            str(at),
        ]

    fast = SupervisorConfig(
        max_retries=3, backoff_s=0.05, backoff_factor=1.0, term_grace_s=2.0
    )

    # 1. preempt mid-run -> supervised resume continues, no redone work
    out = tmp / "preempt"
    import os

    env = dict(os.environ)
    env["MTT_FAULT_PLAN"] = json.dumps(
        [{"point": "worker.epoch", "kind": "preempt", "attempt": 1,
          "match": {"epoch": 2}}]
    )
    res = RunSupervisor(
        worker_cmd(out, "ok"),
        run_dir=out / "supervisor",
        cfg=fast,
        env=env,
        watch_dir=out / "telemetry",
    ).run()
    lines = (
        (out / "work.log").read_text().splitlines()
        if (out / "work.log").exists()
        else []
    )
    epochs_done = [int(ln.split()[1]) for ln in lines]
    if not res.ok or res.n_attempts != 2:
        failures.append(
            f"preempt-resume: verdict={res.verdict} attempts={res.n_attempts}"
        )
    elif epochs_done != [0, 1, 2, 3]:
        failures.append(
            f"preempt-resume: work log {epochs_done} != [0, 1, 2, 3] "
            "(restart redid or skipped epochs instead of resuming)"
        )

    # 2. deterministic crash -> halt after the fingerprint reproduces
    out = tmp / "crash"
    res = RunSupervisor(
        worker_cmd(out, "crash", at=99),
        run_dir=out / "supervisor",
        cfg=fast,
        watch_dir=out / "telemetry",
    ).run()
    if res.verdict != "deterministic" or res.n_attempts != 2:
        failures.append(
            f"deterministic: verdict={res.verdict} attempts={res.n_attempts}"
            " (want deterministic after exactly 2 attempts)"
        )

    # 3. NaN divergence -> rollback relaunch with a scaled LR completes
    out = tmp / "nan"
    res = RunSupervisor(
        worker_cmd(out, "nan"),
        run_dir=out / "supervisor",
        cfg=fast,
        watch_dir=out / "telemetry",
    ).run()
    rollbacks = [
        a for a in res.attempts if a.classification.kind == "divergence"
    ]
    if not res.ok or res.n_attempts != 2 or len(rollbacks) != 1:
        failures.append(
            f"nan-rollback: verdict={res.verdict} attempts={res.n_attempts} "
            f"divergences={len(rollbacks)}"
        )

    if args.keep:
        print(f"selfcheck artifacts kept at {tmp}", file=sys.stderr)
    else:
        shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        for f in failures:
            print(f"resilience selfcheck FAILED: {f}", file=sys.stderr)
        return 1
    print("resilience selfcheck: 3 scenarios OK")
    return 0


# --------------------------------------------------------------------- main


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m masters_thesis_tpu.resilience",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_run = sub.add_parser("run", help="supervise a training command")
    p_run.add_argument("--run-dir", type=Path, required=True)
    p_run.add_argument("--watch-dir", type=Path, default=None,
                       help="child telemetry dir (heartbeat + events)")
    p_run.add_argument("--ckpt-dir", type=Path, default=None)
    p_run.add_argument("--quiet", action="store_true",
                       help="log child output to files only, no passthrough")
    _add_policy_args(p_run)
    p_run.add_argument("cmd", nargs=argparse.REMAINDER)

    p_cls = sub.add_parser("classify", help="classify a failure from disk")
    p_cls.add_argument("--rc", type=int, default=None)
    p_cls.add_argument("--stderr-file", type=Path, default=None)
    p_cls.add_argument("--watch-dir", type=Path, default=None)
    p_cls.add_argument("--since", type=float, default=0.0)
    p_cls.add_argument("--hang-killed", action="store_true")

    p_self = sub.add_parser("selfcheck", help="end-to-end supervisor smoke")
    p_self.add_argument("--keep", action="store_true",
                        help="keep the scratch dir for inspection")

    p_wrk = sub.add_parser("worker")  # internal, used by selfcheck
    p_wrk.add_argument("--out", type=Path, required=True)
    p_wrk.add_argument("--mode", choices=("ok", "crash", "nan", "hang"),
                       default="ok")
    p_wrk.add_argument("--epochs", type=int, default=4)
    p_wrk.add_argument("--at", type=int, default=1,
                       help="epoch at which mode-specific behavior fires")
    p_wrk.add_argument("--sleep-s", type=float, default=0.0)

    args = parser.parse_args(argv)
    if args.command == "run":
        return _cmd_run(args)
    if args.command == "classify":
        return _cmd_classify(args)
    if args.command == "selfcheck":
        return _selfcheck(args)
    if args.command == "worker":
        return _cmd_worker(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
