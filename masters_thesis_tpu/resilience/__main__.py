"""Operator CLI for the resilience package (jax-free, like telemetry's).

Subcommands:

- ``run``       — supervise a training command to completion:
                  ``python -m masters_thesis_tpu.resilience run \\
                      --run-dir results/supervisor --watch-dir results/telemetry \\
                      --ckpt-dir results/ckpt -- python train.py trainer.resume=auto``
                  Exit code: 0 completed, 2 deterministic-failure verdict,
                  1 anything else (retries/budget/rollback exhausted).
- ``classify``  — one-shot failure classification from evidence on disk
                  (return code + stderr tail + crashdump/event streams);
                  prints JSON. Used by ``tools/check.sh`` as a jax-free unit.
- ``selfcheck`` — end-to-end smoke of the supervisor against jax-free
                  worker children: preempt -> resume, deterministic crash ->
                  halt, NaN divergence -> rollback with LR scaling. Exits
                  non-zero on any failed scenario. Mirrors
                  ``telemetry postmortem --selfcheck``.
- ``fleet``     — supervise an N-process fleet: any rank dead or hung
                  restarts the WHOLE fleet from the last verified
                  checkpoint; deterministic host loss degrades to N-1
                  (``--selfcheck`` runs a hermetic 2-rank fleet with an
                  injected rank kill and a deterministic-loss resize).
- ``worker``    — internal: the simulated trainee the selfcheck supervises.
- ``fleet-worker`` — internal: the simulated fleet rank (shared atomic
                  progress commit, crash/hang injection per rank).
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time
from pathlib import Path


def _add_policy_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--max-retries", type=int, default=3)
    p.add_argument("--backoff-s", type=float, default=5.0)
    p.add_argument("--backoff-factor", type=float, default=2.0)
    p.add_argument("--max-backoff-s", type=float, default=300.0)
    p.add_argument("--retry-budget-s", type=float, default=None)
    p.add_argument("--attempt-timeout-s", type=float, default=None)
    p.add_argument("--rollback-attempts", type=int, default=2)
    p.add_argument("--lr-factor", type=float, default=0.5)
    p.add_argument("--hang-timeout-s", type=float, default=None)
    p.add_argument(
        "--probe",
        action="store_true",
        help="health-check the backend before each attempt; a failed "
        "probe pins the child to CPU (one probe shot, no retry burn)",
    )
    p.add_argument("--probe-timeout-s", type=float, default=120.0)
    p.add_argument("--probe-cache", type=Path, default=None)
    p.add_argument(
        "--no-cpu-fallback",
        action="store_true",
        help="record a failed probe as a degradation but do not pin CPU",
    )


def _cfg_from_args(args):
    from masters_thesis_tpu.resilience.supervisor import SupervisorConfig

    return SupervisorConfig(
        max_retries=args.max_retries,
        backoff_s=args.backoff_s,
        backoff_factor=args.backoff_factor,
        max_backoff_s=args.max_backoff_s,
        retry_budget_s=args.retry_budget_s,
        attempt_timeout_s=args.attempt_timeout_s,
        rollback_attempts=args.rollback_attempts,
        lr_factor=args.lr_factor,
        hang_timeout_s=args.hang_timeout_s,
        probe=args.probe,
        probe_timeout_s=args.probe_timeout_s,
        probe_cache=args.probe_cache,
        cpu_fallback=not args.no_cpu_fallback,
    )


# ---------------------------------------------------------------------- run


def _cmd_run(args) -> int:
    from masters_thesis_tpu.resilience.supervisor import RunSupervisor

    if not args.cmd:
        print("run: no command given (use `-- cmd ...`)", file=sys.stderr)
        return 2
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    sup = RunSupervisor(
        cmd,
        run_dir=args.run_dir,
        cfg=_cfg_from_args(args),
        watch_dir=args.watch_dir,
        ckpt_dir=args.ckpt_dir,
        passthrough=not args.quiet,
    )
    result = sup.run()
    print(
        f"[supervisor] verdict={result.verdict} attempts={result.n_attempts}"
        f" lost_work_s={result.lost_work_s:.1f}"
        + (" degraded=cpu" if result.degraded else ""),
        file=sys.stderr,
    )
    if result.ok:
        return 0
    return 2 if result.verdict == "deterministic" else 1


# ----------------------------------------------------------------- classify


def _cmd_classify(args) -> int:
    from masters_thesis_tpu.resilience.supervisor import RunSupervisor

    stderr_tail = ""
    if args.stderr_file:
        stderr_tail = Path(args.stderr_file).read_text(errors="replace")
    sup = RunSupervisor(
        ["true"],
        run_dir=args.watch_dir or ".",
        watch_dir=args.watch_dir,
    )
    cls = sup._classify(
        args.rc,
        args.since,
        stderr_tail,
        hang_killed=args.hang_killed,
        timed_out=False,
    )
    print(
        json.dumps(
            {
                "kind": cls.kind,
                "reason": cls.reason,
                "fingerprint": cls.fingerprint,
                "diverged_epoch": cls.diverged_epoch,
            },
            indent=2,
        )
    )
    return 0


# ------------------------------------------------------------------- worker


def _cmd_worker(args) -> int:
    """Simulated trainee: per-epoch progress file + telemetry + fault
    hooks. Resumes from its own progress file exactly like the real
    trainer resumes from a checkpoint — the selfcheck's proof that a
    supervised restart continues instead of starting over."""
    import os

    from masters_thesis_tpu.resilience import faults
    from masters_thesis_tpu.telemetry import TelemetryRun

    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    progress = out / "progress.json"
    start = 0
    if progress.exists():
        try:
            start = json.loads(progress.read_text())["epoch"] + 1
        except (ValueError, KeyError):
            start = 0
    tel = TelemetryRun(out / "telemetry", run_id="selfcheck-worker")
    rec = tel.attach_flight_recorder(heartbeat_interval_s=0.1)
    lr_scale = float(os.environ.get("MTT_LR_SCALE", "1") or 1.0)
    tel.event(
        "run_started",
        resumed_from=str(progress) if start else None,
        lr_scale=lr_scale,
    )
    diverged = False
    for epoch in range(start, args.epochs):
        faults.fire("worker.epoch", epoch=epoch)
        rec.beat(phase="epoch", epoch=epoch)
        if args.mode == "hang" and epoch == args.at:
            while True:  # a wedged collective, as seen from the host
                time.sleep(3600)
        if args.mode == "nan" and epoch == args.at and lr_scale == 1.0:
            # Divergence heals at a lower LR: the rollback's relaunch
            # (MTT_LR_SCALE < 1) sails past this epoch.
            diverged = True
            break
        with open(out / "work.log", "a") as f:
            f.write(f"{faults.current_attempt()} {epoch}\n")
        progress.write_text(json.dumps({"epoch": epoch}))
        if args.sleep_s:
            time.sleep(args.sleep_s)
    tel.event("run_finished", diverged=diverged, epochs=args.epochs)
    tel.close()
    if args.mode == "crash":
        print("RuntimeError: injected deterministic failure", file=sys.stderr)
        return 3
    return 0


# -------------------------------------------------------------------- fleet


def _fleet_cfg_from_args(args):
    from masters_thesis_tpu.resilience.fleetsup import FleetConfig

    return FleetConfig(
        nprocs=args.nprocs,
        min_nprocs=args.min_nprocs,
        max_relaunches_per_size=args.max_relaunches_per_size,
        max_generations=args.max_generations,
        backoff_s=args.backoff_s,
        backoff_factor=args.backoff_factor,
        max_backoff_s=args.max_backoff_s,
        hang_timeout_s=args.hang_timeout_s,
        term_grace_s=args.term_grace_s,
        poll_interval_s=args.poll_interval_s,
        boot_timeout_s=args.boot_timeout_s,
    )


def _cmd_fleet(args) -> int:
    from masters_thesis_tpu.resilience.fleetsup import FleetSupervisor

    if args.selfcheck:
        return _fleet_selfcheck(args)
    if not args.cmd:
        print("fleet: no command given (use `-- cmd ...`)", file=sys.stderr)
        return 2
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    sup = FleetSupervisor(
        cmd,
        run_dir=args.run_dir,
        cfg=_fleet_cfg_from_args(args),
        ckpt_dir=args.ckpt_dir,
    )
    result = sup.run()
    print(
        f"[fleetsup] verdict={result.verdict}"
        f" generations={result.n_generations}"
        f" final_nprocs={result.final_nprocs}"
        + (" resized" if result.resized else "")
        + f" trace={result.trace_id}",
        file=sys.stderr,
    )
    if result.ok:
        return 0
    return 2 if result.verdict == "deterministic" else 1


# ------------------------------------------------------------- fleet-worker


def _fleet_shard(n: int, world: int, rank: int) -> tuple[int, int]:
    # Inline mirror of parallel.mesh.shard_bounds — this worker must stay
    # jax-free and mesh.py imports jax at module level. Kept in lockstep
    # by tests/test_fleetsup.py.
    base, extra = divmod(n, world)
    lo = rank * base + min(rank, extra)
    return lo, lo + base + (1 if rank < extra else 0)


def _cmd_fleet_worker(args) -> int:
    """Simulated fleet rank for the fleet selfcheck and tests.

    Shards ``--items`` across the generation's world size (env identity
    exported by the fleet supervisor), heartbeats through the flight
    recorder, and — on rank 0 only, mirroring the real checkpoint's
    rank-0 publish contract — commits progress ATOMICALLY after each
    epoch: one file holding the resume epoch, a rolling "params" value,
    and the full work history. A kill at any instant therefore leaves
    every committed epoch in the history exactly once, which is what the
    bit-identical-resume assertion checks.
    """
    import os
    import signal as _signal

    from masters_thesis_tpu.resilience import faults
    from masters_thesis_tpu.telemetry import TelemetryRun
    from masters_thesis_tpu.utils import atomic_write_text

    rank = int(os.environ.get("JAX_PROCESS_INDEX", "0") or 0)
    world = int(os.environ.get("JAX_PROCESS_COUNT", "1") or 1)
    gen = int(os.environ.get("MTT_GENERATION", "0") or 0)
    attempt = faults.current_attempt()
    state = Path(args.state)
    state.mkdir(parents=True, exist_ok=True)
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)

    lo, hi = _fleet_shard(args.items, world, rank)
    with open(state / "shards.log", "a") as f:
        # Single short write under O_APPEND: atomic across ranks.
        f.write(f"{gen} {world} {rank} {lo} {hi}\n")

    progress = state / "progress.json"
    start, value, history = 0, 0, []
    if progress.exists():
        try:
            obj = json.loads(progress.read_text())
            start = obj["epoch"] + 1
            value = obj["value"]
            history = obj["history"]
        except (ValueError, KeyError):
            start, value, history = 0, 0, []

    tel = TelemetryRun(out / "telemetry", run_id=f"fleet-worker-p{rank}")
    rec = tel.attach_flight_recorder(heartbeat_interval_s=0.1)
    tel.event(
        "run_started",
        rank=rank,
        world=world,
        gen=gen,
        shard=[lo, hi],
        resumed_from=str(progress) if start else None,
    )
    for epoch in range(start, args.epochs):
        rec.beat(phase="epoch", epoch=epoch)
        faults.fire("worker.epoch", epoch=epoch, rank=rank)
        crash_here = (
            args.crash_rank is not None
            and rank == args.crash_rank
            and epoch >= args.at
            and (args.crash_mode == "always" or gen == 0)
        )
        if crash_here:
            tel.event("epoch_crash", rank=rank, gen=gen, epoch=epoch)
            if args.crash_kind == "kill":
                os.kill(os.getpid(), _signal.SIGKILL)
            print(
                "RuntimeError: injected deterministic rank failure",
                file=sys.stderr,
            )
            tel.close()
            return 3
        if (
            args.hang_rank is not None
            and rank == args.hang_rank
            and epoch == args.at
            and gen == 0
        ):
            while True:  # a wedged collective, as seen from the host
                time.sleep(3600)
        if args.sleep_s:
            time.sleep(args.sleep_s)
        if rank == 0:
            # The single commit point: value + history move together or
            # not at all (atomic replace), so a SIGKILL mid-epoch can
            # never record the epoch half-done.
            value = (value * 1000003 + epoch) % (2**61 - 1)
            history.append([attempt, gen, world, epoch])
            atomic_write_text(
                progress,
                json.dumps(
                    {"epoch": epoch, "value": value, "history": history}
                ),
            )
    tel.event("run_finished", rank=rank, world=world, gen=gen,
              epochs=args.epochs)
    tel.close()
    return 0


def _fleet_expected_value(epochs: int) -> int:
    value = 0
    for epoch in range(epochs):
        value = (value * 1000003 + epoch) % (2**61 - 1)
    return value


def _fleet_selfcheck(args) -> int:
    """Hermetic fleet smoke: (1) a 2-rank fleet loses one rank to an
    injected SIGKILL mid-epoch -> whole-fleet relaunch resumes from the
    committed progress and the final value is bit-identical to a
    fault-free run, every epoch done exactly once; (2) a deterministic
    rank failure (same fingerprint twice) -> elastic resize to 1 rank,
    which completes."""
    from masters_thesis_tpu.resilience.fleetsup import (
        FleetConfig,
        FleetSupervisor,
    )

    tmp = Path(tempfile.mkdtemp(prefix="fleet-selfcheck-"))
    failures: list[str] = []
    epochs = 5
    expected = _fleet_expected_value(epochs)

    def fleet_cmd(state: Path, *extra: str) -> list[str]:
        return [
            sys.executable,
            "-m",
            "masters_thesis_tpu.resilience",
            "fleet-worker",
            "--state",
            str(state),
            "--out",
            "{out}",
            "--epochs",
            str(epochs),
            "--items",
            "64",
            "--sleep-s",
            "0.05",
            *extra,
        ]

    fast = FleetConfig(
        nprocs=2,
        min_nprocs=1,
        max_relaunches_per_size=2,
        backoff_s=0.05,
        backoff_factor=1.0,
        term_grace_s=2.0,
        poll_interval_s=0.05,
    )

    def check_progress(state: Path, label: str) -> None:
        obj = json.loads((state / "progress.json").read_text())
        done = [entry[3] for entry in obj["history"]]
        if done != list(range(epochs)):
            failures.append(
                f"{label}: history epochs {done} != {list(range(epochs))} "
                "(resume redid or skipped committed work)"
            )
        elif obj["value"] != expected:
            failures.append(
                f"{label}: final value {obj['value']} != fault-free "
                f"{expected} (resume is not bit-identical)"
            )

    # 1. rank 1 SIGKILLed mid-epoch -> all-rank relaunch, verified resume
    state = tmp / "kill-state"
    res = FleetSupervisor(
        fleet_cmd(state, "--crash-rank", "1", "--at", "1",
                  "--crash-kind", "kill"),
        run_dir=tmp / "kill-run",
        cfg=fast,
    ).run()
    if not res.ok or res.n_generations != 2 or res.resized:
        failures.append(
            f"kill-relaunch: verdict={res.verdict} "
            f"generations={res.n_generations} resized={res.resized} "
            "(want completed in exactly 2 generations, no resize)"
        )
    else:
        check_progress(state, "kill-relaunch")

    # 2. deterministic rank loss -> same fingerprint twice -> resize to 1
    state = tmp / "det-state"
    res = FleetSupervisor(
        fleet_cmd(state, "--crash-rank", "1", "--at", "1",
                  "--crash-mode", "always"),
        run_dir=tmp / "det-run",
        cfg=fast,
    ).run()
    if not res.ok or not res.resized or res.final_nprocs != 1:
        failures.append(
            f"deterministic-resize: verdict={res.verdict} "
            f"generations={res.n_generations} resized={res.resized} "
            f"final_nprocs={res.final_nprocs} "
            "(want elastic degradation to 1 rank, then completion)"
        )
    else:
        check_progress(state, "deterministic-resize")

    if getattr(args, "keep", False):
        print(f"fleet selfcheck artifacts kept at {tmp}", file=sys.stderr)
    else:
        shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        for f in failures:
            print(f"fleet selfcheck FAILED: {f}", file=sys.stderr)
        return 1
    print("fleet selfcheck: 2 scenarios OK")
    return 0


# ---------------------------------------------------------------- selfcheck


def _selfcheck(args) -> int:
    from masters_thesis_tpu.resilience.supervisor import (
        RunSupervisor,
        SupervisorConfig,
    )

    tmp = Path(tempfile.mkdtemp(prefix="resilience-selfcheck-"))
    failures: list[str] = []

    def worker_cmd(out: Path, mode: str, epochs: int = 4, at: int = 1):
        return [
            sys.executable,
            "-m",
            "masters_thesis_tpu.resilience",
            "worker",
            "--out",
            str(out),
            "--mode",
            mode,
            "--epochs",
            str(epochs),
            "--at",
            str(at),
        ]

    fast = SupervisorConfig(
        max_retries=3, backoff_s=0.05, backoff_factor=1.0, term_grace_s=2.0
    )

    # 1. preempt mid-run -> supervised resume continues, no redone work
    out = tmp / "preempt"
    import os

    env = dict(os.environ)
    env["MTT_FAULT_PLAN"] = json.dumps(
        [{"point": "worker.epoch", "kind": "preempt", "attempt": 1,
          "match": {"epoch": 2}}]
    )
    res = RunSupervisor(
        worker_cmd(out, "ok"),
        run_dir=out / "supervisor",
        cfg=fast,
        env=env,
        watch_dir=out / "telemetry",
    ).run()
    lines = (
        (out / "work.log").read_text().splitlines()
        if (out / "work.log").exists()
        else []
    )
    epochs_done = [int(ln.split()[1]) for ln in lines]
    if not res.ok or res.n_attempts != 2:
        failures.append(
            f"preempt-resume: verdict={res.verdict} attempts={res.n_attempts}"
        )
    elif epochs_done != [0, 1, 2, 3]:
        failures.append(
            f"preempt-resume: work log {epochs_done} != [0, 1, 2, 3] "
            "(restart redid or skipped epochs instead of resuming)"
        )

    # 2. deterministic crash -> halt after the fingerprint reproduces
    out = tmp / "crash"
    res = RunSupervisor(
        worker_cmd(out, "crash", at=99),
        run_dir=out / "supervisor",
        cfg=fast,
        watch_dir=out / "telemetry",
    ).run()
    if res.verdict != "deterministic" or res.n_attempts != 2:
        failures.append(
            f"deterministic: verdict={res.verdict} attempts={res.n_attempts}"
            " (want deterministic after exactly 2 attempts)"
        )

    # 3. NaN divergence -> rollback relaunch with a scaled LR completes
    out = tmp / "nan"
    res = RunSupervisor(
        worker_cmd(out, "nan"),
        run_dir=out / "supervisor",
        cfg=fast,
        watch_dir=out / "telemetry",
    ).run()
    rollbacks = [
        a for a in res.attempts if a.classification.kind == "divergence"
    ]
    if not res.ok or res.n_attempts != 2 or len(rollbacks) != 1:
        failures.append(
            f"nan-rollback: verdict={res.verdict} attempts={res.n_attempts} "
            f"divergences={len(rollbacks)}"
        )

    if args.keep:
        print(f"selfcheck artifacts kept at {tmp}", file=sys.stderr)
    else:
        shutil.rmtree(tmp, ignore_errors=True)
    if failures:
        for f in failures:
            print(f"resilience selfcheck FAILED: {f}", file=sys.stderr)
        return 1
    print("resilience selfcheck: 3 scenarios OK")
    return 0


# --------------------------------------------------------------------- main


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m masters_thesis_tpu.resilience",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_run = sub.add_parser("run", help="supervise a training command")
    p_run.add_argument("--run-dir", type=Path, required=True)
    p_run.add_argument("--watch-dir", type=Path, default=None,
                       help="child telemetry dir (heartbeat + events)")
    p_run.add_argument("--ckpt-dir", type=Path, default=None)
    p_run.add_argument("--quiet", action="store_true",
                       help="log child output to files only, no passthrough")
    _add_policy_args(p_run)
    p_run.add_argument("cmd", nargs=argparse.REMAINDER)

    p_cls = sub.add_parser("classify", help="classify a failure from disk")
    p_cls.add_argument("--rc", type=int, default=None)
    p_cls.add_argument("--stderr-file", type=Path, default=None)
    p_cls.add_argument("--watch-dir", type=Path, default=None)
    p_cls.add_argument("--since", type=float, default=0.0)
    p_cls.add_argument("--hang-killed", action="store_true")

    p_self = sub.add_parser("selfcheck", help="end-to-end supervisor smoke")
    p_self.add_argument("--keep", action="store_true",
                        help="keep the scratch dir for inspection")

    p_fleet = sub.add_parser("fleet", help="supervise an N-process fleet")
    p_fleet.add_argument("--run-dir", type=Path, default=None)
    p_fleet.add_argument("--ckpt-dir", type=Path, default=None)
    p_fleet.add_argument("--nprocs", type=int, default=2)
    p_fleet.add_argument("--min-nprocs", type=int, default=1,
                         help="elastic-resize floor; below this a "
                         "deterministic failure halts the fleet")
    p_fleet.add_argument("--max-relaunches-per-size", type=int, default=2)
    p_fleet.add_argument("--max-generations", type=int, default=8)
    p_fleet.add_argument("--backoff-s", type=float, default=1.0)
    p_fleet.add_argument("--backoff-factor", type=float, default=2.0)
    p_fleet.add_argument("--max-backoff-s", type=float, default=60.0)
    p_fleet.add_argument("--hang-timeout-s", type=float, default=None,
                         help="heartbeat staleness after which a rank "
                         "counts as hung and the fleet restarts")
    p_fleet.add_argument("--term-grace-s", type=float, default=5.0)
    p_fleet.add_argument("--poll-interval-s", type=float, default=0.2)
    p_fleet.add_argument("--boot-timeout-s", type=float, default=None)
    p_fleet.add_argument("--selfcheck", action="store_true",
                         help="run the hermetic 2-rank fleet smoke "
                         "instead of supervising a command")
    p_fleet.add_argument("--keep", action="store_true",
                         help="(selfcheck) keep the scratch dir")
    p_fleet.add_argument("cmd", nargs=argparse.REMAINDER,
                         help="per-rank command template; {rank} {world} "
                         "{coordinator} {gen} {out} {root} substituted")

    p_fwrk = sub.add_parser("fleet-worker")  # internal, used by selfcheck
    p_fwrk.add_argument("--state", type=Path, required=True,
                        help="shared dir: atomic progress + shard log")
    p_fwrk.add_argument("--out", type=Path, required=True)
    p_fwrk.add_argument("--epochs", type=int, default=4)
    p_fwrk.add_argument("--items", type=int, default=64,
                        help="total items sharded across the fleet")
    p_fwrk.add_argument("--sleep-s", type=float, default=0.0)
    p_fwrk.add_argument("--crash-rank", type=int, default=None)
    p_fwrk.add_argument("--hang-rank", type=int, default=None)
    p_fwrk.add_argument("--at", type=int, default=1,
                        help="epoch at which the injected failure fires")
    p_fwrk.add_argument("--crash-mode", choices=("once", "always"),
                        default="once",
                        help="once: generation 0 only (transient); "
                        "always: every generation the rank exists in "
                        "(deterministic host loss)")
    p_fwrk.add_argument("--crash-kind", choices=("exit", "kill"),
                        default="exit",
                        help="exit: rc=3 with a stderr line; kill: "
                        "SIGKILL self (no evidence beyond the signal)")

    p_wrk = sub.add_parser("worker")  # internal, used by selfcheck
    p_wrk.add_argument("--out", type=Path, required=True)
    p_wrk.add_argument("--mode", choices=("ok", "crash", "nan", "hang"),
                       default="ok")
    p_wrk.add_argument("--epochs", type=int, default=4)
    p_wrk.add_argument("--at", type=int, default=1,
                       help="epoch at which mode-specific behavior fires")
    p_wrk.add_argument("--sleep-s", type=float, default=0.0)

    args = parser.parse_args(argv)
    if args.command == "run":
        return _cmd_run(args)
    if args.command == "classify":
        return _cmd_classify(args)
    if args.command == "selfcheck":
        return _selfcheck(args)
    if args.command == "fleet":
        return _cmd_fleet(args)
    if args.command == "worker":
        return _cmd_worker(args)
    if args.command == "fleet-worker":
        return _cmd_fleet_worker(args)
    return 2


if __name__ == "__main__":
    sys.exit(main())
