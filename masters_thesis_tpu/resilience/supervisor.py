"""Self-healing run supervisor: wrap training as a supervised child.

The supervisor owns the retry loop the trainer cannot own (it is the
process that dies): launch the command as a child, watch its heartbeat,
classify how it ended using the same crashdump/heartbeat/event evidence
the postmortem CLI reads, and decide — per classification — between

- ``transient``  (preemption signal, hang, wedged-backend UNAVAILABLE,
  first occurrence of an unknown crash): retry with exponential backoff,
  under ``max_retries`` and an optional wall-clock ``retry_budget_s``;
- ``divergence`` (child's event stream ends in run_finished
  diverged=true): roll back to the last good checkpoint — which the
  trainer's no-clobber-on-divergence rule guarantees is intact — and
  relaunch with ``MTT_LR_SCALE`` compounded by ``lr_factor``, bounded by
  ``rollback_attempts``;
- ``deterministic`` (an instantly-reproduced identical crash fingerprint,
  or divergence at the same epoch twice): halt with a verdict instead of
  burning the budget replaying the same failure.

Each launch exports ``MTT_ATTEMPT`` so (a) the child's telemetry tags
every event with the attempt, and (b) fault plans are attempt-scoped —
the injected kill that took down attempt 1 stays quiet in attempt 2.

Graceful degradation generalizes bench.py's probe-cache failover: with
``probe=True`` the backend is health-checked before each attempt through
the shared :class:`~masters_thesis_tpu.utils.backend_probe.BackendHealth`
policy (known-wedged lease -> ONE probe attempt, never a 600s retry
burn); a failed probe pins the child to the CPU mesh and emits a
``degradation`` event rather than failing the run.

Jax-free by contract (like the telemetry CLIs): the supervisor must work
exactly when the accelerator runtime is wedged. Checkpoint inspection is
filesystem-only; the child trainer does the real restore.
"""

from __future__ import annotations

import hashlib
import os
import re
import shlex
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Sequence

from masters_thesis_tpu.resilience.backoff import DecorrelatedBackoff
from masters_thesis_tpu.resilience.faults import ATTEMPT_ENV
from masters_thesis_tpu.telemetry.trace import (
    PARENT_SPAN_ENV,
    TRACE_ENV,
    new_trace_id,
)

LR_SCALE_ENV = "MTT_LR_SCALE"
TERM_GRACE_S = 15.0
#: Child stdout/stderr tail kept for fingerprinting + attempt logs.
TAIL_BYTES = 8192

TRANSIENT_PATTERNS = (
    # The relay lease dropping out from under a live run (documented
    # failure mode, docs/OPERATIONS.md) — retriable once the lease clears.
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Socket closed",
    "failed to connect",
)


@dataclass
class SupervisorConfig:
    max_retries: int = 3  # transient retries (attempts = 1 + retries)
    backoff_s: float = 5.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 300.0
    retry_budget_s: float | None = None  # wall budget across ALL attempts
    attempt_timeout_s: float | None = None  # per-attempt wall cap
    rollback_attempts: int = 2  # divergence rollbacks
    lr_factor: float = 0.5  # LR scale per rollback (1.0 = no change)
    hang_timeout_s: float | None = None  # heartbeat staleness -> kill
    term_grace_s: float = TERM_GRACE_S
    probe: bool = False  # pre-attempt backend health check
    probe_timeout_s: float = 120.0
    probe_cache: Path | str | None = None  # default: results/probe_cache.json
    cpu_fallback: bool = True  # wedged backend -> pin child to CPU


@dataclass
class Classification:
    kind: str  # success | transient | divergence | deterministic | timeout
    reason: str
    fingerprint: str | None = None
    diverged_epoch: int | None = None


@dataclass
class AttemptOutcome:
    attempt: int
    rc: int | None
    wall_s: float
    classification: Classification
    lost_work_s: float = 0.0
    hang_killed: bool = False


@dataclass
class ReplicaVerdict:
    """What the fleet should do with a dead serving replica."""

    action: str  # restart | halt
    kind: str  # transient | deterministic | budget_exhausted
    backoff_s: float
    detail: str = ""


class ReplicaRestartPolicy:
    """Evidence-based restart classification for serving-fleet replicas.

    The same discipline as :meth:`RunSupervisor._classify`, applied
    in-process (a fleet replica is a thread + device subset, not a child
    process): a replica death is retriable until the EVIDENCE says
    otherwise —

    - the same failure fingerprint on two CONSECUTIVE deaths is
      deterministic by evidence (a restart would replay the identical
      failure forever);
    - a per-replica restart budget bounds a flapping replica, so the
      fleet converges to draining it instead of thrashing its devices;
    - restarts back off exponentially (serving backoffs are milliseconds,
      not the supervisor's seconds — a dead replica is capacity, and the
      queue is shedding what it can't cover).

    A successful serve resets the fingerprint chain (:meth:`note_healthy`):
    a crash, an hour of clean traffic, then the same crash is a fresh
    incident, not a reproduction. Jax-free; called from the fleet's
    monitor thread.
    """

    def __init__(
        self,
        max_restarts: int = 3,
        backoff_s: float = 0.05,
        backoff_factor: float = 2.0,
        max_backoff_s: float = 2.0,
    ):
        self.max_restarts = max_restarts
        self.backoff_s = backoff_s
        self.backoff_factor = backoff_factor
        self.max_backoff_s = max_backoff_s
        self._state: dict[str, dict] = {}

    def _entry(self, replica: str) -> dict:
        return self._state.setdefault(
            replica, {"restarts": 0, "last_fp": None}
        )

    def note_healthy(self, replica: str) -> None:
        entry = self._state.get(replica)
        if entry is not None:
            entry["last_fp"] = None

    def restarts(self, replica: str) -> int:
        return self._entry(replica)["restarts"]

    def classify(
        self, replica: str, fingerprint: str, detail: str = ""
    ) -> ReplicaVerdict:
        entry = self._entry(replica)
        if fingerprint and entry["last_fp"] == fingerprint:
            return ReplicaVerdict(
                "halt", "deterministic", 0.0,
                f"identical failure fingerprint on consecutive deaths "
                f"({fingerprint}): {detail}",
            )
        if entry["restarts"] >= self.max_restarts:
            return ReplicaVerdict(
                "halt", "budget_exhausted", 0.0,
                f"restart budget exhausted ({self.max_restarts}): {detail}",
            )
        entry["last_fp"] = fingerprint
        entry["restarts"] += 1
        backoff = min(
            self.backoff_s * self.backoff_factor ** (entry["restarts"] - 1),
            self.max_backoff_s,
        )
        return ReplicaVerdict("restart", "transient", backoff, detail)


@dataclass
class SupervisorResult:
    ok: bool
    verdict: str  # completed | deterministic | retries_exhausted |
    #               budget_exhausted | rollback_exhausted
    attempts: list[AttemptOutcome] = field(default_factory=list)
    degraded: bool = False
    lost_work_s: float = 0.0

    @property
    def n_attempts(self) -> int:
        return len(self.attempts)


def _tail(path: Path, n: int = TAIL_BYTES) -> str:
    try:
        data = path.read_bytes()
    except OSError:
        return ""
    return data[-n:].decode(errors="replace")


def _crash_line(stderr_tail: str) -> str:
    """The most identifying line of a crash: the final exception line
    (``Error: ...``) if present, else the last non-empty line."""
    lines = [ln.strip() for ln in stderr_tail.splitlines() if ln.strip()]
    for ln in reversed(lines):
        if re.match(r"^[\w.]*(Error|Exception|Exit|Abort)", ln):
            return ln
    return lines[-1] if lines else ""


def _read_json(path: Path) -> dict | None:
    import json

    try:
        obj = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None


def classify_exit(
    rc: int | None,
    stderr_tail: str,
    *,
    hang_killed: bool = False,
    timed_out: bool = False,
    diverged_epoch: int | None = None,
    crash_phase: str | None = None,
    crash_epoch: int | None = None,
) -> Classification:
    """Evidence-based exit classification, shared by the single-process
    :class:`RunSupervisor` and the fleet supervisor (which gathers the
    same evidence per rank). The caller supplies what it read from disk:
    the stderr tail, any divergence verdict from the child's event
    stream, and the phase/epoch of the freshest crashdump this attempt
    produced (both feed the crash fingerprint, so "died in checkpoint
    publish at epoch 3" and "died in data load at epoch 0" are distinct
    failures even with identical stderr)."""
    if timed_out:
        return Classification("timeout", "attempt wall-clock cap hit")
    if hang_killed:
        return Classification(
            "transient", "hang: heartbeat went stale (watchdog kill)"
        )
    # Divergence first: the trainer HALTS on NaN but exits 0, so the
    # verdict lives in the child's event stream, not the return code.
    if diverged_epoch is not None:
        return Classification(
            "divergence",
            f"run diverged (non-finite loss) at epoch {diverged_epoch}",
            fingerprint=f"nan@epoch{diverged_epoch}",
            diverged_epoch=diverged_epoch,
        )
    if rc == 0:
        return Classification("success", "exited 0")
    if rc is not None and rc < 0:
        sig = -rc
        name = (
            signal.Signals(sig).name
            if sig in signal.Signals._value2member_map_
            else str(sig)
        )
        return Classification(
            "transient", f"killed by {name} (preemption-shaped)"
        )
    if any(p in stderr_tail for p in TRANSIENT_PATTERNS):
        return Classification(
            "transient",
            f"backend unavailable (rc={rc}): "
            f"{_crash_line(stderr_tail)}",
        )
    # Unknown crash: fingerprint it; the retry loop halts when the
    # same fingerprint reproduces (deterministic by evidence).
    crash_line = _crash_line(stderr_tail)
    fp = hashlib.sha1(
        f"{rc}|{crash_line}|{crash_phase}|{crash_epoch}".encode()
    ).hexdigest()[:12]
    return Classification(
        "transient",
        f"crash (rc={rc}): {crash_line or 'no stderr'}",
        fingerprint=fp,
    )


class RunSupervisor:
    """Supervise ``cmd`` to completion, retrying/rolling back per policy.

    ``watch_dir`` is where the CHILD's telemetry lands (heartbeat.json for
    hang detection, events.jsonl for the divergence verdict); ``run_dir``
    holds the supervisor's own stream + per-attempt stdout/stderr logs.
    ``ckpt_dir`` (optional) enables filesystem-level resume/lost-work
    accounting; ``passthrough`` echoes child output to this process's
    stdout/stderr (for pipeline use, e.g. bench's JSON line).
    """

    def __init__(
        self,
        cmd: Sequence[str],
        run_dir: Path | str,
        cfg: SupervisorConfig | None = None,
        env: dict | None = None,
        cwd: Path | str | None = None,
        watch_dir: Path | str | None = None,
        ckpt_dir: Path | str | None = None,
        passthrough: bool = False,
        metrics_port: int | None = None,
        slo_rules=None,
    ) -> None:
        self.cmd = list(cmd)
        self.run_dir = Path(run_dir)
        self.cfg = cfg or SupervisorConfig()
        self.base_env = dict(os.environ if env is None else env)
        self.cwd = str(cwd) if cwd is not None else None
        self.watch_dir = Path(watch_dir) if watch_dir else None
        self.ckpt_dir = Path(ckpt_dir) if ckpt_dir else None
        self.passthrough = passthrough
        self._tel = None
        self._degraded = False
        # Live telemetry plane (telemetry/exposition.py): /metrics + /slo
        # for the whole supervised run. The SLO engine tails the CHILD's
        # streams (watch_dir) so heartbeat-staleness / divergence alerts
        # fire while an attempt is still running, not at the verdict.
        # None disables; 0 binds an ephemeral port.
        self.metrics_port = metrics_port
        self._slo_rules = slo_rules
        self._exposition = None
        self._slo_engine = None
        # One stable trace id for the WHOLE supervised run: adopted from
        # the caller's env when present (a grid runner tracing the cell),
        # minted once otherwise — and propagated FORWARD to every attempt
        # via the env, so retries and rollbacks share the trace instead of
        # being stitched together after the fact.
        self.trace_id = self.base_env.get(TRACE_ENV) or new_trace_id()
        self.base_env[TRACE_ENV] = self.trace_id
        self._trace = None
        self._run_span = None

    # ------------------------------------------------------------ telemetry

    def _telemetry(self):
        if self._tel is None:
            from masters_thesis_tpu.telemetry import TelemetryRun

            self._tel = TelemetryRun(
                self.run_dir, run_id=f"supervisor-{self.run_dir.name}"
            )
        return self._tel

    def _event(self, kind: str, **payload) -> None:
        try:
            self._telemetry().event(kind, **payload)
        except Exception:
            # The supervisor's own telemetry must never kill supervision.
            pass

    def _tracer(self):
        """Span writer on the supervisor's own stream, pinned to the
        run's stable trace id (the supervisor's process env may not carry
        it — it lives in base_env for the children)."""
        if self._trace is None:
            try:
                from masters_thesis_tpu.telemetry.trace import Tracer

                tel = self._telemetry()
                self._trace = Tracer(tel.sink, trace_id=self.trace_id)
                # Share with the TelemetryRun so close() aborts leftovers.
                tel._tracer = self._trace
            except Exception:
                return None
        return self._trace

    # ------------------------------------------------------------- evidence

    def _heartbeats(self) -> list[Path]:
        if self.watch_dir is None or not self.watch_dir.exists():
            return []
        from masters_thesis_tpu.telemetry.flightrec import HEARTBEAT_FILENAME

        return sorted(self.watch_dir.rglob(HEARTBEAT_FILENAME))

    def _last_heartbeat_ts(self) -> float | None:
        best = None
        for hb in self._heartbeats():
            obj = _read_json(hb)
            # last_beat_ts is the PROGRESS marker; the file's own ts keeps
            # advancing even while the main thread hangs (the heartbeat
            # thread outlives a wedged collective), so it must not count.
            ts = obj.get("last_beat_ts") if obj else None
            if ts is None:
                try:
                    ts = hb.stat().st_mtime
                except OSError:
                    continue
            best = ts if best is None else max(best, ts)
        return best

    def _crashdumps(self) -> list[dict]:
        if self.watch_dir is None or not self.watch_dir.exists():
            return []
        from masters_thesis_tpu.telemetry.flightrec import CRASHDUMP_FILENAME

        dumps = []
        for p in sorted(self.watch_dir.rglob(CRASHDUMP_FILENAME)):
            obj = _read_json(p)
            if obj:
                dumps.append(obj)
        return dumps

    def _diverged_epoch(self, since_ts: float) -> int | None:
        """Did the child's event stream end in a divergence halt during
        this attempt? Returns the halting epoch (or -1 if unknown)."""
        if self.watch_dir is None or not self.watch_dir.exists():
            return None
        from masters_thesis_tpu.telemetry.events import read_events
        from masters_thesis_tpu.telemetry.report import EVENTS_FILENAME

        for stream in sorted(self.watch_dir.rglob(EVENTS_FILENAME)):
            events = [
                e
                for e in read_events(stream)
                if (e.get("ts") or 0.0) >= since_ts
            ]
            for ev in reversed(events):
                if ev.get("kind") == "run_finished":
                    if ev.get("diverged"):
                        epochs = [
                            e.get("epoch")
                            for e in events
                            if e.get("kind") == "epoch"
                            and e.get("epoch") is not None
                        ]
                        return int(max(epochs)) if epochs else -1
                    break
        return None

    def _ckpt_state(self) -> tuple[str | None, float | None]:
        """(resume path, mtime) of the last-good checkpoint, fs-only.

        The sidecar json is the publish's final rename, so its presence
        means a complete pair; verification/recovery is the child
        trainer's job (it imports the checkpoint machinery)."""
        if self.ckpt_dir is None:
            return None, None
        for tag in ("last", "last.prev"):
            tree = self.ckpt_dir / tag
            sidecar = self.ckpt_dir / f"{tag}.json"
            if tree.exists() and sidecar.exists():
                try:
                    return str(tree), sidecar.stat().st_mtime
                except OSError:
                    return str(tree), None
        return None, None

    # --------------------------------------------------------------- health

    def _check_backend(self) -> None:
        """Pre-attempt health gate: one probe shot (the supervisor owns
        retries), CPU failover + degradation event when it fails."""
        from masters_thesis_tpu.utils.backend_probe import (
            BackendHealth,
            pin_cpu,
        )

        if self._degraded:
            return  # already failed over; stay on CPU for this run
        cache = self.cfg.probe_cache or Path("results/probe_cache.json")
        health = BackendHealth(cache, timeout_s=self.cfg.probe_timeout_s)
        decision = health.ensure_responsive(single_attempt=True)
        if decision.ok:
            return
        if not self.cfg.cpu_fallback:
            self._event(
                "degradation",
                reason=decision.detail,
                fallback=None,
                probe_attempts=decision.attempts,
            )
            return
        self._degraded = True
        pin_cpu(self.base_env)
        self._event(
            "degradation",
            reason=decision.detail or "backend probe failed",
            fallback="cpu",
            probe_attempts=decision.attempts,
            known_wedged=decision.known_wedged,
        )
        print(
            "[supervisor] backend wedged "
            f"({decision.attempts} probe attempt(s)); degrading to CPU mesh",
            file=sys.stderr,
            flush=True,
        )

    # ------------------------------------------------------------ the child

    def _launch(self, attempt: int, lr_scale: float) -> AttemptOutcome:
        cfg = self.cfg
        self.run_dir.mkdir(parents=True, exist_ok=True)
        out_path = self.run_dir / f"attempt_{attempt}.out"
        err_path = self.run_dir / f"attempt_{attempt}.err"
        env = dict(self.base_env)
        env[ATTEMPT_ENV] = str(attempt)
        if lr_scale != 1.0:
            env[LR_SCALE_ENV] = f"{lr_scale:g}"
        resumed_from, _ = self._ckpt_state()

        start_ts = time.time()
        t0 = time.monotonic()
        deadline = (
            t0 + cfg.attempt_timeout_s if cfg.attempt_timeout_s else None
        )
        tracer = self._tracer()
        attempt_span = None
        if tracer is not None:
            attempt_span = tracer.start(
                "supervisor.attempt", parent=self._run_span, n=attempt,
                lr_scale=lr_scale, resumed=bool(resumed_from),
            )
            # The child's root spans hang off this attempt span — one
            # trace covers the supervisor and every process it launches.
            env[PARENT_SPAN_ENV] = attempt_span.span_id
        self._event(
            "attempt_started",
            n=attempt,
            cmd=shlex.join(self.cmd),
            resumed_from=resumed_from,
            lr_scale=lr_scale,
            degraded=self._degraded,
            trace_id=self.trace_id,
        )

        with open(out_path, "wb") as out_f, open(err_path, "wb") as err_f:
            proc = subprocess.Popen(
                self.cmd,
                stdout=subprocess.PIPE if self.passthrough else out_f,
                stderr=subprocess.PIPE if self.passthrough else err_f,
                env=env,
                cwd=self.cwd,
                start_new_session=True,  # our signals, not the shell's
            )
            pumps = []
            if self.passthrough:
                pumps = [
                    threading.Thread(
                        target=_pump, args=(proc.stdout, sys.stdout, out_f),
                        daemon=True,
                    ),
                    threading.Thread(
                        target=_pump, args=(proc.stderr, sys.stderr, err_f),
                        daemon=True,
                    ),
                ]
                for t in pumps:
                    t.start()

            hang_killed = False
            rc: int | None = None
            while True:
                try:
                    rc = proc.wait(timeout=1.0)
                    break
                except subprocess.TimeoutExpired:
                    pass
                now = time.monotonic()
                if deadline is not None and now > deadline:
                    self._terminate(proc, "attempt timeout")
                    rc = proc.wait()
                    rc = None  # timeout, not the child's own exit
                    break
                if cfg.hang_timeout_s:
                    hb = self._last_heartbeat_ts()
                    if (
                        hb is not None
                        and time.time() - hb > cfg.hang_timeout_s
                        and now - t0 > cfg.hang_timeout_s
                    ):
                        self._terminate(
                            proc,
                            f"heartbeat stale for {time.time() - hb:.0f}s",
                        )
                        proc.wait()
                        rc = None
                        hang_killed = True
                        break
            for t in pumps:
                t.join(timeout=5.0)

        wall_s = time.monotonic() - t0
        classification = self._classify(
            rc,
            start_ts,
            _tail(err_path),
            hang_killed=hang_killed,
            timed_out=(rc is None and not hang_killed),
        )
        # Lost work: wall since the last checkpoint publish this attempt
        # managed (none -> the whole attempt), 0 for successes.
        lost = 0.0
        if classification.kind != "success":
            _, ckpt_mtime_after = self._ckpt_state()
            if ckpt_mtime_after and ckpt_mtime_after > start_ts:
                lost = max(0.0, time.time() - ckpt_mtime_after)
            else:
                # No checkpoint published this attempt: all of it is lost.
                lost = wall_s
        outcome = AttemptOutcome(
            attempt=attempt,
            rc=rc,
            wall_s=wall_s,
            classification=classification,
            lost_work_s=lost,
            hang_killed=hang_killed,
        )
        if tracer is not None and attempt_span is not None:
            tracer.end(
                attempt_span,
                status="ok" if classification.kind == "success" else "error",
                rc=rc,
                classification=classification.kind,
            )
        self._event(
            "attempt_finished",
            n=attempt,
            rc=rc,
            ok=classification.kind == "success",
            wall_s=wall_s,
            classification=classification.kind,
            reason=classification.reason[:500],
            fingerprint=classification.fingerprint,
            lost_work_s=lost,
        )
        return outcome

    def _terminate(self, proc: subprocess.Popen, why: str) -> None:
        print(
            f"[supervisor] killing child pid {proc.pid}: {why} "
            f"(SIGTERM, {self.cfg.term_grace_s:.0f}s grace, then SIGKILL)",
            file=sys.stderr,
            flush=True,
        )
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        try:
            proc.wait(timeout=self.cfg.term_grace_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    # -------------------------------------------------------- classification

    def _classify(
        self,
        rc: int | None,
        start_ts: float,
        stderr_tail: str,
        hang_killed: bool,
        timed_out: bool,
    ) -> Classification:
        """Gather this attempt's on-disk evidence, then delegate to the
        shared :func:`classify_exit` rules."""
        diverged_epoch = None
        if not timed_out and not hang_killed:
            diverged_epoch = self._diverged_epoch(start_ts)
        phase = epoch = None
        for dump in self._crashdumps():
            if (dump.get("ts") or 0.0) >= start_ts:
                phase, epoch = dump.get("phase"), dump.get("epoch")
        return classify_exit(
            rc,
            stderr_tail,
            hang_killed=hang_killed,
            timed_out=timed_out,
            diverged_epoch=diverged_epoch,
            crash_phase=phase,
            crash_epoch=epoch,
        )

    # ------------------------------------------------------------- the loop

    def run(self) -> SupervisorResult:
        cfg = self.cfg
        result = SupervisorResult(ok=False, verdict="retries_exhausted")
        tracer = self._tracer()
        if tracer is not None:
            self._run_span = tracer.start("supervisor.run")
        self._event(
            "supervisor_started",
            cmd=shlex.join(self.cmd),
            max_retries=cfg.max_retries,
            rollback_attempts=cfg.rollback_attempts,
            lr_factor=cfg.lr_factor,
            retry_budget_s=cfg.retry_budget_s,
            probe=cfg.probe,
            trace_id=self.trace_id,
        )
        if self.metrics_port is not None:
            try:
                from masters_thesis_tpu.telemetry.exposition import (
                    start_telemetry_plane,
                )
                from masters_thesis_tpu.telemetry.slo import (
                    default_train_rules,
                )

                self._exposition, self._slo_engine = start_telemetry_plane(
                    self._telemetry(),
                    self.metrics_port,
                    rules=self._slo_rules or default_train_rules(),
                    root=self.watch_dir or self.run_dir,
                )
            except Exception:
                # Monitoring must never kill supervision.
                self._exposition = self._slo_engine = None
        t_start = time.monotonic()
        attempt = 0
        retries = rollbacks = 0
        lr_scale = 1.0
        # Decorrelated jitter: with many supervised runs (or a whole
        # fleet) restarting off the same failure, identical exponential
        # schedules would thundering-herd the coordinator/backend.
        # backoff_factor <= 1.0 keeps the old deterministic constant.
        backoff_policy = DecorrelatedBackoff(
            cfg.backoff_s, cfg.max_backoff_s, cfg.backoff_factor
        )
        seen_fingerprints: list[str] = []
        last_divergence: str | None = None

        while True:
            attempt += 1
            if cfg.probe:
                self._check_backend()
            outcome = self._launch(attempt, lr_scale)
            result.attempts.append(outcome)
            result.lost_work_s += outcome.lost_work_s
            cls = outcome.classification

            if cls.kind == "success":
                result.ok = True
                result.verdict = "completed"
                break
            if cls.kind == "timeout":
                result.verdict = "budget_exhausted"
                break

            if cls.kind == "divergence":
                if cls.fingerprint == last_divergence:
                    result.verdict = "deterministic"
                    self._event(
                        "verdict_deterministic",
                        reason=(
                            "divergence reproduced at the same epoch after "
                            "rollback: " + cls.reason
                        ),
                    )
                    break
                last_divergence = cls.fingerprint
                if rollbacks >= cfg.rollback_attempts:
                    result.verdict = "rollback_exhausted"
                    break
                rollbacks += 1
                lr_scale *= cfg.lr_factor
                resume_from, _ = self._ckpt_state()
                self._event(
                    "rollback",
                    n=rollbacks,
                    lr_scale=lr_scale,
                    resume_from=resume_from,
                    reason=cls.reason,
                )
                print(
                    f"[supervisor] divergence rollback {rollbacks}/"
                    f"{cfg.rollback_attempts}: resume from last-good with "
                    f"LR x{lr_scale:g}",
                    file=sys.stderr,
                    flush=True,
                )
                continue  # rollback relaunches immediately (no backoff)

            # transient
            if cls.fingerprint and cls.fingerprint in seen_fingerprints:
                result.verdict = "deterministic"
                self._event(
                    "verdict_deterministic",
                    reason="identical crash fingerprint reproduced: "
                    + cls.reason,
                    fingerprint=cls.fingerprint,
                )
                break
            if cls.fingerprint:
                seen_fingerprints.append(cls.fingerprint)
            if retries >= cfg.max_retries:
                result.verdict = "retries_exhausted"
                break
            backoff = backoff_policy.next()
            if (
                cfg.retry_budget_s is not None
                and time.monotonic() - t_start + backoff > cfg.retry_budget_s
            ):
                result.verdict = "budget_exhausted"
                break
            retries += 1
            self._event(
                "retry", n=retries, backoff_s=backoff, reason=cls.reason[:500]
            )
            print(
                f"[supervisor] transient failure ({cls.reason}); retry "
                f"{retries}/{cfg.max_retries} in {backoff:.1f}s",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(backoff)

        if tracer is not None and self._run_span is not None:
            tracer.end(
                self._run_span,
                status="ok" if result.ok else "error",
                verdict=result.verdict,
                attempts=result.n_attempts,
            )
            self._run_span = None
        self._event(
            "supervisor_verdict",
            ok=result.ok,
            verdict=result.verdict,
            attempts=result.n_attempts,
            restarts=max(0, result.n_attempts - 1),
            lost_work_s=result.lost_work_s,
            degraded=self._degraded,
            trace_id=self.trace_id,
        )
        result.degraded = self._degraded
        if self._exposition is not None or self._slo_engine is not None:
            try:
                from masters_thesis_tpu.telemetry.exposition import (
                    stop_telemetry_plane,
                )

                stop_telemetry_plane(self._exposition, self._slo_engine)
            except Exception:
                pass
            self._exposition = self._slo_engine = None
        if self._tel is not None:
            try:
                self._tel.close()
            except Exception:
                pass
        return result


def _pump(src, mirror, sink) -> None:
    """Forward a child stream to (console mirror, log file) line-wise."""
    for chunk in iter(lambda: src.readline(), b""):
        try:
            sink.write(chunk)
            sink.flush()
        except (OSError, ValueError):
            pass
        try:
            mirror.buffer.write(chunk)
            mirror.flush()
        except (AttributeError, OSError, ValueError):
            try:
                mirror.write(chunk.decode(errors="replace"))
                mirror.flush()
            except (OSError, ValueError):
                pass
    src.close()
