"""Windowed-dataset construction: lookback/target splitting and feature maps.

Capability parity with the reference's window pipeline
(reference: src/common.py:81-148). The reference uses torch ``unfold`` (a
strided view); here windows are materialized with a gather over precomputed
start indices — static shapes throughout, so the whole pipeline jit-compiles
and can run on device or host.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from masters_thesis_tpu.ops.linalg import ols


def lookback_target_split(
    r_stocks: Array,
    r_market: Array,
    lookback_window: int,
    target_window: int,
    stride: int | None = None,
    prediction: bool = True,
) -> tuple[Array, Array]:
    """Slice return series into strided (lookback, target) window pairs.

    Stocks and market are broadcast against each other, stacked on a trailing
    channel axis, and windowed along time (reference: src/common.py:81-112).

    Args:
        r_stocks: ``(n_stocks, n_samples)`` stock return series.
        r_market: ``(n_samples,)`` market return series (broadcast to stocks).
        lookback_window: encoder context length.
        target_window: supervision horizon length.
        stride: window start spacing; defaults to ``lookback + target``
            (non-overlapping).
        prediction: if True, target is the ``target_window`` steps *after* the
            lookback (disjoint X/y); if False (reconstruction), the target is
            the trailing ``target_window`` steps *inside* the lookback.

    Returns:
        ``X``: ``(n_windows, n_stocks, lookback_window, 2)`` and
        ``y``: ``(n_windows, n_stocks, target_window or lookback_window, 2)``
        with channels ``[r_stock, r_market]``.
    """
    if stride is None:
        stride = lookback_window + target_window

    if not prediction and target_window > lookback_window:
        raise ValueError(
            f"reconstruction task requires target_window ({target_window}) <= "
            f"lookback_window ({lookback_window})"
        )

    total_window = lookback_window + target_window if prediction else lookback_window

    stacked = jnp.stack(jnp.broadcast_arrays(r_stocks, r_market), axis=-1)
    n_samples = stacked.shape[1]
    n_windows = (n_samples - total_window) // stride + 1
    if n_windows < 1:
        raise ValueError(
            f"series of length {n_samples} is shorter than one window "
            f"({total_window} steps); no windows can be formed"
        )

    starts = jnp.arange(n_windows) * stride
    gather = starts[:, None] + jnp.arange(total_window)[None, :]  # (n_win, tw)
    windowed = stacked[:, gather, :]  # (n_stocks, n_win, tw, 2)
    windowed = jnp.transpose(windowed, (1, 0, 2, 3))  # (n_win, n_stocks, tw, 2)

    if prediction:
        x = windowed[:, :, :lookback_window, :]
        y = windowed[:, :, lookback_window:, :]
    else:
        x = windowed
        y = windowed[:, :, lookback_window - target_window :, :]
    return x, y


def add_quadratic_features(
    x: Array, interaction_only: bool = False, include_bias: bool = False
) -> Array:
    """Expand the 2-channel window into polynomial features.

    Produces ``[r_stock, r_market, r_stock*r_market]`` plus the squares when
    not ``interaction_only``, plus an optional all-ones bias channel
    (reference: src/common.py:115-130).

    Args:
        x: ``(n_windows, n_stocks, window, 2)``.

    Returns:
        ``(n_windows, n_stocks, window, n_features)`` with 3..6 features.
    """
    r_stock = x[..., 0]
    r_market = x[..., 1]
    features = [r_stock, r_market, r_stock * r_market]
    if not interaction_only:
        features.extend([r_stock * r_stock, r_market * r_market])
    if include_bias:
        features.append(jnp.ones_like(r_stock))
    return jnp.stack(features, axis=-1)


def ols_features(target: Array) -> tuple[Array, Array, Array, Array]:
    """Per-window OLS supervision features from the *target* window.

    Fits ``r_stock ≈ alpha + beta * r_market`` on each target window, then
    summarizes the factor (mean/var of market returns) and the inverse
    idiosyncratic variance of the fit residuals — these become the labels and
    NLL plug-ins downstream (reference: src/common.py:132-148).

    Variances are unbiased (ddof=1), matching torch's default ``var``.

    Args:
        target: ``(n_windows, n_stocks, target_window, >=2)`` with channels
            ``[r_stock, r_market, ...]``.

    Returns:
        ``alphas``: ``(n_windows, n_stocks)``,
        ``betas``: ``(n_windows, n_stocks)``,
        ``factor``: ``(n_windows, 2)`` = (market mean, market var),
        ``inv_psi``: ``(n_windows, n_stocks)`` = 1 / var(residuals).
    """
    r_stocks = target[:, :, :, 0]  # (n_win, n_stocks, tw)
    r_market = target[:, 0, :, 1]  # (n_win, tw) — market identical across stocks

    alphas, betas = ols(r_market, r_stocks)

    r_pred = alphas[..., None] + betas[..., None] * r_market[:, None, :]
    residuals = r_stocks - r_pred

    factor = jnp.stack(
        [r_market.mean(axis=-1), r_market.var(axis=-1, ddof=1)], axis=-1
    )
    psi = residuals.var(axis=-1, ddof=1)
    inv_psi = 1.0 / psi
    return alphas, betas, factor, inv_psi
