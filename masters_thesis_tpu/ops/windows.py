"""Windowed-dataset construction: lookback/target splitting and feature maps.

Capability parity with the reference's window pipeline
(reference: src/common.py:81-148). The reference uses torch ``unfold`` (a
strided view); here windows are materialized with a gather over precomputed
start indices — static shapes throughout, so the whole pipeline jit-compiles
and can run on device or host.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from masters_thesis_tpu.ops.linalg import ols, ols_k


def lookback_target_split(
    r_stocks: Array,
    r_market: Array,
    lookback_window: int,
    target_window: int,
    stride: int | None = None,
    prediction: bool = True,
) -> tuple[Array, Array]:
    """Slice return series into strided (lookback, target) window pairs.

    Stocks and market are broadcast against each other, stacked on a trailing
    channel axis, and windowed along time (reference: src/common.py:81-112).

    Args:
        r_stocks: ``(n_stocks, n_samples)`` stock return series.
        r_market: ``(n_samples,)`` market return series (broadcast to
            stocks), or ``(n_factors, n_samples)`` factor return series for
            the K-factor workload (each factor becomes one channel).
        lookback_window: encoder context length.
        target_window: supervision horizon length.
        stride: window start spacing; defaults to ``lookback + target``
            (non-overlapping).
        prediction: if True, target is the ``target_window`` steps *after* the
            lookback (disjoint X/y); if False (reconstruction), the target is
            the trailing ``target_window`` steps *inside* the lookback.

    Returns:
        ``X``: ``(n_windows, n_stocks, lookback_window, 1+n_factors)`` and
        ``y``: ``(n_windows, n_stocks, target_window or lookback_window,
        1+n_factors)`` with channels ``[r_stock, f_1 .. f_F]`` (F = 1 for
        the scalar market series).
    """
    if stride is None:
        stride = lookback_window + target_window

    if not prediction and target_window > lookback_window:
        raise ValueError(
            f"reconstruction task requires target_window ({target_window}) <= "
            f"lookback_window ({lookback_window})"
        )

    total_window = lookback_window + target_window if prediction else lookback_window

    if r_market.ndim == 1:
        # Scalar market series: the original two-channel stack, untouched
        # (the K=1 bit-identity anchor).
        stacked = jnp.stack(jnp.broadcast_arrays(r_stocks, r_market), axis=-1)
    else:
        # (F, T) factor block: broadcast each factor across the asset axis
        # as its own trailing channel, [r_stock, f_1 .. f_F].
        factors = jnp.broadcast_to(
            r_market.T[None, :, :],
            (r_stocks.shape[0],) + r_market.T.shape,
        )
        stacked = jnp.concatenate([r_stocks[..., None], factors], axis=-1)
    n_samples = stacked.shape[1]
    n_windows = (n_samples - total_window) // stride + 1
    if n_windows < 1:
        raise ValueError(
            f"series of length {n_samples} is shorter than one window "
            f"({total_window} steps); no windows can be formed"
        )

    starts = jnp.arange(n_windows) * stride
    gather = starts[:, None] + jnp.arange(total_window)[None, :]  # (n_win, tw)
    windowed = stacked[:, gather, :]  # (n_stocks, n_win, tw, 2)
    windowed = jnp.transpose(windowed, (1, 0, 2, 3))  # (n_win, n_stocks, tw, 2)

    if prediction:
        x = windowed[:, :, :lookback_window, :]
        y = windowed[:, :, lookback_window:, :]
    else:
        x = windowed
        y = windowed[:, :, lookback_window - target_window :, :]
    return x, y


def add_quadratic_features(
    x: Array, interaction_only: bool = False, include_bias: bool = False
) -> Array:
    """Expand the ``1+F``-channel window into polynomial features.

    Produces ``[r_stock, f_1..f_F, r_stock*f_1 .. r_stock*f_F]`` plus the
    squares (``r_stock², f_1² .. f_F²``) when not ``interaction_only``, plus
    an optional all-ones bias channel (reference: src/common.py:115-130). At
    F=1 this is exactly the original ``[r_stock, r_market, r_stock*r_market]``
    ordering, elementwise op for op, so the scalar path is bit-identical.

    Args:
        x: ``(n_windows, n_stocks, window, 1+F)``.

    Returns:
        ``(n_windows, n_stocks, window, n_features)`` with ``2F+1`` features
        (interaction-only) or ``3F+2``, plus the optional bias.
    """
    r_stock = x[..., 0]
    factors = [x[..., 1 + i] for i in range(x.shape[-1] - 1)]
    features = [r_stock, *factors, *[r_stock * f for f in factors]]
    if not interaction_only:
        features.extend([r_stock * r_stock, *[f * f for f in factors]])
    if include_bias:
        features.append(jnp.ones_like(r_stock))
    return jnp.stack(features, axis=-1)


def ols_features(target: Array) -> tuple[Array, Array, Array, Array]:
    """Per-window OLS supervision features from the *target* window.

    Fits ``r_stock ≈ alpha + beta * r_market`` on each target window, then
    summarizes the factor (mean/var of market returns) and the inverse
    idiosyncratic variance of the fit residuals — these become the labels and
    NLL plug-ins downstream (reference: src/common.py:132-148).

    Variances are unbiased (ddof=1), matching torch's default ``var``.

    With ``F > 1`` factor channels the fit is the multi-factor regression
    ``r_stock ≈ alpha + Σ_f beta_f * f`` and the factor summary becomes the
    sample mean vector plus the flattened (ddof=1) factor covariance. The
    F=1 branch keeps the original scalar code path, op for op, so the K=1
    pipeline stays bit-identical.

    Args:
        target: ``(n_windows, n_stocks, target_window, 1+F)`` with channels
            ``[r_stock, f_1 .. f_F]``.

    Returns:
        ``alphas``: ``(n_windows, n_stocks)``,
        ``betas``: ``(n_windows, n_stocks)`` at F=1, else
        ``(n_windows, n_stocks, F)``,
        ``factor``: ``(n_windows, 2)`` = (market mean, market var) at F=1,
        else ``(n_windows, F + F²)`` = ``[f_mean | f_cov.ravel()]``,
        ``inv_psi``: ``(n_windows, n_stocks)`` = 1 / var(residuals).
    """
    n_f = target.shape[-1] - 1
    r_stocks = target[:, :, :, 0]  # (n_win, n_stocks, tw)
    if n_f == 1:
        r_market = target[:, 0, :, 1]  # (n_win, tw) — market identical across stocks

        alphas, betas = ols(r_market, r_stocks)

        r_pred = alphas[..., None] + betas[..., None] * r_market[:, None, :]
        residuals = r_stocks - r_pred

        factor = jnp.stack(
            [r_market.mean(axis=-1), r_market.var(axis=-1, ddof=1)], axis=-1
        )
        psi = residuals.var(axis=-1, ddof=1)
        inv_psi = 1.0 / psi
        return alphas, betas, factor, inv_psi

    f = target[:, 0, :, 1:]  # (n_win, tw, F) — factors identical across stocks

    alphas, betas = ols_k(f, r_stocks)  # (n_win, k), (n_win, k, F)

    r_pred = alphas[..., None] + jnp.einsum(
        "wkf,wtf->wkt", betas, f, precision="highest"
    )
    residuals = r_stocks - r_pred

    f_mean = f.mean(axis=1)  # (n_win, F)
    centered = f - f_mean[:, None, :]
    f_cov = jnp.einsum(
        "wtf,wtg->wfg", centered, centered, precision="highest"
    ) / (f.shape[1] - 1)
    factor = jnp.concatenate(
        [f_mean, f_cov.reshape(f_cov.shape[0], -1)], axis=-1
    )
    psi = residuals.var(axis=-1, ddof=1)
    inv_psi = 1.0 / psi
    return alphas, betas, factor, inv_psi
