"""Stateless numerical core: pure-``jnp``, static-shape, jit-safe primitives.

TPU-native re-expression of the reference numerical layer
(reference: src/common.py and the static NLL core of src/model.py:44-69).
Every function here is traceable under ``jax.jit`` and free of Python-level
data-dependent control flow, so XLA can fuse it into the surrounding step.
"""

from masters_thesis_tpu.ops.linalg import ols, ols_k, inverse_returns_covariance
from masters_thesis_tpu.ops.windows import (
    lookback_target_split,
    add_quadratic_features,
    ols_features,
)
from masters_thesis_tpu.ops.losses import (
    multivariate_gaussian_nll,
    single_factor_gaussian_nll,
    kfactor_gaussian_nll,
    mean_squared_error,
    LOG_2PI,
)

__all__ = [
    "ols",
    "ols_k",
    "inverse_returns_covariance",
    "lookback_target_split",
    "add_quadratic_features",
    "ols_features",
    "multivariate_gaussian_nll",
    "single_factor_gaussian_nll",
    "kfactor_gaussian_nll",
    "mean_squared_error",
    "LOG_2PI",
]
