"""Batched linear-algebra primitives for the single-factor model.

Capability parity: ``ols`` reproduces the reference's batched
ordinary-least-squares solver (reference: src/common.py:5-47) and
``inverse_returns_covariance`` its Woodbury-identity inverse covariance
(reference: src/common.py:50-78) — re-designed as pure jnp functions so XLA
lowers them to MXU dot-generals and fuses them into the enclosing jitted step
(the reference runs them as eager CUDA kernel launches).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array


def ols(x: Array, y: Array) -> tuple[Array, Array]:
    """Least-squares intercept + slope of ``y`` on ``x``, batched.

    Solves ``y ≈ alpha + beta * x`` per stock via the normal equations with a
    pseudo-inverse (robust to a degenerate/constant regressor).

    Args:
        x: regressor series — ``(n_samples,)`` or ``(batch, n_samples)``.
        y: regressand series — ``(n_stocks, n_samples)`` or
           ``(batch, n_stocks, n_samples)``.

    Returns:
        ``(alphas, betas)`` each ``(n_stocks,)`` / ``(batch, n_stocks)``;
        size-1 dims are squeezed in the unbatched path, matching the
        reference's unsqueeze/squeeze convention (src/common.py:21-27).
    """
    if x.ndim <= 2 and y.ndim <= 2:
        alphas, betas = _batched_ols(x[None, ...], y[None, ...])
        return alphas.squeeze(), betas.squeeze()
    return _batched_ols(x, y)


def _batched_ols(x: Array, y: Array) -> tuple[Array, Array]:
    """Normal-equation OLS ``(XᵀX)⁺ Xᵀ yᵀ`` with an explicit intercept column.

    x: (batch, n) — regressor.  y: (batch, k, n) — one row per stock.
    """
    design = jnp.stack([jnp.ones_like(x), x], axis=-1)  # (batch, n, 2)
    # These are tiny, accuracy-sensitive contractions: pin them to full f32
    # accumulation so TPU's default bf16 matmul mode cannot degrade the fit.
    gram = jnp.matmul(design.mT, design, precision="highest")  # (batch, 2, 2)
    moment = jnp.matmul(design.mT, y.mT, precision="highest")  # (batch, 2, k)
    coef = jnp.matmul(jnp.linalg.pinv(gram), moment, precision="highest")
    return coef[:, 0, :], coef[:, 1, :]


def ols_k(factors: Array, y: Array) -> tuple[Array, Array]:
    """Multi-factor least squares: ``y ≈ alpha + factors @ beta``, batched.

    The K-factor generalization of :func:`ols`: the design matrix is
    ``[1 | f_1 ... f_F]`` so the solved coefficient vector is ``[K+1]``
    per stock (intercept + one loading per factor). At ``F == 1`` the
    design matrix holds exactly the values :func:`_batched_ols` stacks, so
    the result is bit-identical to the scalar path (the parity anchor —
    tests/test_ops_linalg.py).

    Args:
        factors: factor return series — ``(n_samples, F)`` or
            ``(batch, n_samples, F)``.
        y: regressand series — ``(n_stocks, n_samples)`` or
            ``(batch, n_stocks, n_samples)``.

    Returns:
        ``(alphas, betas)`` with shapes ``(..., n_stocks)`` and
        ``(..., n_stocks, F)``.
    """
    if factors.ndim == 2 and y.ndim == 2:
        alphas, betas = _batched_ols_k(factors[None], y[None])
        return alphas[0], betas[0]
    return _batched_ols_k(factors, y)


def _batched_ols_k(factors: Array, y: Array) -> tuple[Array, Array]:
    """factors: (batch, n, F); y: (batch, k, n)."""
    ones = jnp.ones(factors.shape[:-1] + (1,), factors.dtype)
    design = jnp.concatenate([ones, factors], axis=-1)  # (batch, n, F+1)
    gram = jnp.matmul(design.mT, design, precision="highest")
    moment = jnp.matmul(design.mT, y.mT, precision="highest")  # (b, F+1, k)
    coef = jnp.matmul(jnp.linalg.pinv(gram), moment, precision="highest")
    return coef[:, 0, :], jnp.swapaxes(coef[:, 1:, :], -1, -2)


def inverse_returns_covariance(
    beta: Array, inv_psi: Array, f_var: Array
) -> Array:
    """Inverse of the single-factor return covariance via Woodbury.

    The factor model implies ``Sigma = f_var * beta betaᵀ + Psi`` with
    diagonal idiosyncratic covariance ``Psi``. Woodbury gives

        Sigma⁻¹ = Psi⁻¹ − (Psi⁻¹ beta betaᵀ Psi⁻¹) / (1/f_var + betaᵀ Psi⁻¹ beta)

    (reference: src/common.py:50-78). Kept as a rank-1 correction so the cost
    is O(K²) instead of an O(K³) dense inverse, and everything fuses.

    Args:
        beta: ``(n_stocks, 1)`` factor loadings.
        inv_psi: ``(n_stocks, n_stocks)`` diagonal inverse idiosyncratic cov.
        f_var: scalar factor variance.

    Returns:
        ``(n_stocks, n_stocks)`` inverse covariance.
    """
    inv_psi_beta = jnp.matmul(inv_psi, beta, precision="highest")  # (K, 1)
    beta_t_inv_psi = jnp.matmul(beta.T, inv_psi, precision="highest")  # (1, K)
    denominator = 1.0 / f_var + jnp.matmul(
        beta_t_inv_psi, beta, precision="highest"
    )  # (1, 1)
    correction = (
        jnp.matmul(inv_psi_beta, beta_t_inv_psi, precision="highest") / denominator
    )
    return inv_psi - correction
