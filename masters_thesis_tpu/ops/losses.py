"""Differentiable loss cores, expressed as pure functions.

The reference wraps its multivariate-Gaussian NLL in a TorchMetric with
distributed-reduction state (reference: src/model.py:12-69); here the
*numerics* live as stateless functions (this module) and the *accumulation /
cross-device reduction* lives in ``masters_thesis_tpu.train.steps`` as psum-
reducible (value_sum, weight) pytrees — the idiomatic JAX split of the same
capability.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import Array

LOG_2PI = math.log(2.0 * math.pi)


def multivariate_gaussian_nll(mean: Array, inv_cov: Array, target: Array) -> Array:
    """Negative log-likelihood of ``target`` under N(mean, inv_cov⁻¹).

    ``0.5 * [ n * (K*log 2π − logdet Σ⁻¹) + tr((Y−μ)ᵀ Σ⁻¹ (Y−μ)) ]`` summed
    over the ``n`` target columns (reference: src/model.py:44-69). The trace
    is computed as an elementwise contraction ``sum(diff ⊙ (Σ⁻¹ diff))`` —
    O(K²n) instead of materializing the (n, n) product the reference forms.

    A non-positive-definite ``inv_cov`` yields NaN (sign of slogdet ≤ 0),
    matching ``torch.logdet`` semantics.

    Args:
        mean: ``(K, 1)`` predicted mean per stock.
        inv_cov: ``(K, K)`` inverse covariance.
        target: ``(K, n)`` observed returns, one column per day.

    Returns:
        Scalar NLL (summed over the n columns, not averaged).
    """
    k, n = target.shape
    diff = target - mean  # (K, n)
    quadratic = jnp.sum(jnp.matmul(inv_cov, diff, precision="highest") * diff)
    sign, log_det = jnp.linalg.slogdet(inv_cov)
    log_det = jnp.where(sign > 0, log_det, jnp.nan)
    return 0.5 * (n * (k * LOG_2PI - log_det) + quadratic)


def single_factor_gaussian_nll(
    mean: Array, beta: Array, inv_psi: Array, f_var: Array, target: Array
) -> Array:
    """Gaussian NLL under ``Σ = f_var·β βᵀ + diag(1/inv_psi)``, fused.

    Numerically equal to ``multivariate_gaussian_nll(mean,
    inverse_returns_covariance(β, diag(inv_psi), f_var), target)`` (the
    reference's two-step path, src/common.py:50-78 + src/model.py:44-69) but
    exploits the single-factor structure end to end:

    - matrix determinant lemma:
      ``logdet Σ⁻¹ = Σ log inv_psi − log1p(f_var · βᵀΨ⁻¹β)``
    - rank-1 Woodbury quadratic:
      ``dᵀΣ⁻¹d = dᵀΨ⁻¹d − (βᵀΨ⁻¹d)² / (1/f_var + βᵀΨ⁻¹β)``

    O(K·n) instead of the dense path's O(K³ + K²·n) — this is what makes
    NLL/combined training run at MSE-like throughput. Non-PSD inputs
    (``inv_psi ≤ 0`` or a non-positive Woodbury denominator) yield NaN,
    matching the dense path's ``slogdet`` sign check.

    Args:
        mean: ``(K, 1)`` predicted mean per stock.
        beta: ``(K, 1)`` factor loadings.
        inv_psi: ``(K,)`` inverse idiosyncratic variances.
        f_var: scalar factor variance.
        target: ``(K, n)`` observed returns, one column per day.

    Returns:
        Scalar NLL (summed over the n columns, not averaged).
    """
    k, n = target.shape
    diff = target - mean  # (K, n)
    b = beta[:, 0]
    b_ip = b * inv_psi  # βᵀΨ⁻¹, (K,)
    bt_ip_b = jnp.sum(b * b_ip)
    denom = 1.0 / f_var + bt_ip_b
    proj = jnp.matmul(b_ip[None, :], diff, precision="highest")  # (1, n)
    quadratic = (
        jnp.sum(inv_psi[:, None] * jnp.square(diff))
        - jnp.sum(jnp.square(proj)) / denom
    )
    log_det = jnp.sum(jnp.log(inv_psi)) - jnp.log1p(f_var * bt_ip_b)
    valid = (jnp.min(inv_psi) > 0) & (denom > 0)
    log_det = jnp.where(valid, log_det, jnp.nan)
    return 0.5 * (n * (k * LOG_2PI - log_det) + quadratic)


def kfactor_gaussian_nll(
    mean: Array, beta: Array, inv_psi: Array, f_cov: Array, target: Array
) -> Array:
    """Gaussian NLL under ``Σ = B F Bᵀ + diag(1/inv_psi)``, rank-F Woodbury.

    The K-factor generalization of :func:`single_factor_gaussian_nll`: with
    ``F`` factors the Woodbury correction needs an F×F capacitance solve
    instead of a scalar division,

    - determinant lemma: ``logdet Σ⁻¹ = Σ log inv_psi − logdet F − logdet C``
      with capacitance ``C = F⁻¹ + BᵀΨ⁻¹B``
    - quadratic: ``dᵀΣ⁻¹d = dᵀΨ⁻¹d − (BᵀΨ⁻¹d)ᵀ C⁻¹ (BᵀΨ⁻¹d)``

    O(K·n·F + F³) — at universe scale (K in the thousands, F ≤ 5) the F³
    term is negligible and the cost stays linear in the cross-section.
    Non-PSD inputs (``inv_psi ≤ 0`` or a non-positive-definite ``f_cov``/
    capacitance) yield NaN, matching the dense path's ``slogdet`` check.
    The scalar path stays on :func:`single_factor_gaussian_nll` (a static
    F==1 branch in models/objectives.py) so K=1 numerics are untouched.

    Args:
        mean: ``(K, 1)`` predicted mean per stock.
        beta: ``(K, F)`` factor loadings.
        inv_psi: ``(K,)`` inverse idiosyncratic variances.
        f_cov: ``(F, F)`` factor covariance.
        target: ``(K, n)`` observed returns, one column per day.

    Returns:
        Scalar NLL (summed over the n columns, not averaged).
    """
    k, n = target.shape
    diff = target - mean  # (K, n)
    b_ip = beta * inv_psi[:, None]  # Ψ⁻¹B, (K, F)
    btipb = jnp.matmul(beta.T, b_ip, precision="highest")  # BᵀΨ⁻¹B, (F, F)
    sign_f, logdet_f = jnp.linalg.slogdet(f_cov)
    cap = jnp.linalg.inv(f_cov) + btipb  # capacitance C, (F, F)
    sign_c, logdet_c = jnp.linalg.slogdet(cap)
    proj = jnp.matmul(b_ip.T, diff, precision="highest")  # BᵀΨ⁻¹d, (F, n)
    solve = jnp.linalg.solve(cap, proj)  # C⁻¹ BᵀΨ⁻¹d, (F, n)
    quadratic = (
        jnp.sum(inv_psi[:, None] * jnp.square(diff)) - jnp.sum(proj * solve)
    )
    log_det = jnp.sum(jnp.log(inv_psi)) - logdet_f - logdet_c
    valid = (jnp.min(inv_psi) > 0) & (sign_f > 0) & (sign_c > 0)
    log_det = jnp.where(valid, log_det, jnp.nan)
    return 0.5 * (n * (k * LOG_2PI - log_det) + quadratic)


def mean_squared_error(pred: Array, target: Array) -> Array:
    """Plain MSE over all elements (reference: torchmetrics MeanSquaredError)."""
    return jnp.mean(jnp.square(pred - target))
