"""Differentiable loss cores, expressed as pure functions.

The reference wraps its multivariate-Gaussian NLL in a TorchMetric with
distributed-reduction state (reference: src/model.py:12-69); here the
*numerics* live as stateless functions (this module) and the *accumulation /
cross-device reduction* lives in ``masters_thesis_tpu.train.metrics`` as psum-
reducible pytrees — the idiomatic JAX split of the same capability.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import Array

LOG_2PI = math.log(2.0 * math.pi)


def multivariate_gaussian_nll(mean: Array, inv_cov: Array, target: Array) -> Array:
    """Negative log-likelihood of ``target`` under N(mean, inv_cov⁻¹).

    ``0.5 * [ n * (K*log 2π − logdet Σ⁻¹) + tr((Y−μ)ᵀ Σ⁻¹ (Y−μ)) ]`` summed
    over the ``n`` target columns (reference: src/model.py:44-69). The trace
    is computed as an elementwise contraction ``sum(diff ⊙ (Σ⁻¹ diff))`` —
    O(K²n) instead of materializing the (n, n) product the reference forms.

    A non-positive-definite ``inv_cov`` yields NaN (sign of slogdet ≤ 0),
    matching ``torch.logdet`` semantics.

    Args:
        mean: ``(K, 1)`` predicted mean per stock.
        inv_cov: ``(K, K)`` inverse covariance.
        target: ``(K, n)`` observed returns, one column per day.

    Returns:
        Scalar NLL (summed over the n columns, not averaged).
    """
    k, n = target.shape
    diff = target - mean  # (K, n)
    quadratic = jnp.sum(jnp.matmul(inv_cov, diff, precision="highest") * diff)
    sign, log_det = jnp.linalg.slogdet(inv_cov)
    log_det = jnp.where(sign > 0, log_det, jnp.nan)
    return 0.5 * (n * (k * LOG_2PI - log_det) + quadratic)


def mean_squared_error(pred: Array, target: Array) -> Array:
    """Plain MSE over all elements (reference: torchmetrics MeanSquaredError)."""
    return jnp.mean(jnp.square(pred - target))
