"""Fused Pallas TPU kernel for the LSTM recurrence — the hot op.

The reference leans on the cuDNN fused LSTM kernel for its hot loop
(reference: src/model.py:104, ``torch.nn.LSTM``). The TPU-native analog here
follows the same split cuDNN uses: the input projection for all timesteps is
one large MXU matmul (done OUTSIDE this kernel, where XLA already emits an
optimal batched dot), while the inherently sequential part — the per-timestep
recurrent matmul plus gate math — is fused into a single Pallas kernel:

- Hidden/cell state and the recurrent weight live in VMEM for the entire
  time loop; nothing round-trips to HBM between timesteps, and the per-step
  loop overhead is a hardware loop, not 60 unrolled XLA dynamic-slices.
- Each step is one ``(B_tile, H) @ (H, 4H)`` MXU matmul with the sigmoid/
  tanh gate math fused on the VPU, writing ``h_t`` straight into the VMEM
  output block.
- Training needs gradients, and Pallas kernels don't autodiff through
  in-kernel loops — so the backward pass (standard BPTT) is a second fused
  kernel wired via ``jax.custom_vjp``. Instead of stashing gate activations
  like cuDNN, the backward kernel RECOMPUTES them from the saved ``h``/``c``
  and the input projections (one extra MXU matmul per step) — that drops the
  ``(T, B, 4H)`` stash, which is what lets a whole ~100-row batch (the
  reference's 100-stock window) fit in VMEM as ONE program instead of
  serialized row tiles.
- When the batch does fit in one program, the backward kernel additionally
  writes ``dx`` in place over the input-projection buffer
  (``input_output_aliases``): the sweep runs t = T-1 → 0 and slot ``t`` is
  dead after step ``t``, so the overwrite is hazard-free and saves another
  ``(T, B, 4H)`` of VMEM. Larger batches fall back to a row-tiled grid
  (rows are independent) with per-tile partial ``dw`` summed outside.

Everything is time-major ``(T, B, ...)``: each timestep slice is then a
contiguous ``(rows, lanes)`` tile, matching the TPU's (8, 128) layout.

On non-TPU backends ``lstm_recurrence`` falls back to an identical
``lax.scan`` formulation; tests additionally run the Pallas kernels in
interpreter mode on CPU to pin parity between the two paths.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Single-program threshold / fallback row tile. ~104 rows keeps the aliased
# backward under ~12 MB of VMEM at the reference's largest shape (T=60,
# H=64); the tiled fallback uses 32-row blocks (double-buffered by the grid
# pipeline, so its budget is ~2x per-block bytes). The fallback tile is
# env-tunable (MT_LSTM_ROW_TILE, multiple of 8): RESULTS.md's batch sweep
# shows per-window efficiency halving when batches leave the single-program
# regime, and a larger tile trades VMEM for bigger (tile, H) MXU matmuls —
# measure on the target chip before changing the default.
SINGLE_TILE_MAX_ROWS = 104
ROW_TILE = 32


def _fallback_row_tile() -> int:
    raw = os.environ.get("MT_LSTM_ROW_TILE", str(ROW_TILE))
    try:
        tile = int(raw)
    except ValueError:
        tile = -1  # fall through to the descriptive error
    if tile <= 0 or tile % 8:
        raise ValueError(
            f"MT_LSTM_ROW_TILE must be a positive multiple of 8, got {raw!r}"
        )
    return tile


def _pad_rows(a: jax.Array, b_pad: int) -> jax.Array:
    b = a.shape[1]
    if b == b_pad:
        return a
    return jnp.pad(a, ((0, 0), (0, b_pad - b), (0, 0)))


def _row_tile(b: int) -> int:
    b_pad8 = -(-b // 8) * 8
    if b_pad8 <= SINGLE_TILE_MAX_ROWS:
        return b_pad8
    return _fallback_row_tile()


def _gate_math(gates):
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    return jax.nn.sigmoid(i), jax.nn.sigmoid(f), jnp.tanh(g), jax.nn.sigmoid(o)


# ----------------------------------------------------------------- forward


def _fwd_kernel(x_ref, w_ref, h_out, c_out, h_scr, c_scr):
    n_t = x_ref.shape[0]
    h_scr[:] = jnp.zeros_like(h_scr)
    c_scr[:] = jnp.zeros_like(c_scr)
    w = w_ref[:].astype(jnp.float32)

    def body(t, _):
        gates = x_ref[t].astype(jnp.float32) + lax.dot_general(
            h_scr[:],
            w,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        i, f, g, o = _gate_math(gates)
        c = f * c_scr[:] + i * g
        h = o * jnp.tanh(c)
        h_scr[:] = h
        c_scr[:] = c
        h_out[t] = h.astype(h_out.dtype)
        c_out[t] = c.astype(c_out.dtype)
        return 0

    lax.fori_loop(0, n_t, body, 0)


def _fwd_pallas(x_proj, w_hh_t, *, interpret):
    n_t, b, four_h = x_proj.shape
    hidden = four_h // 4
    tile = _row_tile(b)
    b_pad = -(-b // tile) * tile
    x_padded = _pad_rows(x_proj, b_pad)
    grid = (b_pad // tile,)

    row_block = lambda width: pl.BlockSpec(  # noqa: E731
        (n_t, tile, width), lambda i: (0, i, 0), memory_space=pltpu.VMEM
    )
    hs, cs = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            row_block(four_h),
            pl.BlockSpec(
                (hidden, four_h), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[row_block(hidden), row_block(hidden)],
        out_shape=[
            jax.ShapeDtypeStruct((n_t, b_pad, hidden), x_proj.dtype),
            jax.ShapeDtypeStruct((n_t, b_pad, hidden), x_proj.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, hidden), jnp.float32),
            pltpu.VMEM((tile, hidden), jnp.float32),
        ],
        interpret=interpret,
    )(x_padded, w_hh_t)
    # tile rides the residuals: the backward grid must use the SAME tile
    # the forward padded for, even if MT_LSTM_ROW_TILE changes in between.
    return hs[:, :b], (x_padded, hs, cs, w_hh_t, b, tile)


# ---------------------------------------------------------------- backward


def _bwd_kernel(
    dh_ref, x_ref, h_ref, c_ref, w_ref, dx_out, dw_out, dh_scr, dc_scr, dw_scr
):
    n_t = dh_ref.shape[0]
    dh_scr[:] = jnp.zeros_like(dh_scr)
    dc_scr[:] = jnp.zeros_like(dc_scr)
    dw_scr[:] = jnp.zeros_like(dw_scr)
    w = w_ref[:].astype(jnp.float32)

    def body(k, _):
        t = n_t - 1 - k
        t_prev = jnp.maximum(t - 1, 0)
        not_first = jnp.float32(1.0) - (t == 0).astype(jnp.float32)
        c_prev = c_ref[t_prev].astype(jnp.float32) * not_first
        h_prev = h_ref[t_prev].astype(jnp.float32) * not_first
        # Recompute the activated gates (cheaper in VMEM than stashing them).
        gates = x_ref[t].astype(jnp.float32) + lax.dot_general(
            h_prev, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        i, f, g, o = _gate_math(gates)
        tanh_c = jnp.tanh(c_ref[t].astype(jnp.float32))

        dh = dh_ref[t].astype(jnp.float32) + dh_scr[:]
        do = dh * tanh_c
        dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_scr[:]
        di = dc * g
        dg = dc * i
        df = dc * c_prev
        dc_scr[:] = dc * f
        d_pre = jnp.concatenate(
            [
                di * i * (1.0 - i),
                df * f * (1.0 - f),
                dg * (1.0 - g * g),
                do * o * (1.0 - o),
            ],
            axis=-1,
        )
        # Slot t of the (aliased) input buffer is dead from here on.
        dx_out[t] = d_pre.astype(dx_out.dtype)
        # d h_{t-1} = d_pre @ w_hh_tᵀ : contract the 4H axes.
        dh_scr[:] = lax.dot_general(
            d_pre, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # d w_hh_t += h_{t-1}ᵀ @ d_pre : contract the row axes.
        dw_scr[:] += lax.dot_general(
            h_prev, d_pre, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return 0

    lax.fori_loop(0, n_t, body, 0)
    dw_out[0] = dw_scr[:].astype(dw_out.dtype)


def _bwd_pallas(interpret, residuals, dhs):
    x_padded, hs, cs, w_hh_t, b, tile = residuals
    n_t, b_pad, four_h = x_padded.shape
    hidden = four_h // 4
    dhs = _pad_rows(dhs, b_pad)
    grid = (b_pad // tile,)

    row_block = lambda width: pl.BlockSpec(  # noqa: E731
        (n_t, tile, width), lambda i: (0, i, 0), memory_space=pltpu.VMEM
    )
    dx, dw_partial = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            row_block(hidden),   # dhs
            row_block(four_h),   # x_proj (aliased to dx when grid == 1)
            row_block(hidden),   # hs
            row_block(hidden),   # cs
            pl.BlockSpec(
                (hidden, four_h), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[
            row_block(four_h),
            pl.BlockSpec(
                (1, hidden, four_h), lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_t, b_pad, four_h), x_padded.dtype),
            jax.ShapeDtypeStruct((grid[0], hidden, four_h), w_hh_t.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile, hidden), jnp.float32),
            pltpu.VMEM((tile, hidden), jnp.float32),
            pltpu.VMEM((hidden, four_h), jnp.float32),
        ],
        input_output_aliases={1: 0} if grid[0] == 1 else {},
        interpret=interpret,
    )(dhs, x_padded, hs, cs, w_hh_t)
    return dx[:, :b], jnp.sum(dw_partial, axis=0)


# -------------------------------------------------------------- public API


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _lstm_recurrence_pallas(x_proj, w_hh_t, interpret=False):
    hs, _ = _fwd_pallas(x_proj, w_hh_t, interpret=interpret)
    return hs


def _vjp_fwd(x_proj, w_hh_t, interpret):
    return _fwd_pallas(x_proj, w_hh_t, interpret=interpret)


_lstm_recurrence_pallas.defvjp(_vjp_fwd, _bwd_pallas)


def lstm_recurrence_xla(x_proj: jax.Array, w_hh_t: jax.Array) -> jax.Array:
    """Reference formulation: ``lax.scan`` over time (XLA-fused fallback)."""
    b = x_proj.shape[1]
    hidden = w_hh_t.shape[0]
    carry0 = (
        jnp.zeros((b, hidden), x_proj.dtype),
        jnp.zeros((b, hidden), x_proj.dtype),
    )

    def step(carry, xt):
        h, c = carry
        i, f, g, o = _gate_math(xt + h @ w_hh_t)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    _, hs = lax.scan(step, carry0, x_proj)
    return hs


def lstm_recurrence(
    x_proj: jax.Array, w_hh_t: jax.Array, impl: str = "auto"
) -> jax.Array:
    """Run the LSTM time recurrence over pre-projected inputs.

    Args:
        x_proj: ``(T, B, 4H)`` time-major input projections (``x @ w_ihᵀ``
            plus both biases), gate order i, f, g, o as in ``torch.nn.LSTM``.
        w_hh_t: ``(H, 4H)`` transposed recurrent weight.
        impl: ``"pallas"`` | ``"xla"`` | ``"interpret"`` | ``"auto"``
            (pallas on TPU, xla elsewhere).

    Returns:
        ``(T, B, H)`` hidden states for every timestep.
    """
    if impl == "auto":
        impl = (
            "xla"
            if os.environ.get("MT_TPU_DISABLE_PALLAS")
            else ("pallas" if jax.default_backend() == "tpu" else "xla")
        )
    if impl == "pallas":
        return _lstm_recurrence_pallas(x_proj, w_hh_t, False)
    if impl == "interpret":
        return _lstm_recurrence_pallas(x_proj, w_hh_t, True)
    if impl == "xla":
        return lstm_recurrence_xla(x_proj, w_hh_t)
    raise ValueError(f"unknown lstm impl: {impl!r}")
